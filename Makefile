# Operator entry points (analog of the reference's Makefile:33-34
# test/build targets).  The framework is Python+C++: "build" compiles
# the native codec and generated protobuf in place; "install" does a
# pip install of the package with the pilosa-tpu console script.

PYTHON ?= python

.PHONY: default test lint check bench bench-smoke chaos-smoke install build docker clean generate

default: build test

# Full test suite on the virtual 8-device CPU mesh (tests/conftest.py
# forces the backend; never touches a real TPU).
test:
	$(PYTHON) -m pytest tests/ -q

# Fail on undefined names / unused imports across the package (ruff "F"
# rules, configured in pyproject.toml).
lint:
	$(PYTHON) -m ruff check pilosa_tpu/

# The CI gate (.github/workflows/check.yml): lint plus the tier-1 test
# suite (everything not marked slow) on the forced CPU backend.
check: lint
	env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# Compile the C++ codec and verify the wire module imports.
build:
	$(PYTHON) -c "from pilosa_tpu import native; assert native.available(), 'native build failed'; print('native codec ok')"
	$(PYTHON) -c "from pilosa_tpu.net import wire_pb2; print('wire protobuf ok')"

install:
	$(PYTHON) -m pip install .

# One JSON line on stdout; tiers and progress on stderr.  Uses the
# accelerator when one is reachable, else re-execs onto the CPU backend.
bench:
	$(PYTHON) bench.py

# Tiny CPU-only bench pass (seconds, few slices): asserts the JSON
# artifact parses with the coalesce counters, the cold_restart tier,
# and the program-cache bounds invariant.  BLOCKING in CI
# (.github/workflows/check.yml).
bench-smoke:
	$(PYTHON) tools/bench_smoke.py

# Tiny CPU chaos pass: two in-process nodes under PILOSA_FAULTS (one
# erroring + one delayed RPC leg); a fan-out query must still answer
# exactly.  Non-blocking in CI (.github/workflows/check.yml).
chaos-smoke:
	$(PYTHON) tools/chaos_smoke.py

docker:
	docker build -t pilosa-tpu .

# Regenerate wire_pb2.py from the wire contract (needs protoc).
generate:
	protoc --python_out=. pilosa_tpu/net/wire.proto

clean:
	rm -f pilosa_tpu/native/libpilosa_native.so pilosa_tpu/native/libpilosa_native.so.flags
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
