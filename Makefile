# Operator entry points (analog of the reference's Makefile:33-34
# test/build targets).  The framework is Python+C++: "build" compiles
# the native codec and generated protobuf in place; "install" does a
# pip install of the package with the pilosa-tpu console script.

PYTHON ?= python

.PHONY: default test lint analyze typecheck metrics-lint check bench bench-smoke chaos-smoke device-chaos-smoke load-smoke resize-smoke multichip-smoke tier-smoke replication-smoke subscribe-smoke ingest-smoke ingest-bench sparse-smoke sparse-bench churn-soak gameday gameday-smoke install build docker clean generate

default: build test

# Full test suite on the virtual 8-device CPU mesh (tests/conftest.py
# forces the backend; never touches a real TPU).
test:
	$(PYTHON) -m pytest tests/ -q

# ruff F,E,W,B,UP across the package (configured in pyproject.toml).
# Skips with a notice when ruff isn't installed (the slim dev
# container); CI always installs it, so the gate is real there.
lint:
	@if $(PYTHON) -m ruff --version >/dev/null 2>&1; then \
		$(PYTHON) -m ruff check pilosa_tpu/; \
	else \
		echo "lint: ruff not installed; skipping (CI enforces)"; \
	fi

# The concurrency & compile-hazard analyzer (pilosa_tpu/analyze):
# lock-order graph + cycles, blocking-calls-under-lock, JAX compile-key
# hazards, leaked scoped resources.  Allowlist lives in analyze.toml;
# exits non-zero on any undocumented finding.  BLOCKING in check/CI.
analyze:
	$(PYTHON) -m pilosa_tpu.analyze --json analyze-report.json

# Metrics-documentation lint (tools/metrics_lint.py): AST-extracts
# every metric name from the stats calls in pilosa_tpu/ and fails if
# one is absent from the docs/administration.md metrics reference
# table.  BLOCKING in CI (.github/workflows/check.yml).
metrics-lint:
	$(PYTHON) tools/metrics_lint.py

# mypy non-strict baseline (pyproject [tool.mypy]): the promoted
# modules (exec/plan, device/pool, net/resilience, analyze/*) check
# for real; everything else must import-check.  Skips with a notice
# when mypy isn't installed; CI installs it, so blocking there.
typecheck:
	@if $(PYTHON) -c "import mypy" >/dev/null 2>&1; then \
		$(PYTHON) -m mypy; \
	else \
		echo "typecheck: mypy not installed; skipping (CI enforces)"; \
	fi

# The CI gate (.github/workflows/check.yml): lint + analyzer + types
# plus the tier-1 test suite (everything not marked slow) on the
# forced CPU backend.
check: lint analyze typecheck metrics-lint
	env JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# Compile the C++ codec and verify the wire module imports.
build:
	$(PYTHON) -c "from pilosa_tpu import native; assert native.available(), 'native build failed'; print('native codec ok')"
	$(PYTHON) -c "from pilosa_tpu.net import wire_pb2; print('wire protobuf ok')"

install:
	$(PYTHON) -m pip install .

# One JSON line on stdout; tiers and progress on stderr.  Uses the
# accelerator when one is reachable, else re-execs onto the CPU backend.
bench:
	$(PYTHON) bench.py

# Tiny CPU-only bench pass (seconds, few slices): asserts the JSON
# artifact parses with the coalesce counters, the cold_restart tier,
# and the program-cache bounds invariant.  BLOCKING in CI
# (.github/workflows/check.yml).
bench-smoke:
	$(PYTHON) tools/bench_smoke.py

# Tiny CPU chaos pass: two in-process nodes under PILOSA_FAULTS (one
# erroring + one delayed RPC leg); a fan-out query must still answer
# exactly.  Non-blocking in CI (.github/workflows/check.yml).
chaos-smoke:
	$(PYTHON) tools/chaos_smoke.py

# Device-fault chaos pass (tools/device_chaos_smoke.py): on the virtual
# 8-device mesh, a mixed Count/Range/TopN/Sum storm under EACH injected
# device fault (oom / error / hang) must answer byte-identically via
# host fallback, the device must quarantine within the configured
# threshold, a hung collective must trip the launch watchdog instead of
# wedging the process, and clearing the fault must heal through a
# half-open probe.  BLOCKING in CI (.github/workflows/check.yml).
device-chaos-smoke:
	$(PYTHON) tools/device_chaos_smoke.py

# Tiny CPU open-loop load pass (tools/load_smoke.py over the
# tools/load_harness.py storm generator): asserts the artifact carries
# the goodput-vs-offered-load curve + shed counters, and that shed-rate
# is 0 at trivial load.  Writes load-report.json (uploaded as a CI
# artifact).  Non-blocking in CI (.github/workflows/check.yml).
load-smoke:
	$(PYTHON) tools/load_smoke.py

# Tiny CPU live-resize pass (tools/resize_smoke.py): two real nodes
# grow to three under a concurrent writer; asserts checksummed query
# results before == after, zero dropped writes, the new node owns
# slices, and the sources released theirs.  BLOCKING in CI
# (.github/workflows/check.yml), alongside chaos-smoke.
resize-smoke:
	$(PYTHON) tools/resize_smoke.py

# Mesh data-plane smoke (tools/multichip_smoke.py): virtual 8-device
# CPU mesh; asserts sharded execution engages BY DEFAULT with >1
# device visible, a distinct-query Intersect+Count storm + TopN
# through the coalescer/fusion path (incl. the ICI-reduced "total"
# launch) answers byte-identically to the forced single-device path
# and a numpy oracle, fragment planes spread over the shards, and
# interp program-cache entries stay within bounds.  BLOCKING in CI
# (.github/workflows/check.yml).
multichip-smoke:
	$(PYTHON) tools/multichip_smoke.py

# Tiered-storage smoke (tools/tier_smoke.py): local-FS object store;
# demote under a forced disk budget -> cold-boot a node from an EMPTY
# data dir + store alone -> byte-check Count/TopN/Range vs the donor
# (/debug/tier showing cold->hydrating->hot) -> retention sweep ages
# and deletes time-quantum views with a racing writer reviving one.
# BLOCKING in CI (.github/workflows/check.yml), like resize-smoke.
tier-smoke:
	$(PYTHON) tools/tier_smoke.py

# Quorum-replication smoke (tools/replication_smoke.py): 3-node
# replica-3 write storm at consistency=quorum with one replica KILLED
# mid-storm -> restart -> breaker-triggered hint replay converges
# checksums with zero lost writes and NO anti-entropy tick; a
# consistency=all write against the dead replica fails loudly.
# BLOCKING in CI (.github/workflows/check.yml), like resize-smoke.
replication-smoke:
	$(PYTHON) tools/replication_smoke.py

# Standing-query smoke (tools/subscribe_smoke.py): two real nodes,
# 100+ standing PQL subscriptions (single-row counts, compound trees,
# TopN) under a live import stream; grows the cluster to three nodes
# MID-STREAM, then asserts every subscription converges to the pull
# oracle, updates are version-monotonic, the topology move re-stamped
# subscription epochs, and update lag p99 stays bounded.  CI also runs
# it under PILOSA_LOCK_CHECK=1.  BLOCKING in CI
# (.github/workflows/check.yml), like resize-smoke.
subscribe-smoke:
	$(PYTHON) tools/subscribe_smoke.py

# Durable-ingest smoke (tools/ingest_smoke.py): a child process takes
# a multi-threaded acked write storm (each ack reported only after the
# WAL group commit fsynced) and is kill -9'd mid-storm; reopening the
# data dir must replay the WAL tail with ZERO lost acked bits vs the
# parent's host oracle.  CI runs it under PILOSA_LOCK_CHECK=1.
# BLOCKING in CI (.github/workflows/check.yml), like subscribe-smoke.
ingest-smoke:
	$(PYTHON) tools/ingest_smoke.py

# Compressed-plane smoke (tools/sparse_smoke.py): tiny 1%-density
# clustered corpus on the CPU backend; write-time container selection
# must pick RLE/sparse formats (no dense rows), every answer over the
# compressed planes is byte-checked against a numpy oracle with the
# anchored position-domain count route engaged, and the paged-in rows'
# resident HBM must sit >= 10x below logical dense geometry.  CI runs
# it under PILOSA_LOCK_CHECK=1.  BLOCKING in CI
# (.github/workflows/check.yml), like subscribe-smoke.
sparse-smoke:
	$(PYTHON) tools/sparse_smoke.py

# Sparse bench tier standalone (tools/sparse_bench.py): effective
# Gcols/s + bytes read + format mix + resident ratio over 50%/5%/1%/
# 0.1% density corpora with a byte-identity storm vs the forced-dense
# arm.  One JSON line on stdout; also runs inside make bench (bench.py
# "sparse" tier) and is asserted by bench-smoke.
sparse-bench:
	$(PYTHON) tools/sparse_bench.py

# Ingest bench tier standalone (tools/ingest_bench.py): durable acked
# write throughput with group commit on/off vs the WAL-off baseline,
# read p99 under a 50/50 read/write storm vs read-only, and mirror
# re-stage bytes with delta-scatter on/off.  One JSON line on stdout;
# also runs inside make bench (bench.py "ingest" tier) and is asserted
# by bench-smoke.
ingest-bench:
	$(PYTHON) tools/ingest_bench.py

# The everything-at-once soak (tools/gameday.py): one seeded run
# composing every failure mode the stack claims to survive — a
# multi-tenant fairness storm (victim p99 bounded while the hot tenant
# sheds on quota), a kill -9'd replica recovering via WAL replay +
# hint drain with zero lost acked writes, resize 2->3->2 under load
# with a WINDOWED device-fault timeline and tier demote/hydrate,
# subscription convergence across both cutovers, and gossip under
# datagram loss.  Emits gameday.json; non-blocking soak lane in CI,
# with the --smoke variant blocking.
gameday:
	$(PYTHON) tools/gameday.py --artifact gameday.json

gameday-smoke:
	$(PYTHON) tools/gameday.py --smoke --artifact gameday.json

# Gossip churn soak (tools/churn_soak.py): 20-50 virtual members under
# seeded datagram loss + member flapping; asserts membership converges
# on exactly the live set each cycle with zero false-DOWNs of
# reachable members.  The deterministic tier-1 slice lives in
# tests/test_churn.py; this is the big dial-a-size soak.
churn-soak:
	$(PYTHON) tools/churn_soak.py

docker:
	docker build -t pilosa-tpu .

# Regenerate wire_pb2.py from the wire contract (needs protoc).
generate:
	protoc --python_out=. pilosa_tpu/net/wire.proto

clean:
	rm -f pilosa_tpu/native/libpilosa_native.so pilosa_tpu/native/libpilosa_native.so.flags
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
