# Container image for pilosa-tpu (analog of the reference's Dockerfile:
# builder stage + minimal runtime, server-on-/data entrypoint).
#
# The compute path runs on JAX; inside a container that is the CPU
# backend unless a TPU runtime is mounted in (set JAX_PLATFORMS and the
# libtpu env per your TPU platform).  The C++ codec compiles at build
# time so first boot doesn't need the toolchain.
#
#   docker build -t pilosa-tpu .
#   docker run -p 10101:10101 -v pilosa-data:/data pilosa-tpu

FROM python:3.12-slim AS builder

RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

COPY pyproject.toml README.md /src/
COPY pilosa_tpu /src/pilosa_tpu

# Pre-build the native codec into the installed package so the runtime
# image needs no compiler (lib() compiles next to the source on first
# use).
RUN pip install --no-cache-dir /src \
    && python -c "from pilosa_tpu import native; assert native.available(), 'native codec failed to build'"

FROM python:3.12-slim

COPY --from=builder /usr/local/lib/python3.12/site-packages /usr/local/lib/python3.12/site-packages
COPY --from=builder /usr/local/bin/pilosa-tpu /usr/local/bin/pilosa-tpu

EXPOSE 10101
VOLUME /data

ENTRYPOINT ["pilosa-tpu"]
CMD ["server", "--data-dir", "/data", "--bind", "0.0.0.0:10101"]
