"""Measure TRUE per-execution device time for the bench kernels through
the axon tunnel, immune to its two measurement traps:

  1. ``block_until_ready`` is a lazy acknowledgment — compute runs fully
     async and only a VALUE FETCH truly waits (measured: XLA passes
     "completing" at 10+ TB/s under block_until_ready).  So every
     timing here FOLDS the N outputs into one device scalar and fetches
     it: all N executions must actually finish.
  2. The shared pool has sporadic multi-second stalls (one 45 s stall
     observed mid-probe), so every wall time is the BEST of several
     epochs.  Reusing inputs across epochs is sound because the pool
     does NOT memoize results: fetch-folded repeat-vs-fresh ratios
     measured ~1.0x when this tool characterized the tunnel (r04).

Per-run time = slope between a 28-run and a 4-run folded pass,
cancelling dispatch overhead and the fetch round trip.

Evidence tool for BASELINE.md's bandwidth analysis; exits 0 on partial
failure.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(m):
    print(m, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.exec import plan
    from pilosa_tpu.pql.parser import parse_string

    dev = jax.devices()[0]
    log(f"backend={jax.default_backend()} device={dev}")

    SLICES, WORDS = 954, 32768
    n_pad = (SLICES + 7) // 8 * 8  # 960
    K = 4
    rng = np.random.default_rng(11)
    log(f"staging {K} distinct [{n_pad},2,{WORDS}] uint32 batches (~{K*n_pad*2*WORDS*4/1e9:.1f} GB)")
    batches = []
    for i in range(K):
        arr = rng.integers(0, 2**32, size=(n_pad, 2, WORDS), dtype=np.uint32)
        batches.append(jax.device_put(jnp.asarray(arr)))
    jax.block_until_ready(batches)
    bytes_per = n_pad * 2 * WORDS * 4

    def folded(fn, inputs):
        """Wall seconds for len(inputs) executions, outputs folded into
        one fetched scalar so all of them must really finish."""
        t0 = time.perf_counter()
        acc = None
        for d in inputs:
            part = fn(d).astype(jnp.float32).sum()
            acc = part if acc is None else acc + part
        float(np.asarray(acc))
        return time.perf_counter() - t0

    # The fetch round trip (~75 ms through the tunnel) has several ms of
    # epoch-to-epoch jitter, so the run-count CONTRAST must be large
    # enough that N x per-run-time dwarfs it.  Cycling the 4 distinct
    # batches is sound: repeat-vs-fresh measured ~1.0x (no memoization).
    # lo/hi epochs INTERLEAVE so both see the same pool conditions, and
    # a slope implying more bandwidth than the chip's HBM peak (v5e
    # ~819 GB/s) is retried rather than reported — the same hardening
    # bench.py's slope_time carries.
    N_LO, N_HI = 4, 28
    from bench import hbm_peak_bytes_s, measure_slope

    peak = hbm_peak_bytes_s(jax) if jax.default_backend() == "tpu" else None

    def probe(name, fn):
        try:
            jax.block_until_ready(fn(batches[0]))  # compile
        except Exception as e:  # noqa: BLE001
            log(f"{name}: compile failed {e!r:.200}")
            return None
        slope = measure_slope(
            lambda inputs: folded(fn, inputs),
            [batches[i % K] for i in range(N_LO)],
            [batches[i % K] for i in range(N_HI)],
            bytes_per,
            (peak or 819e9) * 1.25,
            lambda m: log(f"{name}: {m}"),
        )
        if slope is None:
            log(f"{name}: UNRELIABLE (pool interference)")
            return None
        log(
            f"{name}: slope {slope*1e3:.3f} ms/run"
            f" -> {bytes_per/slope/1e9:.0f} GB/s operand read"
        )
        return slope

    probe("stream-sum", jax.jit(lambda d: jnp.sum(d, dtype=jnp.uint32)))
    probe(
        "popcount-sum",
        jax.jit(lambda d: jnp.sum(jax.lax.population_count(d).astype(jnp.int32))),
    )
    probe(
        "and+popcount-sum",
        jax.jit(
            lambda d: jnp.sum(
                jax.lax.population_count(d[:, 0] & d[:, 1]).astype(jnp.int32)
            )
        ),
    )
    probe(
        "and+popcount-rowsum",
        jax.jit(
            lambda d: jnp.sum(
                jax.lax.population_count(d[:, 0] & d[:, 1]).astype(jnp.int32),
                axis=-1,
            )
        ),
    )

    q = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))")
    expr, _ = plan.decompose(q.calls[0].children[0])
    probe(
        "production fused-XLA (per-slice counts)",
        plan.compiled_batched(expr, "count"),
    )
    probe("production limb total-count", plan.compiled_total_count(expr))


if __name__ == "__main__":
    main()
