"""CI smoke pass over the tiered-storage subsystem (pilosa_tpu/tier).

A tiny CPU-only end-to-end wiring check, BLOCKING in CI (like
resize-smoke for the elastic cluster): local-FS store →

    1. a donor node imports multi-slice data (plain bits, a BSI field,
       TopN-shaped rows, a time-quantum frame) and archives it to the
       store (schema + per-fragment checksummed tars);
    2. DEMOTE: the donor's disk budget is forced below its hot bytes —
       the LRU sweep flips fragments to tar-only and queries
       transparently hydrate them back, byte-identically;
    3. COLD BOOT: a second node with an EMPTY data dir and only
       [tier] store configured serves every query byte-identically to
       the donor, with /debug/tier showing cold → hydrating → hot;
    4. RETENTION: expired time-quantum views age to the store and
       delete past the horizon on a sweep, and a racing writer to an
       expired view revives it with no bit loss.

Not a performance measurement — the `tiered` bench tier records those.
Run via ``make tier-smoke``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from datetime import datetime, timedelta

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def main() -> int:  # noqa: PLR0911 — smoke gates exit at first failure
    from pilosa_tpu.net.client import InternalClient
    from pilosa_tpu.net.server import Server
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH

    tmp = tempfile.mkdtemp(prefix="tier-smoke-")
    store_url = os.path.join(tmp, "store")

    def boot(name: str, **kwargs) -> Server:
        s = Server(
            data_dir=os.path.join(tmp, name),
            host="127.0.0.1:0",
            logger=lambda m: print(f"[{name}] {m}", file=sys.stderr),
            tier_store=store_url,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
            tier_sweep_interval_s=3600,
            prewarm=False,
            **kwargs,
        )
        s.open()
        return s

    queries = [
        'Count(Bitmap(frame="f", rowID=1))',
        'Count(Union(Bitmap(frame="f", rowID=1), Bitmap(frame="f", rowID=3)))',
        'Count(Difference(Bitmap(frame="f", rowID=2), Bitmap(frame="f", rowID=4)))',
        'TopN(frame="f", n=8)',
        'Count(Range(frame="f", val > 50))',
    ]

    def run_all(client) -> list:
        out = []
        for q in queries:
            r = client.execute_pql("i", q)
            if hasattr(r, "__iter__"):
                r = [(p.id, p.count) for p in r]
            out.append(r)
        return out

    # ---- 1. donor: seed data across 3 slices + archive to the store --
    donor = boot("donor")
    c0 = InternalClient(donor.host)
    c0.create_index("i")
    c0.create_frame("i", "f", {"rangeEnabled": True})
    c0.create_field("i", "f", "val", 0, 1000)
    for s in range(3):
        bits = [
            ((c * 7 + s) % 13, s * SLICE_WIDTH + (c * 31) % SLICE_WIDTH)
            for c in range(400)
        ]
        c0.import_bits("i", "f", s, bits)
    c0.import_value(
        "i", "f", "val", 0, list(range(64)), [((v * 17) % 999) for v in range(64)]
    )
    want = run_all(c0)
    uploaded = donor.tier.upload_all()
    if uploaded < 4:
        return fail(f"donor uploaded only {uploaded} fragments")
    print(f"tier-smoke: donor archived {uploaded} fragments", file=sys.stderr)

    # ---- 2. demote under a forced disk budget; queries hydrate back --
    donor.tier.disk_budget_bytes = 1
    demoted = donor.tier.enforce_disk_budget()
    if demoted < 4:
        return fail(f"budget sweep demoted only {demoted} fragments")
    snap = donor.tier.snapshot()
    cold_n = snap["countsByState"].get("cold", 0)
    if cold_n < 4:
        return fail(f"expected >=4 cold fragments after demotion: {snap['countsByState']}")
    after_demote = run_all(c0)
    if after_demote != want:
        return fail(f"post-demotion results diverged: {after_demote} != {want}")
    hydrations = donor.tier.snapshot()["countsByState"].get("hot", 0)
    if hydrations < 1:
        return fail("queries did not hydrate demoted fragments")
    print(
        f"tier-smoke: demoted {demoted}, queries hydrated back byte-identically",
        file=sys.stderr,
    )
    donor.close()

    # ---- 3. cold boot: empty data dir + store only -------------------
    cold = boot("empty")
    c1 = InternalClient(cold.host)
    snap = json.loads(c1._check(*c1._request("GET", "/debug/tier")))
    if not snap["fragments"] or any(
        v["state"] != "cold" for v in snap["fragments"].values()
    ):
        return fail(f"cold boot must register every fragment cold: {snap}")
    got = run_all(c1)
    if got != want:
        return fail(f"cold-boot results diverged: {got} != {want}")
    snap = json.loads(c1._check(*c1._request("GET", "/debug/tier")))
    transitions = [
        v["history"] for v in snap["fragments"].values() if v["state"] == "hot"
    ]
    if not transitions or any(
        t[-3:] != ["cold", "hydrating", "hot"] for t in transitions
    ):
        return fail(f"/debug/tier must show cold->hydrating->hot: {snap}")
    print(
        f"tier-smoke: cold boot served {len(queries)} queries byte-identically"
        f" ({len(transitions)} fragments hydrated)",
        file=sys.stderr,
    )
    cold.close()

    # ---- 4. retention: age + delete + racing-writer revival ----------
    ret = boot("retention")
    c2 = InternalClient(ret.host)
    c2.create_index("t")
    c2.create_frame("t", "ev", {"timeQuantum": "YMD"})
    old = datetime.utcnow() - timedelta(days=400)
    recent = datetime.utcnow() - timedelta(days=40)
    pb_bits_old = [(1, c, int(old.timestamp() * 1e9)) for c in range(50)]
    pb_bits_recent = [(2, c, int(recent.timestamp() * 1e9)) for c in range(50)]
    c2.import_bits("t", "ev", 0, pb_bits_old + pb_bits_recent)
    ret.tier.retention_age_s = 30 * 86400.0
    ret.tier.retention_delete_s = 365 * 86400.0
    out = ret.tier.sweep()
    if out["aged"] < 1 or out["deleted"] < 1:
        return fail(f"retention sweep must age and delete: {out}")
    frame = ret.holder.frame("t", "ev")
    old_view = f"standard_{old.strftime('%Y%m%d')}"
    recent_view = f"standard_{recent.strftime('%Y%m%d')}"
    if frame.view(old_view) is not None:
        return fail(f"view {old_view} must be deleted past the horizon")
    v = frame.view(recent_view)
    if v is None or v.cold_slices() != {0}:
        return fail(f"view {recent_view} must be aged to the store")
    # racing writer to the aged view revives it — no bit loss
    before = 50
    c2.execute_pql(
        "t",
        f'SetBit(frame="ev", rowID=2, columnID=999, '
        f'timestamp="{recent.strftime("%Y-%m-%dT%H:%M")}")',
    )
    frag = frame.view(recent_view).fragment(0)
    if frag is None or frag.count() != before + 1 or not frag.contains(2, 999):
        return fail("racing writer must revive the aged view without bit loss")
    print(
        f"tier-smoke: retention aged {out['aged']}, deleted {out['deleted']},"
        " racing writer revived the aged view",
        file=sys.stderr,
    )
    ret.close()

    print(
        "OK: demote -> cold-boot -> byte-check -> retention sweep all green"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
