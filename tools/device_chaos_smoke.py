"""CI smoke for device-fault tolerance (``make device-chaos-smoke``).

Runs on the virtual 8-device CPU mesh (same re-exec harness as
multichip-smoke) and asserts, in one process, the ISSUE-15 chaos
contract for EACH injected fault shape — oom, error, and hang:

* under a persistent device fault, a mixed Count/Range/TopN/Sum storm
  keeps answering BYTE-IDENTICALLY to the pre-fault answers (host
  fallback over the authoritative planes);
* the device quarantines within the configured threshold
  (``/debug/health``-shaped snapshot shows a quarantined path and the
  node-level degraded flag);
* a hang inside the mesh-collective launch trips the launch WATCHDOG
  (``device.watchdogTrips`` > 0) instead of wedging the process — the
  storm query that hit it still answers, bounded by the watchdog;
* clearing the fault heals the device through a half-open probe (state
  back to healthy, degraded flag off) and the device path serves
  again.

Deterministic, seconds, no accelerator required — BLOCKING in
check.yml alongside chaos-smoke/resize-smoke.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

if not os.environ.get("_DEVICE_CHAOS_SMOKE_REEXEC"):
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count=8".strip()
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["_DEVICE_CHAOS_SMOKE_REEXEC"] = "1"
    os.execv(sys.executable, [sys.executable] + sys.argv)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_SLICES = 5
OPEN_MS = 250.0
WATCHDOG_MS = 400.0


def log(msg: str) -> None:
    print(f"[device-chaos-smoke] {msg}", file=sys.stderr, flush=True)


def fail(msg: str) -> "int":
    print(f"FAIL: {msg}", file=sys.stderr, flush=True)
    return 1


def build(tmp: str):
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH

    holder = Holder(os.path.join(tmp, "data"))
    holder.open()
    idx = holder.create_index("i")
    f = idx.create_frame("f", cache_size=64)
    for row in range(1, 5):
        for s in range(N_SLICES):
            for k in range(row + 3):
                f.set_bit(
                    "standard", row, s * SLICE_WIDTH + (row * 37 + k * 911) % SLICE_WIDTH
                )
    f.set_options(range_enabled=True)
    f.create_field("v", -100, 100)
    for s in range(N_SLICES):
        for k in range(12):
            col = s * SLICE_WIDTH + k * 131
            f.import_value("v", [col], [((s * 17 + k * 29) % 201) - 100])
    ft = idx.create_frame("t", cache_size=64)
    for row in range(5):
        for s in range(N_SLICES):
            for k in range(6 + row):
                ft.set_bit(
                    "standard", row, s * SLICE_WIDTH + (row * 53 + k * 197) % SLICE_WIDTH
                )
    return holder


QUERIES = [
    "Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))",
    "Count(Union(Bitmap(rowID=1, frame=f), Bitmap(rowID=3, frame=f)))",
    "Count(Difference(Bitmap(rowID=2, frame=f), Bitmap(rowID=4, frame=f)))",
    "Count(Range(frame=f, v > 10))",
    "Count(Range(frame=f, v <= -5))",
    "Count(Range(frame=f, v >< [-50, 50]))",
    "Sum(frame=f, field=v)",
    "Min(frame=f, field=v)",
    "Max(frame=f, field=v)",
    "TopN(Bitmap(rowID=0, frame=t), frame=t, n=3)",
    "TopN(frame=t, n=2)",
]


def canon(result):
    if hasattr(result, "bits"):
        return ("bits", tuple(result.bits()))
    if isinstance(result, list):
        return ("pairs", tuple((p.id, p.count) for p in result))
    if hasattr(result, "value"):
        return ("valcount", int(result.value), int(result.count))
    if result is None:
        return ("none",)
    return ("val", int(result))


def run_storm(ex, parse_string):
    return [canon(ex.execute("i", parse_string(q))[0]) for q in QUERIES]


def main() -> int:
    import jax

    from pilosa_tpu.cluster.topology import new_cluster
    from pilosa_tpu.device.health import (
        COLLECTIVE,
        STATE_HEALTHY,
        STATE_QUARANTINED,
        DeviceHealth,
    )
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.exec.coalesce import CoalesceScheduler
    from pilosa_tpu.pql.parser import parse_string
    from pilosa_tpu.testing import faults

    n_dev = len(jax.devices())
    log(f"backend={jax.default_backend()} devices={n_dev}")
    if n_dev < 2:
        return fail("expected the virtual 8-device mesh")

    tmp = tempfile.mkdtemp(prefix="device-chaos-smoke-")
    holder = build(tmp)
    cluster = new_cluster(1)
    host = cluster.nodes[0].host

    # Baseline answers on a healthy device path.
    base_ex = Executor(holder, host=host, cluster=cluster)
    try:
        want = run_storm(base_ex, parse_string)
    finally:
        base_ex.close()
    log(f"baseline: {len(want)} mixed queries answered on-device")

    rc = 0
    for kind in ("oom", "error", "hang"):
        dh = DeviceHealth(
            quarantine_threshold=2,
            open_ms=OPEN_MS,
            probe_successes=1,
            watchdog_ms=WATCHDOG_MS,
        )
        co = CoalesceScheduler(max_wait_us=50_000, health=dh)
        ex = Executor(
            holder, host=host, cluster=cluster, coalescer=co, device_health=dh
        )
        try:
            if kind == "hang":
                # ONE hang INSIDE the collective dispatch (the
                # watchdogged site): an injected wedge well past the
                # watchdog deadline — the tripped query must still
                # answer (per-slice fallback), bounded by the watchdog
                # rather than the full wedge.
                faults.install(
                    "device.launch:kind=hang,path=collective,times=1,"
                    f"delay-ms={WATCHDOG_MS * 2:.0f}"
                )
            else:
                faults.install(f"device.launch:kind={kind}")

            trips_before = dh.snapshot()["watchdogTrips"]
            t0 = time.monotonic()
            for round_i in range(2):
                got = run_storm(ex, parse_string)
                if got != want:
                    rc |= fail(
                        f"kind={kind} round={round_i}: answers diverged "
                        "under injected fault"
                    )
            storm_s = time.monotonic() - t0
            snap = dh.snapshot()
            if kind == "hang":
                cpath = snap["paths"].get(COLLECTIVE, {})
                if snap["watchdogTrips"] <= trips_before:
                    rc |= fail(f"hang: watchdog never tripped: {snap}")
                elif cpath.get("quarantines", 0) < 1 or (
                    cpath.get("failures", {}).get("hang", 0) < 1
                ):
                    rc |= fail(
                        f"hang: collective path never quarantined: {snap}"
                    )
                else:
                    # The storm outlives the open window, so the path
                    # may ALREADY have healed through its probe by now
                    # — quarantines>=1 proves the trip quarantined it.
                    log(
                        f"kind=hang: watchdog tripped "
                        f"({snap['watchdogTrips'] - trips_before} trip(s)), "
                        "collective quarantined "
                        f"(state now {cpath.get('state')}), storm "
                        f"{storm_s:.2f}s (process never wedged)"
                    )
            else:
                if not snap["degraded"]:
                    rc |= fail(
                        f"kind={kind}: node never degraded: {snap}"
                    )
                quarantined = [
                    p
                    for p, st in snap["paths"].items()
                    if st["state"] == STATE_QUARANTINED
                ]
                if not quarantined:
                    rc |= fail(f"kind={kind}: nothing quarantined: {snap}")
                kinds_seen = {
                    k
                    for st in snap["paths"].values()
                    for k in st.get("failures", {})
                }
                if kind not in kinds_seen:
                    rc |= fail(
                        f"kind={kind}: classifier never saw it: {snap}"
                    )
                log(
                    f"kind={kind}: byte-identical under fault, "
                    f"quarantined={quarantined}"
                )

            # Recovery: clear the rules, wait out the open window (and
            # for a hang, the abandoned sleeper), probe, heal.
            faults.clear()
            time.sleep(
                (OPEN_MS / 1000.0) + (WATCHDOG_MS * 2 / 1000.0 + 0.2 if kind == "hang" else 0.1)
            )
            got = run_storm(ex, parse_string)
            if got != want:
                rc |= fail(f"kind={kind}: answers diverged after heal")
            snap = dh.snapshot()
            bad = {
                p: st["state"]
                for p, st in snap["paths"].items()
                if st["state"] != STATE_HEALTHY
            }
            if bad:
                # One more storm gives every touched path its probe.
                got = run_storm(ex, parse_string)
                snap = dh.snapshot()
                bad = {
                    p: st["state"]
                    for p, st in snap["paths"].items()
                    if st["state"] != STATE_HEALTHY
                }
            if bad or snap["degraded"]:
                rc |= fail(f"kind={kind}: did not heal: {snap}")
            else:
                log(f"kind={kind}: healed through half-open probe")
        finally:
            faults.clear()
            ex.close()
            co.close()
            dh.close()

    holder.close()
    if os.environ.get("PILOSA_LOCK_CHECK"):
        # Runtime lock-order validation (PR 8): the watchdog runner's
        # collective-mutex acquisitions observed during the storms must
        # be consistent with the static lock graph (the analyze.toml
        # watchdog callback edges complete it).
        from pilosa_tpu.analyze import runtime as lock_check

        problems = lock_check.verify()
        print(lock_check.report().splitlines()[0], file=sys.stderr)
        if problems:
            for p in problems:
                print("lock-check DISAGREEMENT:", p, file=sys.stderr)
            return 1
        log("lock-check ok: runtime order consistent with static graph")
    if rc == 0:
        print(
            "OK: oom/error/hang storms byte-identical via host fallback, "
            "quarantine + watchdog + half-open heal all observed"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
