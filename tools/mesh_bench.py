"""``mesh_scaling`` bench tier: the mesh-sharded data plane measured
end to end (ISSUE 12 / ROADMAP open item 2).

Three sections, one JSON line on stdout:

* **curve** — devices-vs-Gcols/s at 1/2/4/8 devices: a real Holder +
  Executor (coalescer + fusion on, the production path) answering a
  concurrent Intersect+Count storm, with the ``[device] mesh-devices``
  cap selecting the mesh width.  Every point byte-checks against the
  host numpy reference AND against the single-device run — the sharded
  data plane must be invisible in results, visible only in placement.
* **headline** — the BASELINE configs[4] shape: an Intersect+Count at
  ``--headline-columns`` (default 10B columns ≈ 9537 slices) over the
  full mesh through the limb total-count program (the same ICI-reduced
  psum the executor's sharded path dispatches), byte-checked against
  the host count.
* **node_grid** — the real production topology: N HTTP nodes × M
  devices per node; every node of the grid runs the mesh-sharded plane
  over its owned slices and the coordinator reduces over HTTP while
  each node reduces its local slices over the (virtual) ICI.

On hosts without a multi-device accelerator the tier runs on the
virtual 8-device CPU mesh (XLA_FLAGS --xla_force_host_platform_device_
count=8, the same harness the tier-1 suite and MULTICHIP artifacts
use); scaling numbers there measure WIRING, not speedup — all eight
virtual devices share the host cores.  Set MESH_BENCH_USE_BACKEND=1 to
run on the ambient JAX backend instead (a real multi-chip host).
"""

from __future__ import annotations

import json
import os
import sys
import time

# Force the virtual 8-device CPU mesh BEFORE jax initializes, then
# re-exec so the flags latch (mirrors tests/conftest.py).
if os.environ.get("MESH_BENCH_USE_BACKEND") != "1" and not os.environ.get(
    "_MESH_BENCH_REEXEC"
):
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count=8".strip()
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["_MESH_BENCH_REEXEC"] = "1"
    os.execv(sys.executable, [sys.executable] + sys.argv)

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(f"[mesh] {msg}", file=sys.stderr, flush=True)


def _build_leaves(rng, n_slices: int, words: int) -> np.ndarray:
    return rng.integers(0, 2**32, size=(n_slices, 2, words), dtype=np.uint32)


def run_curve(leaves: np.ndarray, device_counts, queries: int, threads: int):
    """Executor end-to-end Gcols/s per mesh width."""
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu.exec import coalesce as coalesce_mod
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.ops import bitplane as bp
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH
    from pilosa_tpu.parallel import mesh as pmesh
    from pilosa_tpu.pql.parser import parse_string

    from bench import build_holder

    n_slices = leaves.shape[0]
    want = int(np.bitwise_count(leaves[:, 0] & leaves[:, 1]).sum())
    q = parse_string(
        "Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))"
    )
    curve: dict = {}
    for d in device_counts:
        bp.configure_mesh_devices(d)
        pmesh._slices_mesh = None  # rebuild the cached mesh at width d
        assert bp.mesh_device_count() == d, (bp.mesh_device_count(), d)
        with tempfile.TemporaryDirectory() as td:
            holder = build_holder(leaves, td)
            co = coalesce_mod.CoalesceScheduler()
            ex = Executor(holder, coalescer=co)
            try:
                got = int(ex.execute("i", q)[0])  # warm + byte-check
                assert got == want, f"devices={d}: {got} != {want}"
                t0 = time.perf_counter()
                with ThreadPoolExecutor(max_workers=threads) as pool:
                    res = list(
                        pool.map(
                            lambda _i: int(ex.execute("i", q)[0]),
                            range(queries),
                        )
                    )
                wall = time.perf_counter() - t0
                assert all(r == want for r in res)
                per_q = wall / queries
                sharded = pmesh.default_slices_mesh() is not None
                assert sharded == (d > 1)
                curve[str(d)] = {
                    "ms_per_query": round(per_q * 1e3, 3),
                    "gcols_per_s": round(
                        n_slices * SLICE_WIDTH / per_q / 1e9, 3
                    ),
                    "sharded": sharded,
                    "byte_identical": True,
                    "count": want,
                }
                log(
                    f"curve {d} device(s): {per_q*1e3:.2f} ms/query, "
                    f"{curve[str(d)]['gcols_per_s']} Gcols/s, "
                    f"sharded={sharded}"
                )
            finally:
                ex.close()
                co.close()
                holder.close()
    bp.configure_mesh_devices(0)
    pmesh._slices_mesh = None
    return curve


def run_headline(columns: int, rng) -> dict:
    """Intersect+Count at ``columns`` over the full mesh: the sharded
    limb total-count (psum over the slices axis), byte-checked."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pilosa_tpu.exec import plan
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH, WORDS_PER_SLICE
    from pilosa_tpu.parallel import mesh as pmesh
    from pilosa_tpu.pql.parser import parse_string

    n_slices = (columns + SLICE_WIDTH - 1) // SLICE_WIDTH
    n_dev = len(jax.local_devices())
    pad = (-n_slices) % n_dev
    log(
        f"headline: {columns} columns = {n_slices} slices (+{pad} pad) "
        f"over {n_dev} devices"
    )
    mesh = pmesh.slice_mesh(n_dev)
    leaves = _build_leaves(rng, n_slices, WORDS_PER_SLICE)
    t0 = time.perf_counter()
    want = int(np.bitwise_count(leaves[:, 0] & leaves[:, 1]).sum())
    host_s = time.perf_counter() - t0
    log(f"host AND+popcount: {host_s:.2f}s -> {want}")
    if pad:
        leaves = np.concatenate(
            [leaves, np.zeros((pad,) + leaves.shape[1:], leaves.dtype)]
        )
    batch = jax.device_put(
        leaves, NamedSharding(mesh, P(pmesh.AXIS_SLICES, None, None))
    )
    jax.block_until_ready(batch)
    q = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))")
    expr, _ = plan.decompose(q.calls[0].children[0])
    fn = plan.compiled_total_count(expr, mesh)
    # Warm (compile) + byte-check, then best-of-N timed passes; the
    # limb fetch forces completion (8 bytes home per pass).
    got = plan.recombine_count_limbs(jax.device_get(fn(batch)))
    assert got == want, f"headline byte-check: {got} != {want}"
    best = float("inf")
    passes = int(os.environ.get("MESH_BENCH_HEADLINE_PASSES", "3"))
    for _ in range(passes):
        t0 = time.perf_counter()
        limbs = jax.device_get(fn(batch))
        best = min(best, time.perf_counter() - t0)
    assert plan.recombine_count_limbs(limbs) == want
    gcols = n_slices * SLICE_WIDTH / best / 1e9
    log(f"headline: {best*1e3:.2f} ms/pass, {gcols:.1f} Gcols/s")
    return {
        "columns": n_slices * SLICE_WIDTH,
        "slices": n_slices,
        "devices": n_dev,
        "ms_per_pass": round(best * 1e3, 3),
        "gcols_per_s": round(gcols, 3),
        "host_reference_s": round(host_s, 3),
        "count": want,
        "byte_identical": True,
    }


def _free_tcp_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _boot_grid_node(tmp: str, name: str, host: str, ring, m_devices: int):
    """One real node in its OWN process (its own JAX runtime and
    virtual mesh — the production topology, and the only sound one:
    in-process nodes would share one device set, which collectives
    cannot)."""
    import subprocess

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        PILOSA_DATA_DIR=f"{tmp}/{name}",
        PILOSA_HOST=host,
        PILOSA_CLUSTER_HOSTS=",".join(ring),
        PILOSA_CLUSTER_POLLING_INTERVAL="1",
        PILOSA_ANTI_ENTROPY_INTERVAL="3600",
        PILOSA_DEVICE_MESH_DEVICES=str(m_devices),
        PILOSA_TPU_PREWARM="false",
        PILOSA_TPU_COMPILATION_CACHE_DIR=f"{tmp}/compile-cache",
    )
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count=8".strip()
    )
    env.pop("_MESH_BENCH_REEXEC", None)
    return subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.cli", "server"],
        env=env,
        cwd=REPO,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )


def _wait_ready(host: str, timeout: float = 120.0) -> None:
    from pilosa_tpu.net.client import InternalClient

    client = InternalClient(host, timeout=2.0)
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            status, data = client._request("GET", "/version")
            client._check(status, data)
            return
        except Exception:  # noqa: BLE001 — still booting
            time.sleep(0.2)
    raise SystemExit(f"FAIL: grid node {host} never became ready")


def run_node_grid(node_counts, device_counts, n_slices: int, bits: int) -> dict:
    """N HTTP nodes × M devices per node — the production topology.
    One PROCESS per node (own JAX runtime, own virtual 8-device mesh;
    [device] mesh-devices selects each node's width), a seeded sparse
    corpus imported over HTTP, and a concurrent Intersect+Count storm
    through the coordinator, byte-checked against the host reference."""
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu.net.client import ClientError, InternalClient
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH

    rng = np.random.default_rng(17)
    # Two overlapping sparse rows per slice; the Intersect count is
    # host-derivable exactly.
    cols1 = [
        rng.choice(SLICE_WIDTH, size=bits, replace=False)
        for _ in range(n_slices)
    ]
    cols2 = [
        rng.choice(SLICE_WIDTH, size=bits, replace=False)
        for _ in range(n_slices)
    ]
    want = sum(
        len(np.intersect1d(c1, c2)) for c1, c2 in zip(cols1, cols2)
    )
    q = 'Count(Intersect(Bitmap(rowID=1, frame="f"), Bitmap(rowID=2, frame="f")))'
    grid: dict = {}
    for m in device_counts:
        for n_nodes in node_counts:
            tmp = tempfile.mkdtemp(prefix=f"mesh-grid-{n_nodes}x{m}-")
            hosts = sorted(f"127.0.0.1:{_free_tcp_port()}" for _ in range(n_nodes))
            procs = []
            try:
                for i, h in enumerate(hosts):
                    procs.append(_boot_grid_node(tmp, f"n{i}", h, hosts, m))
                for h in hosts:
                    _wait_ready(h)
                c0 = InternalClient(hosts[0], timeout=60.0)
                for h in hosts:
                    ch = InternalClient(h, timeout=10.0)
                    for call in ("create_index", "create_frame"):
                        try:
                            getattr(ch, call)(*("i",) if call == "create_index" else ("i", "f"))
                        except ClientError:
                            pass
                for sl in range(n_slices):
                    for row, cols in ((1, cols1[sl]), (2, cols2[sl])):
                        c0.import_bits(
                            "i", "f", sl,
                            (np.full(len(cols), row, np.int64),
                             cols.astype(np.int64) + sl * SLICE_WIDTH),
                        )
                # 1 s polling propagates the slice range; wait for the
                # corpus to converge on the coordinator.
                deadline = time.time() + 60
                while time.time() < deadline:
                    try:
                        if int(c0.execute_query("i", q)[0]) == want:
                            break
                    except (ClientError, ConnectionError):
                        pass
                    time.sleep(0.3)
                got = int(c0.execute_query("i", q)[0])
                assert got == want, f"grid {n_nodes}x{m}: {got} != {want}"
                n_conc, threads = 24, 8
                clients = [
                    InternalClient(hosts[0], timeout=60.0)
                    for _ in range(threads)
                ]
                t0 = time.perf_counter()
                with ThreadPoolExecutor(max_workers=threads) as pool:
                    res = list(
                        pool.map(
                            lambda i: int(
                                clients[i % threads].execute_query("i", q)[0]
                            ),
                            range(n_conc),
                        )
                    )
                per_q = (time.perf_counter() - t0) / n_conc
                assert all(r == want for r in res)
                grid[f"{n_nodes}x{m}"] = {
                    "nodes": n_nodes,
                    "devices_per_node": m,
                    "concurrent_ms_per_query": round(per_q * 1e3, 3),
                    "gcols_per_s": round(
                        n_slices * SLICE_WIDTH / per_q / 1e9, 3
                    ),
                    "byte_identical": True,
                }
                log(
                    f"grid {n_nodes} node(s) x {m} device(s): "
                    f"{per_q*1e3:.2f} ms/query"
                )
            finally:
                for p in procs:
                    p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=20)
                    except Exception:  # noqa: BLE001
                        p.kill()
    return grid


def main() -> int:
    import argparse

    import jax

    p = argparse.ArgumentParser()
    p.add_argument(
        "--slices", type=int,
        default=int(os.environ.get("BENCH_MESH_SLICES", "64")),
    )
    p.add_argument(
        "--headline-columns", type=int,
        default=int(os.environ.get("BENCH_MESH_COLUMNS", str(10**10))),
    )
    p.add_argument("--queries", type=int, default=48)
    p.add_argument("--threads", type=int, default=8)
    args = p.parse_args()

    n_local = len(jax.local_devices())
    device_counts = [d for d in (1, 2, 4, 8) if d <= n_local]
    rng = np.random.default_rng(13)
    log(
        f"backend={jax.default_backend()} devices={n_local} "
        f"curve slices={args.slices} headline columns={args.headline_columns}"
    )

    from pilosa_tpu.ops.bitplane import WORDS_PER_SLICE

    leaves = _build_leaves(rng, args.slices, WORDS_PER_SLICE)
    curve = run_curve(leaves, device_counts, args.queries, args.threads)
    node_grid = run_node_grid(
        node_counts=(1, 2),
        device_counts=list(dict.fromkeys([1, device_counts[-1]])),
        n_slices=min(args.slices, 8),
        bits=int(os.environ.get("BENCH_MESH_GRID_BITS", "512")),
    )
    headline = run_headline(args.headline_columns, rng)

    out = {
        "backend": jax.default_backend(),
        "n_devices_visible": n_local,
        "virtual_mesh": os.environ.get("_MESH_BENCH_REEXEC") == "1",
        "curve": curve,
        "node_grid": node_grid,
        "headline": headline,
    }
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
