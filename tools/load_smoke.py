"""CI smoke pass over tools/load_harness.py: a tiny CPU-only open-loop
run asserting the artifact carries the goodput curve and shed counters,
and that NOTHING is shed at trivial load (an admission layer that sheds
an idle node is misconfigured, full stop).

Not a performance measurement — a wiring check that the admission
layer, the harness, and the artifact contract all hold together, so a
refactor cannot silently break the storm tier the bench trajectory
records.  Writes ``load-report.json`` at the repo root (uploaded as a
CI artifact alongside analyze-report.json).  Run via ``make
load-smoke``; wired non-blocking into check.yml.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORT = os.path.join(REPO, "load-report.json")


def main() -> int:
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
    )
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "load_harness.py"),
            "--self-boot",
            "--compare",
            "--slices", "2",
            # Two fixed points: trivial load (must shed nothing) and a
            # small storm (tiny gates below make it a real overload).
            "--qps", "15,300",
            "--duration", "2",
            "--deadline-ms", "400",
            "--slo-ms", "300",
            "--point-concurrency", "2",
            "--heavy-concurrency", "1",
            "--write-concurrency", "1",
            "--queue-depth", "4",
            "--artifact", REPORT,
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    sys.stderr.write(proc.stderr[-4000:])
    if proc.returncode != 0:
        print(f"FAIL: load_harness exited {proc.returncode}", file=sys.stderr)
        return 1
    try:
        with open(REPORT) as f:
            out = json.loads(f.read())
    except (OSError, json.JSONDecodeError) as e:
        print(f"FAIL: artifact unreadable ({e})", file=sys.stderr)
        return 1

    for side in ("admission_on", "admission_off"):
        sweep = out.get(side)
        if not isinstance(sweep, dict) or not sweep.get("points"):
            print(f"FAIL: artifact missing {side} sweep: {out}", file=sys.stderr)
            return 1
        for pt in sweep["points"]:
            for key in (
                "offered_qps", "goodput_qps", "shed", "shed_rate",
                "deadline_504", "p99_ms", "ok_within_deadline",
            ):
                if key not in pt:
                    print(f"FAIL: point missing {key!r}: {pt}", file=sys.stderr)
                    return 1
    if "max_sustained_qps_at_p99_slo" not in out:
        print("FAIL: artifact missing max_sustained_qps_at_p99_slo",
              file=sys.stderr)
        return 1
    on = out["admission_on"]
    trivial = on["points"][0]
    if trivial["shed"] != 0:
        print(f"FAIL: shed at trivial load: {trivial}", file=sys.stderr)
        return 1
    if trivial["ok_within_deadline"] < trivial["sent"] * 0.9:
        print(f"FAIL: trivial load not served: {trivial}", file=sys.stderr)
        return 1
    snap = on.get("admission_snapshot")
    if not isinstance(snap, dict) or "point" not in snap:
        print(f"FAIL: artifact missing admission snapshot: {on.keys()}",
              file=sys.stderr)
        return 1
    storm = on["points"][-1]
    print(
        "load-smoke ok: trivial load shed-free "
        f"({trivial['ok_within_deadline']}/{trivial['sent']} within "
        f"deadline); storm point goodput {storm['goodput_qps']} qps, "
        f"shed {storm['shed']}; report at {REPORT}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
