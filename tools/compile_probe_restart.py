"""Restart-cold compile probe, run as a FRESH process by bench.py.

Times the compile of the headline fused Intersect+Count program (the
single-device total-count limb reduce over a bucket-1024 batch — the
exact jit key the e2e executor uses for the 954-slice north-star query)
with the persistent compilation cache pointed at argv[1].  bench.py runs
this twice back-to-back: the first populates the on-disk cache (true
cold), the second measures a process restart deserializing the
executable (VERDICT r04 weak #3: "cold query costs 5 s").  Uses
``.lower().compile()`` so the number is compile time only — no device
data transfer pollutes it.

Usage: python tools/compile_probe_restart.py <cache_dir> [bucket]
Prints one float (seconds) on stdout.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.exec import plan, warmup
    from pilosa_tpu.ops import bitplane as bp

    cache_dir = sys.argv[1]
    bucket = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    if not warmup.enable_compile_cache(cache_dir):
        print(f"cannot enable compile cache at {cache_dir}", file=sys.stderr)
        sys.exit(1)
    expr = ("Intersect", ("leaf", 0), ("leaf", 1))
    spec = jax.ShapeDtypeStruct((bucket, 2, bp.WORDS_PER_SLICE), jnp.uint32)
    t0 = time.perf_counter()
    plan.compiled_total_count(expr).lower(spec).compile()
    print(f"{time.perf_counter() - t0:.3f}")


if __name__ == "__main__":
    main()
