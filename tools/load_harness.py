"""Open-loop sustained-load harness: storm a node, sweep offered load,
record goodput-vs-offered-load and max-sustained-QPS-at-p99-SLO.

Closed-loop benchmarks (a fixed worker pool waiting for each answer)
self-throttle under overload and hide the collapse this harness exists
to measure.  Here request START times are fixed by the offered rate
alone — completions never gate arrivals (no coordinated omission), so
when the node saturates, the backlog grows exactly like real traffic
and the curve shows what admission control does about it:

* with shedding, excess load answers 429 in microseconds and goodput
  (answers within their deadline) plateaus at node capacity;
* without it, every request is admitted, queues past its deadline, and
  goodput collapses into 504s-after-burned-work.

Traffic is a weighted mix of cost classes (point Count, heavy
TopN/Range, import writes) against a seeded corpus.  Each request
carries ``X-Deadline-Ms``; a response only counts toward goodput when
it arrives 200 within that budget.

Modes:
  --self-boot        boot an in-process server (CPU or current backend),
                     seed it, sweep, tear down.  --compare runs the
                     sweep twice — admission ON then OFF — into one
                     artifact (the bench's storm tier).
  --host HOST:PORT   storm an external node (expects index/frame/field
                     already seeded unless --seed).

Prints ONE JSON artifact line on stdout (or --artifact PATH); all
progress goes to stderr.  Used by ``make load-smoke``
(tools/load_smoke.py) and bench.py's ``admission_storm`` tier.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# workload mix
# ---------------------------------------------------------------------------


class Workload:
    """Weighted request templates over the seeded corpus.  Deterministic
    per-index choice (no shared RNG lock on the hot path)."""

    def __init__(self, index: str, mix: dict[str, float], slices: int):
        self.index = index
        self.slices = max(1, slices)
        kinds = [(k, w) for k, w in mix.items() if w > 0]
        total = sum(w for _, w in kinds)
        # Weighted 1000-step wheel, deterministically shuffled so a
        # short run still interleaves every kind (requests index the
        # wheel sequentially).
        import random

        self.wheel: list[str] = []
        for kind, w in kinds:
            self.wheel.extend([kind] * max(1, int(round(w / total * 1000))))
        random.Random(0).shuffle(self.wheel)

    def request(self, i: int) -> tuple[str, str, str, bytes]:
        """(kind, method, path, body) for the i-th request."""
        kind = self.wheel[i % len(self.wheel)]
        idx = self.index
        if kind == "count":
            row = i % 2
            return (
                kind,
                "POST",
                f"/index/{idx}/query",
                f'Count(Bitmap(frame="f", rowID={row}))'.encode(),
            )
        if kind == "topn":
            return kind, "POST", f"/index/{idx}/query", b'TopN(frame="f", n=5)'
        if kind == "range":
            return (
                kind,
                "POST",
                f"/index/{idx}/query",
                f'Count(Range(frame="f", v > {i % 7}))'.encode(),
            )
        if kind == "import":
            col = (i * 97) % (self.slices * (1 << 20))
            body = json.dumps(
                {
                    "index": idx,
                    "frame": "f",
                    "field": "v",
                    "slice": col >> 20,
                    "columnIDs": [col],
                    "values": [i % 100],
                }
            ).encode()
            return kind, "POST", "/import-value", body
        raise ValueError(f"unknown kind {kind!r}")


class TenantSpec:
    """One ``name:weight[:qps[:bytes_per_s]]`` entry from ``--tenants``.
    Weight picks the share of storm traffic this tenant generates; the
    optional quotas are forwarded to the self-booted server config so
    the harness can demonstrate 429-on-quota without a config file."""

    __slots__ = ("name", "weight", "qps", "bytes_per_s")

    def __init__(self, name: str, weight: float, qps: float = 0.0,
                 bytes_per_s: float = 0.0):
        self.name = name
        self.weight = weight
        self.qps = qps
        self.bytes_per_s = bytes_per_s

    @classmethod
    def parse(cls, spec: str) -> "TenantSpec":
        parts = spec.split(":")
        if not parts[0] or len(parts) > 4:
            raise ValueError(f"bad tenant spec {spec!r} "
                             "(want name:weight[:qps[:bytes_per_s]])")
        weight = float(parts[1]) if len(parts) > 1 and parts[1] else 1.0
        qps = float(parts[2]) if len(parts) > 2 and parts[2] else 0.0
        bps = float(parts[3]) if len(parts) > 3 and parts[3] else 0.0
        if weight <= 0:
            raise ValueError(f"tenant {parts[0]!r}: weight must be > 0")
        return cls(parts[0], weight, qps, bps)

    def server_spec(self) -> str:
        """The ``[net] tenants`` entry equivalent of this spec."""
        s = f"{self.name}:{max(1, int(round(self.weight)))}"
        if self.qps or self.bytes_per_s:
            s += f":{self.qps:g}"
        if self.bytes_per_s:
            s += f":{self.bytes_per_s:g}"
        return s


def parse_tenants(spec: str) -> list[TenantSpec]:
    return [TenantSpec.parse(p) for p in spec.split(",") if p.strip()]


def tenant_wheel(tenants: list[TenantSpec], steps: int = 1000) -> list[str]:
    """Deterministic weighted wheel of tenant names (same trick as the
    workload mix wheel: request i is charged to wheel[i % len])."""
    import random

    total = sum(t.weight for t in tenants)
    wheel: list[str] = []
    for t in tenants:
        wheel.extend([t.name] * max(1, int(round(t.weight / total * steps))))
    random.Random(1).shuffle(wheel)
    return wheel


_conn_local = threading.local()


def _do_request(
    host: str, method: str, path: str, body: bytes, deadline_ms: float,
    tenant: str = "",
) -> tuple[int, bytes]:
    """One HTTP request on this thread's keep-alive connection
    (reconnect once on a dead socket)."""
    timeout = deadline_ms / 1000.0 * 3 + 1.0
    headers = {"X-Deadline-Ms": str(int(deadline_ms))}
    if tenant:
        headers["X-Tenant"] = tenant
    for attempt in (0, 1):
        conn = getattr(_conn_local, "conn", None)
        if conn is None or getattr(_conn_local, "host", None) != host:
            conn = http.client.HTTPConnection(host, timeout=timeout)
            _conn_local.conn, _conn_local.host = conn, host
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            return resp.status, resp.read()
        except (OSError, http.client.HTTPException):
            conn.close()
            _conn_local.conn = None
            if attempt:
                raise
    raise RuntimeError("unreachable")


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def probe_capacity(
    host: str, workload: Workload, seconds: float, threads: int,
    deadline_ms: float,
) -> float:
    """Closed-loop capacity estimate: ``threads`` workers hammering
    point queries; capacity = completed / wall time."""
    stop = time.monotonic() + seconds
    done = [0] * threads

    def worker(w: int) -> None:
        i = 0
        while time.monotonic() < stop:
            try:
                status, _ = _do_request(
                    host, *workload.request(i)[1:], deadline_ms=deadline_ms
                )
                if status == 200:
                    done[w] += 1
            except Exception:  # noqa: BLE001 — probe is best-effort
                pass
            i += 1

    ts = [threading.Thread(target=worker, args=(w,)) for w in range(threads)]
    t0 = time.monotonic()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.monotonic() - t0
    return sum(done) / max(wall, 1e-9)


def run_point(
    host: str,
    workload: Workload,
    offered_qps: float,
    duration_s: float,
    deadline_ms: float,
    tenants: list[TenantSpec] | None = None,
) -> dict:
    """One open-loop point: fire ``offered_qps * duration_s`` requests
    at fixed schedule times; latency is measured from the SCHEDULED
    start (coordinated-omission-free).  With ``tenants``, each request
    carries ``X-Tenant`` sampled from the weighted tenant wheel and
    stats are additionally broken out per tenant."""
    n = max(1, int(offered_qps * duration_s))
    pool = ThreadPoolExecutor(
        max_workers=min(512, max(16, int(offered_qps * deadline_ms / 1000.0 * 2)))
    )
    lock = threading.Lock()
    stats = {
        "ok_within_deadline": 0,
        "ok_late": 0,
        "shed": 0,
        "deadline_504": 0,
        "errors": 0,
    }
    ok_latencies: list[float] = []
    wheel = tenant_wheel(tenants) if tenants else []
    by_tenant: dict[str, dict] = {
        t.name: {"sent": 0, "ok": 0, "shed": 0, "errors": 0, "lat": []}
        for t in (tenants or [])
    }

    def fire(i: int, t_sched: float) -> None:
        kind, method, path, body = workload.request(i)
        tenant = wheel[i % len(wheel)] if wheel else ""
        try:
            status, _ = _do_request(
                host, method, path, body, deadline_ms, tenant=tenant
            )
        except Exception:  # noqa: BLE001 — client-side failure
            with lock:
                stats["errors"] += 1
                if tenant:
                    by_tenant[tenant]["errors"] += 1
            return
        lat_ms = (time.monotonic() - t_sched) * 1000.0
        with lock:
            ts = by_tenant.get(tenant)
            if ts is not None:
                ts["sent"] += 1
            if status == 200:
                if lat_ms <= deadline_ms:
                    stats["ok_within_deadline"] += 1
                    ok_latencies.append(lat_ms)
                else:
                    stats["ok_late"] += 1
                if ts is not None:
                    ts["ok"] += 1
                    ts["lat"].append(lat_ms)
            elif status == 429:
                stats["shed"] += 1
                if ts is not None:
                    ts["shed"] += 1
            elif status == 504:
                stats["deadline_504"] += 1
            else:
                stats["errors"] += 1
                if ts is not None:
                    ts["errors"] += 1

    t0 = time.monotonic()
    for i in range(n):
        target = t0 + i / offered_qps
        now = time.monotonic()
        if target > now:
            time.sleep(target - now)
        # Open loop: submit at the scheduled instant regardless of how
        # many earlier requests are still in flight.
        pool.submit(fire, i, target)
    pool.shutdown(wait=True)
    wall = time.monotonic() - t0

    ok_latencies.sort()

    def pct(p: float) -> float | None:
        if not ok_latencies:
            return None
        return round(ok_latencies[min(len(ok_latencies) - 1,
                                      int(p * len(ok_latencies)))], 2)

    sent = n
    out = {
        "offered_qps": round(offered_qps, 1),
        "duration_s": round(wall, 2),
        "sent": sent,
        **stats,
        "goodput_qps": round(stats["ok_within_deadline"] / max(wall, 1e-9), 1),
        "shed_rate": round(stats["shed"] / sent, 4),
        "p50_ms": pct(0.50),
        "p99_ms": pct(0.99),
    }
    if by_tenant:
        tenants_out = {}
        for name, ts in by_tenant.items():
            lat = sorted(ts.pop("lat"))
            ts["p99_ms"] = (
                round(lat[min(len(lat) - 1, int(0.99 * len(lat)))], 2)
                if lat else None
            )
            ts["shed_rate"] = round(ts["shed"] / max(ts["sent"], 1), 4)
            tenants_out[name] = ts
        out["tenants"] = tenants_out
    return out


def run_sweep(
    host: str,
    workload: Workload,
    qps_points: list[float],
    duration_s: float,
    deadline_ms: float,
    slo_ms: float,
    tenants: list[TenantSpec] | None = None,
) -> dict:
    points = []
    for qps in qps_points:
        pt = run_point(host, workload, qps, duration_s, deadline_ms,
                       tenants=tenants)
        log(
            f"  offered {pt['offered_qps']:>8} qps -> goodput "
            f"{pt['goodput_qps']:>8} qps, p99 {pt['p99_ms']} ms, "
            f"shed {pt['shed']}, 504 {pt['deadline_504']}, "
            f"errors {pt['errors']}"
        )
        points.append(pt)
    sustained = [
        p["goodput_qps"]
        for p in points
        if p["p99_ms"] is not None and p["p99_ms"] <= slo_ms
    ]
    return {
        "deadline_ms": deadline_ms,
        "slo_ms": slo_ms,
        "points": points,
        "max_sustained_qps_at_p99_slo": max(sustained) if sustained else 0.0,
    }


# ---------------------------------------------------------------------------
# self-boot
# ---------------------------------------------------------------------------


def boot_server(data_dir: str, args, admission_on: bool,
                tenants: list[TenantSpec] | None = None):
    from pilosa_tpu.net.server import Server
    from pilosa_tpu.obs.stats import ExpvarStatsClient

    s = Server(
        data_dir=data_dir,
        host="127.0.0.1:0",
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        stats=ExpvarStatsClient(),
        prewarm=False,
        admission=admission_on,
        admission_point_concurrency=args.point_concurrency,
        admission_heavy_concurrency=args.heavy_concurrency,
        admission_write_concurrency=args.write_concurrency,
        admission_queue_depth=args.queue_depth,
        # Configure the storm tenants server-side so bare X-Tenant tags
        # resolve (unconfigured tags fall back to the default tenant).
        tenants=[t.server_spec() for t in (tenants or [])],
    )
    s.open()
    return s


def seed_corpus(server, slices: int, seed_values: bool) -> None:
    """Small standard corpus: 2 rows over ``slices`` slices plus (for
    the range mix) a BSI field with a few values per slice."""
    import numpy as np

    holder = server.holder
    holder.create_index_if_not_exists("i")
    idx = holder.index("i")
    idx.create_frame_if_not_exists("f", range_enabled=seed_values)
    f = holder.frame("i", "f")
    cols_per = 256
    for sl in range(slices):
        base = sl << 20
        cols = np.arange(cols_per, dtype=np.int64) * 64 + base
        rows = np.zeros(cols_per, dtype=np.int64)
        f.import_bulk(
            np.concatenate([rows, rows + 1]), np.concatenate([cols, cols])
        )
    if seed_values:
        f.create_field("v", 0, 1000)
        for sl in range(slices):
            base = sl << 20
            cols = np.arange(cols_per, dtype=np.int64) * 64 + base
            vals = (cols % 97).astype(np.int64)
            f.import_value("v", cols, vals)
    idx.set_remote_max_slice(slices - 1)


def self_boot_sweep(args, admission_on: bool) -> dict:
    import shutil

    td = tempfile.mkdtemp(prefix="load-harness-")
    tenants = parse_tenants(args.tenants) if args.tenants else None
    server = boot_server(os.path.join(td, "data"), args, admission_on,
                         tenants=tenants)
    try:
        mix = parse_mix(args.mix)
        seed_corpus(server, args.slices, seed_values="range" in mix or "import" in mix)
        workload = Workload("i", mix, args.slices)
        # Warm the query path (compiles, mirrors) before measuring.
        for i in range(8):
            _do_request(
                server.host, *workload.request(i)[1:], deadline_ms=30_000
            )
        if args.qps:
            qps_points = [float(q) for q in args.qps.split(",")]
            capacity = None
        else:
            capacity = probe_capacity(
                server.host, workload, args.probe_s, threads=16,
                deadline_ms=30_000,
            )
            log(f"capacity probe ({'on' if admission_on else 'off'}): "
                f"{capacity:.0f} qps closed-loop")
            qps_points = [
                max(1.0, capacity * m)
                for m in (0.5, 1.0, 1.5, 2.0, 3.0)
            ]
        out = run_sweep(
            server.host, workload, qps_points, args.duration,
            args.deadline_ms, args.slo_ms, tenants=tenants,
        )
        out["admission"] = admission_on
        if capacity is not None:
            out["capacity_qps_closed_loop"] = round(capacity, 1)
        if admission_on and server.admission is not None:
            out["admission_snapshot"] = server.admission.snapshot()
        if tenants is not None:
            out["tenants_snapshot"] = server.tenants.snapshot()
        return out
    finally:
        server.close()
        shutil.rmtree(td, ignore_errors=True)


def parse_mix(spec: str) -> dict[str, float]:
    out: dict[str, float] = {}
    for part in spec.split(","):
        k, _, w = part.partition("=")
        out[k.strip()] = float(w or 1.0)
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--host", default="", help="external node to storm")
    ap.add_argument("--self-boot", action="store_true")
    ap.add_argument(
        "--compare", action="store_true",
        help="self-boot twice: admission on, then off (baseline)",
    )
    ap.add_argument("--index", default="i")
    ap.add_argument("--slices", type=int, default=4)
    ap.add_argument(
        "--mix", default="count=0.55,topn=0.2,range=0.15,import=0.1",
        help="kind=weight[,kind=weight...] over count/topn/range/import",
    )
    ap.add_argument(
        "--qps", default="",
        help="comma-separated offered-load points; empty = probe "
        "capacity and sweep 0.5/1/1.5/2/3x",
    )
    ap.add_argument("--duration", type=float, default=5.0,
                    help="seconds per offered-load point")
    ap.add_argument("--probe-s", type=float, default=3.0)
    ap.add_argument("--deadline-ms", type=float, default=500.0)
    ap.add_argument("--slo-ms", type=float, default=250.0,
                    help="p99 SLO for the max-sustained-QPS figure")
    ap.add_argument(
        "--tenants", default="",
        help="name:weight[:qps[:bytes_per_s]][,name:weight...] — tag "
        "each request with X-Tenant sampled by weight; self-boot also "
        "configures the tenants (weights + quotas) server-side",
    )
    ap.add_argument("--seed", action="store_true",
                    help="with --host: seed the corpus first")
    ap.add_argument("--point-concurrency", type=int, default=32)
    ap.add_argument("--heavy-concurrency", type=int, default=8)
    ap.add_argument("--write-concurrency", type=int, default=16)
    ap.add_argument("--queue-depth", type=int, default=64)
    ap.add_argument("--artifact", default="-",
                    help="artifact path ('-' = stdout)")
    args = ap.parse_args()

    artifact: dict = {"tool": "load_harness", "mix": args.mix}
    if args.tenants:
        artifact["tenant_specs"] = args.tenants
    if args.self_boot or args.compare:
        log("=== sweep with admission control ===")
        artifact["admission_on"] = self_boot_sweep(args, admission_on=True)
        if args.compare:
            log("=== baseline sweep, admission OFF ===")
            artifact["admission_off"] = self_boot_sweep(
                args, admission_on=False
            )
        artifact["max_sustained_qps_at_p99_slo"] = artifact["admission_on"][
            "max_sustained_qps_at_p99_slo"
        ]
    elif args.host:
        from pilosa_tpu.net.client import InternalClient  # noqa: F401 — import check

        mix = parse_mix(args.mix)
        workload = Workload(args.index, mix, args.slices)
        qps_points = [float(q) for q in args.qps.split(",") if q] or None
        if qps_points is None:
            cap = probe_capacity(args.host, workload, args.probe_s, 16,
                                 deadline_ms=30_000)
            log(f"capacity probe: {cap:.0f} qps")
            qps_points = [max(1.0, cap * m) for m in (0.5, 1.0, 1.5, 2.0, 3.0)]
        artifact["sweep"] = run_sweep(
            args.host, workload, qps_points, args.duration,
            args.deadline_ms, args.slo_ms,
            tenants=parse_tenants(args.tenants) if args.tenants else None,
        )
        artifact["max_sustained_qps_at_p99_slo"] = artifact["sweep"][
            "max_sustained_qps_at_p99_slo"
        ]
    else:
        ap.error("need --self-boot or --host")

    line = json.dumps(artifact)
    if args.artifact == "-":
        print(line)
    else:
        with open(args.artifact, "w") as f:
            f.write(line + "\n")
        log(f"artifact written to {args.artifact}")
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
