"""CI smoke for quorum replication (``make replication-smoke``): a
3-node, replica-3 cluster takes a sustained quorum write storm while
one replica is KILLED mid-storm, then restarted — the pass asserts

* every storm write succeeded at consistency=quorum (2 of 3 acks)
  while the replica was down, with hints queued for it;
* after restart the hint replay (breaker-triggered, no operator action)
  drains to ZERO backlog and the restarted replica's fragments
  checksum-agree with the survivors WITHOUT an anti-entropy tick
  (the loop is disabled at a 3600 s interval);
* zero lost writes: every confirmed column is present in the restarted
  replica's LOCAL fragments;
* a sub-quorum write (consistency=all against the dead replica) fails
  loudly.

Deterministic CPU pass, in-process servers; BLOCKING in CI
(.github/workflows/check.yml) like resize-smoke.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_SLICES = 4
STORM_WRITES = 120


def main() -> int:
    from pilosa_tpu.cluster.topology import Cluster
    from pilosa_tpu.net.client import ClientError, InternalClient
    from pilosa_tpu.net.server import Server
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH

    tmp = tempfile.mkdtemp(prefix="replication-smoke-")

    def boot(name, host="127.0.0.1:0", ring=()):
        cluster = Cluster(replica_n=3)
        for h in ring:
            cluster.add_node(h)
        s = Server(
            data_dir=os.path.join(tmp, name),
            host=host,
            cluster=cluster,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
            breaker_open_ms=300.0,
        )
        s.replication.replay_interval_s = 0.2
        s.open()
        return s

    servers = [boot(f"n{i}") for i in range(3)]
    hosts = sorted(s.host for s in servers)
    for s in servers:
        for h in hosts:
            if s.cluster.node_by_host(h) is None:
                s.cluster.add_node(h)
        s.cluster.nodes.sort(key=lambda n: n.host)
    for s in servers:
        s.holder.create_index_if_not_exists("i")
        s.holder.index("i").create_frame_if_not_exists("f")

    s0 = servers[0]
    c0 = InternalClient(s0.host, timeout=10.0)
    for sl in range(N_SLICES):
        c0.execute_query(
            "i", f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH + sl})'
        )
    for s in servers:
        s._tick_max_slices()

    victim = servers[2]
    victim_host = victim.host
    stop = threading.Event()
    written: list[int] = []
    errors: list[str] = []

    def writer():
        cw = InternalClient(s0.host, timeout=10.0)
        for k in range(STORM_WRITES):
            if stop.is_set():
                return
            col = (k % N_SLICES) * SLICE_WIDTH + 100 + k // N_SLICES
            try:
                cw.execute_query(
                    "i", f'SetBit(frame="f", rowID=3, columnID={col})'
                )
                written.append(col)
            except (ClientError, ConnectionError) as e:
                errors.append(f"write {col}: {e}")
                return
            time.sleep(0.005)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    time.sleep(0.1)

    # KILL the replica mid-storm.
    victim.close()
    print(f"[replication-smoke] killed replica {victim_host} mid-storm",
          file=sys.stderr)

    t.join(timeout=60.0)
    stop.set()
    if errors:
        print(f"FAIL: quorum writes errored with one replica down: "
              f"{errors[:3]}", file=sys.stderr)
        return 1
    if len(written) != STORM_WRITES:
        print(f"FAIL: writer confirmed {len(written)}/{STORM_WRITES}",
              file=sys.stderr)
        return 1
    backlog = s0.replication.hints.backlog(victim_host)
    if backlog < 1:
        print("FAIL: no hints queued for the dead replica", file=sys.stderr)
        return 1
    print(f"[replication-smoke] {len(written)} quorum writes ok, "
          f"{backlog} hints queued", file=sys.stderr)

    # Sub-quorum must fail loudly while the replica is down.
    try:
        c0.execute_query(
            "i",
            f'SetBit(frame="f", rowID=6, columnID={SLICE_WIDTH + 42})',
            trace_headers={"X-Write-Consistency": "all"},
        )
        print("FAIL: consistency=all write succeeded with a dead replica",
              file=sys.stderr)
        return 1
    except (ClientError, ConnectionError) as e:
        if "need 3" not in str(e):
            print(f"FAIL: sub-quorum error did not name the ack math: {e}",
                  file=sys.stderr)
            return 1

    # RESTART: the breaker transition triggers replay; converge.
    victim = boot("n2", host=victim_host, ring=hosts)
    servers[2] = victim

    def checksums(server, sl):
        return server.rebalance.delta_action(
            {"index": "i", "slice": sl, "action": "checksum"}
        )["checksums"]

    deadline = time.time() + 60
    converged = False
    while time.time() < deadline:
        if s0.replication.hints.backlog(victim_host) == 0 and all(
            checksums(s0, sl) == checksums(victim, sl)
            for sl in range(N_SLICES)
        ):
            converged = True
            break
        time.sleep(0.2)
    if not converged:
        print(
            "FAIL: no convergence after restart: backlog="
            f"{s0.replication.hints.backlog(victim_host)}",
            file=sys.stderr,
        )
        return 1

    # Zero lost writes: every confirmed column is in the restarted
    # replica's LOCAL fragments.
    view = victim.holder.index("i").frame("f").view("standard")
    have = 0
    for sl in range(N_SLICES):
        frag = view.fragment(sl)
        if frag is not None:
            have += frag._count_of.get(3, 0)
    expect = len(set(written))
    for s in servers:
        s.close()
    if have != expect:
        print(f"FAIL: lost writes: replica has {have} of {expect}",
              file=sys.stderr)
        return 1
    print(
        f"OK: {expect} storm writes at quorum with a mid-storm replica "
        f"kill; hint replay converged checksums on restart with zero "
        "lost writes and no anti-entropy tick"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
