"""``degraded`` bench tier — device-fault tolerance figures.

Measures, on the virtual 8-device CPU mesh (re-exec harness shared with
mesh_bench/multichip_smoke):

* healthy-path Count throughput (Gcols/s) + p50/p99 per-query latency;
* the same storm with the accelerator QUARANTINED (persistent injected
  launch fault): host-fallback Gcols/s + p50/p99, every answer
  byte-checked against the healthy run, plus how many queries the
  quarantine threshold cost before the breaker engaged;
* watchdog trip recovery: one injected hang inside the collective
  dispatch — the tripped query's end-to-end latency IS the recovery
  time (bounded by ``launch-watchdog-ms``, not by the wedge).

Emits one JSON object on stdout; bench.py folds it into the artifact as
``degraded`` and bench-smoke asserts its shape.  Sizing via
``BENCH_DEGRADED_SLICES`` (default 16) and ``BENCH_DEGRADED_ITERS``
(default 30).
"""

from __future__ import annotations

import json
import os
import sys
import time

if not os.environ.get("_DEGRADED_BENCH_REEXEC"):
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count=8".strip()
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["_DEGRADED_BENCH_REEXEC"] = "1"
    os.execv(sys.executable, [sys.executable] + sys.argv)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

N_SLICES = int(os.environ.get("BENCH_DEGRADED_SLICES", "16"))
ITERS = int(os.environ.get("BENCH_DEGRADED_ITERS", "30"))
WATCHDOG_MS = 200.0


def log(msg: str) -> None:
    print(f"[degraded] {msg}", file=sys.stderr, flush=True)


def pct(samples, p):
    if not samples:
        return None
    s = sorted(samples)
    k = min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))
    return round(s[k], 3)


def storm(ex, parse_string, q, iters):
    lat = []
    results = []
    for _ in range(iters):
        t0 = time.monotonic()
        (res,) = ex.execute("i", parse_string(q))
        lat.append((time.monotonic() - t0) * 1e3)
        results.append(int(res))
    return lat, results


def main() -> int:
    import tempfile

    import jax

    from pilosa_tpu.cluster.topology import new_cluster
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.device.health import DeviceHealth
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.exec.coalesce import CoalesceScheduler
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH
    from pilosa_tpu.pql.parser import parse_string
    from pilosa_tpu.testing import faults

    log(
        f"backend={jax.default_backend()} devices={len(jax.devices())} "
        f"slices={N_SLICES} iters={ITERS}"
    )
    rng = np.random.default_rng(11)
    tmp = tempfile.mkdtemp(prefix="degraded-bench-")
    holder = Holder(os.path.join(tmp, "data"))
    holder.open()
    idx = holder.create_index("i")
    f = idx.create_frame("f")
    bits_per = 4096
    rows_l, cols_l = [], []
    for s in range(N_SLICES):
        for row in (1, 2):
            pos = rng.choice(SLICE_WIDTH, size=bits_per, replace=False)
            rows_l.append(np.full(bits_per, row, dtype=np.int64))
            cols_l.append(s * SLICE_WIDTH + pos.astype(np.int64))
    f.import_bulk(np.concatenate(rows_l), np.concatenate(cols_l))
    cluster = new_cluster(1)
    host = cluster.nodes[0].host
    q = "Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))"
    cols_per_query = N_SLICES * SLICE_WIDTH

    def gcols(lat_ms):
        total_s = sum(lat_ms) / 1e3
        return round(cols_per_query * len(lat_ms) / total_s / 1e9, 3)

    out: dict = {"slices": N_SLICES, "iters": ITERS}

    # --- healthy ---------------------------------------------------------
    threshold = 3
    dh = DeviceHealth(
        quarantine_threshold=threshold, open_ms=3600_000, watchdog_ms=0
    )
    co = CoalesceScheduler(health=dh)
    ex = Executor(holder, host=host, cluster=cluster, coalescer=co, device_health=dh)
    try:
        lat, res = storm(ex, parse_string, q, ITERS)
        want = res[0]
        out["healthy"] = {
            "gcols_s": gcols(lat),
            "p50_ms": pct(lat, 50),
            "p99_ms": pct(lat, 99),
        }
        log(f"healthy: {out['healthy']}")

        # --- degraded (quarantined -> host fallback) ---------------------
        faults.install("device.launch:mode=error")
        qn = 0
        while not dh.degraded() and qn < threshold * 4:
            (r,) = ex.execute("i", parse_string(q))
            qn += 1
            assert int(r) == want, "wrong answer while quarantining"
        out["quarantine_queries"] = qn
        out["quarantine_threshold"] = threshold
        lat, res = storm(ex, parse_string, q, ITERS)
        out["byte_identical"] = all(r == want for r in res)
        out["degraded"] = {
            "gcols_s": gcols(lat),
            "p50_ms": pct(lat, 50),
            "p99_ms": pct(lat, 99),
        }
        log(
            f"degraded (host fallback): {out['degraded']} after "
            f"{qn} queries to quarantine"
        )
        faults.clear()
    finally:
        ex.close()
        co.close()
        dh.close()

    # --- watchdog trip recovery -----------------------------------------
    dh = DeviceHealth(
        quarantine_threshold=3, open_ms=3600_000, watchdog_ms=WATCHDOG_MS
    )
    co = CoalesceScheduler(health=dh)
    ex = Executor(holder, host=host, cluster=cluster, coalescer=co, device_health=dh)
    try:
        # Warm the per-slice fallback program so the recovery figure
        # measures the watchdog, not a cold compile.
        ex.execute("i", parse_string(q))
        faults.install(
            "device.launch:kind=hang,path=collective,times=1,"
            f"delay-ms={WATCHDOG_MS * 4:.0f}"
        )
        t0 = time.monotonic()
        (r,) = ex.execute("i", parse_string(q))
        trip_ms = (time.monotonic() - t0) * 1e3
        faults.clear()
        assert int(r) == want, "wrong answer through the watchdog trip"
        out["watchdog"] = {
            "watchdog_ms": WATCHDOG_MS,
            "trip_recovery_ms": round(trip_ms, 3),
            "trips": dh.snapshot()["watchdogTrips"],
        }
        log(f"watchdog: {out['watchdog']}")
    finally:
        faults.clear()
        ex.close()
        co.close()
        dh.close()
        holder.close()

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
