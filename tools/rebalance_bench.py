"""Rebalance bench tier — live 2->3 grow under sustained load, with
every node in its OWN process (per-node GIL isolation, like a real
deployment — an in-process 3-node harness would charge the migration
for scheduler contention production doesn't have).

Boots two `pilosa-tpu server` subprocesses over a seeded corpus,
measures steady-state read latency under a concurrent writer, then
live-grows to a third subprocess node with the migration bandwidth-
throttled, sampling read latency DURING the background copy.  Emits
ONE JSON line:

  steady_p50_ms / steady_p99_ms    (reads, writer running)
  during_p50_ms / during_p99_ms    (reads overlapping the migration)
  p99_ratio                        (during / steady — the SLO figure)
  migration_s, slices_moved
  writes_confirmed, writes_lost    (must be 0)
  results_identical                (bitmap before == after cutover)

Run standalone or embedded by bench.py as the ``rebalance`` tier.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, ".")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from pilosa_tpu.net import codec  # noqa: E402
from pilosa_tpu.net.client import ClientError, InternalClient  # noqa: E402
from pilosa_tpu.ops.bitplane import SLICE_WIDTH  # noqa: E402

N_SLICES = int(os.environ.get("REBALANCE_BENCH_SLICES", "16"))
BITS_PER_SLICE = int(os.environ.get("REBALANCE_BENCH_BITS", "2000"))
THROTTLE_MBPS = float(os.environ.get("REBALANCE_BENCH_THROTTLE_MBPS", "4"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def free_tcp_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def boot_node(tmp: str, name: str, host: str, ring: list[str]):
    """One real node in its own process."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        PILOSA_DATA_DIR=f"{tmp}/{name}",
        PILOSA_HOST=host,
        PILOSA_CLUSTER_HOSTS=",".join(ring),
        PILOSA_CLUSTER_POLLING_INTERVAL="1",
        PILOSA_ANTI_ENTROPY_INTERVAL="3600",
        PILOSA_CLUSTER_REBALANCE_THROTTLE_MBPS=str(THROTTLE_MBPS),
        PILOSA_CLUSTER_REBALANCE_RELEASE_DELAY_MS="0",
        # One persistent compile cache across all nodes: the JOINING
        # node deserializes the fused programs instead of paying a cold
        # XLA compile on the first query routed at it post-flip.
        PILOSA_TPU_COMPILATION_CACHE_DIR=f"{tmp}/compile-cache",
        PILOSA_TPU_PREWARM="true",
    )
    return subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.cli", "server"],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )


def wait_ready(host: str, timeout: float = 90.0) -> None:
    client = InternalClient(host, timeout=2.0)
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            status, data = client._request("GET", "/version")
            client._check(status, data)
            return
        except Exception:  # noqa: BLE001 — still booting
            time.sleep(0.2)
    raise SystemExit(f"FAIL: node {host} never became ready")


def wait_prewarm(host: str, timeout: float = 120.0) -> None:
    """Block until the node's compiled-program count is non-zero and
    stable across two reads — its background prewarm has landed."""
    client = InternalClient(host, timeout=5.0)
    last = -1
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            status, data = client._request("GET", "/metrics")
            body = client._check(status, data).decode()
            n = 0
            for line in body.splitlines():
                if line.startswith("pilosa_exec_programCache_entries "):
                    n = int(float(line.rsplit(" ", 1)[1]))
            if n > 0 and n == last:
                return
            last = n
        except Exception:  # noqa: BLE001 — scrape may race the boot
            pass
        time.sleep(1.0)
    log(f"warning: prewarm on {host} never stabilized; proceeding")


def pql_count(client, row=1):
    return client.execute_pql("i", f'Count(Bitmap(frame="f", rowID={row}))')


def pcts(ms):
    if not ms:
        return 0.0, 0.0
    arr = sorted(ms)
    return (
        arr[len(arr) // 2],
        arr[min(len(arr) - 1, int(len(arr) * 0.99))],
    )


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="rebalance-bench-")
    ports = [free_tcp_port() for _ in range(3)]
    hosts2 = sorted(f"127.0.0.1:{p}" for p in ports[:2])
    host3 = f"127.0.0.1:{ports[2]}"
    hosts3 = sorted(hosts2 + [host3])
    procs = []
    stop = threading.Event()
    try:
        for i, h in enumerate(hosts2):
            procs.append(boot_node(tmp, f"n{i}", h, hosts2))
        for h in hosts2:
            wait_ready(h)
        log(f"2-node ring up: {hosts2}")

        c0 = InternalClient(hosts2[0], timeout=30.0)
        # Static cluster type: no schema broadcaster — create on each
        # member (the joining third node gets the schema pushed by the
        # rebalance coordinator).
        for h in hosts2:
            ch = InternalClient(h, timeout=10.0)
            try:
                ch.create_index("i")
            except ClientError:
                pass
            try:
                ch.create_frame("i", "f")
            except ClientError:
                pass
        rng = np.random.default_rng(11)
        log(f"seeding {N_SLICES} slices x {BITS_PER_SLICE} bits")
        for sl in range(N_SLICES):
            cols = rng.choice(SLICE_WIDTH, size=BITS_PER_SLICE, replace=False)
            c0.import_bits(
                "i", "f", sl,
                (np.ones(len(cols), np.int64),
                 cols.astype(np.int64) + sl * SLICE_WIDTH),
            )
        # Let the 1 s max-slice polling tick propagate the slice range.
        want = N_SLICES * BITS_PER_SLICE
        deadline = time.time() + 30
        while time.time() < deadline and pql_count(c0) != want:
            time.sleep(0.3)
        assert pql_count(c0) == want, "corpus never converged"
        rb = c0.execute_pql("i", 'Bitmap(frame="f", rowID=1)')
        baseline = codec.bitmap_to_json(rb)["bits"]
        log(f"corpus ready: count={want}")

        # The concurrent writer runs through BOTH measurement windows,
        # so the p99 ratio isolates the MIGRATION's interference.
        written: list[int] = []

        def writer():
            cw = InternalClient(hosts2[0], timeout=10.0)
            k = 0
            while not stop.is_set():
                col = (k % N_SLICES) * SLICE_WIDTH + SLICE_WIDTH - 1 - k // N_SLICES
                try:
                    cw.execute_query(
                        "i", f'SetBit(frame="f", rowID=7, columnID={col})'
                    )
                    written.append(col)
                except (ClientError, ConnectionError):
                    pass
                k += 1
                time.sleep(0.005)

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()

        # Warm the query path (compiles, batch caches) before the
        # steady window — cold-start cost is the cold_restart tier's
        # number, not this one's.
        for _ in range(10):
            pql_count(c0)

        steady: list[float] = []
        t_end = time.time() + 3.0
        while time.time() < t_end:
            t0 = time.perf_counter()
            pql_count(c0)
            steady.append((time.perf_counter() - t0) * 1e3)
        steady_p50, steady_p99 = pcts(steady)
        log(f"steady (with writer): p50 {steady_p50:.2f} ms "
            f"p99 {steady_p99:.2f} ms ({len(steady)} samples)")

        # The joining node: configured with the OLD ring (it is not a
        # member until the transition admits it).
        procs.append(boot_node(tmp, "n2", host3, hosts2))
        wait_ready(host3)
        # Let its background prewarm land before admitting it (the
        # operator workflow docs/administration.md prescribes): the
        # first post-flip query must not pay a cold XLA compile.
        wait_prewarm(host3)

        during: list[float] = []

        def sampler():
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    pql_count(c0)
                    during.append((time.perf_counter() - t0) * 1e3)
                except (ClientError, ConnectionError):
                    pass  # begin/commit epoch windows
                time.sleep(0.002)

        st_thread = threading.Thread(target=sampler, daemon=True)
        st_thread.start()

        t0 = time.time()
        status, data = c0._request(
            "POST", "/cluster/resize",
            body=json.dumps({"hosts": hosts3}).encode(),
        )
        c0._check(status, data)
        deadline = time.time() + 600
        while time.time() < deadline:
            st, d = c0._request("GET", "/debug/rebalance")
            snap = json.loads(c0._check(st, d))
            if not snap.get("running") and snap.get("transition") is None:
                break
            if not snap.get("running") and (
                (snap.get("coordinator") or {}).get("error")
            ):
                raise SystemExit(f"FAIL: migration error: {snap}")
            time.sleep(0.1)
        else:
            raise SystemExit("FAIL: migration did not complete")
        migration_s = time.time() - t0
        time.sleep(0.3)
        stop.set()
        wt.join(timeout=10)
        st_thread.join(timeout=10)

        during_p50, during_p99 = pcts(during)
        after = codec.bitmap_to_json(
            c0.execute_pql("i", 'Bitmap(frame="f", rowID=1)')
        )["bits"]
        got7 = codec.bitmap_to_json(
            c0.execute_pql("i", 'Bitmap(frame="f", rowID=7)')
        )["bits"]
        lost = len(set(written)) - len(set(got7) & set(written))
        moved = 0
        for sl in range(N_SLICES):
            nodes = c0.fragment_nodes("i", sl)
            if nodes and nodes[0]["host"] == host3:
                moved += 1
        out = {
            "steady_p50_ms": round(steady_p50, 3),
            "steady_p99_ms": round(steady_p99, 3),
            "during_p50_ms": round(during_p50, 3),
            "during_p99_ms": round(during_p99, 3),
            "p99_ratio": round(during_p99 / steady_p99, 2) if steady_p99 else 0,
            "migration_s": round(migration_s, 2),
            "slices_moved": moved,
            "during_samples": len(during),
            "writes_confirmed": len(set(written)),
            "writes_lost": lost,
            "results_identical": after == baseline,
            "throttle_mbps": THROTTLE_MBPS,
            "slices": N_SLICES,
            "isolation": "process-per-node",
        }
        log(
            f"migration {migration_s:.1f}s, {moved} slices moved; "
            f"reads during: p50 {during_p50:.2f} ms p99 {during_p99:.2f} ms "
            f"({out['p99_ratio']}x steady); writes lost: {lost}"
        )
        print(json.dumps(out))
        if lost or not out["results_identical"]:
            raise SystemExit("FAIL: correctness violated under migration")
        return 0
    finally:
        stop.set()
        for p in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()


if __name__ == "__main__":
    raise SystemExit(main())
