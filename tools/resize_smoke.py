"""Resize smoke — a tiny CPU 2->3 live grow with checksummed results
(`make resize-smoke`, BLOCKING-eligible in CI alongside chaos-smoke).

Boots two real in-process HTTP nodes, seeds a small corpus, records
the query answer, then live-grows the cluster to three nodes while a
writer keeps importing — asserting:

* the migration completes (background coordinator, /debug/rebalance),
* query results are byte-identical before vs after the cutover,
* zero writes were dropped (every confirmed write is countable after),
* the new node owns slices and the sources released theirs,
* the rebalance counters/surfaces are populated.
"""

from __future__ import annotations

import json
import sys
import tempfile
import threading
import time

sys.path.insert(0, ".")

import os  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from pilosa_tpu.cluster.topology import Cluster  # noqa: E402
from pilosa_tpu.net import codec  # noqa: E402
from pilosa_tpu.net.client import ClientError, InternalClient  # noqa: E402
from pilosa_tpu.net.server import Server  # noqa: E402
from pilosa_tpu.ops.bitplane import SLICE_WIDTH  # noqa: E402

N_SLICES = 5


def boot(tmp, name, ring=()):
    cluster = Cluster(replica_n=1)
    for h in ring:
        cluster.add_node(h)
    s = Server(
        data_dir=f"{tmp}/{name}",
        cluster=cluster,
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        rebalance_release_delay_ms=0.0,
    )
    s.open()
    return s


def bits(client, row=1):
    for _ in range(10):
        try:
            rb = client.execute_pql("i", f'Bitmap(frame="f", rowID={row})')
            return codec.bitmap_to_json(rb)["bits"]
        except (ClientError, ConnectionError):
            time.sleep(0.1)
    raise SystemExit("FAIL: query never answered")


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="resize-smoke-")
    s0 = boot(tmp, "n0")
    s1 = boot(tmp, "n1")
    s2 = None
    stop = threading.Event()
    try:
        hosts2 = sorted([s0.host, s1.host])
        for s in (s0, s1):
            for h in hosts2:
                if s.cluster.node_by_host(h) is None:
                    s.cluster.add_node(h)
            s.cluster.nodes.sort(key=lambda n: n.host)
            s.holder.create_index_if_not_exists("i")
            s.holder.index("i").create_frame_if_not_exists("f")

        c0 = InternalClient(s0.host, timeout=10.0)
        for sl in range(N_SLICES):
            c0.execute_query(
                "i", f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH + sl})'
            )
        for s in (s0, s1):
            s._tick_max_slices()
        baseline = bits(c0)
        assert len(baseline) == N_SLICES, baseline

        s2 = boot(tmp, "n2", ring=hosts2)
        hosts3 = sorted(hosts2 + [s2.host])

        written: list[int] = []

        def writer():
            cw = InternalClient(s0.host, timeout=10.0)
            k = 0
            while not stop.is_set():
                col = (k % N_SLICES) * SLICE_WIDTH + 500 + k // N_SLICES
                try:
                    cw.execute_query(
                        "i", f'SetBit(frame="f", rowID=3, columnID={col})'
                    )
                    written.append(col)
                except (ClientError, ConnectionError):
                    pass  # retried next loop; only confirmed writes count
                k += 1
                time.sleep(0.01)

        t = threading.Thread(target=writer, daemon=True)
        t.start()

        status, data = c0._request(
            "POST", "/cluster/resize",
            body=json.dumps({"hosts": hosts3}).encode(),
        )
        c0._check(status, data)
        deadline = time.time() + 120
        while time.time() < deadline:
            st, d = c0._request("GET", "/debug/rebalance")
            snap = json.loads(c0._check(st, d))
            if not snap.get("running") and snap.get("transition") is None:
                break
            if not snap.get("running") and (
                (snap.get("coordinator") or {}).get("error")
            ):
                raise SystemExit(f"FAIL: migration error: {snap}")
            time.sleep(0.2)
        else:
            raise SystemExit("FAIL: resize did not complete in 120s")
        time.sleep(0.3)
        stop.set()
        t.join(timeout=10)

        for s in (s0, s1, s2):
            assert s.cluster.hosts() == hosts3, (s.host, s.cluster.hosts())
            cc = InternalClient(s.host, timeout=10.0)
            got = bits(cc)
            assert got == baseline, f"checksum mismatch on {s.host}"
            got3 = bits(cc, row=3)
            assert got3 == sorted(set(written)), (
                f"dropped writes on {s.host}: "
                f"{len(set(written)) - len(got3)} missing"
            )

        owned2 = {
            sl
            for sl in range(N_SLICES)
            if s2.cluster.fragment_nodes("i", sl)[0].host == s2.host
        }
        assert owned2, "grow moved no slices to the new node"
        for s in (s0, s1):
            for sl in owned2:
                assert s.holder.fragment("i", "f", "standard", sl) is None, (
                    f"{s.host} kept released slice {sl}"
                )
        print(
            json.dumps(
                {
                    "ok": True,
                    "slices_moved_to_new_node": sorted(owned2),
                    "concurrent_writes": len(set(written)),
                    "baseline_bits": len(baseline),
                }
            )
        )
        print("resize smoke OK", file=sys.stderr)
        return 0
    finally:
        stop.set()
        for s in (s0, s1, s2):
            if s is not None:
                s.close()


if __name__ == "__main__":
    raise SystemExit(main())
