"""Sparse bench tier (bench.py ``sparse``): what compressed device
planes buy when rows are far below dense occupancy.

Four corpora at 50% / 5% / 1% / 0.1% row density (even rows clustered
runs, odd rows uniform scatter — exercising the RLE, sparse-position,
and dense container formats the write-time selector picks between),
each driven through a distinct-query Count storm in two arms:

* **auto** — write-time per-row format selection on (the default);
  eligible fold-only counts route through the anchored position-domain
  kernels and read bytes proportional to density.
* **dense** — ``configure_plane_format("dense")``: every row a full
  128 KiB word plane, the pre-PR-19 path.

Reports per density: effective Gcols/s per arm, the speedup, the bytes
the device actually read (the perf registry's effective-byte counter
for the anchored site) vs the logical dense geometry, the container
format mix, and — at 1% and 0.1% — the compressed-vs-logical resident
HBM ratio after paging every row through ``device_row``.  A PQL storm
(Count over Intersect/Union/Difference, Bitmap, TopN, Range, Sum) runs
in both arms and the artifact's ``byte_identical`` flag asserts the
results match bit for bit; the tool exits non-zero on any divergence.

Timing figures are only meaningful on a real accelerator — bench-smoke
asserts the correctness/wiring fields (byte identity, format mix,
resident ratio), never the speedup.

Scale knobs: ``BENCH_SPARSE_SLICES`` (default 2), ``BENCH_SPARSE_ROWS``
(default 6), ``BENCH_SPARSE_REPS`` (timing reps per density, default 6).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DENSITIES = [(0.5, "50"), (0.05, "5"), (0.01, "1"), (0.001, "0.1")]


def log(msg: str) -> None:
    print(f"[sparse] {msg}", file=sys.stderr)


def build_corpus(holder, index, density, slices, n_rows, rng):
    """Row 1 uniform scatter (-> packed positions, or dense when the
    density is high enough that positions cost more than words), every
    other row clustered runs (-> RLE) — the mostly-clustered shape real
    bitmap data takes (the reason roaring carries a run container)."""
    import numpy as np

    from pilosa_tpu.ops import bitplane as bp

    idx = holder.create_index_if_not_exists(index)
    f = idx.create_frame_if_not_exists("f")
    f.set_options(range_enabled=True)
    if f.bsi_field("v") is None:
        f.create_field("v", 0, 1000)
    sw = bp.SLICE_WIDTH
    card = max(8, int(density * sw))
    oracle: dict[int, set] = {}
    rows_out, cols_out = [], []
    for row in range(n_rows):
        cols = set()
        for s in range(slices):
            base_off = s * sw
            if row == 1:
                pos = rng.choice(sw, size=card, replace=False)
                cols.update(int(p) + base_off for p in pos)
            else:
                # clustered: ~16 runs covering `card` positions
                n_runs = min(16, card)
                run_len = max(1, card // n_runs)
                starts = rng.choice(
                    max(1, sw - run_len), size=n_runs, replace=False
                )
                for st in starts:
                    cols.update(
                        range(base_off + int(st), base_off + int(st) + run_len)
                    )
        oracle[row] = cols
        for c in sorted(cols):
            rows_out.append(row)
            cols_out.append(c)
    f.import_bulk(rows_out, cols_out)
    # BSI values on a tail of row 0's columns so Range/Sum touch the
    # compressed-format fragment family too.
    vcols = sorted(oracle[0])[: min(500, len(oracle[0]))]
    f.import_value("v", vcols, [(c % 1000) for c in vcols])
    return f, oracle


def storm(ex, index, parse, n_rows):
    """The byte-identity PQL storm: one result list, order-stable."""
    pairs = [(i, (i + 1) % n_rows) for i in range(n_rows)]
    out = []
    for a, b in pairs:
        for shape in (
            f"Count(Intersect(Bitmap(rowID={a}, frame=f),"
            f" Bitmap(rowID={b}, frame=f)))",
            f"Count(Union(Bitmap(rowID={a}, frame=f),"
            f" Bitmap(rowID={b}, frame=f)))",
            f"Count(Difference(Bitmap(rowID={a}, frame=f),"
            f" Bitmap(rowID={b}, frame=f)))",
        ):
            (r,) = ex.execute(index, parse(shape), None, None)
            out.append(("count", shape, int(r)))
    (bm,) = ex.execute(index, parse("Bitmap(rowID=0, frame=f)"), None, None)
    out.append(("bitmap", "row0", tuple(bm.bits())))
    (tn,) = ex.execute(index, parse("TopN(frame=f, n=3)"), None, None)
    out.append(("topn", "n3", tuple((p.id, p.count) for p in tn)))
    (rg,) = ex.execute(
        index, parse("Range(frame=f, v > 500)"), None, None
    )
    out.append(("range", "v>500", tuple(rg.bits())))
    (sm,) = ex.execute(index, parse("Sum(frame=f, field=v)"), None, None)
    out.append(("sum", "v", (int(sm.value), int(sm.count))))
    return out


def count_loop(ex, index, parse, n_rows, reps):
    """Distinct Count(Intersect) queries (defeating the assembled-batch
    cache) — the timing workload."""
    t0 = time.perf_counter()
    total = 0
    for r in range(reps):
        a = r % n_rows
        b = (r + 1 + (r % max(1, n_rows - 1))) % n_rows
        if a == b:
            b = (b + 1) % n_rows
        (c,) = ex.execute(
            index,
            parse(
                f"Count(Intersect(Bitmap(rowID={a}, frame=f),"
                f" Bitmap(rowID={b}, frame=f)))"
            ),
            None,
            None,
        )
        total += int(c)
    return time.perf_counter() - t0, total


def main() -> int:
    import numpy as np

    import pilosa_tpu.core.fragment as fr
    from pilosa_tpu import device as device_mod
    from pilosa_tpu.cluster.topology import new_cluster
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.view import VIEW_STANDARD
    from pilosa_tpu.exec import plan
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.obs import perf as perf_mod
    from pilosa_tpu.ops import bitplane as bp
    from pilosa_tpu.pql.parser import parse_string

    slices = int(os.environ.get("BENCH_SPARSE_SLICES", "2"))
    n_rows = int(os.environ.get("BENCH_SPARSE_ROWS", "6"))
    reps = int(os.environ.get("BENCH_SPARSE_REPS", "6"))
    sw = bp.SLICE_WIDTH

    # Zero dense budget: every row lands in the sparse tier, where the
    # device path pages compressed payloads instead of full planes.
    orig_init = fr.Fragment.__init__

    def sparse_init(self, *a, **kw):
        kw.setdefault("dense_row_budget", 0)
        orig_init(self, *a, **kw)

    fr.Fragment.__init__ = sparse_init
    tmp = tempfile.mkdtemp(prefix="sparse_bench_")
    densities_out: dict[str, dict] = {}
    ok = True
    try:
        h = Holder(os.path.join(tmp, "data"))
        h.open()
        c = new_cluster(1)
        ex = Executor(h, host=c.nodes[0].host, cluster=c)
        rng = np.random.default_rng(1234)
        for density, tag in DENSITIES:
            index = f"sb{tag.replace('.', '_')}"
            frame, oracle = build_corpus(
                h, index, density, slices, n_rows, rng
            )

            # format mix across every (row, slice)
            mix: dict[str, int] = {}
            logical_rows = 0
            compressed_bytes = 0
            for s in range(slices):
                frag = h.fragment(index, "f", VIEW_STANDARD, s)
                if frag is None:
                    continue
                for row in range(n_rows):
                    hp = frag.host_payload(row)
                    if hp is None:
                        continue
                    fmt, _payload, nbytes, _card = hp
                    mix[bp.FMT_NAMES[fmt]] = mix.get(bp.FMT_NAMES[fmt], 0) + 1
                    logical_rows += 1
                    compressed_bytes += nbytes

            # auto arm: storm for identity, loop for timing, perf deltas
            bp.configure_plane_format(mode="auto")
            plan.clear_program_caches()
            auto_storm = storm(ex, index, parse_string, n_rows)
            count_loop(ex, index, parse_string, n_rows, reps)  # warm compiles
            site0 = (
                perf_mod.registry()
                .snapshot()["sites"]
                .get("anchored", {"bytes": 0, "eff_bytes": 0})
            )
            t_auto, total_a = count_loop(ex, index, parse_string, n_rows, reps)
            site1 = (
                perf_mod.registry()
                .snapshot()["sites"]
                .get("anchored", {"bytes": 0, "eff_bytes": 0})
            )
            eff_read = site1.get("eff_bytes", 0) - site0.get("eff_bytes", 0)
            logical_read = site1.get("bytes", 0) - site0.get("bytes", 0)

            # dense arm: same storms with per-row formats forced off
            bp.configure_plane_format(mode="dense")
            plan.clear_program_caches()
            dense_storm = storm(ex, index, parse_string, n_rows)
            count_loop(ex, index, parse_string, n_rows, reps)  # warm compiles
            t_dense, total_d = count_loop(
                ex, index, parse_string, n_rows, reps
            )
            bp.configure_plane_format(mode="auto")

            identical = auto_storm == dense_storm and total_a == total_d
            if not identical:
                ok = False
                for qa, qd in zip(auto_storm, dense_storm):
                    if qa != qd:
                        log(f"DIVERGENCE at {density}: {qa} != {qd}")

            cols_scanned = reps * slices * sw
            entry = {
                "density_pct": density * 100,
                "effective_gcols_s": round(cols_scanned / t_auto / 1e9, 4),
                "dense_gcols_s": round(cols_scanned / t_dense / 1e9, 4),
                "speedup": round(t_dense / t_auto, 2) if t_auto > 0 else 0.0,
                "bytes_read": int(eff_read),
                "logical_bytes": int(logical_read),
                "format_mix": mix,
                "compressed_row_bytes": compressed_bytes,
                "logical_row_bytes": logical_rows * bp.WORDS_PER_SLICE * 4,
                "byte_identical": identical,
                "storm_queries": len(auto_storm),
            }

            # resident HBM ratio: page every row through device_row and
            # read this corpus's sparse-pool entries back out of the
            # /debug/hbm snapshot.
            if density <= 0.01:
                for s in range(slices):
                    frag = h.fragment(index, "f", VIEW_STANDARD, s)
                    if frag is None:
                        continue
                    for row in range(n_rows):
                        frag.device_row(row)
                snap = device_mod.pool().snapshot()
                res = sum(
                    fent["bytes"]
                    for fent in snap["fragments"]
                    if fent.get("kind") == "sparse"
                    and str(fent.get("fragment", "")).startswith(index)
                )
                logi = sum(
                    fent["logical_bytes"]
                    for fent in snap["fragments"]
                    if fent.get("kind") == "sparse"
                    and str(fent.get("fragment", "")).startswith(index)
                )
                entry["resident_bytes"] = res
                entry["resident_logical_bytes"] = logi
                entry["resident_ratio"] = (
                    round(logi / res, 1) if res else 0.0
                )
            densities_out[tag] = entry
            log(
                f"density {tag}%: auto {entry['effective_gcols_s']} vs dense"
                f" {entry['dense_gcols_s']} Gcols/s ({entry['speedup']}x),"
                f" read {eff_read} of {logical_read} logical bytes,"
                f" mix {mix}, identical={identical}"
            )
        h.close()
    finally:
        fr.Fragment.__init__ = orig_init
        shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps({"densities": densities_out}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
