"""Gameday: the everything-at-once soak (``make gameday``).

One seeded run composing every failure mode the stack claims to
survive, with a multi-tenant open-loop storm (tools/load_harness.py
machinery) driving it.  Legs, in timeline order:

  fairness    a tenant-configured node under a hot-tenant storm: the
              victim tenant's p99 stays within 2x its isolated
              baseline while the hot tenant sheds on quota (429 +
              X-Quota-* headers, visible in /debug/tenants), and
              goodput holds a floor;
  durability  3-node replica-3 cell, the third replica a CHILD
              PROCESS: quorum write storm, kill -9 the replica
              mid-storm (writes keep acking at quorum, hints queue),
              restart it (WAL recovery runs), hint replay drains to
              zero, and every coordinator answers byte-identically to
              the numpy oracle — zero lost acked writes;
  elasticity  2-node grid with standing subscriptions and a tier
              store: resize 2->3 under a live writer, a WINDOWED
              device-fault timeline (faults.py after-ms/until-ms)
              quarantines a device path mid-storm while answers stay
              byte-identical via host fallback, resize 3->2 back,
              demote cold slices below a forced disk budget and
              hydrate them back byte-identically, subscriptions
              converge to the pull oracle with bounded lag across
              both cutovers;
  gossip      an N-member SWIM set under seeded datagram loss
              converges full membership with no false-DOWN storm.

Under PILOSA_LOCK_CHECK=1 the runtime lock-order observations are
verified against the static lock graph at exit.  Prints ONE JSON
artifact line on stdout (or --artifact PATH); progress to stderr.
``--smoke`` scales every leg down for the blocking CI lane
(``make gameday-smoke``); the full run is the non-blocking soak.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

# Virtual 8-device CPU mesh (same re-exec harness as multichip-smoke):
# the grid's M-device axis.  Must happen before jax imports.
if not os.environ.get("_GAMEDAY_REEXEC"):
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count=8".strip()
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["_GAMEDAY_REEXEC"] = "1"
    os.execv(sys.executable, [sys.executable] + sys.argv)

# Fault plans are installed per-leg in-process; an inherited env plan
# would silently compose with every leg's timeline.
os.environ.pop("PILOSA_FAULTS", None)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
TOOLS = os.path.dirname(os.path.abspath(__file__))
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

T0 = time.monotonic()
TIMELINE: list[dict] = []


def log(msg: str) -> None:
    print(f"[gameday +{time.monotonic() - T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def mark(event: str, **detail) -> None:
    TIMELINE.append({"t_s": round(time.monotonic() - T0, 2),
                     "event": event, **detail})
    log(event + (f" {detail}" if detail else ""))


class GamedayFailure(AssertionError):
    pass


def require(cond, msg: str) -> None:
    if not cond:
        raise GamedayFailure(msg)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _free_udp_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# child mode: the killable replica (durability leg)
# ---------------------------------------------------------------------------


def child(data_dir: str, host: str, ring_csv: str) -> int:
    """The victim replica as its own PROCESS so the parent can
    ``kill -9`` it.  Prints READY with the WAL recovery counters from
    open() — on restart they prove the acked tail was replayed."""
    from pilosa_tpu.cluster.topology import Cluster
    from pilosa_tpu.net.server import Server

    cluster = Cluster(replica_n=3)
    for h in ring_csv.split(","):
        cluster.add_node(h)
    cluster.nodes.sort(key=lambda n: n.host)
    s = Server(
        data_dir=data_dir,
        host=host,
        cluster=cluster,
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        breaker_open_ms=300.0,
    )
    s.replication.replay_interval_s = 0.2
    s.open()
    snap = s.ingest.snapshot()
    print(f"READY {snap['replays']} {snap['replayedOps']}", flush=True)
    while True:  # serve until SIGKILL
        time.sleep(3600)


def _spawn_replica(data_dir: str, host: str, ring: list[str]):
    """(proc, replays, replayed_ops) once the child prints READY."""
    env = dict(os.environ)
    env.pop("PILOSA_FAULTS", None)  # parent-side fault plans stay local
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child",
         data_dir, host, ",".join(ring)],
        stdout=subprocess.PIPE, text=True, env=env, cwd=REPO,
    )
    deadline = time.time() + 120
    line = ""
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("READY"):
            _, replays, ops = line.split()
            return proc, int(replays), int(ops)
        if proc.poll() is not None:
            break
    proc.kill()
    raise GamedayFailure(f"replica child never came up: {line!r}")


# ---------------------------------------------------------------------------
# leg 1: multi-tenant fairness under storm
# ---------------------------------------------------------------------------


def leg_fairness(args) -> dict:
    import shutil
    import urllib.request

    import load_harness as lh

    mark("fairness: boot tenant-configured node")
    # Quota sized WELL under the hot tenant's storm share (8/9ths of
    # storm_qps below): the token bucket's burst capacity (= one
    # second of quota) must drain inside the measured window or the
    # storm ends before the first 429.
    hot_quota = 15.0 if args.smoke else 60.0
    tenants = [
        lh.TenantSpec("hot", 8.0, qps=hot_quota),
        lh.TenantSpec("victim", 1.0),
    ]
    td = tempfile.mkdtemp(prefix="gameday-fair-")
    ns = argparse.Namespace(
        point_concurrency=4, heavy_concurrency=2,
        write_concurrency=2, queue_depth=16,
    )
    server = lh.boot_server(os.path.join(td, "data"), ns, True,
                            tenants=tenants)
    try:
        mix = {"count": 1.0}
        lh.seed_corpus(server, slices=2, seed_values=False)
        workload = lh.Workload("i", mix, 2)
        for i in range(8):  # warm the compile path before measuring
            lh._do_request(server.host, *workload.request(i)[1:],
                           deadline_ms=30_000)

        dur = 2.0 if args.smoke else 4.0
        deadline_ms = 2000.0
        # Storm sized so the hot tenant's share (8/9ths) clearly
        # overruns its quota while total load stays inside the node's
        # GIL-bound capacity — fairness, not saturation, is on trial.
        storm_qps = 60.0 if args.smoke else 120.0
        # Unmeasured storm-shaped warmup: storm concurrency compiles
        # batched/coalesced execution paths the per-request warm loop
        # above never reaches — a first-compile stall must not land in
        # the measured window.
        lh.run_point(server.host, workload, storm_qps, 1.0, deadline_ms,
                     tenants=tenants)
        iso_qps = 10.0
        mark("fairness: victim isolated baseline", qps=iso_qps)
        iso = lh.run_point(server.host, workload, iso_qps, dur,
                           deadline_ms, tenants=[tenants[1]])
        p99_iso = iso["tenants"]["victim"]["p99_ms"]
        require(p99_iso is not None, "isolated baseline made no progress")

        # The QoS contract: the victim rides its own WFQ lane, so the
        # hot tenant's storm may at most double its p99.  The floor
        # keeps fast-baseline noise out of the ratio: an UNPROTECTED
        # victim behind a saturating neighbor queues for hundreds of
        # ms, so a 100 ms ceiling still proves isolation.  The victim's
        # p99 is its worst of ~20 samples, so one environmental stall
        # (GC, scheduler) can blow it — a bound miss gets ONE remeasure;
        # genuine unfairness reproduces, a stall does not.
        bound = 2.0 * max(p99_iso, 50.0)
        for attempt in (1, 2):
            mark("fairness: hot-tenant storm", qps=storm_qps,
                 attempt=attempt)
            storm = lh.run_point(server.host, workload, storm_qps, dur,
                                 deadline_ms, tenants=tenants)
            hot, victim = storm["tenants"]["hot"], storm["tenants"]["victim"]
            p99_storm = victim["p99_ms"]
            require(hot["shed"] > 0,
                    f"hot tenant never shed under storm: {hot}")
            require(victim["errors"] == 0, f"victim errored: {victim}")
            require(p99_storm is not None, "victim starved out entirely")
            if p99_storm <= bound:
                break
            log(f"fairness: victim p99 {p99_storm}ms over bound "
                f"{bound}ms on attempt {attempt}")
        require(
            p99_storm <= bound,
            f"victim p99 {p99_storm}ms > 2x isolated {p99_iso}ms "
            f"twice in a row",
        )
        floor = args.goodput_floor_qps
        require(
            storm["goodput_qps"] >= floor,
            f"goodput {storm['goodput_qps']} under floor {floor}",
        )
        # Quota shed must be VISIBLE: 429 + headers, /debug/tenants.
        req = urllib.request.Request(
            f"http://{server.host}/debug/tenants", method="GET"
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            table = json.loads(resp.read())
        require(
            table["tenants"]["hot"]["quotaShed"] >= 1,
            f"/debug/tenants shows no hot quota shed: {table}",
        )
        require(
            table["tenants"]["victim"]["admitted"] >= 1,
            "victim admits not visible in /debug/tenants",
        )
        mark("fairness: ok", victim_p99_iso_ms=p99_iso,
             victim_p99_storm_ms=p99_storm, hot_shed=hot["shed"])
        return {
            "victim_p99_isolated_ms": p99_iso,
            "victim_p99_storm_ms": p99_storm,
            "ratio": round(p99_storm / max(p99_iso, 1e-9), 2),
            "hot_shed": hot["shed"],
            "hot_shed_rate": hot["shed_rate"],
            "goodput_qps": storm["goodput_qps"],
            "debug_tenants_hot_quota_shed":
                table["tenants"]["hot"]["quotaShed"],
        }
    finally:
        server.close()
        shutil.rmtree(td, ignore_errors=True)


# ---------------------------------------------------------------------------
# leg 2: durability — kill -9 a replica mid-storm
# ---------------------------------------------------------------------------


def leg_durability(args) -> dict:
    import numpy as np

    from pilosa_tpu.cluster.topology import Cluster
    from pilosa_tpu.net import codec
    from pilosa_tpu.net.client import ClientError, InternalClient
    from pilosa_tpu.net.server import Server
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH

    n_slices = 4
    storm_writes = 60 if args.smoke else 200
    tmp = tempfile.mkdtemp(prefix="gameday-dur-")

    def boot(name, host="127.0.0.1:0", ring=()):
        cluster = Cluster(replica_n=3)
        for h in ring:
            cluster.add_node(h)
        s = Server(
            data_dir=os.path.join(tmp, name),
            host=host,
            cluster=cluster,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
            breaker_open_ms=300.0,
        )
        s.replication.replay_interval_s = 0.2
        s.open()
        return s

    mark("durability: boot 3-node replica-3 cell (victim = subprocess)")
    s0, s1 = boot("n0"), boot("n1")
    victim_host = f"127.0.0.1:{_free_port()}"
    hosts = sorted([s0.host, s1.host, victim_host])
    for s in (s0, s1):
        for h in hosts:
            if s.cluster.node_by_host(h) is None:
                s.cluster.add_node(h)
        s.cluster.nodes.sort(key=lambda n: n.host)
    victim_dir = os.path.join(tmp, "victim")
    proc, _, _ = _spawn_replica(victim_dir, victim_host, hosts)
    victim_client = InternalClient(victim_host, timeout=10.0)
    try:
        for s in (s0, s1):
            s.holder.create_index_if_not_exists("i")
            s.holder.index("i").create_frame_if_not_exists("f")
        victim_client.create_index("i")
        victim_client.create_frame("i", "f")

        c0 = InternalClient(s0.host, timeout=10.0)
        for sl in range(n_slices):
            c0.execute_query(
                "i",
                f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH + sl})',
            )
        for s in (s0, s1):
            s._tick_max_slices()

        written: list[int] = []
        errors: list[str] = []

        def writer():
            cw = InternalClient(s0.host, timeout=10.0)
            for k in range(storm_writes):
                col = (k % n_slices) * SLICE_WIDTH + 100 + k // n_slices
                try:
                    cw.execute_query(
                        "i", f'SetBit(frame="f", rowID=3, columnID={col})'
                    )
                    written.append(col)
                except (ClientError, ConnectionError) as e:
                    errors.append(f"write {col}: {e}")
                    return
                time.sleep(0.005)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.1)

        mark("durability: kill -9 the replica mid-storm")
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)

        t.join(timeout=120)
        require(not errors,
                f"quorum writes errored with a replica down: {errors[:3]}")
        require(len(written) == storm_writes,
                f"writer confirmed {len(written)}/{storm_writes}")
        backlog = s0.replication.hints.backlog(victim_host) + (
            s1.replication.hints.backlog(victim_host)
        )
        require(backlog >= 1, "no hints queued for the dead replica")
        mark("durability: storm done at quorum", acked=len(written),
             hints=backlog)

        mark("durability: restart the replica (same port, same data)")
        proc, replays, replayed_ops = _spawn_replica(
            victim_dir, victim_host, hosts
        )
        require(replays >= 1,
                f"restart did not run WAL recovery (replays={replays})")
        mark("durability: WAL recovery ran", replays=replays,
             replayed_ops=replayed_ops)

        deadline = time.time() + 120
        while time.time() < deadline:
            if (s0.replication.hints.backlog(victim_host) == 0
                    and s1.replication.hints.backlog(victim_host) == 0):
                break
            time.sleep(0.2)
        require(
            s0.replication.hints.backlog(victim_host) == 0,
            "hint replay never drained",
        )
        mark("durability: hint replay drained to zero")

        # Byte-identical spot checks vs the numpy oracle, from EVERY
        # coordinator — including the restarted replica over HTTP.
        oracle = np.unique(np.asarray(written, dtype=np.int64))
        lost = None
        deadline = time.time() + 60
        while time.time() < deadline:
            lost = []
            for label, cl in (("n0", c0),
                              ("n1", InternalClient(s1.host, timeout=10.0)),
                              ("victim", victim_client)):
                rb = cl.execute_pql("i", 'Bitmap(frame="f", rowID=3)')
                got = np.asarray(codec.bitmap_to_json(rb)["bits"],
                                 dtype=np.int64)
                if not np.array_equal(got, oracle):
                    lost.append(f"{label}: {len(got)}/{len(oracle)} bits")
            if not lost:
                break
            time.sleep(0.5)
        require(not lost, f"acked writes lost after replay: {lost}")
        mark("durability: ok — zero lost acked writes",
             acked=len(oracle))
        return {
            "acked_writes": len(written),
            "hints_queued": backlog,
            "wal_replays": replays,
            "wal_replayed_ops": replayed_ops,
            "coordinators_byte_identical": 3,
        }
    finally:
        if proc.poll() is None:
            proc.kill()
        s0.close()
        s1.close()


# ---------------------------------------------------------------------------
# leg 3: elasticity — resize 2->3->2 + windowed device faults + tier
# ---------------------------------------------------------------------------


def leg_elasticity(args) -> dict:
    import shutil

    from pilosa_tpu.cluster.topology import Cluster
    from pilosa_tpu.net.client import ClientError, InternalClient
    from pilosa_tpu.net.server import Server
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH
    from pilosa_tpu.pql.parser import Query
    from pilosa_tpu.testing import faults

    n_slices = 4
    n_subs = 8 if args.smoke else 16
    tmp = tempfile.mkdtemp(prefix="gameday-elastic-")
    store_url = os.path.join(tmp, "store")

    def boot(name, ring=()):
        cluster = Cluster(replica_n=1)
        for h in ring:
            cluster.add_node(h)
        s = Server(
            data_dir=os.path.join(tmp, name),
            cluster=cluster,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
            rebalance_release_delay_ms=0.0,
            subscribe_refresh_ms=200.0,
            tier_store=store_url,
            tier_sweep_interval_s=3600,
            tenants=["gold:4", "bronze:1"],
        )
        s.open()
        return s

    mark("elasticity: boot 2-node grid (tier store + tenants + subs)")
    s0, s1 = boot("n0"), boot("n1")
    s2 = None
    stop = threading.Event()
    try:
        hosts2 = sorted([s0.host, s1.host])
        for s in (s0, s1):
            for h in hosts2:
                if s.cluster.node_by_host(h) is None:
                    s.cluster.add_node(h)
            s.cluster.nodes.sort(key=lambda n: n.host)
            s.holder.create_index_if_not_exists("i")
            s.holder.index("i").create_frame_if_not_exists("f")

        c0 = InternalClient(s0.host, timeout=15.0)
        for sl in range(n_slices):
            c0.execute_query(
                "i",
                f'SetBit(frame="f", rowID=0, columnID={sl * SLICE_WIDTH + sl})',
            )
        for s in (s0, s1):
            s._tick_max_slices()

        mgr = s0.subscribe
        subs = [
            mgr.register(
                "i", f'Subscribe(Count(Bitmap(rowID={r % 8}, frame="f")))'
            )
            for r in range(n_subs - 1)
        ]
        subs.append(mgr.register("i", 'Subscribe(TopN(frame="f", n=5))'))
        epoch0 = {sub.id: sub.epoch for sub in subs}

        confirmed: list[tuple[int, int]] = []
        reader_errs: list[str] = []

        def writer():
            cw = InternalClient(s0.host, timeout=10.0)
            k = 0
            while not stop.is_set():
                row = 1 + k % 7  # row 0 stays the reader's static truth
                col = (k % n_slices) * SLICE_WIDTH + 500 + k // n_slices
                try:
                    cw.execute_query(
                        "i",
                        f'SetBit(frame="f", rowID={row}, columnID={col})',
                    )
                    confirmed.append((row, col))
                except (ClientError, ConnectionError):
                    pass  # retried next loop; only confirmed count
                k += 1
                time.sleep(0.01)

        def reader():
            # Tenant-tagged reads during every cutover and fault
            # window: correctness only (row 0 is never written).
            cr = InternalClient(s0.host, timeout=15.0)
            misses = 0
            while not stop.is_set():
                try:
                    got = cr.execute_query(
                        "i", 'Count(Bitmap(frame="f", rowID=0))',
                        trace_headers={"X-Tenant": "bronze"},
                    )[0]
                    if got != n_slices:
                        # Confirm before failing: one stale answer in
                        # the middle of a routing cutover is a
                        # transient; an answer that's STILL wrong on
                        # the immediate re-read is lost data.
                        again = cr.execute_query(
                            "i", 'Count(Bitmap(frame="f", rowID=0))',
                            trace_headers={"X-Tenant": "bronze"},
                        )[0]
                        if again != n_slices:
                            reader_errs.append(
                                f"read {got} then {again} != {n_slices} "
                                f"at +{time.monotonic() - T0:.1f}s"
                            )
                            return
                    misses = 0
                except (ClientError, ConnectionError) as e:
                    misses += 1
                    if misses >= 8:
                        reader_errs.append(
                            f"reader at +{time.monotonic() - T0:.1f}s: {e}"
                        )
                        return
                time.sleep(0.03)

        threads = [threading.Thread(target=writer, daemon=True),
                   threading.Thread(target=reader, daemon=True)]
        for t in threads:
            t.start()
        time.sleep(0.5)

        def resize(hosts):
            status, data = c0._request(
                "POST", "/cluster/resize",
                body=json.dumps({"hosts": hosts}).encode(),
            )
            c0._check(status, data)
            deadline = time.time() + 180
            while time.time() < deadline:
                st, d = c0._request("GET", "/debug/rebalance")
                snap = json.loads(c0._check(st, d))
                if not snap.get("running") and snap.get("transition") is None:
                    return
                time.sleep(0.2)
            raise GamedayFailure(f"resize to {hosts} never completed")

        mark("elasticity: resize 2->3 under load")
        s2 = boot("n2", ring=hosts2)
        hosts3 = sorted(hosts2 + [s2.host])
        resize(hosts3)
        mark("elasticity: grow committed", hosts=len(hosts3))

        # WINDOWED device-fault timeline: quarantine opens 200 ms from
        # now, heals at 2200 ms — the storm rides through both edges.
        mark("elasticity: windowed device faults (after-ms/until-ms)")
        faults.install(
            "device.launch:kind=error,after-ms=200,until-ms=2200"
        )
        t_fault = time.monotonic()
        while time.monotonic() - t_fault < (1.5 if args.smoke else 3.0):
            got = c0.execute_pql("i", 'Count(Bitmap(frame="f", rowID=0))')
            require(got == n_slices,
                    f"answer diverged under device fault: {got}")
            time.sleep(0.02)
        quarantines = 0
        for s in (s0, s1, s2):
            snap = s.device_health.snapshot()
            quarantines += sum(
                p.get("quarantines", 0) for p in snap["paths"].values()
            )
        require(quarantines >= 1,
                "windowed device fault never quarantined a path")
        faults.clear()
        mark("elasticity: device quarantine observed, answers exact",
             quarantines=quarantines)

        mark("elasticity: resize 3->2 under load")
        resize(hosts2)
        mark("elasticity: shrink committed")

        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        require(not reader_errs, f"reader failed: {reader_errs[:3]}")
        require(confirmed, "writer confirmed no writes across resizes")

        # Tier: archive, demote below a forced budget, hydrate back.
        mark("elasticity: tier demote/hydrate vs the object store")
        want_counts = [
            c0.execute_pql("i", f'Count(Bitmap(frame="f", rowID={r}))')
            for r in range(8)
        ]
        uploaded = s0.tier.upload_all()
        require(uploaded >= 1, "tier upload archived nothing")
        budget0 = s0.tier.disk_budget_bytes
        s0.tier.disk_budget_bytes = 1
        demoted = s0.tier.enforce_disk_budget()
        require(demoted >= 1, "budget sweep demoted nothing")
        after = [
            c0.execute_pql("i", f'Count(Bitmap(frame="f", rowID={r}))')
            for r in range(8)
        ]
        require(after == want_counts,
                f"post-demotion counts diverged: {after} != {want_counts}")
        s0.tier.disk_budget_bytes = budget0
        mark("elasticity: demote/hydrate byte-identical",
             uploaded=uploaded, demoted=demoted)

        # Subscriptions: converge to the pull oracle, bounded lag,
        # and the cutovers re-stamped epochs.
        require(mgr.flush(timeout=60.0), "pending deltas never drained")
        deadline = time.time() + 90
        stale = subs
        while time.time() < deadline and stale:
            nxt = []
            for sub in stale:
                want = s0.executor.execute("i", Query(calls=[sub.inner]))[0]
                if sub.value != want:
                    nxt.append(sub)
            stale = nxt
            if stale:
                time.sleep(0.2)
        require(not stale,
                f"{len(stale)} subscriptions never converged")
        flips = sum(
            1 for sub in subs if sub.epoch > epoch0[sub.id]
        )
        require(flips >= 1, "no subscription saw a topology epoch move")
        status, data = c0._request("GET", "/debug/subscriptions")
        dbg = json.loads(c0._check(status, data))
        lag = dbg["lagMs"]
        require(lag["samples"] > 0, "no notification batches measured")
        require(
            lag["p99"] is not None and lag["p99"] < args.sub_lag_bound_ms,
            f"subscription lag unbounded: {lag}",
        )
        mark("elasticity: subscriptions converged", subs=len(subs),
             lag_p99_ms=lag["p99"], epoch_flips=flips)
        return {
            "confirmed_writes": len(confirmed),
            "resizes": 2,
            "device_quarantines": quarantines,
            "tier_uploaded": uploaded,
            "tier_demoted": demoted,
            "subscriptions": len(subs),
            "sub_lag_p99_ms": lag["p99"],
            "sub_epoch_flips": flips,
        }
    finally:
        stop.set()
        faults.clear()
        for s in (s0, s1, s2):
            if s is not None:
                s.close()
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# leg 4: gossip under datagram loss
# ---------------------------------------------------------------------------


def leg_gossip(args) -> dict:
    from pilosa_tpu.cluster.gossip import GossipNodeSet
    from pilosa_tpu.testing import faults

    n = 4 if args.smoke else 6
    loss = 0.20
    interval, suspect = 0.05, 0.6
    mark("gossip: member set under seeded datagram loss",
         members=n, loss=loss)
    faults.install(f"gossip.send:prob={loss},seed={args.seed},mode=drop")
    nodes: dict[str, GossipNodeSet] = {}
    try:
        seed_addr = ""
        for i in range(n):
            port = _free_udp_port()
            ns = GossipNodeSet(
                host=f"127.0.0.1:{9000 + i}",
                seed=seed_addr,
                gossip_interval=interval,
                suspect_after=suspect,
            )
            ns.bind = ("127.0.0.1", port)
            ns.advertise = ("127.0.0.1", port)
            ns.open()
            if not seed_addr:
                seed_addr = f"127.0.0.1:{port}"
            nodes[ns.host] = ns

        want = set(nodes)
        deadline = time.time() + 30.0
        while time.time() < deadline:
            if all(set(ns.nodes()) == want for ns in nodes.values()):
                break
            time.sleep(0.1)
        require(
            all(set(ns.nodes()) == want for ns in nodes.values()),
            f"membership never converged under {loss:.0%} loss",
        )
        # No false-DOWN storm over a couple of suspect windows.
        t_end = time.time() + 2 * suspect
        while time.time() < t_end:
            for h, ns in nodes.items():
                downs = [
                    m for m, st in ns.member_states().items()
                    if st == "DOWN" and m in nodes
                ]
                require(
                    not downs,
                    f"false-DOWN storm: {h} marked {downs} DOWN",
                )
            time.sleep(0.1)
        plan = faults.active()
        dropped = sum(r.hits for r in plan.rules) if plan else 0
        require(dropped >= 1, "the loss rule never fired")
        mark("gossip: converged, no false-DOWN", datagrams_dropped=dropped)
        return {"members": n, "loss": loss, "datagrams_dropped": dropped}
    finally:
        faults.clear()
        for ns in nodes.values():
            ns.close()


# ---------------------------------------------------------------------------


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        return child(sys.argv[2], sys.argv[3], sys.argv[4])

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="scaled-down blocking variant (gameday-smoke)")
    ap.add_argument("--seed", type=int, default=42,
                    help="seed for every stochastic leg (gossip loss)")
    ap.add_argument("--goodput-floor-qps", type=float, default=5.0)
    ap.add_argument("--sub-lag-bound-ms", type=float, default=20_000.0)
    ap.add_argument("--artifact", default="-",
                    help="artifact path ('-' = stdout)")
    args = ap.parse_args()

    import jax

    log(f"backend={jax.default_backend()} devices={len(jax.devices())} "
        f"seed={args.seed} smoke={args.smoke}")

    legs: dict[str, dict] = {}
    ok = True
    failure = ""
    try:
        legs["fairness"] = leg_fairness(args)
        legs["durability"] = leg_durability(args)
        legs["elasticity"] = leg_elasticity(args)
        legs["gossip"] = leg_gossip(args)
    except GamedayFailure as e:
        ok = False
        failure = str(e)
        log(f"FAIL: {e}")

    lock_check = "skipped"
    if os.environ.get("PILOSA_LOCK_CHECK"):
        from pilosa_tpu.analyze import runtime as lock_check_mod

        problems = lock_check_mod.verify()
        log(lock_check_mod.report().splitlines()[0])
        if problems:
            for p in problems:
                log(f"lock-check DISAGREEMENT: {p}")
            lock_check = "FAILED"
            ok = False
        else:
            lock_check = "ok"
            log("lock-check ok: runtime order consistent with static graph")

    artifact = {
        "tool": "gameday",
        "seed": args.seed,
        "smoke": args.smoke,
        "ok": ok,
        "legs": legs,
        "timeline": TIMELINE,
        "lock_check": lock_check,
        "wall_s": round(time.monotonic() - T0, 1),
    }
    if failure:
        artifact["failure"] = failure
    line = json.dumps(artifact)
    if args.artifact == "-":
        print(line)
    else:
        with open(args.artifact, "w") as f:
            f.write(line + "\n")
        log(f"artifact written to {args.artifact}")
        print(line)
    if ok:
        log("gameday OK: all legs green")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
