"""Gossip churn soak — 20-50 virtual nodes under injected datagram
loss and member flapping (`make churn-soak`).

Boots N in-process GossipNodeSets (no HTTP servers — pure membership),
installs a seeded deterministic datagram-loss plan at the gossip.send
boundary (testing/faults.py), and runs flap cycles: kill a random
subset, wait for the survivors to converge on exactly the live set
(no false-DOWN of reachable members along the way), revive the dead on
their old identities, wait for the full set to heal.  Exits non-zero
on any convergence failure; prints a JSON report.

    python tools/churn_soak.py [--nodes 24] [--loss 0.25] [--cycles 3]
"""

from __future__ import annotations

import argparse
import json
import socket
import sys
import time

sys.path.insert(0, ".")

from pilosa_tpu.cluster.gossip import GossipNodeSet  # noqa: E402
from pilosa_tpu.testing import faults  # noqa: E402

INTERVAL = 0.05
SUSPECT = 0.8


def free_udp_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def mk(i: int, port: int, seed_addr: str) -> GossipNodeSet:
    ns = GossipNodeSet(
        host=f"127.0.0.1:{20000 + i}",
        seed=seed_addr,
        gossip_interval=INTERVAL,
        suspect_after=SUSPECT,
    )
    ns.bind = ("127.0.0.1", port)
    ns.advertise = ("127.0.0.1", port)
    return ns


def converged(nodes: dict[str, GossipNodeSet]) -> bool:
    want = set(nodes)
    return all(set(ns.nodes()) == want for ns in nodes.values())


def wait_converged(nodes, timeout: float, label: str, report: dict) -> float:
    t0 = time.time()
    deadline = t0 + timeout
    while time.time() < deadline:
        if converged(nodes):
            dt = time.time() - t0
            report.setdefault("convergence_s", []).append(
                {"phase": label, "seconds": round(dt, 2)}
            )
            return dt
        time.sleep(0.1)
    views = {h: sorted(ns.nodes()) for h, ns in nodes.items()}
    raise SystemExit(
        f"FAIL: {label}: no convergence within {timeout}s: "
        + json.dumps(views, indent=2)
    )


def assert_no_false_down(nodes, window_s: float, report: dict) -> None:
    t_end = time.time() + window_s
    while time.time() < t_end:
        for h, ns in nodes.items():
            downs = [
                m
                for m, st in ns.member_states().items()
                if st == "DOWN" and m in nodes
            ]
            if downs:
                raise SystemExit(
                    f"FAIL: false-DOWN storm: {h} marked live members "
                    f"{downs} DOWN under loss"
                )
        time.sleep(0.1)
    report["false_down_observations"] = 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=24)
    ap.add_argument("--loss", type=float, default=0.25)
    ap.add_argument("--cycles", type=int, default=3)
    ap.add_argument("--flap", type=int, default=0, help="nodes per flap (default n//6)")
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()
    flap_n = args.flap or max(2, args.nodes // 6)

    faults.install(
        f"gossip.send:prob={args.loss},seed={args.seed},mode=drop"
    )
    report: dict = {
        "nodes": args.nodes,
        "loss": args.loss,
        "cycles": args.cycles,
        "flap_per_cycle": flap_n,
    }
    nodes: dict[str, GossipNodeSet] = {}
    ports: dict[str, int] = {}
    try:
        seed_addr = ""
        for i in range(args.nodes):
            port = free_udp_port()
            ns = mk(i, port, seed_addr)
            ns.open()
            if not seed_addr:
                seed_addr = f"127.0.0.1:{port}"
            nodes[ns.host] = ns
            ports[ns.host] = port
        print(
            f"booted {args.nodes} virtual members, loss={args.loss}",
            file=sys.stderr,
        )
        wait_converged(nodes, 60.0, "boot", report)
        assert_no_false_down(nodes, 4 * SUSPECT, report)

        import random

        rng = random.Random(args.seed)
        for cycle in range(args.cycles):
            flapped = rng.sample(sorted(nodes), flap_n)
            for h in flapped:
                nodes.pop(h).close()
            print(f"cycle {cycle}: flapped {flapped}", file=sys.stderr)
            wait_converged(nodes, 60.0, f"cycle{cycle}-down", report)
            for h in flapped:
                i = int(h.rsplit(":", 1)[1]) - 20000
                ns = mk(i, ports[h], seed_addr)
                ns.open()
                nodes[h] = ns
            wait_converged(nodes, 60.0, f"cycle{cycle}-heal", report)

        report["ok"] = True
        print(json.dumps(report))
        print("churn soak OK", file=sys.stderr)
        return 0
    finally:
        faults.reset()
        for ns in nodes.values():
            ns.close()


if __name__ == "__main__":
    raise SystemExit(main())
