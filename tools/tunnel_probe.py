"""Disentangle axon-tunnel artifacts from real device time.

The naive timing loop (same executable + same args, 20 iters) can be
distorted by the tunnel: per-dispatch RTT, async queueing, or
result caching of identical (executable, args) pairs.  This probe:

1. times a trivial scalar program (pure RTT floor),
2. times the headline AND+popcount over K DISTINCT input batches
   cycled round-robin (defeats any same-args caching),
3. times it with the SAME batch repeatedly (what bench.py does),
and prints all three so the real compute time can be read off.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp

from pilosa_tpu.ops.bitplane import np_count

N_SLICES = 954
WORDS = 32768


def timed(name, thunk, iters=20):
    jax.block_until_ready(thunk(0))
    t0 = time.perf_counter()
    for i in range(iters):
        out = thunk(i)
    jax.block_until_ready(out)
    s = (time.perf_counter() - t0) / iters
    gbps = (N_SLICES * 2 * WORDS * 4) / s / 1e9
    print(f"{name:44s} {s*1e3:8.3f} ms  ({gbps:7.1f} GB/s-equiv)", flush=True)
    return s


def main():
    print(f"backend={jax.default_backend()} devices={jax.devices()}", flush=True)
    rng = np.random.default_rng(7)

    one = jnp.float32(1.0)

    @jax.jit
    def trivial(x):
        return x + 1.0

    # NOTE: pipelined — this is amortized per-dispatch overhead, NOT the
    # synchronous round-trip floor (that is `trivial SYNC` below).
    timed("trivial scalar add (pipelined dispatch)", lambda i: trivial(one))

    @jax.jit
    def count(batch):
        return jax.vmap(
            lambda l: jnp.sum(jax.lax.population_count(l[0] & l[1]).astype(jnp.int32))
        )(batch)

    K = 4
    batches = [
        jnp.asarray(
            rng.integers(0, 2**32, size=(N_SLICES, 2, WORDS), dtype=np.uint32)
        )
        for _ in range(K)
    ]
    jax.block_until_ready(batches)
    hosts = [
        int(np_count(np.asarray(b[:, 0]) & np.asarray(b[:, 1])))
        for b in batches
    ]

    timed("count, SAME batch every iter", lambda i: count(batches[0]))
    timed(f"count, {K} distinct batches cycled", lambda i: count(batches[i % K]))

    # verify correctness of the cycled results
    for k in range(K):
        got = int(np.asarray(count(batches[k]), np.int64).sum())
        assert got == hosts[k], (k, got, hosts[k])
    print("bit-exact on all distinct batches", flush=True)

    # sync-every-iteration timing (no queue pipelining)
    def sync_timed(name, thunk, iters=20):
        jax.block_until_ready(thunk(0))
        lat = []
        for i in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(thunk(i))
            lat.append(time.perf_counter() - t0)
        p50 = sorted(lat)[len(lat) // 2]
        print(f"{name:44s} p50 {p50*1e3:8.3f} ms  min {min(lat)*1e3:.3f}", flush=True)

    sync_timed("count SYNC, same batch", lambda i: count(batches[0]))
    sync_timed(f"count SYNC, {K} distinct cycled", lambda i: count(batches[i % K]))
    sync_timed("trivial SYNC", lambda i: trivial(one))


if __name__ == "__main__":
    main()
