"""Compressed-plane smoke (``make sparse-smoke``): a tiny 1%-density
clustered corpus on the CPU backend, asserting the PR-19 container
format pipeline end to end in seconds:

* write-time format selection picks compressed containers (RLE for the
  clustered rows, packed positions for the scattered row — no corpus
  row may stay dense at 1%);
* every executor answer over the compressed planes is byte-checked
  against an independent numpy set oracle, and Count results route
  through the anchored position-domain kernels (the plan.anchored
  program family is non-empty afterwards);
* paging rows through ``device_row`` leaves the fragment's sparse pool
  resident at >= 10x below its logical dense geometry, with the
  format mix annotated in the /debug/hbm snapshot;
* the anchored launch site's effective bytes sit below its logical
  bytes in /debug/perf.

Runs under ``PILOSA_LOCK_CHECK=1`` in CI like subscribe-smoke: the
runtime lock-acquisition order the compressed read path produces must
stay consistent with the static lock graph.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(f"[sparse-smoke] {msg}", file=sys.stderr)


def main() -> int:
    import numpy as np

    import pilosa_tpu.core.fragment as fr
    from pilosa_tpu import device as device_mod
    from pilosa_tpu.cluster.topology import new_cluster
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.core.view import VIEW_STANDARD
    from pilosa_tpu.exec import plan
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.obs import perf as perf_mod
    from pilosa_tpu.ops import bitplane as bp
    from pilosa_tpu.pql.parser import parse_string

    # Zero dense budget: every row lands in the sparse tier where the
    # compressed device path engages.
    orig_init = fr.Fragment.__init__

    def sparse_init(self, *a, **kw):
        kw.setdefault("dense_row_budget", 0)
        orig_init(self, *a, **kw)

    fr.Fragment.__init__ = sparse_init
    tmp = tempfile.mkdtemp(prefix="sparse_smoke_")
    try:
        h = Holder(os.path.join(tmp, "data"))
        h.open()
        c = new_cluster(1)
        ex = Executor(h, host=c.nodes[0].host, cluster=c)
        idx = h.create_index_if_not_exists("i")
        f = idx.create_frame_if_not_exists("f")

        rng = np.random.default_rng(99)
        sw = bp.SLICE_WIDTH
        card = int(0.01 * sw)  # 1% density
        oracle: dict[int, set] = {}
        rows_in, cols_in = [], []
        for row in range(9):
            cols: set = set()
            if row == 1:
                for p in rng.choice(sw, size=card, replace=False):
                    cols.add(int(p))
            else:
                run_len = card // 8
                for st in rng.choice(sw - run_len, size=8, replace=False):
                    cols.update(range(int(st), int(st) + run_len))
            oracle[row] = cols
            for cc in sorted(cols):
                rows_in.append(row)
                cols_in.append(cc)
        f.import_bulk(rows_in, cols_in)

        # --- format mix: no dense rows at 1% -------------------------
        frag = h.fragment("i", "f", VIEW_STANDARD, 0)
        mix: dict[str, int] = {}
        for row in range(9):
            fmt, _p, nbytes, fcard = frag.host_payload(row)
            mix[bp.FMT_NAMES[fmt]] = mix.get(bp.FMT_NAMES[fmt], 0) + 1
            assert fcard == len(oracle[row]), (row, fcard, len(oracle[row]))
        log(f"format mix: {mix}")
        assert mix.get("rle", 0) == 8, mix
        assert mix.get("sparse", 0) == 1, mix
        assert mix.get("dense", 0) == 0, mix

        # --- byte-check vs the numpy oracle --------------------------
        def q(pql):
            return ex.execute("i", parse_string(pql), None, None)

        plan.clear_program_caches()
        checks = 0
        for a in range(9):
            b = (a + 1) % 9
            (cnt,) = q(
                f"Count(Intersect(Bitmap(rowID={a}, frame=f),"
                f" Bitmap(rowID={b}, frame=f)))"
            )
            assert cnt == len(oracle[a] & oracle[b]), (a, b, cnt)
            (cnt,) = q(
                f"Count(Union(Bitmap(rowID={a}, frame=f),"
                f" Bitmap(rowID={b}, frame=f)))"
            )
            assert cnt == len(oracle[a] | oracle[b]), (a, b, cnt)
            (cnt,) = q(
                f"Count(Difference(Bitmap(rowID={a}, frame=f),"
                f" Bitmap(rowID={b}, frame=f)))"
            )
            assert cnt == len(oracle[a] - oracle[b]), (a, b, cnt)
            (bm,) = q(f"Bitmap(rowID={a}, frame=f)")
            assert bm.bits() == sorted(oracle[a]), a
            checks += 4
        anchored_programs = plan.program_cache_stats().get("plan.anchored", 0)
        log(f"{checks} answers byte-checked; "
            f"{anchored_programs} anchored programs compiled")
        assert anchored_programs > 0, "anchored route never engaged"

        # --- compressed residency ------------------------------------
        for row in range(9):
            assert frag.device_row(row) is not None
        snap = device_mod.pool().snapshot()
        sparse_rows = [
            fent
            for fent in snap["fragments"]
            if fent.get("kind") == "sparse"
            and str(fent.get("fragment", "")).startswith("i")
        ]
        assert sparse_rows, snap["fragments"]
        res = sum(fent["bytes"] for fent in sparse_rows)
        logi = sum(fent["logical_bytes"] for fent in sparse_rows)
        ratio = logi / res if res else 0.0
        fmts_note = sparse_rows[0].get("formats")
        log(
            f"resident {res} B vs logical {logi} B ({ratio:.1f}x), "
            f"pool formats {fmts_note}"
        )
        assert ratio >= 10, (res, logi)
        assert isinstance(fmts_note, dict) and fmts_note, sparse_rows[0]

        # --- effective vs logical launch bytes -----------------------
        site = perf_mod.registry().snapshot()["sites"].get("anchored")
        assert site is not None and site["launches"] >= 1, site
        assert 0 < site["eff_bytes"] < site["bytes"], site
        log(
            f"anchored site: {site['launches']} launches, "
            f"{site['eff_bytes']} effective of {site['bytes']} logical B"
        )

        h.close()
        log("sparse smoke OK")
    finally:
        fr.Fragment.__init__ = orig_init
        shutil.rmtree(tmp, ignore_errors=True)

    if os.environ.get("PILOSA_LOCK_CHECK"):
        # Runtime lock-order validation: the compressed read path's
        # acquisition order (fragment lock -> pool lock) must stay
        # consistent with the static lock graph (pilosa_tpu/analyze).
        from pilosa_tpu.analyze import runtime as lock_check

        problems = lock_check.verify()
        print(lock_check.report().splitlines()[0])
        if problems:
            for p in problems:
                print("lock-check DISAGREEMENT:", p)
            return 1
        print("lock-check ok: runtime order consistent with static graph")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
