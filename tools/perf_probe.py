"""TPU perf probe: find the bandwidth-bound formulation of Intersect+Count.

Run on a real TPU (plain `python tools/perf_probe.py`, one process at a
time through the axon tunnel).  Times 8+ formulations of the headline
AND+popcount reduce on identical data — plain XLA shapes, manual SWAR,
MXU-dot reduce, and Pallas variants — so the blessed `ops/bitplane.py`
formulation is chosen by measurement.

Workload: 954 slices x 2 rows x 32768 u32 words (250 MB total operands).
v5e HBM ~819 GB/s => floor ~0.305 ms. r02 plain-XLA: 1.91 ms (131 GB/s).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interp():
    return jax.default_backend() != "tpu"

N_SLICES = 954
WORDS = 32768

def bench(name, fn, *args, iters=20):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    s = (time.perf_counter() - t0) / iters
    gbps = (N_SLICES * 2 * WORDS * 4) / s / 1e9
    print(f"{name:40s} {s*1e3:7.3f} ms  {gbps:7.1f} GB/s", flush=True)
    return out, s

def main():
    print(f"backend={jax.default_backend()} devices={jax.devices()}", flush=True)
    rng = np.random.default_rng(7)
    leaves = rng.integers(0, 2**32, size=(N_SLICES, 2, WORDS), dtype=np.uint32)
    host = int(np.bitwise_count(leaves[:, 0] & leaves[:, 1]).sum())
    dev = jnp.asarray(leaves)
    A = jnp.asarray(np.ascontiguousarray(leaves[:, 0]))
    B = jnp.asarray(np.ascontiguousarray(leaves[:, 1]))
    jax.block_until_ready((dev, A, B))

    # 1. current plain-XLA shape: vmap over slices, per-slice scalar
    @jax.jit
    def v1(batch):
        return jax.vmap(lambda l: jnp.sum(jax.lax.population_count(l[0] & l[1]).astype(jnp.int32)))(batch)
    out, _ = bench("v1 vmap per-slice scalars", v1, dev)
    assert int(np.asarray(out, np.int64).sum()) == host

    # 2. one flat scalar reduce
    @jax.jit
    def v2(a, b):
        return jnp.sum(jax.lax.population_count(a & b).astype(jnp.int32), dtype=jnp.int64)
    out, _ = bench("v2 flat scalar (separate A,B)", v2, A, B)
    assert int(out) == host

    # 2b. flat scalar from the interleaved batch
    @jax.jit
    def v2b(batch):
        return jnp.sum(jax.lax.population_count(batch[:, 0] & batch[:, 1]).astype(jnp.int32), dtype=jnp.int64)
    out, _ = bench("v2b flat scalar (batch slice)", v2b, dev)
    assert int(out) == host

    # 3. no popcount — pure bandwidth ceiling probe (xor+sum, wrong answer)
    @jax.jit
    def v3(a, b):
        return jnp.sum((a ^ b).astype(jnp.uint32))
    bench("v3 xor+sum (no popcount)", v3, A, B)

    # 3b. pure read: sum of A only (125 MB)
    @jax.jit
    def v3b(a):
        return jnp.sum(a)
    _, s = bench("v3b sum(A) only (125MB)", v3b, A)
    print(f"    -> one-operand read bw: {N_SLICES*WORDS*4/s/1e9:.1f} GB/s", flush=True)

    # 4. manual SWAR popcount
    def swar(v):
        v = v - ((v >> 1) & jnp.uint32(0x55555555))
        v = (v & jnp.uint32(0x33333333)) + ((v >> 2) & jnp.uint32(0x33333333))
        v = (v + (v >> 4)) & jnp.uint32(0x0F0F0F0F)
        return (v * jnp.uint32(0x01010101)) >> 24
    @jax.jit
    def v4(a, b):
        return jnp.sum(swar(a & b).astype(jnp.int32), dtype=jnp.int64)
    out, _ = bench("v4 manual SWAR popcount", v4, A, B)
    assert int(out) == host

    # 5. two-stage: per-row int32 partials then jnp.sum
    @jax.jit
    def v5(a, b):
        p = jnp.sum(jax.lax.population_count(a & b).astype(jnp.int32), axis=-1)
        return jnp.sum(p, dtype=jnp.int64)
    out, _ = bench("v5 two-stage row partials", v5, A, B)
    assert int(out) == host

    # 6. MXU reduce: popcount -> bf16, dot with ones
    @jax.jit
    def v6(a, b):
        p = jax.lax.population_count(a & b).astype(jnp.bfloat16)
        ones = jnp.ones((WORDS,), jnp.bfloat16)
        return jax.lax.dot_general(p, ones, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
    out, _ = bench("v6 popcount+MXU-dot reduce", v6, A, B)
    assert int(np.asarray(out, np.float64).sum()) == host

    # 7. pallas production kernel: (8,128) lane-partial tiles per step
    # (the ops/kernels.py formulation — Mosaic-legal output blocks).
    from pilosa_tpu.ops import kernels

    n7 = (N_SLICES // 8) * 8  # truncate to whole 8-row grid steps
    A8, B8 = A[:n7], B[:n7]
    host8 = int(np.bitwise_count(leaves[:n7, 0] & leaves[:n7, 1]).sum())

    @jax.jit
    def v7(a, b):
        return jnp.sum(kernels.fused_count_rows(a, b, "and"))

    out, s = bench("v7 pallas (8,128) lane partials", v7, A8, B8)
    print(f"    (bw adj for {n7}/{N_SLICES}: {n7*2*WORDS*4/s/1e9:.1f} GB/s)", flush=True)
    assert int(out) == host8, (int(out), host8)

    # 8. pallas: 2D grid over (row chunks, word chunks), (8,128) lane
    # partials per tile so wide rows pipeline through smaller VMEM blocks.
    RT, CT = 8, 8192
    def k8(a_ref, b_ref, o_ref):
        w = a_ref[:] & b_ref[:]
        o_ref[:] = jnp.sum(
            jax.lax.population_count(w).astype(jnp.int32).reshape(RT, CT // 128, 128),
            axis=1,
        )
    @jax.jit
    def v8(a, b):
        n = a.shape[0]
        part = pl.pallas_call(
            k8,
            grid=(n // RT, WORDS // CT),
            in_specs=[pl.BlockSpec((RT, CT), lambda i, j: (i, j)),
                      pl.BlockSpec((RT, CT), lambda i, j: (i, j))],
            out_specs=pl.BlockSpec((RT, 128), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((n, (WORDS // CT) * 128), jnp.int32),
            interpret=_interp(),
        )(a, b)
        return jnp.sum(part, dtype=jnp.int64)
    out, _ = bench("v8 pallas 2D grid lane partials", v8, A8, B8)
    assert int(out) == host8, (int(out), host8)

    print("host count:", host, flush=True)

if __name__ == "__main__":
    main()
