"""Metrics-documentation lint: every metric the code emits must be in
the administration.md metrics reference table.

Extraction is AST-based, not regex: every call of the form
``<expr>.count(...)`` / ``.gauge`` / ``.histogram`` / ``.timing`` /
``.set`` / ``.count_with_custom_tags`` anywhere under ``pilosa_tpu/``
whose first argument is a string literal (or f-string) is a metric
emission.  F-string placeholders normalize to ``*`` (so
``f"exec.launch.gbps[site:{name}]"`` lints as
``exec.launch.gbps[site:*]``), and tag suffixes (``name[tag:...]``)
are stripped to the base name — the docs table documents base names
with representative tag forms.

The documentation side is every backtick-quoted token in
``docs/administration.md``; a metric passes when its base name matches
the base of some documented token (``*`` in either side is a
wildcard).  Exits non-zero listing every undocumented metric —
BLOCKING in CI (.github/workflows/check.yml) via ``make metrics-lint``.
"""

from __future__ import annotations

import ast
import fnmatch
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PKG = ROOT / "pilosa_tpu"
DOC = ROOT / "docs" / "administration.md"

STATS_METHODS = {
    "count",
    "gauge",
    "histogram",
    "timing",
    "set",
    "count_with_custom_tags",
}


def _literal_name(node: ast.expr) -> str | None:
    """First-argument string value, with f-string holes as ``*``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def emitted_metrics() -> dict[str, list[str]]:
    """``{metric base name: [file:line, ...]}`` for every stats call."""
    out: dict[str, list[str]] = {}
    for path in sorted(PKG.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:  # pragma: no cover — CI lint catches
            print(f"metrics-lint: cannot parse {path}: {e}")
            sys.exit(2)
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in STATS_METHODS
                and node.args
            ):
                continue
            name = _literal_name(node.args[0])
            if name is None:
                continue
            # Not a metric: Event.set() has no args, but guard against
            # any stray .set("...") on non-stats objects by requiring a
            # metric-shaped name (dotted/camelCase word, optional tag
            # suffix — matches every real emission in the tree).
            base = name.split("[")[0]
            if not re.fullmatch(r"[A-Za-z][\w.*]*", base):
                continue
            rel = path.relative_to(ROOT)
            out.setdefault(base, []).append(f"{rel}:{node.lineno}")
    return out


def documented_tokens() -> set[str]:
    """Base names of every backtick-quoted token in administration.md."""
    text = DOC.read_text()
    return {
        tok.split("[")[0].split("{")[0]
        for tok in re.findall(r"`([^`\n]+)`", text)
    }


def main() -> int:
    emitted = emitted_metrics()
    documented = documented_tokens()
    missing = {}
    for base, sites in emitted.items():
        ok = any(
            fnmatch.fnmatch(base, doc) or fnmatch.fnmatch(doc, base)
            for doc in documented
        )
        if not ok:
            missing[base] = sites
    if missing:
        print(
            f"metrics-lint: {len(missing)} metric(s) emitted by the code "
            "but absent from docs/administration.md (metrics reference "
            "table):"
        )
        for base in sorted(missing):
            print(f"  {base}  ({missing[base][0]})")
        return 1
    print(
        f"metrics-lint: ok — {len(emitted)} emitted metric name(s), all "
        "documented in docs/administration.md"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
