"""Replication bench tier (bench.py ``replication``): write latency at
each consistency level and the hint-replay drain rate.

Boots a 3-node, replica-3 in-process cluster on the CPU backend and
measures

* write p50/p99 (ms) of single SetBit requests through one coordinator
  at consistency one / quorum / all — the cost of each ack level on a
  healthy cluster;
* hint replay drain rate: kill a replica, push a burst of quorum
  writes (each queuing a hint), restart it, and time the
  breaker-triggered replay from first backlog to checksum convergence
  — hints/s and the end-to-end recovery seconds.

One JSON line on stdout; progress on stderr.  Scale knobs:
``BENCH_REPLICATION_WRITES`` (per level, default 80) and
``BENCH_REPLICATION_HINTS`` (burst size, default 150).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_SLICES = 4


def log(msg: str) -> None:
    print(f"[replication] {msg}", file=sys.stderr)


def pctl(xs, p):
    xs = sorted(xs)
    return round(xs[min(len(xs) - 1, int(len(xs) * p))] * 1000.0, 3)


def main() -> int:
    from pilosa_tpu.cluster.topology import Cluster
    from pilosa_tpu.net.client import InternalClient
    from pilosa_tpu.net.server import Server
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH

    writes_per_level = int(os.environ.get("BENCH_REPLICATION_WRITES", "80"))
    hint_burst = int(os.environ.get("BENCH_REPLICATION_HINTS", "150"))
    tmp = tempfile.mkdtemp(prefix="replication-bench-")

    def boot(name, host="127.0.0.1:0", ring=()):
        cluster = Cluster(replica_n=3)
        for h in ring:
            cluster.add_node(h)
        s = Server(
            data_dir=os.path.join(tmp, name),
            host=host,
            cluster=cluster,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
            breaker_open_ms=300.0,
        )
        s.replication.replay_interval_s = 0.1
        s.open()
        return s

    servers = [boot(f"n{i}") for i in range(3)]
    hosts = sorted(s.host for s in servers)
    for s in servers:
        for h in hosts:
            if s.cluster.node_by_host(h) is None:
                s.cluster.add_node(h)
        s.cluster.nodes.sort(key=lambda n: n.host)
    for s in servers:
        s.holder.create_index_if_not_exists("i")
        s.holder.index("i").create_frame_if_not_exists("f")

    s0 = servers[0]
    c0 = InternalClient(s0.host, timeout=30.0)
    for sl in range(N_SLICES):
        c0.execute_query(
            "i", f'SetBit(frame="f", rowID=1, columnID={sl * SLICE_WIDTH + sl})'
        )
    for s in servers:
        s._tick_max_slices()

    # -- write latency per consistency level ---------------------------
    out: dict = {"writes": {}, "replicas": 3, "nodes": 3}
    col = 10_000
    for level in ("one", "quorum", "all"):
        lat = []
        for _ in range(writes_per_level):
            col += 1
            q = (
                f'SetBit(frame="f", rowID=2, '
                f'columnID={(col % N_SLICES) * SLICE_WIDTH + col})'
            )
            t0 = time.perf_counter()
            c0.execute_query(
                "i", q, trace_headers={"X-Write-Consistency": level}
            )
            lat.append(time.perf_counter() - t0)
        out["writes"][level] = {
            "n": len(lat),
            "p50_ms": pctl(lat, 0.50),
            "p99_ms": pctl(lat, 0.99),
        }
        log(
            f"write {level}: p50 {out['writes'][level]['p50_ms']} ms, "
            f"p99 {out['writes'][level]['p99_ms']} ms"
        )

    # -- hint replay drain rate ----------------------------------------
    victim = servers[2]
    victim_host = victim.host
    victim.close()
    t0 = time.perf_counter()
    for k in range(hint_burst):
        col += 1
        c0.execute_query(
            "i",
            f'SetBit(frame="f", rowID=3, '
            f'columnID={(k % N_SLICES) * SLICE_WIDTH + 50_000 + k})',
        )
    burst_s = time.perf_counter() - t0
    backlog = s0.replication.hints.backlog(victim_host)
    log(f"burst: {hint_burst} quorum writes in {burst_s:.2f}s with one "
        f"replica dead ({backlog} hints queued)")

    victim = boot("n2", host=victim_host, ring=hosts)
    servers[2] = victim

    def checksums(server, sl):
        return server.rebalance.delta_action(
            {"index": "i", "slice": sl, "action": "checksum"}
        )["checksums"]

    t0 = time.perf_counter()
    deadline = time.time() + 120
    while time.time() < deadline:
        if s0.replication.hints.backlog(victim_host) == 0 and all(
            checksums(s0, sl) == checksums(victim, sl)
            for sl in range(N_SLICES)
        ):
            break
        time.sleep(0.05)
    else:
        log("FAIL: hint replay never converged")
        for s in servers:
            s.close()
        return 1
    drain_s = time.perf_counter() - t0
    # The replayed counter lands AFTER the pass's verify leg; poll
    # briefly so the artifact records the real figure.
    replayed = 0
    deadline = time.time() + 10
    while time.time() < deadline:
        replayed = (
            s0.replication.hints.snapshot()["targets"]
            .get(victim_host, {})
            .get("replayed", 0)
        )
        if replayed >= backlog:
            break
        time.sleep(0.05)
    out["hint_replay"] = {
        "queued": backlog,
        "replayed": replayed,
        "drain_s": round(drain_s, 3),
        "hints_per_s": round(replayed / drain_s, 1) if drain_s > 0 else 0.0,
        "converged": True,
    }
    log(
        f"hint replay: {replayed} hints drained in {drain_s:.2f}s "
        f"({out['hint_replay']['hints_per_s']}/s), checksums converged"
    )
    for s in servers:
        s.close()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
