"""Standing-query bench tier (bench.py ``standing`` section).

Boots ONE real in-process node twice — standing queries enabled, then
the identical node with ``subscribe_enabled=False`` — and measures, on
the enabled node, N >= 1000 registered subscriptions under a live
SetBit stream:

* registration throughput (ms/subscription, compile + snapshot eval),
* update lag p50/p99 (write-arrival -> notification-batch done; the
  manager's recorded per-batch lag ring, read via
  ``/debug/subscriptions``),
* delta-evaluation tier counts (adjust / slice / full) — proof the
  incremental paths, not blanket re-pulls, carried the load,

and on BOTH nodes the query-path p50/p99 for a synchronous PQL storm
racing the same writer — the subscriptions-off run is the baseline the
``p99_ratio`` figure is taken against (the write-path listener fan-out
must not tax readers).

A CPU subprocess tier like cluster_bench/rebalance_bench: one JSON
line on stdout, progress on stderr prefixed ``[standing]``.
"""

from __future__ import annotations

import json
import sys
import tempfile
import threading
import time

sys.path.insert(0, ".")

import os  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from pilosa_tpu.cluster.topology import Cluster  # noqa: E402
from pilosa_tpu.net.client import ClientError, InternalClient  # noqa: E402
from pilosa_tpu.net.server import Server  # noqa: E402
from pilosa_tpu.ops.bitplane import SLICE_WIDTH  # noqa: E402
from pilosa_tpu.pql.parser import Query  # noqa: E402

N_SUBS = int(os.environ.get("STANDING_SUBS", "1000"))
N_ROWS = 64
N_SLICES = 2
N_QUERIES = int(os.environ.get("STANDING_QUERIES", "150"))


def log(msg: str) -> None:
    print(f"[standing] {msg}", file=sys.stderr, flush=True)


def pcts(lats: list) -> dict:
    lats = sorted(lats)
    return {
        "p50_ms": round(lats[len(lats) // 2] * 1e3, 2),
        "p99_ms": round(
            lats[min(len(lats) - 1, int(len(lats) * 0.99))] * 1e3, 2
        ),
    }


def boot(tmp: str, name: str, enabled: bool) -> Server:
    s = Server(
        data_dir=f"{tmp}/{name}",
        cluster=Cluster(replica_n=1),
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        subscribe_enabled=enabled,
        subscribe_max_subscriptions=max(10_000, N_SUBS * 2),
    )
    s.open()
    s.cluster.add_node(s.host)
    s.holder.create_index_if_not_exists("i")
    s.holder.index("i").create_frame_if_not_exists("f")
    c = InternalClient(s.host, timeout=10.0)
    for sl in range(N_SLICES):
        c.execute_query(
            "i", f'SetBit(frame="f", rowID=0, columnID={sl * SLICE_WIDTH})'
        )
    s._tick_max_slices()
    return s


def query_storm(host: str, stop: threading.Event) -> tuple[list, list]:
    """(query latencies, confirmed writes) for a synchronous PQL storm
    racing a 5 ms-interval SetBit writer — identical on both boots."""
    confirmed: list = []

    def writer():
        cw = InternalClient(host, timeout=10.0)
        k = 0
        while not stop.is_set():
            row = k % N_ROWS
            col = (k % N_SLICES) * SLICE_WIDTH + 1000 + k // N_SLICES
            try:
                cw.execute_query(
                    "i", f'SetBit(frame="f", rowID={row}, columnID={col})'
                )
                confirmed.append((row, col))
            except (ClientError, ConnectionError):
                pass
            k += 1
            time.sleep(0.005)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    c = InternalClient(host, timeout=10.0)
    pql = (
        'Count(Intersect(Bitmap(rowID=0, frame="f"),'
        ' Bitmap(rowID=1, frame="f")))'
    )
    c.execute_query("i", pql)  # warm the program outside the timed loop
    lats = []
    for _ in range(N_QUERIES):
        t0 = time.perf_counter()
        c.execute_query("i", pql)
        lats.append(time.perf_counter() - t0)
    stop.set()
    t.join(timeout=10)
    return lats, confirmed


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="standing-bench-")
    out: dict = {"subscriptions": N_SUBS}

    # --- baseline: identical node + storm, subscriptions OFF ----------
    s = boot(tmp, "off", enabled=False)
    try:
        lats, confirmed = query_storm(s.host, threading.Event())
        off = pcts(lats)
        off["writes"] = len(confirmed)
    finally:
        s.close()
    log(f"subscriptions-off query path: p50 {off['p50_ms']} ms "
        f"p99 {off['p99_ms']} ms over {N_QUERIES} queries")

    # --- enabled: N subs registered, same storm -----------------------
    s = boot(tmp, "on", enabled=True)
    try:
        mgr = s.subscribe
        t0 = time.perf_counter()
        subs = []
        for i in range(N_SUBS - 2):
            subs.append(
                mgr.register(
                    "i",
                    f'Subscribe(Count(Bitmap(rowID={i % N_ROWS}, frame="f")))',
                )
            )
        subs.append(
            mgr.register(
                "i",
                'Subscribe(Count(Union(Bitmap(rowID=0, frame="f"),'
                ' Bitmap(rowID=1, frame="f"))))',
            )
        )
        subs.append(mgr.register("i", 'Subscribe(TopN(frame="f", n=5))'))
        reg_s = time.perf_counter() - t0
        assert len(subs) == N_SUBS
        out["registration_ms_per_sub"] = round(reg_s / N_SUBS * 1e3, 3)
        log(f"registered {N_SUBS} subscriptions in {reg_s:.2f}s "
            f"({out['registration_ms_per_sub']} ms/sub)")

        lats, confirmed = query_storm(s.host, threading.Event())
        on = pcts(lats)
        on["writes"] = len(confirmed)

        # Quiesce, then spot-check convergence against the pull oracle:
        # a lag number for updates that are WRONG would be meaningless.
        assert mgr.flush(timeout=60.0), "pending deltas never drained"
        for sub in subs[:: max(1, N_SUBS // 50)]:
            want = s.executor.execute("i", Query(calls=[sub.inner]))[0]
            assert sub.value == want, (sub.pql, sub.value, want)

        c = InternalClient(s.host, timeout=10.0)
        status, data = c._request("GET", "/debug/subscriptions")
        dbg = json.loads(c._check(status, data))
        out["lag_ms"] = dbg["lagMs"]
        out["updates"] = dbg["counters"]["updates"]
        out["batches"] = dbg["counters"]["batches"]
        out["evals"] = dbg["counters"]["evals"]
        assert out["lag_ms"]["samples"] > 0, "no notification batches"
        assert out["updates"] > 0, "no updates emitted"
    finally:
        s.close()
    log(f"subscriptions-on query path: p50 {on['p50_ms']} ms "
        f"p99 {on['p99_ms']} ms; update lag p50 {out['lag_ms']['p50']} ms "
        f"p99 {out['lag_ms']['p99']} ms over {out['batches']} batches "
        f"({out['updates']} updates; evals {out['evals']})")

    out["query_path"] = {
        "off": off,
        "on": on,
        "p99_ratio": (
            round(on["p99_ms"] / off["p99_ms"], 2) if off["p99_ms"] else None
        ),
    }
    print(json.dumps(out))
    log(f"query-path p99 ratio on/off: {out['query_path']['p99_ratio']}x")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
