"""Cluster-reduce bench: coordinator fan-out/reduce cost vs node count.

BASELINE configs[4] is the reference's 4-node cluster Intersect+Count
(reference: executor.go:1149-1243 mapReduce over nodes).  This tier
boots 1/2/4 REAL in-process servers (each with its own HTTP listener,
holder, and executor; cluster.type=static with hash-identical
placement), primes every node's owned slices with the same 2-row
workload, and measures the same PQL Intersect+Count through the
coordinator — so the curve isolates the coordinator's remote fan-out +
reduce overhead from the kernel itself.

Runs on the CPU backend in a fresh process (bench.py spawns it with
JAX_PLATFORMS=cpu before any device work): coordinator overhead is
host-side, and the numbers must not depend on a shared TPU pool's mood.

Prints ONE JSON line:
    {"tier": "cluster_reduce", "slices": S, "per_node": {"1": {...}, ...}}
with sync p50 and concurrent ms/query per node count.  Everything else
goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def boot_cluster(n_nodes: int, data_root: str, slices: int, rows):
    """``n_nodes`` servers sharing one static cluster map; every node's
    owned fragments primed from ``rows[slice]`` (uint32[2, words])."""
    from pilosa_tpu.cluster.topology import Cluster
    from pilosa_tpu.net.server import Server
    from pilosa_tpu.ops import bitplane as bp

    servers = []
    clusters = []
    for i in range(n_nodes):
        cluster = Cluster(replica_n=1)
        s = Server(
            data_dir=os.path.join(data_root, f"n{i}"),
            cluster=cluster,
            anti_entropy_interval=3600,
            polling_interval=3600,
            cache_flush_interval=3600,
        )
        s.open()
        servers.append(s)
        clusters.append(cluster)
    hosts = sorted(s.host for s in servers)
    for c in clusters:
        for h in hosts:
            if c.node_by_host(h) is None:
                c.add_node(h)
        c.nodes.sort(key=lambda n: n.host)

    from bench import prime_fragment  # repo root is on sys.path

    for s in servers:
        holder = s.holder
        holder.create_index_if_not_exists("i")
        holder.index("i").create_frame_if_not_exists("f")
        view = holder.frame("i", "f").create_view_if_not_exists("standard")
        for sl in s.cluster.owns_slices("i", slices - 1, s.host):
            prime_fragment(
                view.create_fragment_if_not_exists(sl), rows[sl], bp.pad_rows
            )
        # every node must know the cluster max slice or the coordinator
        # under-fans (the polling loop is off in this fixture)
        holder.index("i").set_remote_max_slice(slices - 1)
    return servers


def measure(host: str, want: int, n_sync: int = 9, n_conc: int = 48,
            threads: int = 16):
    from pilosa_tpu.net.client import InternalClient

    client = InternalClient(host, timeout=60.0)
    q = 'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
    got = client.execute_query("i", q)[0]
    assert int(got) == want, f"cluster bit-exactness: {got} != {want}"
    times = []
    for _ in range(n_sync):
        t0 = time.perf_counter()
        client.execute_query("i", q)
        times.append(time.perf_counter() - t0)
    times.sort()
    p50 = times[len(times) // 2]
    clients = [InternalClient(host, timeout=60.0) for _ in range(threads)]
    pool = ThreadPoolExecutor(max_workers=threads)
    t0 = time.perf_counter()
    futs = [
        pool.submit(clients[k % threads].execute_query, "i", q)
        for k in range(n_conc)
    ]
    for f in futs:
        assert int(f.result()[0]) == want
    conc = (time.perf_counter() - t0) / n_conc
    pool.shutdown()
    return p50, conc


def main() -> None:
    slices = int(os.environ.get("CLUSTER_BENCH_SLICES", "64"))
    rng = np.random.default_rng(11)
    rows = rng.integers(
        0, 2**32, size=(slices, 2, 32768), dtype=np.uint32
    )
    want = int(np.bitwise_count(rows[:, 0] & rows[:, 1]).sum())

    out = {}
    for n_nodes in (1, 2, 4):
        with tempfile.TemporaryDirectory() as d:
            t0 = time.time()
            servers = boot_cluster(n_nodes, d, slices, rows)
            try:
                p50, conc = measure(servers[0].host, want)
                out[str(n_nodes)] = {
                    "sync_p50_ms": round(p50 * 1e3, 2),
                    "concurrent_ms_per_query": round(conc * 1e3, 2),
                }
                log(
                    f"cluster_reduce nodes={n_nodes} slices={slices}: "
                    f"sync p50 {p50*1e3:.1f} ms, concurrent "
                    f"{conc*1e3:.2f} ms/query (setup {time.time()-t0:.0f}s)"
                )
            finally:
                for s in servers:
                    s.close()
    print(json.dumps({"tier": "cluster_reduce", "slices": slices, "per_node": out}))


if __name__ == "__main__":
    main()
