"""CI chaos smoke: two in-process nodes under PILOSA_FAULTS — one
erroring and one delayed RPC leg — and a fan-out query must still
answer correctly.

Not a benchmark and not the full chaos suite (tests/test_resilience.py)
— a wiring check that the resilience layer actually engages end to end:
the injected transport error is retried, the injected delay is absorbed
within the deadline, the answer is exact, and the fault rules really
fired.  Run via ``make chaos-smoke``; wired into CI as a non-blocking
step next to bench-smoke.
"""

from __future__ import annotations

import os
import socket
import sys
import tempfile

# CPU backend before jax/pilosa import (same bootstrap as
# tests/conftest.py: the container may route JAX at a TPU tunnel), and
# the repo root on sys.path so `make chaos-smoke` works uninstalled.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main() -> int:
    p0, p1 = _free_port(), _free_port()
    h0, h1 = f"127.0.0.1:{p0}", f"127.0.0.1:{p1}"
    # One ERRORING leg: the first query RPC to node 1 dies on send (the
    # retry policy must absorb it).  One DELAYED leg: node 1's next
    # query receive stalls 150 ms (well inside the deadline).
    os.environ["PILOSA_FAULTS"] = (
        f"rpc.send:host={h1},path=/index/*/query,nth=1,mode=error;"
        f"rpc.recv:host={h1},path=/index/*/query,nth=1,mode=delay,delay-ms=150"
    )

    import jax

    jax.config.update("jax_platforms", "cpu")

    from pilosa_tpu.cluster.topology import Cluster
    from pilosa_tpu.net.client import InternalClient
    from pilosa_tpu.net.server import Server
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH
    from pilosa_tpu.testing import faults

    quiet = dict(
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        retry_backoff_ms=20,
    )
    with tempfile.TemporaryDirectory() as td:

        def make(name: str, host: str) -> Server:
            cluster = Cluster(replica_n=1)
            s = Server(
                data_dir=os.path.join(td, name),
                host=host,
                cluster=cluster,
                **quiet,
            )
            s.open()
            for h in sorted([h0, h1]):
                if cluster.node_by_host(h) is None:
                    cluster.add_node(h)
            cluster.nodes.sort(key=lambda n: n.host)
            return s

        s0 = make("n0", h0)
        s1 = make("n1", h1)
        try:
            for s in (s0, s1):
                s.holder.create_index_if_not_exists("i")
                s.holder.index("i").create_frame_if_not_exists("f")
            # Seed bits straight into each OWNER's holder (no RPC):
            # the fault rules must fire on the read query's fan-out,
            # not get consumed by single-shot write legs.
            n_slices = 4
            for sl in range(n_slices):
                owner = s0.cluster.fragment_nodes("i", sl)[0].host
                srv = s0 if owner == h0 else s1
                srv.holder.frame("i", "f").set_bit(
                    "standard", 1, sl * SLICE_WIDTH
                )
            for s in (s0, s1):
                s.holder.index("i").set_remote_max_slice(n_slices - 1)
            c0 = InternalClient(s0.host, timeout=10.0)

            got = c0.execute_pql("i", 'Count(Bitmap(frame="f", rowID=1))')
            assert got == n_slices, f"chaos query answered {got}, want {n_slices}"

            plan = faults.active()
            assert plan is not None, "fault plan never loaded from env"
            fired = [r for r in plan.rules if r.hits > 0]
            assert fired, f"no fault rule fired: {plan.snapshot()}"
            print(
                "chaos-smoke ok: count exact under "
                f"{len(fired)}/{len(plan.rules)} fired fault rule(s); "
                f"rules={plan.snapshot()}"
            )
        finally:
            s0.close()
            s1.close()
    if os.environ.get("PILOSA_LOCK_CHECK"):
        # Runtime lock-order validation (PR 8): every acquisition order
        # observed during the chaos pass must be consistent with the
        # static lock graph (pilosa_tpu/analyze).
        from pilosa_tpu.analyze import runtime as lock_check

        problems = lock_check.verify()
        print(lock_check.report().splitlines()[0])
        if problems:
            for p in problems:
                print("lock-check DISAGREEMENT:", p)
            return 1
        print("lock-check ok: runtime order consistent with static graph")
    return 0


if __name__ == "__main__":
    sys.exit(main())
