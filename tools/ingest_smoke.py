"""CI smoke for the durable ingest subsystem (``make ingest-smoke``):
a child process takes a sustained multi-threaded quorum write storm —
every ack reported to the parent only AFTER the executor's durability
wait (i.e. after the WAL group commit fsynced the record) — and is
``kill -9``'d mid-storm.  The parent then reopens the same data dir and
asserts

* ZERO lost acked bits: every column the child acked before the kill is
  present in the restarted holder's fragments (host oracle via
  ``Fragment.contains``);
* recovery actually ran: the restarted manager reports >= 1 WAL replay
  with > 0 replayed ops — proving the bits came back from the log, not
  from a data-file flush (the storm stays far below the 64 KiB op-log
  flush threshold, so without the WAL every storm bit would be lost);
* the child was genuinely killed mid-storm (it never exited on its own).

Deterministic CPU pass; BLOCKING in CI (.github/workflows/check.yml)
under ``PILOSA_LOCK_CHECK=1`` like subscribe-smoke.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WRITERS = 4
# Kill once this many acks crossed the pipe; the per-thread write cap is
# far larger so the storm can never finish before the kill.
MIN_ACKS = int(os.environ.get("INGEST_SMOKE_MIN_ACKS", "200"))
WRITES_PER_THREAD = 200_000


def child(data_dir: str) -> int:
    """Storm process: single node, WAL on, acks printed only after the
    write returned (durability wait included).  Runs until killed."""
    from pilosa_tpu.net.handler import Request
    from pilosa_tpu.net.server import Server

    srv = Server(data_dir=data_dir, host="127.0.0.1:0",
                 anti_entropy_interval=3600, polling_interval=3600)
    srv.open()
    srv.holder.create_index_if_not_exists("i")
    srv.holder.index("i").create_frame_if_not_exists("f")
    out_mu = threading.Lock()

    def storm(row: int) -> None:
        for k in range(WRITES_PER_THREAD):
            col = k * WRITERS + row
            q = f'SetBit(frame="f", rowID={row}, columnID={col})'
            r = srv.handler.dispatch(
                Request("POST", "/index/i/query", body=q.encode())
            )
            if r.status != 200:
                with out_mu:
                    print(f"ERR {r.status} {r.body!r}", flush=True)
                return
            # The dispatch above returned only after the executor's
            # durability wait: this record is on disk.  The print is the
            # ack the parent's oracle records — kernel pipe buffering
            # preserves it across our own SIGKILL.
            with out_mu:
                print(f"ACK {row} {col}", flush=True)

    threads = [
        threading.Thread(target=storm, args=(t,), daemon=True)
        for t in range(WRITERS)
    ]
    print("READY", flush=True)
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # Reaching here means the parent failed to kill us mid-storm.
    print("DONE", flush=True)
    srv.close()
    return 0


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        return child(sys.argv[2])

    tmp = tempfile.mkdtemp(prefix="ingest-smoke-")
    data_dir = os.path.join(tmp, "node")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child", data_dir],
        stdout=subprocess.PIPE,
        text=True,
    )
    acked: set[tuple[int, int]] = set()
    errors: list[str] = []
    done = threading.Event()

    def reader() -> None:
        for line in proc.stdout:
            parts = line.split()
            if parts and parts[0] == "ACK":
                acked.add((int(parts[1]), int(parts[2])))
            elif parts and parts[0] == "ERR":
                errors.append(line.strip())
            elif parts and parts[0] == "DONE":
                errors.append("storm finished before the kill")
        done.set()

    t = threading.Thread(target=reader, daemon=True)
    t.start()

    deadline = time.time() + 180
    while time.time() < deadline:
        if errors or done.is_set():
            break
        if len(acked) >= MIN_ACKS:
            break
        time.sleep(0.02)

    killed_mid_storm = proc.poll() is None and not done.is_set()
    if killed_mid_storm:
        os.kill(proc.pid, signal.SIGKILL)
        print(f"[ingest-smoke] kill -9 after {len(acked)} acks",
              file=sys.stderr)
    proc.wait(timeout=30)
    # Drain acks that reached the pipe before the kill.
    done.wait(timeout=30)

    if errors:
        print(f"FAIL: {errors[:3]}", file=sys.stderr)
        return 1
    if not killed_mid_storm:
        print("FAIL: child exited before the mid-storm kill", file=sys.stderr)
        return 1
    if len(acked) < MIN_ACKS:
        print(f"FAIL: only {len(acked)} acks before deadline", file=sys.stderr)
        return 1

    # RESTART: reopen the same data dir; recovery replays the WAL tail.
    from pilosa_tpu.net.server import Server

    srv = Server(data_dir=data_dir, host="127.0.0.1:0",
                 anti_entropy_interval=3600, polling_interval=3600)
    srv.open()
    try:
        snap = srv.ingest.snapshot()
        view = srv.holder.index("i").frame("f").view("standard")
        from pilosa_tpu.ops.bitplane import SLICE_WIDTH

        lost = []
        for row, col in sorted(acked):
            frag = view.fragment(col // SLICE_WIDTH)
            if frag is None or not frag.contains(row, col):
                lost.append((row, col))
    finally:
        srv.close()

    if lost:
        print(f"FAIL: {len(lost)} acked bits lost after kill -9: "
              f"{lost[:10]}", file=sys.stderr)
        return 1
    if snap["replays"] < 1 or snap["replayedOps"] < 1:
        print(f"FAIL: restart did not replay the WAL "
              f"(replays={snap['replays']} ops={snap['replayedOps']}) — "
              "the acked bits survived by some other path", file=sys.stderr)
        return 1
    print(
        f"OK: kill -9 mid-storm lost zero of {len(acked)} acked bits; "
        f"restart replayed {snap['replayedOps']} WAL ops across "
        f"{snap['replays']} fragments"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
