"""Ingest bench tier (bench.py ``ingest``): what durability costs and
what delta-scatter saves.

Three measurement legs on the CPU backend, one JSON line on stdout:

* **Durable write throughput, group commit on / off / WAL off** — an
  8-thread acked SetBit storm through the full handler/executor path.
  ``group_on`` batches concurrent writers into one fsync per window
  (2 ms); ``group_off`` forces a commit per append (window 0, batch 1);
  ``wal_off`` is the pre-WAL baseline.  Reports acks/s, WAL MB/s, the
  fsync count vs ack count (the group-commit amplification win), mean
  group size, and write p50/p99 — bench-smoke asserts fsyncs << acks
  with the write p99 bounded by the commit window.

* **Read p99 under a 50/50 read/write storm** — writers park on
  group-commit futures (GIL released), so the fsync wait must stay OFF
  the read path.  A control leg runs the identical storm against a
  disjoint frame (same WAL/fsync load, zero read-path interplay) to
  carry the in-process thread-scheduling noise in the denominator:
  bench-smoke asserts the mixed read p99 is <= 1.5x the control p99.

* **Mirror re-stage bytes, scatter on / off** — a point-write + device
  read loop against a dense fragment.  Scatter ON applies each delta as
  one tiny fused launch and keeps the HBM mirror; OFF invalidates and
  re-uploads the full plane per round.  bench-smoke asserts the byte
  ratio is >= 100x.

Scale knobs: ``BENCH_INGEST_WRITES`` (per thread, default 250),
``BENCH_INGEST_THREADS`` (default 8), ``BENCH_INGEST_READS`` (default
400), ``BENCH_INGEST_RESTAGE_ROUNDS`` (default 150).
"""

from __future__ import annotations

import json
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(f"[ingest] {msg}", file=sys.stderr)


def pctl(xs, p):
    xs = sorted(xs)
    return round(xs[min(len(xs) - 1, int(len(xs) * p))] * 1000.0, 3)


def boot(data_dir, **kw):
    from pilosa_tpu.net.server import Server

    srv = Server(data_dir=data_dir, host="127.0.0.1:0",
                 anti_entropy_interval=3600, polling_interval=3600, **kw)
    srv.open()
    srv.holder.create_index_if_not_exists("i")
    srv.holder.index("i").create_frame_if_not_exists("f")
    return srv


def write_storm(srv, threads: int, writes: int, row_base: int = 0):
    """Acked SetBit storm through the handler; returns (latencies_s,
    acks, wall_s)."""
    from pilosa_tpu.net.handler import Request

    lat: list[list[float]] = [[] for _ in range(threads)]
    errs: list[str] = []

    def run(t: int) -> None:
        for k in range(writes):
            col = k * threads + t
            q = f'SetBit(frame="f", rowID={row_base + t}, columnID={col})'
            t0 = time.perf_counter()
            r = srv.handler.dispatch(
                Request("POST", "/index/i/query", body=q.encode())
            )
            lat[t].append(time.perf_counter() - t0)
            if r.status != 200:
                errs.append(f"{r.status} {r.body!r}")
                return

    ts = [threading.Thread(target=run, args=(t,)) for t in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    if errs:
        raise RuntimeError(f"storm errors: {errs[:3]}")
    flat = [x for per in lat for x in per]
    return flat, len(flat), wall


def durability_arm(tmp: str, name: str, **server_kw) -> dict:
    threads = int(os.environ.get("BENCH_INGEST_THREADS", "8"))
    writes = int(os.environ.get("BENCH_INGEST_WRITES", "250"))
    srv = boot(os.path.join(tmp, name), **server_kw)
    try:
        lat, acks, wall = write_storm(srv, threads, writes)
        snap = srv.ingest.snapshot() if srv.ingest is not None else {}
    finally:
        srv.close()
    fsyncs = int(snap.get("totalFsyncs", 0))
    appends = int(snap.get("totalAppends", 0))
    wal_bytes = sum(
        w.get("walBytesWritten", 0) for w in snap.get("writers", [])
    )
    arm = {
        "acks": acks,
        "wall_s": round(wall, 3),
        "acks_per_s": round(acks / wall, 1) if wall > 0 else 0.0,
        "wal_mb_per_s": round(wal_bytes / wall / 1e6, 3) if wall > 0 else 0.0,
        "fsyncs": fsyncs,
        "appends": appends,
        "mean_group_size": round(appends / fsyncs, 1) if fsyncs else 0.0,
        "write_p50_ms": pctl(lat, 0.50),
        "write_p99_ms": pctl(lat, 0.99),
    }
    log(f"{name}: {arm['acks_per_s']} acks/s, {fsyncs} fsyncs for "
        f"{acks} acks (group {arm['mean_group_size']}), "
        f"write p99 {arm['write_p99_ms']} ms")
    return arm


def read_storm_arm(tmp: str) -> dict:
    """Read p99 under a 50/50 acked write storm.

    Three legs: ``read_only`` (quiet process, reported for scale),
    ``control`` (the same paced acked-writer storm against a DISJOINT
    frame — full WAL, group-commit, and fsync load, zero read-path
    interplay), and ``mixed`` (the storm hits the very fragment being
    read).  The asserted ratio is mixed/control: in-process writer
    threads cost a reader GIL/scheduler time no matter what they write,
    so the control leg carries that noise in the denominator and the
    ratio isolates what durable ingest itself — fragment-lock holds,
    fsync waits, pending scatter applies — adds to the read tail.

    The reads cycle over 8 distinct rowIDs, overflowing the executor's
    4-entry batch cache, so every leg measures FULL query execution.  A
    fixed query would let the control leg serve version-validated cache
    hits (its fragment never changes) while the mixed leg's writes
    invalidate every read — a cache-semantics asymmetry that predates
    the WAL and would swamp the ingest signal."""
    from pilosa_tpu.net.handler import Request

    reads = int(os.environ.get("BENCH_INGEST_READS", "400"))
    srv = boot(os.path.join(tmp, "mixed"))
    try:
        # The control storm writes frame "g": same index, same slice,
        # different fragment — the reads below never touch it.
        srv.holder.index("i").create_frame_if_not_exists("g")
        # Seed the read rows so the Counts have real work.
        for row in range(1, 9):
            for col in range(0, 2048, 7):
                srv.handler.dispatch(Request(
                    "POST", "/index/i/query",
                    body=f'SetBit(frame="f", rowID={row}, '
                         f'columnID={col + row})'.encode(),
                ))
        srv.ingest.wait_durable()

        def read_leg() -> list[float]:
            # Warmup absorbs one-time costs (plane upload, program
            # compiles, the first scatter apply) that would otherwise
            # land as a p99 outlier in whichever leg runs first.
            lat = []
            for i in range(reads + 20):
                q = f'Count(Bitmap(frame="f", rowID={1 + i % 8}))'
                t0 = time.perf_counter()
                r = srv.handler.dispatch(Request(
                    "POST", "/index/i/query", body=q.encode(),
                ))
                if i >= 20:
                    lat.append(time.perf_counter() - t0)
                assert r.status == 200, r.body
            return lat

        def stormed_leg(frame: str) -> list[float]:
            stop = threading.Event()

            def writer(t: int) -> None:
                # Open-loop 50/50 mix: writes paced to roughly the
                # read rate rather than a saturating spin — the point
                # is whether durable-write work leaks into the read
                # path, not raw GIL contention between saturated
                # dispatch loops.
                k = 0
                while not stop.is_set():
                    col = 4096 + k * 4 + t
                    srv.handler.dispatch(Request(
                        "POST", "/index/i/query",
                        body=f'SetBit(frame="{frame}", rowID=9, '
                             f'columnID={col})'.encode(),
                    ))
                    k += 1
                    time.sleep(0.002)

            ws = [threading.Thread(target=writer, args=(t,), daemon=True)
                  for t in range(2)]
            for w in ws:
                w.start()
            try:
                # Let the first group-commit tick land before measuring:
                # the committer's first pending-scatter apply for this
                # plane shape compiles its program while holding the
                # fragment lock, a one-time stall no steady state pays.
                time.sleep(0.05)
                return read_leg()
            finally:
                stop.set()
                for w in ws:
                    w.join(timeout=30)

        ro = read_leg()
        # Alternate the legs and take the median per-leg p99: a p99
        # estimated from a few hundred samples rides on its 2-3 worst
        # draws, and one scheduler/GC hiccup landing in either leg
        # would swing the asserted ratio by 2x.
        controls, mixeds = [], []
        for _ in range(3):
            controls.append(pctl(stormed_leg("g"), 0.99))
            mixeds.append(pctl(stormed_leg("f"), 0.99))
    finally:
        srv.close()
    p99_ro = pctl(ro, 0.99)
    p99_control = statistics.median(controls)
    p99_mixed = statistics.median(mixeds)
    arm = {
        "reads": reads,
        "read_only_p99_ms": p99_ro,
        "control_p99_ms": p99_control,
        "mixed_p99_ms": p99_mixed,
        "p99_ratio": (
            round(p99_mixed / p99_control, 2) if p99_control > 0 else 0.0
        ),
    }
    log(f"read p99: quiet {p99_ro} ms, control storm {p99_control} ms, "
        f"50/50 storm {p99_mixed} ms -> ratio {arm['p99_ratio']}x")
    return arm


def restage_arm(tmp: str) -> dict:
    """Mirror re-stage bytes across a point-write + device-read loop,
    scatter on vs off (fragment-level: the mirror mechanics live below
    the server)."""
    from pilosa_tpu.core.fragment import Fragment
    from pilosa_tpu.device import pool
    from pilosa_tpu.ingest import scatter as ingest_scatter

    rounds = int(os.environ.get("BENCH_INGEST_RESTAGE_ROUNDS", "150"))
    out = {}
    was = ingest_scatter.ENABLED
    try:
        for name, enabled in (("scatter_on", True), ("scatter_off", False)):
            ingest_scatter.ENABLED = enabled
            frag = Fragment(os.path.join(tmp, name, "0"),
                            "i", "f", "standard", 0)
            frag.open()
            try:
                for row in range(4):
                    for col in range(0, 4096, 3):
                        frag.set_bit(row, col)
                frag.device_row(0)  # initial upload
                before = pool().restage_bytes()
                t0 = time.perf_counter()
                for k in range(rounds):
                    frag.set_bit(k % 4, 5000 + k)
                    frag.device_row(k % 4)  # forces mirror sync
                wall = time.perf_counter() - t0
                delta = pool().restage_bytes() - before
            finally:
                frag.close()
            out[name] = {
                "rounds": rounds,
                "restage_bytes": int(delta),
                "wall_s": round(wall, 3),
            }
            log(f"{name}: {delta} re-staged bytes over {rounds} rounds "
                f"({out[name]['wall_s']}s)")
    finally:
        ingest_scatter.ENABLED = was
    on = max(1, out["scatter_on"]["restage_bytes"])
    out["bytes_ratio"] = round(out["scatter_off"]["restage_bytes"] / on, 1)
    out["scatter"] = dict(ingest_scatter.counters())
    log(f"re-stage bytes ratio (off/on): {out['bytes_ratio']}x")
    return out


def main() -> int:
    # Mixed-workload tail control: CPython's default 5 ms GIL switch
    # interval lets one thread's bytecode stretch sit on the GIL for an
    # entire ~1 ms read's p99 budget; 0.5 ms bounds that hold with no
    # measurable throughput cost at bench scale.
    sys.setswitchinterval(0.0005)
    tmp = tempfile.mkdtemp(prefix="ingest-bench-")
    try:
        out: dict = {"write": {}}
        out["write"]["group_on"] = durability_arm(tmp, "group_on")
        out["write"]["group_off"] = durability_arm(
            tmp, "group_off",
            ingest_group_commit_ms=0.0, ingest_group_commit_max=1,
        )
        out["write"]["wal_off"] = durability_arm(
            tmp, "wal_off", ingest_wal=False,
        )
        out["read"] = read_storm_arm(tmp)
        out["restage"] = restage_arm(tmp)
        print(json.dumps(out))
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
