"""Standing-query smoke (`make subscribe-smoke`, BLOCKING in CI).

Boots two real in-process HTTP nodes, registers N >= 100 standing
queries, streams live imports at them, live-grows the cluster to three
nodes MID-STREAM, and asserts:

* every subscription converges to the from-scratch pull oracle after
  the stream quiesces (no lost or phantom updates),
* the update streams are version-monotonic and carry absolute values,
* the topology move re-stamped subscription epochs (snapshot-then-
  stream across the cutover) and nothing was dropped,
* update lag stays bounded (p99 from /debug/subscriptions),
* under PILOSA_LOCK_CHECK=1 the observed lock acquisition order stays
  consistent with the static lock graph (pilosa_tpu/analyze).
"""

from __future__ import annotations

import json
import sys
import tempfile
import threading
import time

sys.path.insert(0, ".")

import os  # noqa: E402

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from pilosa_tpu.cluster.topology import Cluster  # noqa: E402
from pilosa_tpu.net.client import ClientError, InternalClient  # noqa: E402
from pilosa_tpu.net.server import Server  # noqa: E402
from pilosa_tpu.ops.bitplane import SLICE_WIDTH  # noqa: E402
from pilosa_tpu.pql.parser import Query  # noqa: E402

N_SUBS = 100
N_SLICES = 4
# Generous on a shared CPU runner: the bound catches unbounded growth
# (a stuck notifier, a leak), not jitter.
LAG_P99_BOUND_MS = 20_000.0


def boot(tmp, name, ring=()):
    cluster = Cluster(replica_n=1)
    for h in ring:
        cluster.add_node(h)
    s = Server(
        data_dir=f"{tmp}/{name}",
        cluster=cluster,
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        rebalance_release_delay_ms=0.0,
        subscribe_refresh_ms=200.0,
    )
    s.open()
    return s


def drain(client, sid, after):
    """Drain one subscription's retained updates past ``after``,
    asserting version monotonicity; returns (last, cursor)."""
    last = None
    while True:
        status, data = client._request(
            "GET", f"/subscribe/{sid}/poll?after={after}&timeout_ms=50"
        )
        doc = json.loads(client._check(status, data))
        if doc.get("timeout"):
            return last, after
        assert doc["version"] > after, "versions must be monotonic"
        last, after = doc, doc["version"]


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="subscribe-smoke-")
    s0 = boot(tmp, "n0")
    s1 = boot(tmp, "n1")
    s2 = None
    stop = threading.Event()
    try:
        hosts2 = sorted([s0.host, s1.host])
        for s in (s0, s1):
            for h in hosts2:
                if s.cluster.node_by_host(h) is None:
                    s.cluster.add_node(h)
            s.cluster.nodes.sort(key=lambda n: n.host)
            s.holder.create_index_if_not_exists("i")
            s.holder.index("i").create_frame_if_not_exists("f")

        c0 = InternalClient(s0.host, timeout=10.0)
        for sl in range(N_SLICES):
            c0.execute_query(
                "i", f'SetBit(frame="f", rowID=0, columnID={sl * SLICE_WIDTH + sl})'
            )
        for s in (s0, s1):
            s._tick_max_slices()

        # N single-row counts + a few compound trees + a TopN: every
        # write stream row has a watcher.
        mgr = s0.subscribe
        subs = []
        for row in range(N_SUBS - 3):
            subs.append(
                mgr.register(
                    "i", f'Subscribe(Count(Bitmap(rowID={row % 16}, frame="f")))'
                )
            )
        subs.append(
            mgr.register(
                "i",
                'Subscribe(Count(Union(Bitmap(rowID=0, frame="f"),'
                ' Bitmap(rowID=1, frame="f"))))',
            )
        )
        subs.append(
            mgr.register(
                "i",
                'Subscribe(Count(Intersect(Bitmap(rowID=0, frame="f"),'
                ' Bitmap(rowID=2, frame="f"))))',
            )
        )
        subs.append(mgr.register("i", 'Subscribe(TopN(frame="f", n=5))'))
        assert len(subs) >= 100, len(subs)
        cursors = {sub.id: sub.version for sub in subs}
        epoch0 = {sub.id: sub.epoch for sub in subs}

        confirmed: list[tuple[int, int]] = []

        def writer():
            cw = InternalClient(s0.host, timeout=10.0)
            k = 0
            while not stop.is_set():
                row = k % 16
                col = (k % N_SLICES) * SLICE_WIDTH + 500 + k // N_SLICES
                try:
                    cw.execute_query(
                        "i", f'SetBit(frame="f", rowID={row}, columnID={col})'
                    )
                    confirmed.append((row, col))
                except (ClientError, ConnectionError):
                    pass  # retried next loop; only confirmed writes count
                k += 1
                time.sleep(0.005)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(1.0)

        # Live 2->3 grow MID-STREAM.
        s2 = boot(tmp, "n2", ring=hosts2)
        hosts3 = sorted(hosts2 + [s2.host])
        status, data = c0._request(
            "POST", "/cluster/resize",
            body=json.dumps({"hosts": hosts3}).encode(),
        )
        c0._check(status, data)
        deadline = time.time() + 120
        while time.time() < deadline:
            st, d = c0._request("GET", "/debug/rebalance")
            snap = json.loads(c0._check(st, d))
            if not snap.get("running") and snap.get("transition") is None:
                break
            time.sleep(0.2)
        else:
            raise SystemExit("FAIL: resize did not complete in 120s")

        time.sleep(1.0)
        stop.set()
        t.join(timeout=10)
        assert confirmed, "writer confirmed no writes"

        # Quiesce, then every subscription must equal the pull oracle.
        assert mgr.flush(timeout=30.0), "pending deltas never drained"
        deadline = time.time() + 60
        stale = subs
        while time.time() < deadline and stale:
            nxt = []
            for sub in stale:
                want = s0.executor.execute("i", Query(calls=[sub.inner]))[0]
                if sub.value != want:
                    nxt.append(sub)
            stale = nxt
            if stale:
                time.sleep(0.2)
        assert not stale, (
            f"{len(stale)} subscriptions never converged; first: "
            f"{stale[0].pql} = {stale[0].value}"
        )

        # Delivery: monotonic versions ending at the oracle value, and
        # the topology move re-stamped every subscription's epoch.
        flipped = 0
        for sub in subs[:20] + subs[-3:]:
            upd, cursors[sub.id] = drain(c0, sub.id, cursors[sub.id])
            if upd is not None:
                assert upd["value"] == sub.value_json, sub.pql
            if sub.epoch > epoch0[sub.id]:
                flipped += 1
        assert flipped > 0, "no subscription saw the topology epoch move"
        assert mgr.epoch_flips >= 1, "manager never observed the flip"

        status, data = c0._request("GET", "/debug/subscriptions")
        dbg = json.loads(c0._check(status, data))
        assert dbg["count"] == len(subs), dbg["count"]
        lag = dbg["lagMs"]
        assert lag["samples"] > 0, "no notification batches measured"
        assert lag["p99"] is not None and lag["p99"] < LAG_P99_BOUND_MS, lag
        assert dbg["pending"]["bits"] == 0, dbg["pending"]

        print(
            json.dumps(
                {
                    "ok": True,
                    "subscriptions": len(subs),
                    "confirmed_writes": len(confirmed),
                    "updates": dbg["counters"]["updates"],
                    "batches": dbg["counters"]["batches"],
                    "epoch_flips": dbg["counters"]["epochFlips"],
                    "evals": dbg["counters"]["evals"],
                    "lag_ms": lag,
                }
            )
        )
        print("subscribe smoke OK", file=sys.stderr)
    finally:
        stop.set()
        for s in (s0, s1, s2):
            if s is not None:
                s.close()
    if os.environ.get("PILOSA_LOCK_CHECK"):
        # Runtime lock-order validation: every acquisition order the
        # standing-query engine produced (fragment lock -> pending
        # lock, notifier evaluation, delivery) must be consistent with
        # the static lock graph (pilosa_tpu/analyze).
        from pilosa_tpu.analyze import runtime as lock_check

        problems = lock_check.verify()
        print(lock_check.report().splitlines()[0])
        if problems:
            for p in problems:
                print("lock-check DISAGREEMENT:", p)
            return 1
        print("lock-check ok: runtime order consistent with static graph")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
