"""CI smoke pass over bench.py: a tiny CPU-only run that asserts the
JSON artifact parses and carries the coalescer's counters plus the
``bsi`` tier (Range/Sum over integer bit-planes), the ``mixed_storm``
tier (distinct-query fusion counters present, zero errors at trivial
load, launches < queries), the ``cold_restart`` tier
(time-to-first-answer under lazy staging), and the program-cache
entries/bounds invariant — including the new ``interp`` family.

Not a performance measurement — a wiring check: the bench's executor
tiers must produce one valid JSON line on stdout with the coalesce
section (launches / occupancy / dispatches-per-query per concurrent
tier) and the bsi tier's Gcols/s + ms/query figures, so a refactor
cannot silently break the artifact the perf trajectory is built from.
Run via ``make bench-smoke``; a BLOCKING CI step since PR 7.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(
        os.environ,
        # CPU backend, trimmed iteration counts (bench.py's validated
        # fallback mode), and a tiny column count so the whole pass is
        # seconds, not hours.
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        BENCH_CPU_FALLBACK="1",
        BENCH_COLUMNS=str(4 * (1 << 20)),  # 4 slices
        BENCH_SKIP_RESTART_PROBE="1",
        BENCH_SKIP_CLUSTER_TIER="1",
        BENCH_SKIP_HBM_TIER="1",
        # The open-loop storm tier has its own smoke (make load-smoke).
        BENCH_SKIP_ADMISSION_TIER="1",
        # The live-resize tier has its own smoke (make resize-smoke).
        BENCH_SKIP_REBALANCE_TIER="1",
        # The quorum-replication tier has its own smoke
        # (make replication-smoke).
        BENCH_SKIP_REPLICATION_TIER="1",
        # The composed-failure soak has its own smoke
        # (make gameday-smoke).
        BENCH_SKIP_GAMEDAY_TIER="1",
        # Mesh-scaling tier at smoke scale: tiny curve corpus, a
        # 16M-column headline (the 10B default is the real bench run),
        # light node-grid seeding.
        BENCH_MESH_SLICES="8",
        BENCH_MESH_COLUMNS=str(16 * (1 << 20)),
        BENCH_MESH_GRID_BITS="256",
        # Ingest tier at smoke scale: a shorter acked-write storm and
        # re-stage loop (still >= 100 rounds so the scatter-vs-
        # invalidate byte ratio assertion below stays meaningful).
        BENCH_INGEST_WRITES="80",
        BENCH_INGEST_READS="150",
        BENCH_INGEST_RESTAGE_ROUNDS="120",
        # Sparse tier at smoke scale: one slice per density corpus,
        # few timing reps — the assertions below are correctness/
        # wiring (byte identity, format mix, resident ratio), never
        # CPU timing.
        BENCH_SPARSE_SLICES="1",
        BENCH_SPARSE_ROWS="6",
        BENCH_SPARSE_REPS="3",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=1200,
    )
    sys.stderr.write(proc.stderr[-4000:])
    if proc.returncode != 0:
        print(f"FAIL: bench.py exited {proc.returncode}", file=sys.stderr)
        return 1
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    if not lines:
        print("FAIL: no stdout artifact", file=sys.stderr)
        return 1
    try:
        out = json.loads(lines[-1])
    except json.JSONDecodeError as e:
        print(f"FAIL: artifact is not JSON ({e}): {lines[-1]!r}", file=sys.stderr)
        return 1
    for key in ("metric", "value", "unit"):
        if key not in out:
            print(f"FAIL: artifact missing {key!r}", file=sys.stderr)
            return 1
    co = out.get("coalesce")
    if not isinstance(co, dict) or "total" not in co or "tiers" not in co:
        print(f"FAIL: artifact missing coalesce counters: {out}", file=sys.stderr)
        return 1
    total = co["total"]
    for key in ("launches", "queries", "mean_occupancy", "pad_rows"):
        if key not in total:
            print(f"FAIL: coalesce total missing {key!r}: {total}", file=sys.stderr)
            return 1
    if total["launches"] < 1 or total["queries"] < total["launches"]:
        print(f"FAIL: implausible coalesce counters: {total}", file=sys.stderr)
        return 1
    bsi = out.get("bsi")
    if not isinstance(bsi, dict):
        print(f"FAIL: artifact missing bsi tier: {out}", file=sys.stderr)
        return 1
    for section in ("range", "sum"):
        sec = bsi.get(section)
        if not isinstance(sec, dict):
            print(f"FAIL: bsi tier missing {section!r}: {bsi}", file=sys.stderr)
            return 1
        for key in ("gcols_s", "ms_per_query"):
            if not isinstance(sec.get(key), (int, float)) or sec[key] <= 0:
                print(
                    f"FAIL: bsi {section} missing/implausible {key!r}: {sec}",
                    file=sys.stderr,
                )
                return 1
    ms = out.get("mixed_storm")
    if not isinstance(ms, dict):
        print(f"FAIL: artifact missing mixed_storm tier: {out}", file=sys.stderr)
        return 1
    if ms.get("errors") != 0:
        print(f"FAIL: mixed_storm recorded errors: {ms}", file=sys.stderr)
        return 1
    for section in ("fusion_on", "fusion_off"):
        sec = ms.get(section)
        if not isinstance(sec, dict) or not sec:
            print(
                f"FAIL: mixed_storm missing {section!r}: {ms}", file=sys.stderr
            )
            return 1
    on_tiers = [
        v for v in ms["fusion_on"].values() if isinstance(v, dict)
    ]
    if not on_tiers or any(t.get("launches", 0) < 1 for t in on_tiers):
        print(
            f"FAIL: mixed_storm fusion-on launches implausible: {ms}",
            file=sys.stderr,
        )
        return 1
    # Fusion must actually engage on the mixed storm: interpreter
    # launches carrying >1 distinct-tree query each, and launches well
    # under the query count.
    total_fused = sum(t.get("fused_queries", 0) for t in on_tiers)
    total_q = sum(t.get("queries", 0) for t in on_tiers)
    total_launches = sum(t.get("launches", 0) for t in on_tiers)
    if total_fused < 1 or total_launches >= total_q:
        print(
            f"FAIL: mixed_storm fusion counters implausible"
            f" (fused={total_fused}, launches={total_launches},"
            f" queries={total_q}): {ms}",
            file=sys.stderr,
        )
        return 1
    for key in ("speedup", "interp_entries", "interp_entries_after_diversity"):
        if key not in ms:
            print(f"FAIL: mixed_storm missing {key!r}: {ms}", file=sys.stderr)
            return 1
    mesh = out.get("mesh_scaling")
    if not isinstance(mesh, dict):
        print(f"FAIL: artifact missing mesh_scaling tier: {out}", file=sys.stderr)
        return 1
    curve = mesh.get("curve")
    if not isinstance(curve, dict) or set(curve) != {"1", "2", "4", "8"}:
        print(
            f"FAIL: mesh_scaling curve must cover 1/2/4/8 devices: {mesh}",
            file=sys.stderr,
        )
        return 1
    for d, point in curve.items():
        if not point.get("byte_identical") or point.get("gcols_per_s", 0) <= 0:
            print(
                f"FAIL: mesh_scaling curve[{d}] implausible: {point}",
                file=sys.stderr,
            )
            return 1
        if point.get("sharded") != (d != "1"):
            print(
                f"FAIL: sharded execution must engage by default at"
                f" {d} devices: {point}",
                file=sys.stderr,
            )
            return 1
    hl = mesh.get("headline")
    if (
        not isinstance(hl, dict)
        or not hl.get("byte_identical")
        or hl.get("gcols_per_s", 0) <= 0
        or hl.get("devices", 0) < 2
    ):
        print(f"FAIL: mesh_scaling headline implausible: {hl}", file=sys.stderr)
        return 1
    ngrid = mesh.get("node_grid")
    if not isinstance(ngrid, dict) or not ngrid:
        print(f"FAIL: mesh_scaling missing node_grid: {mesh}", file=sys.stderr)
        return 1
    if not any(row.get("devices_per_node", 0) > 1 for row in ngrid.values()):
        print(
            f"FAIL: node_grid never ran a multi-device node: {ngrid}",
            file=sys.stderr,
        )
        return 1
    if not all(row.get("byte_identical") for row in ngrid.values()):
        print(f"FAIL: node_grid byte-check failed: {ngrid}", file=sys.stderr)
        return 1
    cold = out.get("cold_restart")
    if not isinstance(cold, dict):
        print(f"FAIL: artifact missing cold_restart tier: {out}", file=sys.stderr)
        return 1
    for key in ("first_answer_ms", "staging_complete_ms", "staging",
                "programs_compiled"):
        if key not in cold:
            print(f"FAIL: cold_restart missing {key!r}: {cold}", file=sys.stderr)
            return 1
    tiered = out.get("tiered")
    if not isinstance(tiered, dict):
        print(f"FAIL: artifact missing tiered tier: {out}", file=sys.stderr)
        return 1
    for section in ("unbounded", "tiered"):
        sec = tiered.get(section)
        if not isinstance(sec, dict) or any(
            k not in sec for k in ("p50_ms", "p99_ms")
        ):
            print(f"FAIL: tiered tier missing {section!r}: {tiered}", file=sys.stderr)
            return 1
    tt = tiered["tiered"]
    # The demotion/hydration cycle must actually run: a disk budget
    # << total bytes with zero demotions or hydrations means the cold
    # tier silently disengaged.
    if tt.get("demotions", 0) < 1 or tt.get("hydrations", 0) < 1:
        print(
            f"FAIL: tiered tier recorded no demotion/hydration cycle: {tt}",
            file=sys.stderr,
        )
        return 1
    if not (0 < tt.get("cold_hit_rate", 0) <= 1):
        print(f"FAIL: implausible cold-hit rate: {tt}", file=sys.stderr)
        return 1
    if tt.get("hydrate_p99_ms", 0) <= 0:
        print(f"FAIL: tiered tier missing hydration latency: {tt}", file=sys.stderr)
        return 1
    dg = out.get("degraded")
    if not isinstance(dg, dict):
        print(f"FAIL: artifact missing degraded tier: {out}", file=sys.stderr)
        return 1
    for section in ("healthy", "degraded"):
        sec = dg.get(section)
        if not isinstance(sec, dict) or sec.get("gcols_s", 0) <= 0 or (
            sec.get("p99_ms", 0) <= 0
        ):
            print(
                f"FAIL: degraded tier {section!r} implausible: {dg}",
                file=sys.stderr,
            )
            return 1
    if not dg.get("byte_identical"):
        print(
            f"FAIL: degraded tier host fallback not byte-identical: {dg}",
            file=sys.stderr,
        )
        return 1
    # The breaker must engage within its configured threshold (+ the
    # single transient retry), and the watchdog trip must recover in
    # bounded time — not the injected wedge's full duration.
    if dg.get("quarantine_queries", 99) > dg.get("quarantine_threshold", 0) + 1:
        print(f"FAIL: quarantine never engaged at threshold: {dg}", file=sys.stderr)
        return 1
    wd = dg.get("watchdog")
    if (
        not isinstance(wd, dict)
        or wd.get("trips", 0) < 1
        or not (0 < wd.get("trip_recovery_ms", 0) < wd.get("watchdog_ms", 0) * 4)
    ):
        print(f"FAIL: degraded tier watchdog implausible: {wd}", file=sys.stderr)
        return 1
    st = out.get("standing")
    if not isinstance(st, dict):
        print(f"FAIL: artifact missing standing tier: {out}", file=sys.stderr)
        return 1
    if st.get("subscriptions", 0) < 1000:
        print(
            f"FAIL: standing tier must run >= 1000 subscriptions: {st}",
            file=sys.stderr,
        )
        return 1
    lag = st.get("lag_ms")
    if (
        not isinstance(lag, dict)
        or lag.get("samples", 0) < 1
        or not isinstance(lag.get("p50"), (int, float))
        or not isinstance(lag.get("p99"), (int, float))
        or lag["p99"] <= 0
    ):
        print(f"FAIL: standing tier lag implausible: {st}", file=sys.stderr)
        return 1
    if st.get("updates", 0) < 1:
        print(f"FAIL: standing tier emitted no updates: {st}", file=sys.stderr)
        return 1
    qp = st.get("query_path")
    ratio = (qp or {}).get("p99_ratio")
    if not isinstance(qp, dict) or not isinstance(ratio, (int, float)):
        print(f"FAIL: standing tier missing query_path: {st}", file=sys.stderr)
        return 1
    # "Unchanged" with CI-runner headroom: the write-side listener
    # fan-out must not visibly tax the synchronous read path.
    if not (0 < ratio <= 3.0):
        print(
            f"FAIL: query-path p99 with subscriptions on is {ratio}x the"
            f" subscriptions-off baseline: {qp}",
            file=sys.stderr,
        )
        return 1
    ig = out.get("ingest")
    if not isinstance(ig, dict):
        print(f"FAIL: artifact missing ingest tier: {out}", file=sys.stderr)
        return 1
    gw = (ig.get("write") or {}).get("group_on")
    if not isinstance(gw, dict) or gw.get("acks", 0) < 1:
        print(f"FAIL: ingest tier group_on arm implausible: {ig}",
              file=sys.stderr)
        return 1
    # Group commit must actually batch: well under one fsync per acked
    # write (the whole point of the window), and the durable-write p99
    # bounded by the commit window — a per-ack-fsync regression shows
    # up as fsyncs ~= acks long before it shows up in latency.
    if gw.get("fsyncs", 0) < 1 or gw["fsyncs"] * 4 > gw["acks"]:
        print(
            f"FAIL: group commit not batching (fsyncs={gw.get('fsyncs')}"
            f" for {gw.get('acks')} acks): {gw}",
            file=sys.stderr,
        )
        return 1
    if not (0 < gw.get("write_p99_ms", 0) <= 50.0):
        print(f"FAIL: durable write p99 unbounded: {gw}", file=sys.stderr)
        return 1
    if (ig["write"].get("wal_off") or {}).get("fsyncs", -1) != 0:
        print(f"FAIL: wal_off arm fsynced: {ig['write']}", file=sys.stderr)
        return 1
    rd = ig.get("read")
    ig_ratio = (rd or {}).get("p99_ratio")
    if not isinstance(rd, dict) or not isinstance(ig_ratio, (int, float)):
        print(f"FAIL: ingest tier missing read arm: {ig}", file=sys.stderr)
        return 1
    # The WAL fsync wait must stay off the read path: read p99 under
    # the 50/50 storm within 1.5x of the control leg (the identical
    # writer storm against a disjoint frame, so in-process thread-
    # scheduling noise cancels and the ratio isolates what durable
    # ingest adds to the read tail).
    if not (0 < ig_ratio <= 1.5):
        print(
            f"FAIL: read p99 under 50/50 ingest storm is {ig_ratio}x"
            f" the control-storm baseline: {rd}",
            file=sys.stderr,
        )
        return 1
    rs = ig.get("restage")
    if (
        not isinstance(rs, dict)
        or (rs.get("scatter_off") or {}).get("restage_bytes", 0) <= 0
        or rs.get("bytes_ratio", 0) < 100
    ):
        print(
            f"FAIL: delta-scatter re-stage saving under 100x: {rs}",
            file=sys.stderr,
        )
        return 1
    if (rs.get("scatter") or {}).get("launches", 0) < 1:
        print(f"FAIL: scatter arm never launched: {rs}", file=sys.stderr)
        return 1
    # Sparse tier (ISSUE 19): compressed device planes.  Every density
    # corpus must report byte-identical results between the auto and
    # forced-dense arms; the low-density corpora must actually pick
    # compressed container formats; and the 1% corpus's resident HBM
    # must sit >= 10x below its logical dense geometry.
    sp = out.get("sparse")
    if not isinstance(sp, dict) or not isinstance(sp.get("densities"), dict):
        print(f"FAIL: artifact missing sparse tier: {out}", file=sys.stderr)
        return 1
    spd = sp["densities"]
    for tag in ("50", "5", "1", "0.1"):
        ent = spd.get(tag)
        if not isinstance(ent, dict):
            print(f"FAIL: sparse tier missing density {tag}: {spd}",
                  file=sys.stderr)
            return 1
        if ent.get("byte_identical") is not True:
            print(
                f"FAIL: sparse {tag}% storm diverged from the dense arm:"
                f" {ent}",
                file=sys.stderr,
            )
            return 1
        if ent.get("storm_queries", 0) < 1:
            print(f"FAIL: sparse {tag}% storm ran no queries: {ent}",
                  file=sys.stderr)
            return 1
    d1 = spd["1"]
    mix1 = d1.get("format_mix", {})
    if mix1.get("rle", 0) < 1 or mix1.get("sparse", 0) < 1:
        print(
            f"FAIL: 1% corpus picked no compressed formats: {mix1}",
            file=sys.stderr,
        )
        return 1
    if d1.get("resident_ratio", 0) < 10:
        print(
            f"FAIL: 1% resident HBM under 10x below logical: {d1}",
            file=sys.stderr,
        )
        return 1
    if d1.get("bytes_read", 0) <= 0 or d1.get("logical_bytes", 0) <= d1.get(
        "bytes_read", 0
    ):
        print(
            f"FAIL: 1% effective bytes not below logical: {d1}",
            file=sys.stderr,
        )
        return 1
    pc = out.get("program_cache")
    if not isinstance(pc, dict) or "entries" not in pc or "bounds" not in pc:
        print(f"FAIL: artifact missing program_cache: {out}", file=sys.stderr)
        return 1
    for fam, bound in pc["bounds"].items():
        if pc["entries"].get(fam, 0) > bound:
            print(
                f"FAIL: program cache family {fam!r} exceeds its hard"
                f" bound: {pc}",
                file=sys.stderr,
            )
            return 1
    # Launch telemetry (obs/perf.py): the artifact's perf block must
    # carry per-site roofline figures — the bench drives the coalescer
    # hard, so at minimum the coalesce site recorded launches with a
    # positive achieved GB/s, and every reported site is self-
    # consistent (launches >= 1, gbps > 0 whenever bytes moved).
    perf = out.get("perf")
    if not isinstance(perf, dict) or not isinstance(perf.get("sites"), dict):
        print(f"FAIL: artifact missing perf block: {out}", file=sys.stderr)
        return 1
    sites = perf["sites"]
    if not sites:
        print("FAIL: perf block recorded no launch sites", file=sys.stderr)
        return 1
    for name, site in sites.items():
        if site.get("launches", 0) < 1:
            print(f"FAIL: perf site {name!r} implausible: {site}", file=sys.stderr)
            return 1
    if "coalesce" not in sites or sites["coalesce"].get("gbps", 0) <= 0:
        print(
            f"FAIL: perf block missing coalesce-site bandwidth: {sites}",
            file=sys.stderr,
        )
        return 1
    if not isinstance(perf.get("compile_ms"), dict):
        print(f"FAIL: perf block missing compile_ms: {perf}", file=sys.stderr)
        return 1
    # The native histogram families must render as valid Prometheus
    # exposition (in-process — the smoke already booted servers above;
    # this checks the renderer directly so a grammar regression fails
    # here, not in a user's scraper).
    sys.path.insert(0, REPO)
    from pilosa_tpu.obs import perf as perf_mod

    lh = perf_mod.LatencyHistograms(slo_ms=50.0)
    lh.observe_query("point", 12.0)
    lh.observe_http("GET", "/index/{index}/query", 3.0)
    text = lh.render()
    types = [ln for ln in text.splitlines() if ln.startswith("# TYPE")]
    fams = [ln.split()[2] for ln in types]
    if len(fams) != len(set(fams)):
        print(f"FAIL: duplicate # TYPE lines in histogram render: {fams}",
              file=sys.stderr)
        return 1
    for fam in ("pilosa_query_latency_ms", "pilosa_http_latency_ms"):
        if fam not in fams or f"{fam}_bucket{{" not in text:
            print(f"FAIL: histogram family {fam} missing: {fams}",
                  file=sys.stderr)
            return 1
        if f"{fam}_count" not in text or f"{fam}_sum" not in text:
            print(f"FAIL: {fam} missing _count/_sum", file=sys.stderr)
            return 1
        if 'le="+Inf"' not in text:
            print("FAIL: histogram missing +Inf bucket", file=sys.stderr)
            return 1
    print(
        f"OK: metric={out['metric']} value={out['value']} {out['unit']};"
        f" coalesce launches={total['launches']}"
        f" queries={total['queries']}"
        f" mean_occupancy={total['mean_occupancy']};"
        f" bsi range {bsi['range']['gcols_s']} Gcols/s"
        f" / sum {bsi['sum']['gcols_s']} Gcols/s;"
        f" mixed_storm fused={total_fused}/{total_q} queries over"
        f" {total_launches} launches, speedup={ms['speedup']},"
        f" interp entries {ms['interp_entries']}->"
        f"{ms['interp_entries_after_diversity']};"
        f" mesh curve {[curve[d]['gcols_per_s'] for d in ('1', '2', '4', '8')]}"
        f" Gcols/s, headline {hl['columns']} cols @ {hl['devices']} dev"
        f" = {hl['gcols_per_s']} Gcols/s, grid {sorted(ngrid)};"
        f" cold restart first answer {cold['first_answer_ms']} ms;"
        f" tiered p99 {tt['p99_ms']} ms ({tt['demotions']} demotions,"
        f" {tt['hydrations']} hydrations, cold-hit {tt['cold_hit_rate']});"
        f" degraded {dg['degraded']['gcols_s']} vs healthy"
        f" {dg['healthy']['gcols_s']} Gcols/s, watchdog recovery"
        f" {dg['watchdog']['trip_recovery_ms']} ms;"
        f" standing {st['subscriptions']} subs, lag p99 {lag['p99']} ms,"
        f" query-path p99 ratio {ratio}x;"
        f" ingest {gw['acks_per_s']} acks/s ({gw['fsyncs']} fsyncs /"
        f" {gw['acks']} acks), 50/50 read p99 {ig_ratio}x, re-stage"
        f" saving {rs['bytes_ratio']}x;"
        f" sparse 1% mix {d1['format_mix']}, resident"
        f" {d1.get('resident_ratio')}x below logical, byte-identical;"
        f" perf sites {sorted(sites)} (coalesce"
        f" {sites['coalesce']['gbps']} GB/s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
