"""CI smoke for the mesh-sharded data plane (``make multichip-smoke``).

Runs on the virtual 8-device CPU mesh (re-execs itself with
``XLA_FLAGS=--xla_force_host_platform_device_count=8``, the same
harness as the tier-1 suite and the MULTICHIP artifacts) and asserts,
in one process, the ISSUE-12 wiring contract:

* with >1 device visible, sharded execution engages BY DEFAULT — the
  executor's assembled batch is mesh-sharded and fragment planes are
  spread over the mesh shards (slice mod n_devices);
* a tiny mixed storm of DISTINCT Intersect+Count queries plus TopN,
  through the production path (coalescer + fusion + the ICI-reduced
  "total" launch), answers BYTE-IDENTICALLY to the forced
  single-device host path ([device] mesh-devices = 1) and to an
  independent numpy oracle;
* the interpreter program-cache entry counts stay within their derived
  hard bounds (``exec.programCache.entries[cache:interp] <= bound``).

Deterministic, seconds, no accelerator required — BLOCKING in
check.yml alongside resize-smoke/chaos-smoke.
"""

from __future__ import annotations

import os
import sys

if not os.environ.get("_MULTICHIP_SMOKE_REEXEC"):
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count=8".strip()
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["_MULTICHIP_SMOKE_REEXEC"] = "1"
    os.execv(sys.executable, [sys.executable] + sys.argv)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

N_SLICES = 11  # deliberately not a multiple of 8: exercises spill/pad
BITS_PER_ROW = 64
ROWS = 6


def log(msg: str) -> None:
    print(f"[multichip-smoke] {msg}", file=sys.stderr, flush=True)


def build(tmp: str):
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH

    rng = np.random.default_rng(23)
    holder = Holder(tmp)
    holder.open()
    idx = holder.create_index("i")
    f = idx.create_frame("f")
    bits: dict[int, set] = {r: set() for r in range(ROWS)}
    for r in range(ROWS):
        for s in range(N_SLICES):
            for c in rng.choice(SLICE_WIDTH // 64, size=BITS_PER_ROW, replace=False):
                col = s * SLICE_WIDTH + int(c)
                f.set_bit("standard", r, col)
                bits[r].add(col)
    return holder, bits


def run_queries(ex, parse_string, queries):
    from concurrent.futures import ThreadPoolExecutor

    def one(q):
        return ex.execute("i", parse_string(q))

    with ThreadPoolExecutor(max_workers=8) as pool:
        return list(pool.map(one, queries))


def main() -> int:
    import tempfile

    import jax

    from pilosa_tpu.exec import coalesce as coalesce_mod
    from pilosa_tpu.exec import plan
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.net import codec
    from pilosa_tpu.ops import bitplane as bp
    from pilosa_tpu.parallel import mesh as pmesh
    from pilosa_tpu.pql.parser import parse_string

    assert len(jax.local_devices()) == 8, jax.local_devices()
    assert bp.mesh_device_count() == 8
    assert pmesh.default_slices_mesh() is not None, (
        "sharded execution must engage by default with >1 device visible"
    )

    with tempfile.TemporaryDirectory() as tmp:
        holder, bits = build(tmp)

        # A distinct-query mix: pairwise Intersect+Count (fuses into
        # ICI-reduced "total" interpreter launches), row reads, TopN.
        count_qs = [
            f"Count(Intersect(Bitmap(rowID={a}, frame=f),"
            f" Bitmap(rowID={b}, frame=f)))"
            for a in range(ROWS)
            for b in range(a + 1, ROWS)
        ]
        row_q = "Bitmap(rowID=0, frame=f)"
        topn_q = "TopN(frame=f, n=4)"

        # --- sharded (default) pass, production path -------------------
        co = coalesce_mod.CoalesceScheduler()
        ex = Executor(holder, coalescer=co)
        try:
            sharded_counts = [
                int(r[0]) for r in run_queries(ex, parse_string, count_qs)
            ]
            (row_res,) = ex.execute("i", parse_string(row_q))
            sharded_bits = codec.bitmap_to_json(row_res)["bits"]
            (topn_res,) = ex.execute("i", parse_string(topn_q))
            sharded_topn = [(p.id, p.count) for p in topn_res]
            # The default batch really is mesh-sharded.
            call = parse_string(count_qs[0]).calls[0].children[0]
            ent = ex._cached_batch("i", call, list(range(N_SLICES)))
            assert ent["mesh"] is not None, "batch must be mesh-sharded"
            assert len(ent["batch"].devices()) == 8
            snap = co.snapshot()
            assert snap["launches"] > 0
        finally:
            ex.close()
            co.close()

        # Fragment planes spread over the mesh shards.
        view = holder.index("i").frame("f").view("standard")
        for frag in view.fragments():
            (dev,) = frag.device_plane().devices()
            assert dev == bp.home_device(frag.slice), (
                f"slice {frag.slice} plane on {dev}, want "
                f"{bp.home_device(frag.slice)}"
            )
        spread = {
            next(iter(f.device_plane().devices())) for f in view.fragments()
        }
        assert len(spread) == 8, f"planes on {len(spread)} devices, want 8"

        # --- numpy oracle ---------------------------------------------
        oracle = [
            len(bits[a] & bits[b])
            for a in range(ROWS)
            for b in range(a + 1, ROWS)
        ]
        assert sharded_counts == oracle, (sharded_counts, oracle)
        assert sharded_bits == sorted(bits[0])
        want_topn = sorted(
            ((r, len(bits[r])) for r in range(ROWS)),
            key=lambda p: (-p[1], p[0]),
        )[:4]
        assert sharded_topn == want_topn, (sharded_topn, want_topn)

        # --- forced single-device host path: byte-identical ------------
        bp.configure_mesh_devices(1)
        pmesh._slices_mesh = None
        try:
            assert pmesh.default_slices_mesh() is None
            co1 = coalesce_mod.CoalesceScheduler()
            ex1 = Executor(holder, coalescer=co1)
            try:
                host_counts = [
                    int(r[0]) for r in run_queries(ex1, parse_string, count_qs)
                ]
                (row1,) = ex1.execute("i", parse_string(row_q))
                host_bits = codec.bitmap_to_json(row1)["bits"]
                (topn1,) = ex1.execute("i", parse_string(topn_q))
                host_topn = [(p.id, p.count) for p in topn1]
            finally:
                ex1.close()
                co1.close()
        finally:
            bp.configure_mesh_devices(0)
            pmesh._slices_mesh = None
        assert sharded_counts == host_counts
        assert sharded_bits == host_bits
        assert sharded_topn == host_topn

        # --- interp program-cache entries within bounds ----------------
        stats = plan.program_cache_stats()
        bounds = plan.program_cache_bounds()
        assert stats["interp"] <= bounds["interp"], (stats, bounds)

        holder.close()

    log(
        f"OK: {len(count_qs)} distinct sharded counts + row + TopN "
        f"byte-identical to the single-device path and the numpy oracle;"
        f" planes spread over 8 shards; interp entries "
        f"{stats['interp']} <= bound {bounds['interp']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
