"""Anti-entropy: background convergence of replicas.

Three levels (reference: holder.go:357-556, fragment.go:1317-1498,
driven by server.go:200-236 every 10 minutes): column-attribute sync,
row-attribute sync, and fragment block sync with majority-consensus
merge.
"""

from pilosa_tpu.sync.syncer import FragmentSyncer, HolderSyncer

__all__ = ["FragmentSyncer", "HolderSyncer"]
