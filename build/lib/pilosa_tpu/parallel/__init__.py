"""Parallel layer: slice -> TPU-device sharding and mesh collectives.

reference equivalent: the slice->node map/reduce in executor.go:1131-1283
and the HTTP reduce fan-in — replaced intra-host by XLA collectives over
ICI (SURVEY.md §2.10 table).
"""

from pilosa_tpu.parallel.mesh import (
    AXIS_ROWS,
    AXIS_SLICES,
    distributed_count,
    distributed_topn,
    query_step,
    shard_planes,
    slice_mesh,
)

__all__ = [
    "AXIS_SLICES",
    "AXIS_ROWS",
    "slice_mesh",
    "shard_planes",
    "distributed_count",
    "distributed_topn",
    "query_step",
]
