"""Core storage hierarchy: Holder -> Index -> Frame -> View -> Fragment.

Mirrors the reference's data model (reference: docs/glossary.md): an
**Index** is a database; a **Frame** is a row namespace; a **View** is a
physical layout (standard / inverse / time-generated); a **Fragment** is
the intersection of one frame-view and one 2^20-column **slice** — here a
dense uint32 bit-plane that lives on host RAM authoritatively and is
mirrored into TPU HBM for query execution.
"""
