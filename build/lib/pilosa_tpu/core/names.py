"""Name and label validation (reference: pilosa.go:52,104-121)."""

from __future__ import annotations

import re

# reference: pilosa.go:52 — ^[a-z][a-z0-9_-]*$ capped at 64 chars
_NAME_RE = re.compile(r"^[a-z][a-z0-9_-]{0,63}$")
# labels allow mixed case (reference: pilosa.go:53)
_LABEL_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_-]{0,63}$")


class ValidationError(ValueError):
    pass


def validate_name(name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise ValidationError(f"invalid index or frame name: {name!r}")
    return name


def validate_label(label: str) -> str:
    if not _LABEL_RE.match(label or ""):
        raise ValidationError(f"invalid label: {label!r}")
    return label
