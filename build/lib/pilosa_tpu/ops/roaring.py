"""Roaring file-format codec, bit-compatible with the reference.

The on-disk format (reference: roaring/roaring.go:507-660) is the
framework's checkpoint format — keeping it byte-compatible means the
reference's ``pilosa check`` / ``pilosa inspect`` tools and backup tars
work unchanged against our data files, and golden files cut from either
implementation validate the other.

Layout (all little-endian):

    u32 cookie = 12346
    u32 containerCount                  # non-empty containers only
    containerCount * { u64 key, u32 n-1 }
    containerCount * { u32 offset }     # absolute byte offset of payload
    payloads:
        n <= 4096  -> n * u32 sorted low-bits ("array" container)
        n >  4096  -> 1024 * u64 bitmap words ("bitmap" container)
    op-log, repeated until EOF:
        u8 type (0=add, 1=remove), u64 value, u32 FNV-1a(first 9 bytes)

A container covers 2^16 bit-positions; its key is ``value >> 16``
(reference: roaring/roaring.go:1786-1787).  In-memory we do not keep
containers at all — decoding scatters straight into a dense numpy uint32
bit-plane and encoding re-sparsifies, choosing array vs bitmap form by
the same ArrayMaxSize = 4096 rule (reference: roaring/roaring.go:893).
"""

from __future__ import annotations

import io
import struct
from dataclasses import dataclass, field

import numpy as np

COOKIE = 12346
HEADER_SIZE = 8
ARRAY_MAX_SIZE = 4096
CONTAINER_BITS = 1 << 16
CONTAINER_WORDS64 = CONTAINER_BITS // 64  # 1024 u64 words ("bitmapN")
OP_SIZE = 13

OP_ADD = 0
OP_REMOVE = 1

_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193


def fnv1a32(data: bytes) -> int:
    """32-bit FNV-1a (stdlib has no FNV; matches Go's hash/fnv.New32a)."""
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFF
    return h


class CorruptError(ValueError):
    pass


@dataclass
class ContainerInfo:
    """Stats for one container (reference: roaring.ContainerInfo,
    roaring/roaring.go:669-683) — powers the ``inspect`` CLI."""

    key: int
    type: str  # "array" | "bitmap"
    n: int
    alloc: int


@dataclass
class BitmapInfo:
    ops: int
    containers: list[ContainerInfo] = field(default_factory=list)


def decode(data: bytes) -> dict[int, np.ndarray]:
    """Decode a roaring file into {container_key: uint64[1024] words},
    applying the trailing op-log (reference: roaring/roaring.go:567-646).

    Dispatches to the C++ codec (pilosa_tpu/native) when available; the
    Python path is the fallback and parity oracle."""
    return decode_with_ops(data)[0]


def decode_with_ops(data: bytes) -> tuple[dict[int, np.ndarray], int]:
    """decode() plus the replayed op count — one parse serves both the
    containers and Fragment.open's op-counter bookkeeping."""
    from pilosa_tpu import native

    try:
        res = native.decode(data)
    except native.NativeCorruptError as e:
        raise CorruptError(str(e)) from e
    if res is not None:
        return res
    containers, ops_offset, _ = _decode_containers(data)
    op_n = _apply_ops(containers, data, ops_offset)
    return containers, op_n


def _decode_containers(data: bytes):
    if len(data) < HEADER_SIZE:
        raise CorruptError("data too small")
    cookie, key_n = struct.unpack_from("<II", data, 0)
    if cookie != COOKIE:
        raise CorruptError("invalid roaring file")

    if HEADER_SIZE + key_n * 16 > len(data):
        raise CorruptError(
            f"header claims {key_n} containers but file is {len(data)} bytes"
        )
    keys = np.zeros(key_n, dtype=np.uint64)
    ns = np.zeros(key_n, dtype=np.int64)
    for i in range(key_n):
        key, n_minus_1 = struct.unpack_from("<QI", data, HEADER_SIZE + i * 12)
        keys[i] = key
        ns[i] = n_minus_1 + 1

    offsets_at = HEADER_SIZE + key_n * 12
    containers: dict[int, np.ndarray] = {}
    ops_offset = offsets_at + key_n * 4
    infos: list[ContainerInfo] = []
    for i in range(key_n):
        (offset,) = struct.unpack_from("<I", data, offsets_at + i * 4)
        if offset >= len(data):
            raise CorruptError(f"offset out of bounds: off={offset}, len={len(data)}")
        n = int(ns[i])
        key = int(keys[i])
        words = np.zeros(CONTAINER_WORDS64, dtype=np.uint64)
        payload_len = n * 4 if n <= ARRAY_MAX_SIZE else CONTAINER_WORDS64 * 8
        if offset + payload_len > len(data):
            raise CorruptError(
                f"container payload out of bounds: off={offset}, "
                f"need={payload_len}, len={len(data)}"
            )
        if n <= ARRAY_MAX_SIZE:
            values = np.frombuffer(data, dtype="<u4", count=n, offset=offset)
            if values.size and int(values.max()) >= CONTAINER_BITS:
                raise CorruptError(
                    f"array value out of range in container key={key}: "
                    f"{int(values.max())}"
                )
            widx = (values // 64).astype(np.int64)
            masks = np.uint64(1) << (values % 64).astype(np.uint64)
            np.bitwise_or.at(words, widx, masks)
            end = offset + n * 4
            infos.append(ContainerInfo(key, "array", n, n * 4))
        else:
            words[:] = np.frombuffer(
                data, dtype="<u8", count=CONTAINER_WORDS64, offset=offset
            )
            end = offset + CONTAINER_WORDS64 * 8
            infos.append(ContainerInfo(key, "bitmap", n, CONTAINER_WORDS64 * 8))
        containers[key] = words
        ops_offset = max(ops_offset, end)
    return containers, ops_offset, infos


def _apply_ops(containers: dict[int, np.ndarray], data: bytes, ops_offset: int) -> int:
    """Replay the op-log; returns the number of ops applied."""
    pos = ops_offset
    op_n = 0
    while pos < len(data):
        if len(data) - pos < OP_SIZE:
            raise CorruptError(f"op data out of bounds: len={len(data) - pos}")
        typ = data[pos]
        (value,) = struct.unpack_from("<Q", data, pos + 1)
        (chk,) = struct.unpack_from("<I", data, pos + 9)
        want = fnv1a32(data[pos : pos + 9])
        if chk != want:
            raise CorruptError(f"checksum mismatch: exp={want:08x}, got={chk:08x}")
        key = value >> 16
        word, shift = divmod(value & 0xFFFF, 64)
        if key not in containers:
            containers[key] = np.zeros(CONTAINER_WORDS64, dtype=np.uint64)
        mask = np.uint64(1) << np.uint64(shift)
        if typ == OP_ADD:
            containers[key][word] |= mask
        elif typ == OP_REMOVE:
            containers[key][word] &= ~mask
        else:
            raise CorruptError(f"invalid op type: {typ}")
        pos += OP_SIZE
        op_n += 1
    return op_n


def encode(containers: dict[int, np.ndarray]) -> bytes:
    """Serialize {container_key: uint64[1024]} to the reference file format.

    Empty containers are dropped (reference: roaring/roaring.go:510-531
    skips c.n == 0).  Containers with <= 4096 bits are written in array
    form, else bitmap form.  Dispatches to the C++ codec when available.
    """
    from pilosa_tpu import native

    res = native.encode(containers)
    if res is not None:
        return res
    keys = sorted(k for k, w in containers.items() if _words_count(w) > 0)
    header = bytearray()
    header += struct.pack("<II", COOKIE, len(keys))

    payloads: list[bytes] = []
    ns: list[int] = []
    for key in keys:
        words = containers[key]
        n = _words_count(words)
        ns.append(n)
        if n <= ARRAY_MAX_SIZE:
            payloads.append(_words_to_array_bytes(words))
        else:
            payloads.append(words.astype("<u8", copy=False).tobytes())

    for key, n in zip(keys, ns):
        header += struct.pack("<QI", key, n - 1)
    offset = len(header) + 4 * len(keys)
    for p in payloads:
        header += struct.pack("<I", offset)
        offset += len(p)

    out = io.BytesIO()
    out.write(bytes(header))
    for p in payloads:
        out.write(p)
    return out.getvalue()


def encode_op(typ: int, value: int) -> bytes:
    """One 13-byte op-log record (reference: roaring/roaring.go:1746-1762)."""
    buf = struct.pack("<BQ", typ, value)
    return buf + struct.pack("<I", fnv1a32(buf))


def _words_count(words: np.ndarray) -> int:
    return int(np.unpackbits(words.view(np.uint8)).sum())


def _words_to_array_bytes(words: np.ndarray) -> bytes:
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    (positions,) = np.nonzero(bits)
    return positions.astype("<u4").tobytes()


def info(data: bytes) -> BitmapInfo:
    """Container stats + op count for ``inspect`` (reference:
    roaring.Bitmap.Info, roaring/roaring.go:669-683, ctl/inspect.go)."""
    containers, ops_offset, infos = _decode_containers(data)
    op_n = _apply_ops(containers, data, ops_offset)
    return BitmapInfo(ops=op_n, containers=infos)


def check(data: bytes) -> list[str]:
    """Consistency check (reference: roaring.Bitmap.Check,
    roaring/roaring.go:686-706, driven by ctl/check.go).  Returns a list
    of problem strings, empty when healthy."""
    errs: list[str] = []
    try:
        containers, ops_offset, infos = _decode_containers(data)
    except CorruptError as e:
        return [str(e)]
    for ci in infos:
        actual = _words_count(containers[ci.key])
        if ci.n != actual:
            errs.append(
                f"container key={ci.key} count mismatch: n={ci.n}, count={actual}"
            )
    try:
        _apply_ops(containers, data, ops_offset)
    except CorruptError as e:
        errs.append(str(e))
    return errs


# ---------------------------------------------------------------------------
# Bridges between the container dict and the dense slice-row planes used by
# pilosa_tpu.core.fragment.  A fragment file covers bit positions
# row*SLICE_WIDTH + (column % SLICE_WIDTH); container key k covers positions
# [k*2^16, (k+1)*2^16) — i.e. 16 consecutive containers per row.
# ---------------------------------------------------------------------------


def containers_to_plane(containers: dict[int, np.ndarray], slice_width: int) -> np.ndarray:
    """Densify into a (rows, slice_width/32) uint32 plane."""
    per_row = slice_width // CONTAINER_BITS
    max_key = max(containers.keys(), default=-1)
    rows = (max_key // per_row) + 1 if max_key >= 0 else 0
    plane = np.zeros((max(rows, 1), slice_width // 32), dtype=np.uint32)
    words32_per_container = CONTAINER_BITS // 32
    for key, words in containers.items():
        row, cidx = divmod(key, per_row)
        lo = cidx * words32_per_container
        plane[row, lo : lo + words32_per_container] = words.view("<u4").astype(np.uint32)
    return plane


def plane_to_containers(plane: np.ndarray, slice_width: int) -> dict[int, np.ndarray]:
    """Sparsify a (rows, slice_width/32) plane into the container dict."""
    per_row = slice_width // CONTAINER_BITS
    words32_per_container = CONTAINER_BITS // 32
    out: dict[int, np.ndarray] = {}
    nz_rows = np.nonzero(plane.any(axis=1))[0]
    for row in nz_rows:
        for cidx in range(per_row):
            lo = cidx * words32_per_container
            chunk = plane[row, lo : lo + words32_per_container]
            if chunk.any():
                out[int(row) * per_row + cidx] = np.ascontiguousarray(chunk).view(
                    np.uint64
                ).copy()
    return out


def containers_to_row_map(
    containers: dict[int, np.ndarray], slice_width: int
) -> dict[int, np.ndarray]:
    """Sparse densify: container dict -> {row_id: uint32[slice_width/32]}.

    Unlike :func:`containers_to_plane`, memory scales with *touched* rows,
    so tall-sparse fragments (inverse views, high rowIDs) stay cheap —
    the dense-plane analog of roaring's pay-per-container storage.
    """
    per_row = slice_width // CONTAINER_BITS
    words32_per_container = CONTAINER_BITS // 32
    out: dict[int, np.ndarray] = {}
    for key, words in containers.items():
        row, cidx = divmod(key, per_row)
        r = out.get(row)
        if r is None:
            r = out[row] = np.zeros(slice_width // 32, dtype=np.uint32)
        lo = cidx * words32_per_container
        r[lo : lo + words32_per_container] = words.view("<u4").astype(np.uint32)
    return out


def row_map_to_containers(
    row_map: dict[int, np.ndarray], slice_width: int
) -> dict[int, np.ndarray]:
    """Inverse of :func:`containers_to_row_map`; empty containers are
    dropped (the reference never serializes empty containers)."""
    per_row = slice_width // CONTAINER_BITS
    words32_per_container = CONTAINER_BITS // 32
    out: dict[int, np.ndarray] = {}
    for row in sorted(row_map):
        words = row_map[row]
        for cidx in range(per_row):
            lo = cidx * words32_per_container
            chunk = words[lo : lo + words32_per_container]
            if chunk.any():
                out[int(row) * per_row + cidx] = (
                    np.ascontiguousarray(chunk).view(np.uint64).copy()
                )
    return out
