"""net — the HTTP+protobuf surface: handler, internal client, server.

External compatibility layer: the route table, wire messages, and JSON
shapes match the reference server (reference: handler.go, client.go,
internal/*.proto), so existing clients and multi-node deployments keep
working while the data plane underneath runs on XLA.
"""
