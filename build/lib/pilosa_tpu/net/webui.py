"""Embedded web console.

A single-page query console served from `/` — the counterpart of the
reference's statik-embedded WebUI (reference: webui/index.html,
webui/assets/main.js, handler.go:169-182).  Re-written from scratch:
query box POSTs PQL to /index/<index>/query, cluster state from
/status, schema browser from /schema.
"""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>pilosa-tpu console</title>
<link rel="stylesheet" href="/assets/main.css">
</head>
<body>
<header><h1>pilosa-tpu</h1><span id="version"></span></header>
<main>
  <section id="query-section">
    <h2>Query</h2>
    <div class="row">
      <input id="index-name" placeholder="index" value="">
      <button id="run">Run &#9654;</button>
    </div>
    <textarea id="query" rows="4"
      placeholder="Count(Bitmap(frame='f', rowID=1))"></textarea>
    <pre id="output"></pre>
  </section>
  <section id="schema-section">
    <h2>Schema</h2>
    <pre id="schema"></pre>
  </section>
  <section id="cluster-section">
    <h2>Cluster</h2>
    <pre id="cluster"></pre>
  </section>
</main>
<script src="/assets/main.js"></script>
</body>
</html>
"""

MAIN_JS = """'use strict';
function get(url, cb) {
  fetch(url).then(function (r) { return r.json(); }).then(cb)
    .catch(function (e) { console.error(url, e); });
}
function refresh() {
  get('/version', function (v) {
    document.getElementById('version').textContent = 'v' + v.version;
  });
  get('/schema', function (s) {
    document.getElementById('schema').textContent =
      JSON.stringify(s.indexes, null, 2);
    var first = s.indexes && s.indexes[0];
    var input = document.getElementById('index-name');
    if (first && !input.value) input.value = first.name;
  });
  get('/status', function (s) {
    document.getElementById('cluster').textContent =
      JSON.stringify(s.status, null, 2);
  });
}
document.getElementById('run').addEventListener('click', function () {
  var index = document.getElementById('index-name').value;
  var query = document.getElementById('query').value;
  fetch('/index/' + encodeURIComponent(index) + '/query', {
    method: 'POST', body: query,
  }).then(function (r) { return r.json(); }).then(function (out) {
    document.getElementById('output').textContent =
      JSON.stringify(out, null, 2);
    refresh();
  }).catch(function (e) {
    document.getElementById('output').textContent = String(e);
  });
});
refresh();
"""

MAIN_CSS = """body { font-family: monospace; margin: 0; background: #111;
  color: #dcdcdc; }
header { padding: 0.6rem 1rem; background: #222; display: flex;
  align-items: baseline; gap: 1rem; }
h1 { font-size: 1.1rem; margin: 0; color: #7fd4ff; }
h2 { font-size: 0.95rem; color: #9fe89f; }
main { padding: 1rem; max-width: 60rem; }
.row { display: flex; gap: 0.5rem; margin-bottom: 0.5rem; }
input, textarea { width: 100%; background: #1b1b1b; color: #dcdcdc;
  border: 1px solid #333; padding: 0.4rem; font-family: inherit; }
button { background: #245; color: #cfe; border: 1px solid #368;
  padding: 0.4rem 1rem; cursor: pointer; }
pre { background: #1b1b1b; border: 1px solid #333; padding: 0.6rem;
  overflow: auto; min-height: 1rem; }
"""

ASSETS = {
    "main.js": (MAIN_JS, "application/javascript"),
    "main.css": (MAIN_CSS, "text/css"),
}
