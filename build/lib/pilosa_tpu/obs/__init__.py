"""Observability: stats clients, hierarchical tags, latency histograms.

reference: stats.go (StatsClient interface + nop/expvar/multi impls),
statsd/statsd.go (DataDog dogstatsd client).
"""

from pilosa_tpu.obs.stats import (
    ExpvarStatsClient,
    MultiStatsClient,
    NopStatsClient,
    StatsDClient,
    new_stats_client,
)

__all__ = [
    "ExpvarStatsClient",
    "MultiStatsClient",
    "NopStatsClient",
    "StatsDClient",
    "new_stats_client",
]
