"""Cluster messaging — schema broadcast + membership abstractions.

The control plane carries five schema messages between nodes so every
node can route queries for indexes/frames it has never written
(reference: broadcast.go:26-166):

  CreateSliceMessage  — a view grew a new max slice
  CreateIndexMessage / DeleteIndexMessage
  CreateFrameMessage / DeleteFrameMessage

Messages travel as a 1-byte type tag + protobuf payload.  Three
transports, selected by ``cluster.type`` config:

  static — no-op broadcaster, fixed node list (single node / tests)
  http   — POST the envelope to every peer's internal listener
           (reference: httpbroadcast/)
  gossip — UDP gossip membership + TCP sync broadcast
           (reference: gossip/ on hashicorp/memberlist); see
           cluster/gossip.py
"""

from __future__ import annotations

from typing import Protocol

from pilosa_tpu.net import wire_pb2 as wire

# Message type bytes (reference: broadcast.go:109-124)
MSG_CREATE_SLICE = 1
MSG_CREATE_INDEX = 2
MSG_DELETE_INDEX = 3
MSG_CREATE_FRAME = 4
MSG_DELETE_FRAME = 5

_TYPE_OF = {
    wire.CreateSliceMessage: MSG_CREATE_SLICE,
    wire.CreateIndexMessage: MSG_CREATE_INDEX,
    wire.DeleteIndexMessage: MSG_DELETE_INDEX,
    wire.CreateFrameMessage: MSG_CREATE_FRAME,
    wire.DeleteFrameMessage: MSG_DELETE_FRAME,
}

_CLASS_OF = {v: k for k, v in _TYPE_OF.items()}


def marshal_message(msg) -> bytes:
    """type byte + protobuf payload (reference: broadcast.go:126-146)."""
    typ = _TYPE_OF.get(type(msg))
    if typ is None:
        raise ValueError(f"message type not implemented: {type(msg).__name__}")
    return bytes([typ]) + msg.SerializeToString()


def unmarshal_message(data: bytes):
    """reference: broadcast.go:148-166"""
    if not data:
        raise ValueError("empty message")
    cls = _CLASS_OF.get(data[0])
    if cls is None:
        raise ValueError(f"invalid message type: {data[0]}")
    msg = cls()
    msg.ParseFromString(data[1:])
    return msg


class Broadcaster(Protocol):
    """reference: broadcast.go:61-64"""

    def send_sync(self, msg) -> None: ...
    def send_async(self, msg) -> None: ...


class BroadcastHandler(Protocol):
    """Implemented by Server (reference: broadcast.go:87-89)."""

    def receive_message(self, msg) -> None: ...


class BroadcastReceiver(Protocol):
    """reference: broadcast.go:96-100"""

    def start(self, handler: BroadcastHandler) -> None: ...


class NodeSet(Protocol):
    """Membership view (reference: broadcast.go:26-32)."""

    def nodes(self) -> list[str]: ...
    def open(self) -> None: ...


# ---------------------------------------------------------------------------
# static (single node / tests) — reference: broadcast.go:34-58,70-107
# ---------------------------------------------------------------------------


class NopBroadcaster:
    def send_sync(self, msg) -> None:
        pass

    def send_async(self, msg) -> None:
        pass


class NopBroadcastReceiver:
    def start(self, handler) -> None:
        pass


class StaticNodeSet:
    """Fixed host list from config."""

    def __init__(self, hosts: list[str] | None = None):
        self._hosts = list(hosts or [])

    def nodes(self) -> list[str]:
        return list(self._hosts)

    def open(self) -> None:
        pass

    def join(self, hosts: list[str]) -> None:
        for h in hosts:
            if h not in self._hosts:
                self._hosts.append(h)


# ---------------------------------------------------------------------------
# http broadcast — reference: httpbroadcast/messenger.go
# ---------------------------------------------------------------------------


class HTTPBroadcaster:
    """POST the message envelope to every peer's internal endpoint
    (reference: httpbroadcast/messenger.go:43-122).  Peers run an
    HTTPBroadcastReceiver on ``internal_host``."""

    def __init__(self, internal_hosts: list[str], timeout: float = 10.0):
        self.internal_hosts = list(internal_hosts)
        self.timeout = timeout

    def _post(self, host: str, payload: bytes) -> None:
        import http.client

        conn = http.client.HTTPConnection(host, timeout=self.timeout)
        try:
            conn.request(
                "POST",
                "/messages",
                body=payload,
                headers={"Content-Type": "application/octet-stream"},
            )
            resp = conn.getresponse()
            resp.read()
            if resp.status >= 400:
                raise RuntimeError(f"broadcast to {host}: http {resp.status}")
        finally:
            conn.close()

    def send_sync(self, msg) -> None:
        payload = marshal_message(msg)
        errors = []
        for host in self.internal_hosts:
            try:
                self._post(host, payload)
            except Exception as e:  # noqa: BLE001 — collect per-peer errors
                errors.append(f"{host}: {e}")
        if errors:
            raise RuntimeError("; ".join(errors))

    def send_async(self, msg) -> None:
        import threading

        payload = marshal_message(msg)
        for host in self.internal_hosts:
            threading.Thread(
                target=lambda h=host: self._safe_post(h, payload), daemon=True
            ).start()

    def _safe_post(self, host: str, payload: bytes) -> None:
        try:
            self._post(host, payload)
        except Exception:  # noqa: BLE001 — async is best-effort
            pass


class HTTPBroadcastReceiver:
    """Second HTTP listener for inter-node messages (reference:
    httpbroadcast/messenger.go:139-175; default internal port 14000)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, logger=None):
        self.host = host
        self.port = port
        self.logger = logger or (lambda m: None)
        self._server = None
        self._thread = None

    @property
    def bound_host(self) -> str:
        if self._server is None:
            return f"{self.host}:{self.port}"
        addr = self._server.server_address
        return f"{addr[0]}:{addr[1]}"

    def start(self, handler) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        import threading

        logger = self.logger

        class _Receiver(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_POST(self):
                if self.path != "/messages":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length") or 0)
                data = self.rfile.read(length)
                try:
                    msg = unmarshal_message(data)
                    handler.receive_message(msg)
                except Exception as e:  # noqa: BLE001 — peer boundary
                    logger(f"receive message error: {e}")
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, fmt, *args):
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), _Receiver)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()


class HTTPNodeSet:
    """Static membership for the http cluster type (reference:
    httpbroadcast/messenger.go:177-201)."""

    def __init__(self, hosts: list[str] | None = None):
        self._hosts = list(hosts or [])

    def nodes(self) -> list[str]:
        return list(self._hosts)

    def open(self) -> None:
        pass

    def join(self, hosts: list[str]) -> None:
        for h in hosts:
            if h not in self._hosts:
                self._hosts.append(h)
