"""Cluster layer: topology, membership, broadcast.

reference: cluster.go, broadcast.go, gossip/, httpbroadcast/
"""

from pilosa_tpu.cluster.topology import (
    DEFAULT_PARTITION_N,
    DEFAULT_REPLICA_N,
    Cluster,
    Node,
    fnv64a,
    jump_hash,
)

__all__ = [
    "Cluster",
    "Node",
    "fnv64a",
    "jump_hash",
    "DEFAULT_PARTITION_N",
    "DEFAULT_REPLICA_N",
]
