import sys

from pilosa_tpu.cli.main import main

sys.exit(main())
