"""Command line interface.

Subcommand surface matches the reference CLI (reference: cmd/*.go +
ctl/*.go): server, import, export, backup, restore, check, inspect,
bench, sort, config, generate-config.  Run as ``python -m
pilosa_tpu.cli <subcommand>``.
"""

from pilosa_tpu.cli.main import main

__all__ = ["main"]
