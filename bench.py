"""Benchmark: 1B-column PQL Intersect+Count throughput (BASELINE.json
north_star / configs[3]-shaped workload).

Measures three tiers on the accelerator, logging all to stderr:

1. RAW KERNEL — the fused AND+popcount program over a pre-staged
   [954, 2, 32768] device batch (the compute ceiling).  Distinct input
   batches are cycled so a result-caching tunnel cannot fake the number.
2. END-TO-END EXECUTOR — the same query as PQL text through
   ``Executor.execute`` against a real Holder with 954 fragments:
   parsing, leaf resolution, batch assembly/caching, reduce
   (reference path: handlePostQuery -> mapReduce,
   executor.go:1246-1282).  BASELINE's north-star metric is THIS.
3. TopN — the real executor path over ranked-cache candidates
   (reference: fragment.go:505-639, executor.go:281-321; all-local
   queries take the folded single-device-fetch protocol, which returns
   results identical to the reference's two-phase refetch).

BANDWIDTH ACCOUNTING: the fused Intersect+Count reads two operands of
total_columns/8 bytes each and writes nothing that leaves the chip, so
effective bytes/query = total_columns/4.  Every Gcols/s figure is
accompanied by effective GB/s and % of HBM peak (v5e ~819 GB/s) so the
distance to the memory-bound ceiling is visible in the artifacts.

THROUGHPUT vs LATENCY: the executor tiers report (a) single-query
synchronous p50 latency and (b) per-query time under CONCURRENT load
(a thread pool issuing many queries at once — how the reference's
HTTP server runs, one goroutine per request).  The headline is the
concurrent throughput: BASELINE's north star is "rows/sec", and when
the TPU sits behind a network tunnel (axon), a synchronous single
query pays a fixed ~70 ms round trip that measures the tunnel, not
the engine — concurrent queries overlap those round trips exactly
like production traffic would.  Both numbers go to stderr.

The host-CPU numpy ``bitwise_count`` pass stands in for the reference's
Go/amd64 POPCNT roaring loop (reference: roaring/assembly_amd64.s);
goal >=10x (BASELINE.md).

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
(the end-to-end executor throughput — the honest number).
Progress goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def wait_for_backend(attempts: int = 14, delay_s: float = 60.0) -> bool:
    """Probe accelerator init in SUBPROCESSES until one succeeds;
    returns False if the accelerator never comes up.

    The axon TPU tunnel can be wedged for many minutes after an earlier
    killed process (leaked session grant); a failed in-process backend
    init is cached by JAX, so probing must happen out-of-process.  Turns
    a transiently-wedged tunnel into a delayed bench instead of a
    crashed one (round-1 BENCH artifact failure mode)."""
    import subprocess
    import time as _time

    hung = 0
    for i in range(attempts):
        # Generous timeout until the tunnel has HUNG three times (a
        # fast-failing probe says nothing about init/compile time);
        # shorter after that, so a dead tunnel reaches the CPU fallback
        # in ~1.5h instead of ~3.5h.
        probe_timeout = 900 if hung < 3 else 240
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices(); print('OK')"],
                capture_output=True,
                text=True,
                timeout=probe_timeout,
            )
        except subprocess.TimeoutExpired:
            hung += 1
            log(
                f"backend probe {i + 1}/{attempts} HUNG ({probe_timeout}s);"
                f" retrying in {delay_s:.0f}s"
            )
            _time.sleep(delay_s)
            continue
        if probe.returncode == 0 and "OK" in probe.stdout:
            return True
        tail = (probe.stderr or probe.stdout).strip().splitlines()
        log(
            f"backend probe {i + 1}/{attempts} failed"
            f" ({tail[-1] if tail else 'no output'}); retrying in {delay_s:.0f}s"
        )
        _time.sleep(delay_s)
    return False


def reexec_cpu_fallback() -> None:
    """The accelerator never came up: re-exec this bench on the CPU
    backend in a fresh process (in-process fallback is impossible — a
    wedged tunnel HANGS backend init rather than failing it).  The
    artifact then records an honest, clearly-labeled CPU number instead
    of a crash (the r01 failure mode)."""
    import subprocess

    log("TPU backend never came up after all probes; "
        "re-running the ENTIRE bench on the CPU backend (metric will be "
        "labeled *_cpu_fallback — NOT comparable to TPU rounds)")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["BENCH_CPU_FALLBACK"] = "1"
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)], env=env)
    sys.exit(proc.returncode)


def with_retries(label: str, fn, attempts: int = 3, delay_s: float = 90.0):
    """Run ``fn()`` with retries: the axon tunnel can drop mid-run
    (UNAVAILABLE backend errors) and recover once the pool session
    re-establishes.  Re-probes the backend (in a subprocess) before
    each retry so a wedged grant gets time to expire."""
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — transient tunnel faults
            if i == attempts - 1:
                raise
            log(f"{label} attempt {i + 1}/{attempts} failed ({e!r:.300}); retrying in {delay_s:.0f}s")
            time.sleep(delay_s)
            wait_for_backend(attempts=3, delay_s=60.0)


def measure_slope(fold, lo_in, hi_in, bytes_per, sanity_peak, log_fn,
                  epochs: int = 6, tries: int = 3) -> float | None:
    """Interleaved lo/hi fetch-folded slope with HBM-peak plausibility
    retry — THE slope methodology, shared by bench.py and
    tools/cache_probe.py.  ``fold(inputs) -> wall seconds`` must force
    execution (fold outputs into one fetched scalar); lo/hi epochs
    interleave so both see the same pool conditions; a slope implying
    more operand bandwidth than the chip's HBM peak is retried and
    ultimately reported as None rather than published."""
    n = len(hi_in) - len(lo_in)
    for attempt in range(tries):
        lo = hi = float("inf")
        for _ in range(epochs):
            lo = min(lo, fold(lo_in))
            hi = min(hi, fold(hi_in))
        s = (hi - lo) / n
        if s > 0 and (sanity_peak is None or bytes_per / s <= sanity_peak):
            return s
        log_fn(
            f"slope measurement implausible (slope {s*1e6:.1f} us/run);"
            f" pool interference — retry {attempt + 1}/{tries}"
        )
    return None


def hbm_peak_bytes_s(jax_mod) -> float | None:
    """Per-generation HBM peak for the %-of-peak roofline figure; None
    (omit the percentage) for unrecognized device kinds rather than
    reporting against the wrong ceiling."""
    kind = jax_mod.devices()[0].device_kind.lower()
    for pat, peak in (
        ("v5 lite", 819e9), ("v5e", 819e9), ("v5litepod", 819e9),
        ("v6 lite", 1640e9), ("v6e", 1640e9),
        ("v5p", 2765e9), ("v5", 2765e9),
        ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
    ):
        if pat in kind:
            return peak
    log(f"unknown TPU device kind {kind!r}: omitting %-of-HBM-peak")
    return None


def prime_fragment(frag, rows: np.ndarray, pad_rows_fn) -> None:
    """Plane-inject ``rows`` (uint32[n, words]) into a fragment and
    prime its caches — shared by every bench tier (the import path is
    not what the bench measures)."""
    n = rows.shape[0]
    plane = np.zeros((pad_rows_fn(n), rows.shape[1]), np.uint32)
    plane[:n] = rows
    counts = np.bitwise_count(rows).sum(axis=-1, dtype=np.int64)
    frag._plane = plane
    frag._slot_of = {r: r for r in range(n)}
    frag._count_of = {r: int(counts[r]) for r in range(n)}
    frag._max_row_id = n - 1
    frag._version += 1
    for r in range(n):
        frag.cache.bulk_add(r, int(counts[r]))
    frag.cache.invalidate()


def build_holder(leaves: np.ndarray, data_dir: str):
    """A real Holder with one fragment per slice holding rows {1, 2}
    from ``leaves`` (uint32[n_slices, 2, words]) — plane-injected (the
    import path is not what this bench measures)."""
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.ops import bitplane as bp

    holder = Holder(data_dir)
    holder.open()
    idx = holder.create_index("i")
    f = idx.create_frame("f")
    view = f.create_view_if_not_exists("standard")
    # Rows 1 and 2 occupy slots 0 and 1 (shifted ids, so prime_fragment
    # does not fit); plane-inject directly.
    counts = np.bitwise_count(leaves).sum(axis=-1, dtype=np.int64)
    for s in range(leaves.shape[0]):
        frag = view.create_fragment_if_not_exists(s)
        plane = np.zeros((bp.pad_rows(2), leaves.shape[2]), np.uint32)
        plane[:2] = leaves[s]
        frag._plane = plane
        frag._slot_of = {1: 0, 2: 1}
        frag._count_of = {1: int(counts[s, 0]), 2: int(counts[s, 1])}
        frag._max_row_id = 2
        frag._version += 1
    return holder


def main() -> None:
    # The re-exec marker only counts with the CPU platform actually
    # forced — a leaked BENCH_CPU_FALLBACK alone must not skip the
    # wedge-avoiding probe or mislabel a TPU number.
    cpu_fallback = (
        os.environ.get("BENCH_CPU_FALLBACK") == "1"
        and os.environ.get("JAX_PLATFORMS") == "cpu"
    )
    if not cpu_fallback and not wait_for_backend():
        reexec_cpu_fallback()

    import jax
    import jax.numpy as jnp

    from pilosa_tpu.exec import plan, warmup
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH, WORDS_PER_SLICE
    from pilosa_tpu.pql.parser import parse_string

    # Persistent XLA compile cache (exec/warmup.py): restarts
    # deserialize the fused programs from disk instead of recompiling —
    # the fix for the 5 s cold query.  The dir lives next to bench.py so
    # it survives across driver rounds.
    cache_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax-compile-cache"
    )
    had_cache = os.path.isdir(cache_dir) and bool(os.listdir(cache_dir))
    warmup.enable_compile_cache(cache_dir)
    log(f"compile cache: {cache_dir} ({'warm' if had_cache else 'cold'})")
    # Restart probes (fresh subprocesses, sequential — never concurrent
    # with this process's device use): first run populates the disk
    # cache (true cold compile), second measures a process restart
    # loading it.  Run BEFORE this process touches the backend so the
    # TPU tunnel only ever has one client.
    restart_probe: dict = {}
    if os.environ.get("BENCH_SKIP_RESTART_PROBE") != "1":
        import subprocess

        probe = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools",
            "compile_probe_restart.py",
        )
        for label in ("cold" if not had_cache else "warm-disk", "restart"):
            try:
                out = subprocess.run(
                    [sys.executable, probe, cache_dir],
                    capture_output=True,
                    timeout=600,
                    text=True,
                )
                if out.returncode != 0 or not out.stdout.strip():
                    log(f"restart probe failed ({label}): rc={out.returncode} "
                        f"stderr={out.stderr.strip()[-300:]!r}")
                    break
                t = float(out.stdout.strip().splitlines()[-1])
                restart_probe[label.replace("-", "_") + "_compile_s"] = round(
                    t, 3
                )
                log(f"headline-program compile, fresh process ({label}): "
                    f"{t*1e3:.0f} ms")
            except Exception as e:
                log(f"restart probe failed ({label}): {e}")
                break

    # Cluster-reduce tier (BASELINE configs[4] shape): runs in a CPU
    # subprocess BEFORE this process touches the device — coordinator
    # fan-out/reduce overhead is host-side and must not ride the shared
    # TPU pool's variance.  ~1 min.
    cluster_reduce = None
    if os.environ.get("BENCH_SKIP_CLUSTER_TIER") != "1":
        import subprocess

        cb = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools", "cluster_bench.py"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        try:
            out = subprocess.run(
                [sys.executable, cb], env=env, capture_output=True,
                timeout=900, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                for line in out.stderr.strip().splitlines():
                    log(line)
                last = out.stdout.strip().splitlines()[-1]
                log(f"cluster_reduce tier: {last}")
                try:
                    cluster_reduce = json.loads(last)
                except json.JSONDecodeError:
                    pass
            else:
                log(f"cluster tier failed: rc={out.returncode} "
                    f"stderr={out.stderr.strip()[-300:]!r}")
        except Exception as e:
            log(f"cluster tier failed: {e}")

    # Admission-storm tier: the open-loop sustained-load harness
    # (tools/load_harness.py) self-boots a node twice — admission ON
    # then OFF — and sweeps offered load past 2-3x capacity, recording
    # goodput-vs-offered-load and the max-sustained-QPS-at-p99-SLO
    # figure.  A CPU subprocess like the cluster tier: admission and
    # the HTTP/queue path under storm are host-side, and the open-loop
    # generator must not contend with this process's device work.
    admission_storm = None
    if os.environ.get("BENCH_SKIP_ADMISSION_TIER") != "1":
        import subprocess

        lh = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools", "load_harness.py"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        try:
            out = subprocess.run(
                [sys.executable, lh, "--self-boot", "--compare",
                 "--slices", "8", "--duration", "5", "--deadline-ms", "500",
                 "--slo-ms", "250",
                 # Gates sized to the CPU node this tier boots (see
                 # docs/administration.md "Sizing the gates"): C/S*1000
                 # against single-digit-ms service times.  The config
                 # defaults are sized for TPU-class nodes and would
                 # over-admit here.
                 "--point-concurrency", "4", "--heavy-concurrency", "2",
                 "--write-concurrency", "2", "--queue-depth", "4"],
                env=env, capture_output=True, timeout=900, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                for line in out.stderr.strip().splitlines():
                    log(line)
                admission_storm = json.loads(
                    out.stdout.strip().splitlines()[-1]
                )
                log(
                    "admission_storm tier: max sustained "
                    f"{admission_storm['max_sustained_qps_at_p99_slo']} qps "
                    "at p99 SLO"
                )
            else:
                log(f"admission tier failed: rc={out.returncode} "
                    f"stderr={out.stderr.strip()[-300:]!r}")
        except Exception as e:
            log(f"admission tier failed: {e}")

    # Rebalance tier: live 2->3 grow under sustained load, one process
    # per node (tools/rebalance_bench.py) — read p50/p99 during the
    # background slice migration vs steady state, migration seconds,
    # zero-lost-writes and byte-identical-results checks.  Host-side
    # like the other cluster tiers; runs before this process touches
    # the device.
    rebalance_tier = None
    if os.environ.get("BENCH_SKIP_REBALANCE_TIER") != "1":
        import subprocess

        rbt = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools",
            "rebalance_bench.py",
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        try:
            out = subprocess.run(
                [sys.executable, rbt], env=env, capture_output=True,
                timeout=900, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                for line in out.stderr.strip().splitlines():
                    log(line)
                rebalance_tier = json.loads(out.stdout.strip().splitlines()[-1])
                log(
                    "rebalance tier: migration "
                    f"{rebalance_tier['migration_s']}s, read p99 "
                    f"{rebalance_tier['p99_ratio']}x steady, "
                    f"{rebalance_tier['writes_lost']} writes lost"
                )
            else:
                log(f"rebalance tier failed: rc={out.returncode} "
                    f"stderr={out.stderr.strip()[-300:]!r}")
        except Exception as e:
            log(f"rebalance tier failed: {e}")

    # Replication tier (ISSUE 14 / ROADMAP 4): quorum write latency at
    # one/quorum/all over a 3-node replica-3 cluster, plus the hinted-
    # handoff drain rate — kill a replica under a quorum write burst,
    # restart it, time breaker-triggered replay to checksum
    # convergence (tools/replication_bench.py subprocess, CPU).
    replication_tier = None
    if os.environ.get("BENCH_SKIP_REPLICATION_TIER") != "1":
        import subprocess

        rpt = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools",
            "replication_bench.py",
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        try:
            out = subprocess.run(
                [sys.executable, rpt], env=env, capture_output=True,
                timeout=900, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                for line in out.stderr.strip().splitlines():
                    if line.startswith("[replication]"):
                        log(line)
                replication_tier = json.loads(
                    out.stdout.strip().splitlines()[-1]
                )
                hr = replication_tier.get("hint_replay", {})
                log(
                    "replication tier: quorum write p99 "
                    f"{replication_tier['writes']['quorum']['p99_ms']} ms, "
                    f"hint drain {hr.get('hints_per_s')}/s "
                    f"(converged={hr.get('converged')})"
                )
            else:
                log(f"replication tier failed: rc={out.returncode} "
                    f"stderr={out.stderr.strip()[-300:]!r}")
        except Exception as e:
            log(f"replication tier failed: {e}")

    # Degraded tier (ISSUE 15): device-fault tolerance figures —
    # healthy vs quarantined-host-fallback Count Gcols/s + p50/p99
    # (every degraded answer byte-checked), queries-to-quarantine at
    # the configured threshold, and the watchdog trip recovery time
    # for a hang injected inside the collective dispatch
    # (tools/degraded_bench.py subprocess on the virtual mesh, CPU).
    degraded_tier = None
    if os.environ.get("BENCH_SKIP_DEGRADED_TIER") != "1":
        import subprocess

        dgt = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools",
            "degraded_bench.py",
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        try:
            out = subprocess.run(
                [sys.executable, dgt], env=env, capture_output=True,
                timeout=900, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                for line in out.stderr.strip().splitlines():
                    if line.startswith("[degraded]"):
                        log(line)
                degraded_tier = json.loads(out.stdout.strip().splitlines()[-1])
                log(
                    "degraded tier: healthy "
                    f"{degraded_tier['healthy']['gcols_s']} Gcols/s vs "
                    f"host-fallback {degraded_tier['degraded']['gcols_s']} "
                    f"Gcols/s; watchdog trip recovery "
                    f"{degraded_tier['watchdog']['trip_recovery_ms']} ms"
                )
            else:
                log(f"degraded tier failed: rc={out.returncode} "
                    f"stderr={out.stderr.strip()[-300:]!r}")
        except Exception as e:
            log(f"degraded tier failed: {e}")

    # Standing-query tier (ISSUE 16): N >= 1000 push-based PQL
    # subscriptions under a live import stream — registration ms/sub,
    # update-lag p50/p99, delta-eval tier counts, and the query-path
    # p99 with subscriptions on vs the identical node with them off
    # (tools/standing_bench.py subprocess, CPU: the subscribe engine is
    # host-side — listener fan-out, coalescing, incremental eval).
    standing_tier = None
    if os.environ.get("BENCH_SKIP_STANDING_TIER") != "1":
        import subprocess

        sbt = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools",
            "standing_bench.py",
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        try:
            out = subprocess.run(
                [sys.executable, sbt], env=env, capture_output=True,
                timeout=900, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                for line in out.stderr.strip().splitlines():
                    if line.startswith("[standing]"):
                        log(line)
                standing_tier = json.loads(out.stdout.strip().splitlines()[-1])
                log(
                    "standing tier: "
                    f"{standing_tier['subscriptions']} subscriptions, "
                    f"update lag p99 {standing_tier['lag_ms']['p99']} ms, "
                    "query-path p99 ratio "
                    f"{standing_tier['query_path']['p99_ratio']}x off"
                )
            else:
                log(f"standing tier failed: rc={out.returncode} "
                    f"stderr={out.stderr.strip()[-300:]!r}")
        except Exception as e:
            log(f"standing tier failed: {e}")

    # Ingest tier (ISSUE 18): what durability costs and what delta-
    # scatter saves — acked write throughput with group commit on/off
    # vs the WAL-off baseline (fsyncs vs acks), read p99 under a 50/50
    # read/write storm vs read-only, and mirror re-stage bytes with
    # scatter on/off (tools/ingest_bench.py subprocess, CPU).
    ingest_tier = None
    if os.environ.get("BENCH_SKIP_INGEST_TIER") != "1":
        import subprocess

        igt = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools",
            "ingest_bench.py",
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        try:
            out = subprocess.run(
                [sys.executable, igt], env=env, capture_output=True,
                timeout=900, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                for line in out.stderr.strip().splitlines():
                    if line.startswith("[ingest]"):
                        log(line)
                ingest_tier = json.loads(out.stdout.strip().splitlines()[-1])
                gw = ingest_tier["write"]["group_on"]
                log(
                    f"ingest tier: {gw['acks_per_s']} durable acks/s "
                    f"({gw['fsyncs']} fsyncs / {gw['acks']} acks), "
                    f"50/50 read p99 {ingest_tier['read']['p99_ratio']}x "
                    "the control storm, re-stage bytes "
                    f"{ingest_tier['restage']['bytes_ratio']}x saved by "
                    "scatter"
                )
            else:
                log(f"ingest tier failed: rc={out.returncode} "
                    f"stderr={out.stderr.strip()[-300:]!r}")
        except Exception as e:
            log(f"ingest tier failed: {e}")

    # Sparse tier (ISSUE 19): compressed device planes — effective
    # Gcols/s, device bytes read vs logical geometry, container-format
    # mix, and compressed-vs-logical resident HBM over 50%/5%/1%/0.1%
    # density corpora, with a byte-identity PQL storm against the
    # forced-dense arm (tools/sparse_bench.py subprocess, CPU).
    sparse_tier = None
    if os.environ.get("BENCH_SKIP_SPARSE_TIER") != "1":
        import subprocess

        spt = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools",
            "sparse_bench.py",
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        try:
            out = subprocess.run(
                [sys.executable, spt], env=env, capture_output=True,
                timeout=900, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                for line in out.stderr.strip().splitlines():
                    if line.startswith("[sparse]"):
                        log(line)
                sparse_tier = json.loads(out.stdout.strip().splitlines()[-1])
                d1 = sparse_tier["densities"]["1"]
                log(
                    "sparse tier: 1% density "
                    f"{d1['effective_gcols_s']} Gcols/s effective "
                    f"({d1['speedup']}x dense arm), resident HBM "
                    f"{d1.get('resident_ratio', 0)}x below logical, "
                    f"mix {d1['format_mix']}"
                )
            else:
                log(f"sparse tier failed: rc={out.returncode} "
                    f"stderr={out.stderr.strip()[-300:]!r}")
        except Exception as e:
            log(f"sparse tier failed: {e}")

    # Gameday tier: the everything-at-once soak (tools/gameday.py) at
    # full scale — multi-tenant fairness under a quota-shedding storm,
    # kill -9 replica recovery with zero lost acked writes, resize
    # 2->3->2 under a windowed device-fault timeline with tier
    # demote/hydrate and subscription convergence, gossip under
    # datagram loss.  A CPU subprocess like the cluster tiers (it
    # re-execs onto its own virtual 8-device mesh); the per-leg
    # numbers (victim p99 ratio, recovery counters, sub lag) land in
    # the artifact as the composed-failure resilience record.
    gameday_tier = None
    if os.environ.get("BENCH_SKIP_GAMEDAY_TIER") != "1":
        import subprocess

        gd = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools",
            "gameday.py",
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        try:
            out = subprocess.run(
                [sys.executable, gd], env=env, capture_output=True,
                timeout=900, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                for line in out.stderr.strip().splitlines():
                    if line.startswith("[gameday"):
                        log(line)
                gameday_tier = json.loads(out.stdout.strip().splitlines()[-1])
                fair = gameday_tier["legs"]["fairness"]
                log(
                    "gameday tier: all legs green — victim p99 "
                    f"{fair['victim_p99_storm_ms']} ms under storm "
                    f"({fair['ratio']}x isolated), hot shed "
                    f"{fair['hot_shed']}, wall {gameday_tier['wall_s']} s"
                )
            else:
                log(f"gameday tier failed: rc={out.returncode} "
                    f"stderr={out.stderr.strip()[-300:]!r}")
        except Exception as e:
            log(f"gameday tier failed: {e}")

    # Mesh-scaling tier (ISSUE 12 / ROADMAP 2): the mesh-sharded data
    # plane end to end — devices-vs-Gcols/s curve at 1/2/4/8 devices,
    # the 10B-column Intersect+Count headline over the full mesh (ICI-
    # reduced limb total-count), and the N-nodes × M-devices grid with
    # one process per node.  Runs on the virtual 8-device CPU mesh
    # (tools/mesh_bench.py re-execs itself onto it) BEFORE this process
    # touches the device — the tunnel only ever has one client.
    mesh_scaling = None
    if os.environ.get("BENCH_SKIP_MESH_TIER") != "1":
        import subprocess

        mb = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools", "mesh_bench.py"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        try:
            out = subprocess.run(
                [sys.executable, mb], env=env, capture_output=True,
                timeout=1800, text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                for line in out.stderr.strip().splitlines():
                    if line.startswith("[mesh]"):
                        log(line)
                mesh_scaling = json.loads(out.stdout.strip().splitlines()[-1])
                hl = mesh_scaling.get("headline") or {}
                log(
                    "mesh_scaling tier: headline "
                    f"{hl.get('columns')} columns @ {hl.get('devices')} "
                    f"devices -> {hl.get('gcols_per_s')} Gcols/s, "
                    f"byte_identical={hl.get('byte_identical')}"
                )
            else:
                log(f"mesh tier failed: rc={out.returncode} "
                    f"stderr={out.stderr.strip()[-300:]!r}")
        except Exception as e:
            log(f"mesh tier failed: {e}")

    total_columns = int(os.environ.get("BENCH_COLUMNS", 1_000_000_000))
    n_slices = (total_columns + SLICE_WIDTH - 1) // SLICE_WIDTH  # 954
    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    log(f"building {n_slices} slices x 2 rows x {WORDS_PER_SLICE} words (~50% density)")

    rng = np.random.default_rng(7)
    leaves = rng.integers(
        0, 2**32, size=(n_slices, 2, WORDS_PER_SLICE), dtype=np.uint32
    )

    # --- host-CPU baseline: the reference's popcntAndSlice loop shape ---
    a, b = leaves[:, 0], leaves[:, 1]
    t0 = time.perf_counter()
    host_count = int(np.bitwise_count(a & b).sum())
    host_s = time.perf_counter() - t0
    log(f"host AND+popcount: {host_s:.3f}s -> {host_count}")

    # --- device: fused Intersect+Count, batched over all slices ---
    q = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))")
    expr, _ = plan.decompose(q.calls[0].children[0])

    # Distinct batches, cycled: defeats any (executable, args) result
    # caching between the client and the chip.  Batch 0 is `leaves`
    # (the bit-exactness anchor).  The slice axis pads (zero slices) to
    # a multiple of 8 — zero slices contribute nothing to the counts,
    # and every timed program sees the identical padded shape.
    n_pad = (n_slices + 7) // 8 * 8

    def staged(arr: np.ndarray):
        if n_pad != arr.shape[0]:
            arr = np.concatenate(
                [arr, np.zeros((n_pad - arr.shape[0],) + arr.shape[1:], arr.dtype)]
            )
        return jnp.asarray(arr)

    n_batches = 3
    devs = [staged(leaves)]
    host_counts = [host_count]
    for _ in range(n_batches - 1):
        extra = rng.integers(
            0, 2**32, size=(n_slices, 2, WORDS_PER_SLICE), dtype=np.uint32
        )
        host_counts.append(int(np.bitwise_count(extra[:, 0] & extra[:, 1]).sum()))
        devs.append(staged(extra))
    jax.block_until_ready(devs)

    # TIMING METHODOLOGY (characterized r04, tools/cache_probe.py):
    # through the axon tunnel ``block_until_ready`` is a lazy
    # acknowledgment — compute runs fully async and only a VALUE FETCH
    # truly waits (naive block-timed loops "measured" 10+ TB/s).  So a
    # measurement FOLDS N executions' outputs into one device scalar
    # and fetches it (all N must really finish), and the per-run time
    # is the SLOPE between a 28-run and a 4-run folded pass — the fixed
    # dispatch + fetch round trip cancels.  Cycling 3 distinct staged
    # batches is sound: the pool does NOT memoize results (fetch-folded
    # repeat-vs-fresh ratio measured ~1.0x), and distinct batches still
    # defeat any (executable, args) result cache if one ever appears.
    # CPU fallback must finish within the driver's patience: the same
    # slope scheme at 1B columns takes >1h on the host backend, so the
    # fallback trims iteration counts (its artifact is a labeled
    # availability record, not a TPU-comparable number).
    N_LO, N_HI = (2, 6) if cpu_fallback else (4, 28)
    SLOPE_EPOCHS = 2 if cpu_fallback else 6

    def folded_wall(fn, inputs) -> float:
        acc = None
        t0 = time.perf_counter()
        for d in inputs:
            part = fn(d).astype(jnp.float32).sum()
            acc = part if acc is None else acc + part
        float(np.asarray(acc))
        return time.perf_counter() - t0

    sanity_peak = hbm_peak_bytes_s(jax) if jax.default_backend() == "tpu" else None

    def slope_time(fn) -> float | None:
        """True per-execution device seconds for ``fn`` (see
        measure_slope for the methodology)."""
        return measure_slope(
            lambda inputs: folded_wall(fn, inputs),
            [devs[i % n_batches] for i in range(N_LO)],
            [devs[i % n_batches] for i in range(N_HI)],
            devs[0].size * 4,
            sanity_peak * 1.25 if sanity_peak else None,
            log,
            epochs=SLOPE_EPOCHS,
        )

    def time_variant(name: str, fn) -> float | None:
        for d, want in zip(devs, host_counts):  # warmup/compile + exactness
            got = int(np.asarray(jax.block_until_ready(fn(d)), dtype=np.int64).sum())
            assert got == want, f"bit-exactness ({name}): {got} != {want}"
        s = slope_time(fn)
        if s is None:
            log(f"device {name} Intersect+Count: slope UNRELIABLE (pool interference)")
        else:
            log(
                f"device {name} Intersect+Count: {s*1e3:.2f} ms/query"
                f" (fold-fetched slope, best of {SLOPE_EPOCHS} epochs)"
            )
        return s

    # --- roofline decomposition (stderr evidence for the bandwidth
    # analysis): a pure streaming reduce (1 vector op/word — the
    # practical memory-bound ceiling for this access pattern), popcount
    # +reduce (~12 bit-hack ops/word on the VPU — TPUs have no popcount
    # unit), and the production fused AND+popcount+reduce.  If popcount
    # tracks fused and both sit far below the streaming ceiling, the
    # kernel is VPU-popcount-bound, not HBM-bound, and %-of-HBM-peak is
    # the wrong roofline for it.
    def probe(name, fn):
        if cpu_fallback:
            return None  # TPU evidence only; hour-scale on the host
        try:
            f = jax.jit(fn)
            jax.block_until_ready(f(devs[0]))  # compile
            s = slope_time(f)
            if s is None:
                log(f"roofline {name}: UNRELIABLE (pool interference)")
                return None
            gbs = (devs[0].size * 4) / s / 1e9
            log(f"roofline {name}: {s*1e3:.2f} ms/pass ({gbs:.0f} GB/s read)")
            return s
        except Exception as e:  # noqa: BLE001 — probes are evidence only
            log(f"roofline {name} failed: {e!r:.200}")
            return None

    stream_s = probe("stream-sum", lambda d: jnp.sum(d, dtype=jnp.uint32))
    probe(
        "popcount-sum",
        lambda d: jnp.sum(
            jax.lax.population_count(d).astype(jnp.int32), dtype=jnp.int32
        ),
    )
    probe(
        "and+popcount-sum",
        lambda d: jnp.sum(
            jax.lax.population_count(d[:, 0] & d[:, 1]).astype(jnp.int32),
            dtype=jnp.int32,
        ),
    )
    # Per-row partials instead of a full scalar reduce: if this is much
    # faster than and+popcount-sum, the scalar reduce is breaking XLA's
    # fusion (materializing the popcount array in HBM); measured, the
    # two track each other — the scalar reduce fuses fine.
    probe(
        "and+popcount-rowsum",
        lambda d: jnp.sum(
            jax.lax.population_count(d[:, 0] & d[:, 1]).astype(jnp.int32),
            axis=-1,
            dtype=jnp.int32,
        ),
    )

    # The raw kernel: XLA's fused bitwise+popcount+reduce (the only
    # path — the handwritten-Pallas variant was measured 0.068x this
    # and deleted, see ops/bitplane.py).
    dev_s = with_retries(
        "raw-kernel tier",
        lambda: time_variant("fused-XLA", plan.compiled_batched(expr, "count")),
    )

    # --- tier 2: END-TO-END PQL through the executor -------------------
    # A real Holder with 954 fragments; the query arrives as PQL text and
    # runs the full dispatch: parse -> leaf resolution -> batch assembly
    # (cached across queries) -> fused program -> reduce.
    coalesce_stats = None
    topn_breakdown = None
    try:
        e2e_s, coalesce_stats, topn_breakdown = with_retries(
            "e2e executor tier",
            lambda: run_executor_tiers(
                leaves, host_count, rng, dev_s, cpu_fallback
            ),
        )
        metric = "e2e_pql_intersect_count_1b_columns"
    except Exception as e:  # noqa: BLE001 — the artifact must survive
        log(f"e2e executor tier FAILED ({e!r:.400}); falling back to raw kernel metric")
        if dev_s is None:
            raise
        e2e_s = dev_s
        metric = "intersect_count_1b_columns"

    # --- tier 5: HBM pressure (budget below total plane bytes) ---------
    hbm_pressure = None
    if os.environ.get("BENCH_SKIP_HBM_TIER") != "1":
        try:
            hbm_pressure = with_retries(
                "hbm-pressure tier",
                lambda: run_hbm_pressure_tier(rng, cpu_fallback),
                attempts=2,
            )
        except Exception as e:  # noqa: BLE001 — the artifact must survive
            log(f"hbm-pressure tier FAILED ({e!r:.300})")

    # --- tier 6: BSI Range/Sum over integer bit-planes -----------------
    bsi_tier = None
    if os.environ.get("BENCH_SKIP_BSI_TIER") != "1":
        try:
            bsi_tier = with_retries(
                "bsi tier",
                lambda: run_bsi_tier(rng, n_slices, cpu_fallback),
                attempts=2,
            )
        except Exception as e:  # noqa: BLE001 — the artifact must survive
            log(f"bsi tier FAILED ({e!r:.300})")

    # --- tier 6a: mixed DISTINCT-query storm, fusion on vs off --------
    # The plane-major multi-query fusion headline: a weighted mix of
    # distinct Count/Range/TopN trees under concurrent load, measured
    # with the interpreter fusion tier enabled and disabled.  The
    # kernel is memory-bound (47.7% of HBM peak raw), so every further
    # Gcols/s must come from amortizing passes across queries — this
    # tier is where that shows up or doesn't.
    mixed_storm = None
    if os.environ.get("BENCH_SKIP_MIXED_TIER") != "1":
        try:
            mixed_storm = with_retries(
                "mixed-storm tier",
                lambda: run_mixed_storm_tier(rng, cpu_fallback),
                attempts=2,
            )
        except Exception as e:  # noqa: BLE001 — the artifact must survive
            log(f"mixed-storm tier FAILED ({e!r:.300})")

    # --- tier 6b: multi-node Intersect+Count, device-resident planes ---
    # BASELINE configs[4]'s distributed query (the reference's whole
    # point, executor.go:1149-1243) finally on the headline bench: real
    # in-process HTTP nodes sharing this process's accelerator, planes
    # device-resident, per-node-count throughput.
    cluster_tpu = None
    if os.environ.get("BENCH_SKIP_CLUSTER_TIER") != "1":
        try:
            cluster_tpu = with_retries(
                "cluster-tpu tier",
                lambda: run_cluster_tpu_tier(leaves, cpu_fallback),
                attempts=2,
            )
        except Exception as e:  # noqa: BLE001 — the artifact must survive
            log(f"cluster-tpu tier FAILED ({e!r:.300})")

    # --- tier 7: cold restart (time-to-first-answer while staging) -----
    cold_restart = None
    if os.environ.get("BENCH_SKIP_COLD_TIER") != "1":
        try:
            cold_restart = with_retries(
                "cold-restart tier",
                lambda: run_cold_restart_tier(rng, cpu_fallback),
                attempts=2,
            )
            cold_restart.update(restart_probe)
        except Exception as e:  # noqa: BLE001 — the artifact must survive
            log(f"cold-restart tier FAILED ({e!r:.300})")

    # --- tier 8: tiered storage (disk budget << total plane bytes) -----
    # The object-store cold tier (pilosa_tpu/tier): a skewed query
    # storm over more fragments than the disk budget admits, so the
    # LRU demotes and demand hydration pulls fragments back — versus
    # the identical storm unbounded.  Records hydration p50/p99, the
    # cold-hit rate, demotion/hydration cycle counts, and steady-state
    # query p99 vs the unbounded baseline.
    tiered = None
    if os.environ.get("BENCH_SKIP_TIERED_TIER") != "1":
        try:
            tiered = with_retries(
                "tiered tier",
                lambda: run_tiered_tier(rng, cpu_fallback),
                attempts=2,
            )
        except Exception as e:  # noqa: BLE001 — the artifact must survive
            log(f"tiered tier FAILED ({e!r:.300})")

    if cpu_fallback:
        metric += "_cpu_fallback"

    cols_per_s = total_columns / e2e_s
    vs = host_s / e2e_s
    # Effective traffic: 2 operands x 1/8 B/col, nothing written back.
    bytes_per_query = total_columns / 4
    hbm_peak = sanity_peak
    e2e_gbs = bytes_per_query / e2e_s / 1e9

    def pct_peak(gbs: float) -> str:
        return f" = {gbs*1e9/hbm_peak*100:.1f}% of HBM peak" if hbm_peak else ""

    if dev_s is not None:
        raw_gbs = bytes_per_query / dev_s / 1e9
        log(
            f"raw-kernel ceiling: {total_columns/dev_s/1e9:.1f} Gcols/s"
            f" ({raw_gbs:.0f} GB/s{pct_peak(raw_gbs)});"
            f" headline: {cols_per_s/1e9:.1f} Gcols/s"
            f" ({e2e_gbs:.0f} GB/s{pct_peak(e2e_gbs)})"
        )
    else:
        log(
            f"raw-kernel ceiling UNRELIABLE this run;"
            f" headline: {cols_per_s/1e9:.1f} Gcols/s"
            f" ({e2e_gbs:.0f} GB/s{pct_peak(e2e_gbs)})"
        )
    out = {
        "metric": metric,
        "value": round(cols_per_s / 1e9, 3),
        "unit": "Gcols/s",
        "vs_baseline": round(vs, 2),
        "effective_gb_s": round(e2e_gbs, 1),
    }
    if dev_s is not None:
        out["raw_kernel_gb_s"] = round(bytes_per_query / dev_s / 1e9, 1)
        if stream_s is not None and stream_s > 0:
            # kernel-vs-floor: the fused kernel's bandwidth as a
            # fraction of the SAME-MOMENT streaming-reduce ceiling (the
            # attainable bandwidth through a shared congested pool) —
            # the skeptic-proof roofline figure (VERDICT r04 weak #5);
            # both read the same byte count, so the ratio is just
            # time-over-time.
            out["raw_kernel_vs_stream_floor"] = round(stream_s / dev_s, 3)
            # The ISSUE-19 headline figure: the raw and+popcount
            # kernel's bandwidth as a percentage of the stream floor
            # (BENCH_r05 recorded 64.8% with the pre-restructure
            # kernel; the chunked-limb/Pallas path targets 85%).
            out["raw_kernel_floor_pct"] = round(100.0 * stream_s / dev_s, 1)
            out["stream_floor_gb_s"] = round(
                bytes_per_query / stream_s / 1e9, 1
            )
    if hbm_peak:
        out["pct_hbm_peak"] = round(e2e_gbs * 1e9 / hbm_peak * 100, 2)
        if dev_s is not None:
            out["raw_kernel_pct_hbm_peak"] = round(
                bytes_per_query / dev_s / 1e9 * 1e9 / hbm_peak * 100, 2
            )
    if coalesce_stats is not None:
        out["coalesce"] = coalesce_stats
    if topn_breakdown:
        out["topn_src_breakdown_p50_ms"] = topn_breakdown
    if hbm_pressure is not None:
        out["hbm_pressure"] = hbm_pressure
    if bsi_tier is not None:
        out["bsi"] = bsi_tier
    if mixed_storm is not None:
        out["mixed_storm"] = mixed_storm
    if cold_restart is not None:
        out["cold_restart"] = cold_restart
    if tiered is not None:
        out["tiered"] = tiered
    if cluster_reduce is not None:
        out["cluster_reduce"] = cluster_reduce
    if cluster_tpu is not None:
        out["cluster_tpu"] = cluster_tpu
    if mesh_scaling is not None:
        out["mesh_scaling"] = mesh_scaling
    if admission_storm is not None:
        out["admission_storm"] = admission_storm
    if rebalance_tier is not None:
        out["rebalance"] = rebalance_tier
    if replication_tier is not None:
        out["replication"] = replication_tier
    if degraded_tier is not None:
        out["degraded"] = degraded_tier
    if standing_tier is not None:
        out["standing"] = standing_tier
    if ingest_tier is not None:
        out["ingest"] = ingest_tier
    if sparse_tier is not None:
        out["sparse"] = sparse_tier
    if gameday_tier is not None:
        out["gameday"] = gameday_tier
    out["program_cache"] = {
        "entries": plan.program_cache_stats(),
        "bounds": plan.program_cache_bounds(),
    }
    # Launch telemetry snapshot (obs/perf.py): the per-site roofline
    # view — achieved GB/s (and % of measured stream floor when the
    # probe ran) for every device launch path the run exercised, plus
    # per-cache first-compile cost.  The bench asserts on this block
    # (tools/bench_smoke.py), so keep keys stable.
    try:
        from pilosa_tpu.obs import perf as perf_mod

        psnap = perf_mod.registry().snapshot()
        out["perf"] = {
            "floor_gbps": psnap.get("floor_gbps"),
            "sites": {
                name: {
                    "launches": s["launches"],
                    "gbps": s["gbps"],
                    "floor_pct": s.get("floor_pct"),
                }
                for name, s in psnap.get("sites", {}).items()
            },
            "compile_ms": plan.program_cache_compile_ms(),
        }
    except Exception as e:  # noqa: BLE001 — the artifact must survive
        log(f"perf snapshot FAILED ({e!r:.300})")
    print(json.dumps(out))


def measure_query(
    ex, index, pq, check, n_serial=8, n_conc=48, threads=16, trials=3
):
    """Measure one warm query both ways; returns (p50_serial_s,
    per_query_concurrent_s, p50_under_load_s).  ``check(result)``
    asserts correctness on every single result.  The concurrent pass
    runs ``trials`` times and the BEST trial wins: the shared TPU pool
    behind the axon tunnel has sporadic multi-second stalls, and the
    best trial is the engine's capability rather than the pool's worst
    moment (every trial's results are still correctness-checked)."""
    import concurrent.futures

    def one(_i):
        t0 = time.perf_counter()
        res = ex.execute(index, pq)
        check(res)
        return time.perf_counter() - t0

    lat = [one(i) for i in range(n_serial)]
    p50 = sorted(lat)[len(lat) // 2] if lat else float("nan")
    best = (float("inf"), [])
    for _ in range(trials):
        with concurrent.futures.ThreadPoolExecutor(threads) as pool:
            t0 = time.perf_counter()
            conc_lat = list(pool.map(one, range(n_conc)))
            wall = time.perf_counter() - t0
        if wall < best[0]:
            best = (wall, conc_lat)
    wall, conc_lat = best
    per_q = wall / n_conc
    conc_p50 = sorted(conc_lat)[len(conc_lat) // 2]
    return p50, per_q, conc_p50


def run_tiered_tier(rng, cpu_fb=False) -> dict:
    """Tiered-storage scenario (pilosa_tpu/tier): local-FS store,
    disk budget set to ~1/3 of the hot fragment bytes (and the HBM
    budget to half the per-device plane bytes), then a SKEWED Count
    storm over every slice — the working set stays hot while the long
    tail cycles demote->hydrate — versus the identical storm
    unbounded.  The p99 ratio is the cost of serving an index that
    does not fit local storage; the demotion/hydration counters prove
    the cycle actually ran."""
    import jax

    from pilosa_tpu import device as device_mod
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.device.pool import PlanePool
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.obs.stats import ExpvarStatsClient
    from pilosa_tpu.ops import bitplane as bpl
    from pilosa_tpu.pql.parser import parse_string
    from pilosa_tpu.tier import LocalFSStore, TierManager

    n_dev = max(1, len(jax.local_devices()))
    n_slices = 12 if cpu_fb else 32
    rows = 16  # pad_rows(16) x 128 KiB = 2 MiB plane per fragment
    n_queries = n_slices * (6 if cpu_fb else 10)
    hot_set = max(2, n_slices // 4)

    with tempfile.TemporaryDirectory() as d:
        holder = Holder(os.path.join(d, "data"))
        holder.open()
        idx = holder.create_index("tiered")
        fr = idx.create_frame("t", cache_size=256)
        view = fr.create_view_if_not_exists("standard")
        planes = rng.integers(
            0, 2**32, size=(n_slices, rows, bpl.WORDS_PER_SLICE),
            dtype=np.uint32,
        )
        for s in range(n_slices):
            frag = view.create_fragment_if_not_exists(s)
            prime_fragment(frag, planes[s], bpl.pad_rows)
            frag.snapshot()  # disk accounting needs the real file bytes
        want = {
            s: int(np.bitwise_count(planes[s][0]).sum())
            for s in range(n_slices)
        }
        total_disk = sum(
            os.path.getsize(view.fragment(s).path) for s in range(n_slices)
        )
        plane_bytes = view.fragment(0)._plane.nbytes
        per_dev = (n_slices + n_dev - 1) // n_dev
        hbm_budget = per_dev * plane_bytes // 2
        pq = parse_string("Count(Bitmap(rowID=0, frame=t))")

        # 80% of queries hit the hot quarter, 20% sweep the tail — the
        # access pattern tiering exists for.
        seq = [
            int(rng.integers(0, hot_set))
            if rng.random() < 0.8
            else int(rng.integers(0, n_slices))
            for _ in range(n_queries)
        ]

        def storm(mgr) -> list:
            lats = []
            ex = Executor(holder, host="localhost:0")
            try:
                for s in seq:
                    t0 = time.perf_counter()
                    (n,) = ex.execute("tiered", pq, slices=[s])
                    lats.append(time.perf_counter() - t0)
                    assert n == want[s], (s, n, want[s])
            finally:
                ex.close()
            lats.sort()
            return lats

        def pcts(lats) -> dict:
            return {
                "p50_ms": round(lats[len(lats) // 2] * 1e3, 2),
                "p99_ms": round(
                    lats[min(len(lats) - 1, int(len(lats) * 0.99))] * 1e3, 2
                ),
            }

        # Warm compiles outside any timed window (shared fixed cost).
        warm_ex = Executor(holder, host="localhost:0")
        try:
            for s in range(n_slices):
                warm_ex.execute("tiered", pq, slices=[s])
        finally:
            warm_ex.close()

        out = {
            "n_fragments": n_slices,
            "total_disk_mib": round(total_disk / 2**20, 2),
        }
        baseline = pcts(storm(None))
        out["unbounded"] = baseline

        stats = ExpvarStatsClient()
        store = LocalFSStore(os.path.join(d, "store"), stats=stats)
        disk_budget = max(1, total_disk // 3)
        mgr = TierManager(
            holder, store, stats=stats, disk_budget_bytes=disk_budget
        )
        mgr.attach_all()
        mgr.upload_all(include_schema=False)
        pool = PlanePool(budget_bytes=hbm_budget)
        prev = device_mod._set_pool(pool)
        try:
            mgr.enforce_disk_budget()  # initial demotion to budget
            lats = storm(mgr)
            # drain the async budget sweeps before reading counters
            t0 = time.monotonic()
            while mgr._enforcing and time.monotonic() - t0 < 30:
                time.sleep(0.05)
        finally:
            device_mod._set_pool(prev)
        snap = stats.snapshot()
        counts = snap.get("counts", {})
        hyd = snap.get("histograms", {}).get("tier.hydrateMs", {})
        tier = pcts(lats)
        tier.update(
            {
                "disk_budget_mib": round(disk_budget / 2**20, 2),
                "hbm_budget_mib": round(hbm_budget / 2**20, 2),
                "demotions": counts.get("tier.demotions", 0),
                "hydrations": counts.get("tier.hydrations", 0),
                "cold_hit_rate": round(
                    counts.get("tier.hydrations", 0) / len(seq), 3
                ),
                "hydrate_p50_ms": round(hyd.get("p50", 0.0), 2),
                "hydrate_p99_ms": round(hyd.get("p99", 0.0), 2),
            }
        )
        out["tiered"] = tier
        out["p99_ratio"] = (
            round(tier["p99_ms"] / baseline["p99_ms"], 2)
            if baseline["p99_ms"]
            else None
        )
        log(
            f"tiered: disk budget {tier['disk_budget_mib']} MiB of"
            f" {out['total_disk_mib']} MiB total; p50"
            f" {tier['p50_ms']:.2f} ms p99 {tier['p99_ms']:.2f} ms"
            f" ({out['p99_ratio']}x unbounded p99"
            f" {baseline['p99_ms']:.2f} ms); {tier['demotions']}"
            f" demotions, {tier['hydrations']} hydrations (cold-hit"
            f" rate {tier['cold_hit_rate']}), hydrate p50"
            f" {tier['hydrate_p50_ms']} ms p99 {tier['hydrate_p99_ms']} ms"
        )
        holder.close()
        return out


def run_hbm_pressure_tier(rng, cpu_fb=False) -> dict:
    """HBM-pressure scenario (device/pool.py): per-device budget set to
    HALF the per-device plane bytes, then a per-slice TopN sweep over
    more fragments than fit — versus the identical sweep unbounded.
    Reports evictions, prefetch hit rate, and p50/p99 query latency for
    both, so the cost of paging planes HBM<->host under pressure is a
    tracked number, not a guess."""
    import jax

    from pilosa_tpu import device as device_mod
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.device.pool import PlanePool
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.ops import bitplane as bpl
    from pilosa_tpu.pql.parser import parse_string

    n_dev = max(1, len(jax.local_devices()))
    n_slices = 16 if cpu_fb else 32
    rows = 16  # pad_rows(16) x 128 KiB = 2 MiB plane per fragment
    rounds = 2 if cpu_fb else 3

    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        idx = holder.create_index("hbm")
        fr = idx.create_frame("h", cache_size=256)
        view = fr.create_view_if_not_exists("standard")
        planes = rng.integers(
            0, 2**32, size=(n_slices, rows, bpl.WORDS_PER_SLICE),
            dtype=np.uint32,
        )
        for s in range(n_slices):
            prime_fragment(
                view.create_fragment_if_not_exists(s), planes[s], bpl.pad_rows
            )
        frags = [view.fragment(s) for s in range(n_slices)]
        plane_bytes = frags[0]._plane.nbytes
        per_dev = (n_slices + n_dev - 1) // n_dev
        budget = per_dev * plane_bytes // 2
        pq = parse_string("TopN(Bitmap(rowID=0, frame=h), frame=h, n=8)")

        def sweep(pool) -> list:
            # Cold mirrors per variant: the comparison is paging cost,
            # not residual warmth from the previous variant.
            for frag in frags:
                frag._invalidate_device()
            lats = []
            ex = Executor(
                holder,
                host="localhost:0",
                prefetcher=device_mod.Prefetcher(pool=pool),
            )
            try:
                for _ in range(rounds):
                    for s in range(n_slices):
                        t0 = time.perf_counter()
                        (pairs,) = ex.execute("hbm", pq, slices=[s])
                        lats.append(time.perf_counter() - t0)
                        assert len(pairs) == 8
            finally:
                ex.close()
            lats.sort()
            return lats

        # One warm sweep outside any timed window: compiles and
        # first-touch-per-device dispatch are fixed costs shared by both
        # variants, not part of the paging story (sweep() re-colds the
        # mirrors, so the timed variants still pay their own uploads).
        warm_ex = Executor(holder, host="localhost:0")
        try:
            for s in range(n_slices):
                warm_ex.execute("hbm", pq, slices=[s])
        finally:
            warm_ex.close()

        out = {
            "n_fragments": n_slices,
            "plane_mib": round(plane_bytes / 2**20, 2),
            "budget_mib_per_device": round(budget / 2**20, 2),
        }
        for label, b in (("unbounded", 0), ("budgeted", budget)):
            pool = PlanePool(budget_bytes=b)
            prev = device_mod._set_pool(pool)
            try:
                lats = sweep(pool)
            finally:
                device_mod._set_pool(prev)
            snap = pool.snapshot()
            c = snap["counters"]
            fetches = c["prefetchHit"] + c["prefetchMiss"]
            tier = {
                "p50_ms": round(lats[len(lats) // 2] * 1e3, 2),
                "p99_ms": round(
                    lats[min(len(lats) - 1, int(len(lats) * 0.99))] * 1e3, 2
                ),
                "evictions": c["evictions"],
                "prefetch_hit_rate": (
                    round(c["prefetchHit"] / fetches, 3) if fetches else None
                ),
                "max_resident_mib": round(
                    max(
                        (dv["max_resident_bytes"] for dv in snap["devices"]),
                        default=0,
                    )
                    / 2**20,
                    2,
                ),
            }
            out[label] = tier
            log(
                f"hbm-pressure {label}: p50 {tier['p50_ms']:.2f} ms,"
                f" p99 {tier['p99_ms']:.2f} ms, evictions"
                f" {tier['evictions']}, prefetch hit rate"
                f" {tier['prefetch_hit_rate']}, max resident"
                f" {tier['max_resident_mib']} MiB"
                f" (budget {out['budget_mib_per_device']} MiB/device)"
            )
        holder.close()
        return out


def run_cluster_tpu_tier(leaves, cpu_fb=False) -> dict:
    """``cluster_tpu`` tier: BASELINE configs[4]'s multi-node
    Intersect+Count with device-resident planes.  Boots 1/2/4 real
    in-process servers (own HTTP listener, holder, executor; static
    hash-identical placement) sharing THIS process's accelerator,
    primes each node's owned slices, warms the mirrors onto the device,
    and measures the same PQL through the coordinator — sync p50 plus
    concurrent ms/query and Gcols/s per node count.  With >1 device
    visible the tier additionally records the node × device GRID (the
    production topology: each node's local map leg runs the
    mesh-sharded plane over its owned slices), keyed "NxM"; the full
    process-isolated grid over the virtual mesh is the mesh_scaling
    tier's node_grid (tools/mesh_bench.py)."""
    import jax

    from pilosa_tpu.ops import bitplane as bp
    from pilosa_tpu.parallel import mesh as pmesh

    n_slices = min(
        len(leaves), int(os.environ.get("BENCH_CLUSTER_TPU_SLICES", "128"))
    )
    rows = leaves[:n_slices]
    want = int(np.bitwise_count(rows[:, 0] & rows[:, 1]).sum())
    q = 'Count(Intersect(Bitmap(rowID=0, frame="f"), Bitmap(rowID=1, frame="f")))'
    n_local = len(jax.local_devices())
    device_counts = [d for d in (1, 2, 4, 8) if d <= n_local] or [1]
    out: dict = {
        "slices": n_slices,
        "devices_visible": n_local,
        "per_node": {},
        "grid": {},
    }
    quiet = dict(
        anti_entropy_interval=3600,
        polling_interval=3600,
        cache_flush_interval=3600,
        prewarm=False,
    )
    for m_devices in device_counts:
        bp.configure_mesh_devices(m_devices)
        pmesh._slices_mesh = None
        try:
            out["grid"].update(
                _cluster_tpu_node_rows(
                    rows, want, q, quiet, m_devices, out["per_node"]
                )
            )
        finally:
            bp.configure_mesh_devices(0)
            pmesh._slices_mesh = None
    return out


def _cluster_tpu_node_rows(
    rows, want, q, quiet, m_devices, per_node
) -> dict:
    """One device-width column of the cluster_tpu grid; also fills the
    legacy ``per_node`` table when running at the widest mesh."""
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu.cluster.topology import Cluster
    from pilosa_tpu.net.client import InternalClient
    from pilosa_tpu.net.server import Server
    from pilosa_tpu.ops import bitplane as bp
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH

    n_slices = rows.shape[0]
    grid: dict = {}
    for n_nodes in (1, 2, 4):
        with tempfile.TemporaryDirectory() as td:
            servers = []
            clusters = []
            try:
                for i in range(n_nodes):
                    cluster = Cluster(replica_n=1)
                    s = Server(
                        data_dir=os.path.join(td, f"n{i}"),
                        cluster=cluster,
                        **quiet,
                    )
                    s.open()
                    servers.append(s)
                    clusters.append(cluster)
                hosts = sorted(s.host for s in servers)
                for c in clusters:
                    for h in hosts:
                        if c.node_by_host(h) is None:
                            c.add_node(h)
                    c.nodes.sort(key=lambda n: n.host)
                for s in servers:
                    holder = s.holder
                    holder.create_index_if_not_exists("i")
                    holder.index("i").create_frame_if_not_exists("f")
                    view = holder.frame("i", "f").create_view_if_not_exists(
                        "standard"
                    )
                    for sl in s.cluster.owns_slices(
                        "i", n_slices - 1, s.host
                    ):
                        prime_fragment(
                            view.create_fragment_if_not_exists(sl),
                            rows[sl],
                            bp.pad_rows,
                        )
                    holder.index("i").set_remote_max_slice(n_slices - 1)
                coord = servers[0].host
                client = InternalClient(coord, timeout=120.0)
                # Warm: compiles + host->device mirror uploads; planes
                # stay device-resident for the measured queries.
                got = int(client.execute_query("i", q)[0])
                assert got == want, f"cluster bit-exactness: {got} != {want}"
                times = []
                for _ in range(9):
                    t0 = time.perf_counter()
                    client.execute_query("i", q)
                    times.append(time.perf_counter() - t0)
                times.sort()
                p50 = times[len(times) // 2]
                n_conc, threads = 48, 16
                clients = [
                    InternalClient(coord, timeout=120.0)
                    for _ in range(threads)
                ]
                t0 = time.perf_counter()
                with ThreadPoolExecutor(max_workers=threads) as pool:
                    list(
                        pool.map(
                            lambda i: clients[i % threads].execute_query(
                                "i", q
                            ),
                            range(n_conc),
                        )
                    )
                conc_s = (time.perf_counter() - t0) / n_conc
                gcols = n_slices * SLICE_WIDTH / conc_s / 1e9
                row = {
                    "sync_p50_ms": round(p50 * 1e3, 3),
                    "concurrent_ms_per_query": round(conc_s * 1e3, 3),
                    "gcols_per_s": round(gcols, 3),
                }
                grid[f"{n_nodes}x{m_devices}"] = dict(
                    row, nodes=n_nodes, devices_per_node=m_devices
                )
                # Device widths run ascending, so the legacy per_node
                # table ends up recording the WIDEST mesh's figures.
                per_node[str(n_nodes)] = row
                log(
                    f"cluster_tpu {n_nodes} node(s) x {m_devices} "
                    f"device(s): sync p50 {p50*1e3:.2f} ms, concurrent "
                    f"{conc_s*1e3:.2f} ms/query, {gcols:.2f} Gcols/s"
                )
            finally:
                for s in servers:
                    s.close()
    return grid


def run_bsi_tier(rng, n_slices, cpu_fb=False) -> dict:
    """``bsi`` tier: BSI Range + Sum over the standard corpus slice
    count.  A depth-8 integer field (every column valued, uniform
    0..255) plane-injected into a range-enabled frame; measures
    ``Count(Range(v > 100))`` and ``Sum(field=v)`` end to end through
    the executor with the coalescer on (the production path), reporting
    Gcols/s + ms/query.  Expected results come from an independent host
    computation over the injected planes, so the tier is also a
    bit-exactness anchor at corpus scale."""
    from pilosa_tpu import bsi
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.exec.coalesce import CoalesceScheduler
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.ops import bitplane as bpl
    from pilosa_tpu.pql.parser import parse_string

    depth = 8
    pred = 100
    trim = dict(n_serial=2, trials=1) if cpu_fb else dict(n_serial=8, trials=3)
    total_columns = n_slices * bpl.SLICE_WIDTH
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        idx = holder.create_index("b")
        fr = idx.create_frame("fb")
        fr.set_options(range_enabled=True)
        fr.create_field("v", 0, (1 << depth) - 1)
        view = fr.create_view_if_not_exists(bsi.field_view_name("v"))
        planes = rng.integers(
            0, 2**32, size=(n_slices, depth, bpl.WORDS_PER_SLICE),
            dtype=np.uint32,
        )
        ones = np.full((1, bpl.WORDS_PER_SLICE), 0xFFFFFFFF, np.uint32)
        zeros = np.zeros((1, bpl.WORDS_PER_SLICE), np.uint32)
        for s in range(n_slices):
            prime_fragment(
                view.create_fragment_if_not_exists(s),
                np.concatenate([ones, zeros, planes[s]]),
                bpl.pad_rows,
            )

        # Host reference, straight from the planes: Sum is the weighted
        # plane dot; the Range count rides the gt ripple in numpy.
        plane_pops = np.bitwise_count(planes).sum(axis=-1, dtype=np.int64)
        want_sum = int(sum((1 << k) * int(plane_pops[:, k].sum()) for k in range(depth)))
        gt = np.zeros((n_slices, bpl.WORDS_PER_SLICE), np.uint32)
        eq = np.full((n_slices, bpl.WORDS_PER_SLICE), 0xFFFFFFFF, np.uint32)
        for k in reversed(range(depth)):
            b = planes[:, k]
            if (pred >> k) & 1:
                eq_new = eq & b
            else:
                gt = gt | (eq & b)
                eq_new = eq & ~b
            eq = eq_new
        want_gt = int(np.bitwise_count(gt).sum())

        co = CoalesceScheduler()
        ex = Executor(holder, host="localhost:0", coalescer=co)
        out = {
            "depth": depth,
            "bucket": bsi.pad_depth(depth),
            "columns": total_columns,
        }
        try:
            rq = parse_string(f"Count(Range(frame=fb, v > {pred}))")
            sq = parse_string("Sum(frame=fb, field=v)")

            def check_range(res):
                assert int(res[0]) == want_gt, f"bsi Range exactness: {res[0]}"

            def check_sum(res):
                vc = res[0]
                assert (int(vc.value), int(vc.count)) == (
                    want_sum,
                    total_columns,
                ), f"bsi Sum exactness: {vc}"

            for label, pq, check in (
                ("range", rq, check_range),
                ("sum", sq, check_sum),
            ):
                t0 = time.perf_counter()
                (got,) = ex.execute("b", pq)
                check([got])
                cold_s = time.perf_counter() - t0
                p50, per_q, conc_p50 = measure_query(
                    ex, "b", pq, check, n_conc=8 if cpu_fb else 32, **trim
                )
                tier = {
                    "cold_ms": round(cold_s * 1e3, 2),
                    "ms_per_query": round(p50 * 1e3, 3),
                    "concurrent_ms_per_query": round(per_q * 1e3, 3),
                    "p50_under_load_ms": round(conc_p50 * 1e3, 3),
                    "gcols_s": round(total_columns / per_q / 1e9, 3),
                    "sync_gcols_s": round(total_columns / p50 / 1e9, 3),
                }
                out[label] = tier
                log(
                    f"bsi {label} (depth {depth}): cold {tier['cold_ms']:.1f} ms;"
                    f" sync p50 {tier['ms_per_query']:.2f} ms/query"
                    f" ({tier['sync_gcols_s']:.2f} Gcols/s); concurrent"
                    f" {tier['concurrent_ms_per_query']:.2f} ms/query"
                    f" ({tier['gcols_s']:.2f} Gcols/s)"
                )
            snap = co.snapshot()
            out["coalesce_launches"] = snap["launches"]
            out["coalesced_queries"] = snap["queries"]
        finally:
            ex.close()
            co.close()
            holder.close()
        return out


def run_mixed_storm_tier(rng, cpu_fb=False) -> dict:
    """``mixed_storm`` tier: a weighted mix of DISTINCT Count / Range /
    TopN queries under concurrent load, fusion ON vs OFF.

    Before this tier, the concurrent figures all measured storms of
    ONE query shape — exactly what the per-compile-key coalescer
    batches.  A realistic mix of distinct trees never shared a launch:
    this tier boots the same executor twice (CoalesceScheduler with
    ``fuse`` enabled/disabled), runs the identical mix at each
    concurrency step, and records Gcols/s, coalesce launches, fused
    queries per launch, and the interpreter program-cache entries —
    including after a second, more-diverse mix, which must NOT grow
    them (opcode tables are data; the jit key is pure geometry).
    Every worker checks its result against the direct (uncoalesced)
    executor's answer, so the speedup is anchored to byte-identical
    results."""
    import concurrent.futures

    from pilosa_tpu import bsi
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.exec import plan
    from pilosa_tpu.exec.coalesce import CoalesceScheduler
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.ops import bitplane as bpl
    from pilosa_tpu.pql.parser import parse_string

    n_slices = 8 if cpu_fb else 64
    depth = 8
    total_columns = n_slices * bpl.SLICE_WIDTH
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        idx = holder.create_index("ms")
        fr = idx.create_frame("f", cache_size=256)
        view = fr.create_view_if_not_exists("standard")
        rows = rng.integers(
            0, 2**32, size=(n_slices, 8, bpl.WORDS_PER_SLICE), dtype=np.uint32
        )
        for s in range(n_slices):
            prime_fragment(
                view.create_fragment_if_not_exists(s), rows[s], bpl.pad_rows
            )
        fr.set_options(range_enabled=True)
        fr.create_field("v", 0, (1 << depth) - 1)
        bview = fr.create_view_if_not_exists(bsi.field_view_name("v"))
        planes = rng.integers(
            0, 2**32, size=(n_slices, depth, bpl.WORDS_PER_SLICE),
            dtype=np.uint32,
        )
        ones = np.full((1, bpl.WORDS_PER_SLICE), 0xFFFFFFFF, np.uint32)
        zeros = np.zeros((1, bpl.WORDS_PER_SLICE), np.uint32)
        for s in range(n_slices):
            prime_fragment(
                bview.create_fragment_if_not_exists(s),
                np.concatenate([ones, zeros, planes[s]]),
                bpl.pad_rows,
            )
        ft = idx.create_frame("t", cache_size=512)
        tview = ft.create_view_if_not_exists("standard")
        trows = rng.integers(
            0, 2**32, size=(n_slices, 32, bpl.WORDS_PER_SLICE),
            dtype=np.uint32,
        )
        for s in range(n_slices):
            prime_fragment(
                tview.create_fragment_if_not_exists(s), trows[s], bpl.pad_rows
            )

        # The weighted mix: ~60% point counts, ~25% BSI ranges, ~15%
        # TopN(src) — every entry a DISTINCT tree or predicate.
        count_qs = [
            f"Count(Intersect(Bitmap(rowID={i}, frame=f),"
            f" Bitmap(rowID={j}, frame=f)))"
            for i, j in ((0, 1), (1, 2), (2, 3), (3, 4))
        ] + [
            f"Count(Union(Bitmap(rowID={i}, frame=f),"
            f" Bitmap(rowID={j}, frame=f)))"
            for i, j in ((4, 5), (5, 6))
        ]
        range_qs = [
            f"Count(Range(frame=f, v > {p}))" for p in (20, 80, 140, 200)
        ]
        topn_qs = [
            f"TopN(Bitmap(rowID={i}, frame=t), frame=t, n=8)"
            for i in (0, 1, 2)
        ]
        mix = count_qs * 2 + range_qs + topn_qs
        parsed = {q: parse_string(q) for q in {*mix}}

        def canon(res):
            if hasattr(res, "bits"):
                return ("bits", tuple(res.bits()))
            if isinstance(res, list):
                return ("pairs", tuple((p.id, p.count) for p in res))
            return ("val", int(res))

        direct = Executor(holder, host="localhost:0")
        try:
            want = {
                q: canon(direct.execute("ms", pq)[0])
                for q, pq in parsed.items()
            }
        finally:
            direct.close()

        tiers = (8,) if cpu_fb else (16, 64, 128)
        per_tier_mult = 3 if cpu_fb else 6

        def run_setting(fuse_on: bool) -> dict:
            co = CoalesceScheduler(fuse=fuse_on)
            ex = Executor(holder, host="localhost:0", coalescer=co)
            setting: dict = {}
            try:
                # Warm serial (batch caches + direct program compiles),
                # then one untimed mini-storm so the fused interpreter
                # geometries compile OUTSIDE the measured windows.
                for q, pq in parsed.items():
                    got = canon(ex.execute("ms", pq)[0])
                    assert got == want[q], f"mixed_storm identity: {q}"

                def one(i):
                    q = mix[i % len(mix)]
                    got = canon(ex.execute("ms", parsed[q])[0])
                    assert got == want[q], f"mixed_storm identity: {q}"

                with concurrent.futures.ThreadPoolExecutor(8) as pool:
                    list(pool.map(one, range(2 * len(mix))))
                for conc in tiers:
                    n_q = conc * per_tier_mult
                    before = co.snapshot()
                    t0 = time.perf_counter()
                    with concurrent.futures.ThreadPoolExecutor(conc) as pool:
                        list(pool.map(one, range(n_q)))
                    wall = time.perf_counter() - t0
                    snap = co.snapshot()
                    launches = snap["launches"] - before["launches"]
                    fused_q = snap["fused_queries"] - before["fused_queries"]
                    fused_l = (
                        snap["fused_launches"] - before["fused_launches"]
                    )
                    gcols = n_q * total_columns / wall / 1e9
                    setting[str(conc)] = {
                        "queries": n_q,
                        "gcols_s": round(gcols, 3),
                        "ms_per_query": round(wall / n_q * 1e3, 3),
                        "launches": launches,
                        "fused_launches": fused_l,
                        "fused_queries": fused_q,
                        "fused_per_launch": (
                            round(fused_q / fused_l, 2) if fused_l else None
                        ),
                    }
                    log(
                        f"mixed_storm fuse={'on' if fuse_on else 'off'}"
                        f" conc={conc}: {gcols:.2f} Gcols/s,"
                        f" {launches} launches for {n_q} queries"
                        f" ({fused_q} fused over {fused_l} interp launches)"
                    )
                setting["fetch_launches"] = co.snapshot()["fetch_launches"]
            finally:
                ex.close()
                co.close()
            return setting

        out: dict = {
            "slices": n_slices,
            "columns": total_columns,
            "distinct_queries": len(parsed),
            "errors": 0,
        }
        out["fusion_on"] = run_setting(True)
        entries_on = plan.program_cache_stats()["interp"]
        out["fusion_off"] = run_setting(False)
        out["speedup"] = {
            str(c): round(
                out["fusion_on"][str(c)]["gcols_s"]
                / out["fusion_off"][str(c)]["gcols_s"],
                3,
            )
            for c in tiers
            if out["fusion_off"][str(c)]["gcols_s"]
        }
        out["interp_entries"] = entries_on

        # Diversity probe: a SECOND fused storm over a strictly more
        # diverse mix (new predicates, new row pairs, new TopN rows)
        # must leave the interpreter's compiled-entry count unchanged —
        # expression tables are data, geometry is the only jit key.
        div_qs = [
            f"Count(Range(frame=f, v > {p}))"
            for p in (5, 33, 77, 111, 155, 199, 233, 250)
        ] + [
            f"Count(Intersect(Bitmap(rowID={i}, frame=f),"
            f" Bitmap(rowID={j}, frame=f)))"
            for i, j in ((0, 7), (1, 6), (2, 5), (3, 7))
        ]
        div_parsed = [parse_string(q) for q in div_qs]
        co = CoalesceScheduler(fuse=True)
        ex = Executor(holder, host="localhost:0", coalescer=co)
        try:
            with concurrent.futures.ThreadPoolExecutor(8) as pool:
                list(
                    pool.map(
                        lambda i: ex.execute(
                            "ms", div_parsed[i % len(div_parsed)]
                        ),
                        range(3 * len(div_parsed)),
                    )
                )
        finally:
            ex.close()
            co.close()
        out["interp_entries_after_diversity"] = plan.program_cache_stats()[
            "interp"
        ]
        log(
            f"mixed_storm: speedup {out['speedup']}; interp program-cache"
            f" entries {entries_on} ->"
            f" {out['interp_entries_after_diversity']} after diversity"
        )
        holder.close()
        return out


def run_cold_restart_tier(rng, cpu_fb=False) -> dict:
    """``cold_restart`` tier: the rolling-restart fast path.  Builds a
    node's data dir, warms its mirrors (the pre-restart incarnation,
    residency table persisted at close), then "restarts" — fresh
    residency pool, holder reopened from disk — and measures
    time-to-first-answer while the lazy background staging lane
    (device/prefetch.py, ordered by the persisted residency table)
    streams the mirrors up, plus staging-complete time and programs
    compiled in the window.  Tracks the 4.79 s eager-staging cold e2e
    this path replaces (VERDICT item 4); the fresh-process compile
    numbers ride in from tools/compile_probe_restart.py."""
    from pilosa_tpu import device as device_mod
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.exec import plan
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.ops import bitplane as bpl
    from pilosa_tpu.pql.parser import parse_string

    n_slices = 8 if cpu_fb else 64
    bits_per_row = 256
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        idx = holder.create_index("i")
        f = idx.create_frame("f")
        view = f.create_view_if_not_exists("standard")
        for s in range(n_slices):
            frag = view.create_fragment_if_not_exists(s)
            base = s * bpl.SLICE_WIDTH
            for r in (1, 2):
                for c in rng.integers(0, bpl.SLICE_WIDTH, size=bits_per_row):
                    frag.set_bit(r, base + int(c))
            frag.flush_ops()
        holder.warm_device_mirrors()
        holder.close()  # persists the residency table

        # "Restart": device state gone (fresh pool), data re-opened
        # from disk, serving starts immediately, staging drains behind.
        prev_pool = device_mod._set_pool(device_mod.PlanePool())
        try:
            progs_before = plan.program_cache_entries()
            t0 = time.perf_counter()
            h2 = Holder(d)
            h2.open()
            pf = device_mod.Prefetcher()
            job = h2.stage_device_mirrors(pf)
            ex = Executor(h2, prefetcher=pf)
            pq = parse_string(
                "Count(Intersect(Bitmap(rowID=1, frame=f),"
                " Bitmap(rowID=2, frame=f)))"
            )
            (got,) = ex.execute("i", pq)
            t_first = time.perf_counter() - t0
            in_flight = not job.done()
            job.wait()
            t_staged = time.perf_counter() - t0
            progs = plan.program_cache_entries() - progs_before
            tier = {
                "slices": n_slices,
                "first_answer_ms": round(t_first * 1e3, 2),
                "staging_in_flight_at_first_answer": in_flight,
                "staging_complete_ms": round(t_staged * 1e3, 2),
                "staging": job.snapshot(),
                "programs_compiled": progs,
                "count": int(got),
            }
            log(
                f"cold restart ({n_slices} slices): first answer"
                f" {tier['first_answer_ms']:.0f} ms (staging in flight:"
                f" {in_flight}); staging complete"
                f" {tier['staging_complete_ms']:.0f} ms;"
                f" {progs} programs compiled in the window"
            )
            ex.close()
            h2.close()
        finally:
            device_mod._set_pool(prev_pool)
        return tier


def run_executor_tiers(leaves, host_count, rng, dev_s, cpu_fb=False):
    """Executor tiers; returns ``(e2e_s, coalesce_stats)`` — the e2e
    per-query seconds under concurrent load (the throughput the
    north-star metric names) and the coalescer's per-tier launch /
    occupancy record for the artifact.

    ``dev_s`` may be None when the raw-kernel slope was unreliable (the
    "x raw kernel" annotations degrade gracefully).  ``cpu_fb`` is
    main()'s validated fallback flag — passed down, NOT re-derived from
    the env, so a leaked BENCH_CPU_FALLBACK can never trim (and
    mislabel) a healthy TPU measurement."""
    import jax  # noqa: F401 — backend already up
    # One trim policy for every fallback-shortened tier.
    trim = dict(n_serial=2, trials=1) if cpu_fb else dict(n_serial=8, trials=3)
    from pilosa_tpu.exec.coalesce import CoalesceScheduler
    from pilosa_tpu.exec.executor import Executor
    from pilosa_tpu.pql.parser import parse_string

    # The coalescer under test is the production configuration: the
    # concurrent tiers below are exactly the query storms it exists for,
    # and its launches/occupancy land in the artifact so the perf
    # trajectory shows WHERE the throughput came from.
    co = CoalesceScheduler()
    coalesce_stats = {"tiers": {}}

    def co_tier(label: str, queries: int, before: dict) -> dict:
        snap = co.snapshot()
        launches = snap["launches"] - before["launches"]
        qn = snap["queries"] - before["queries"]
        tier = {
            "launches": launches,
            "coalesced_queries": qn,
            "mean_batch_occupancy": (
                round(qn / launches, 2) if launches else None
            ),
            "dispatches_per_query": (
                round(launches / queries, 3) if queries else None
            ),
            "pad_rows": snap["pad_rows"] - before["pad_rows"],
        }
        coalesce_stats["tiers"][label] = tier
        log(
            f"coalesce {label}: {launches} launches for {qn} queries ->"
            f" mean occupancy {tier['mean_batch_occupancy']},"
            f" {tier['dispatches_per_query']} dispatches/query"
        )
        return snap

    from pilosa_tpu.obs.trace import Tracer

    tr = Tracer(capacity=64)
    with tempfile.TemporaryDirectory() as d:
        holder = build_holder(leaves, d)
        ex = Executor(holder, host="localhost:0", coalescer=co, tracer=tr)
        pq = parse_string("Count(Intersect(Bitmap(rowID=1, frame=f), Bitmap(rowID=2, frame=f)))")
        t0 = time.perf_counter()
        (got,) = ex.execute("i", pq)
        cold_s = time.perf_counter() - t0
        assert int(got) == host_count, f"e2e bit-exactness: {got} != {host_count}"
        from pilosa_tpu.exec import warmup as _warmup

        cache_note = (
            ", persistent cache on" if _warmup.enabled_cache_dir() else ""
        )
        log(f"e2e executor COLD (assembly+compile{cache_note}): {cold_s*1e3:.1f} ms")

        def check_count(res):
            assert int(res[0]) == host_count, f"e2e bit-exactness: {res[0]}"

        co_before = co.snapshot()
        n_conc_16 = 16 if cpu_fb else 48
        p50, e2e_16, conc_p50 = measure_query(
            ex, "i", pq, check_count, n_conc=n_conc_16, **trim
        )
        log(
            f"e2e executor Intersect+Count: sync p50 {p50*1e3:.2f} ms/query"
            f" (incl. tunnel round trip); CONCURRENT(16) {e2e_16*1e3:.2f}"
            f" ms/query throughput, p50 latency under load"
            f" {conc_p50*1e3:.2f} ms"
            + (f" ({e2e_16/dev_s:.2f}x raw kernel)" if dev_s else "")
        )
        co_before = co_tier(
            "count_concurrent_16",
            trim["n_serial"] + trim["trials"] * n_conc_16,
            co_before,
        )
        # N threads x ~70 ms tunnel RTT floor throughput at ~70/N
        # ms/query REGARDLESS of engine speed (r03's 4.61 ms at 16
        # threads was exactly this floor).  Climb the thread ladder
        # until the engine, not the RTT, is the limiter; the 16-thread
        # figure above stays for r03 comparability.
        tiers = {16: e2e_16}
        for threads in () if cpu_fb else (64, 128):
            _, per_q, _ = measure_query(
                ex, "i", pq, check_count,
                n_serial=0, n_conc=3 * threads, threads=threads,
            )
            tiers[threads] = per_q
            log(
                f"e2e executor Intersect+Count CONCURRENT({threads}):"
                f" {per_q*1e3:.2f} ms/query throughput"
                + (f" ({per_q/dev_s:.2f}x raw kernel)" if dev_s else "")
            )
            co_before = co_tier(
                f"count_concurrent_{threads}", 3 * 3 * threads, co_before
            )
        best_t = min(tiers, key=tiers.get)
        e2e_s = tiers[best_t]
        log(f"e2e headline uses the {best_t}-thread figure")

        # --- tier 3: TopN through the executor --------------------------
        # 2048 ranked-cache candidate rows in one fragment, scored against
        # a src row (reference: executor.go:281-321, BASELINE configs[2]).
        # All slices are local, so this takes the folded protocol: ONE
        # device fetch per query where r03 paid two phases.
        from pilosa_tpu.ops import bitplane as bpl

        cand = rng.integers(
            0, 2**32, size=(2048, bpl.WORDS_PER_SLICE), dtype=np.uint32
        )
        idx = holder.index("i")
        ft = idx.create_frame("t", cache_size=4096)
        view = ft.create_view_if_not_exists("standard")
        prime_fragment(
            view.create_fragment_if_not_exists(0), cand, bpl.pad_rows
        )

        tq = parse_string("TopN(Bitmap(rowID=0, frame=t), frame=t, n=100)")
        (warm,) = ex.execute("i", tq)  # compile + page
        assert len(warm) == 100

        def check_topn(res):
            pairs = res[0]
            assert len(pairs) == 100 and pairs[0].count >= pairs[-1].count

        t_p50, t_per_q, t_conc_p50 = measure_query(
            ex, "i", tq, check_topn, n_conc=8 if cpu_fb else 32, **trim
        )
        log(
            f"e2e executor TopN(n=100) folded single-fetch over 2048 rows:"
            f" sync p50 {t_p50*1e3:.2f} ms (incl. tunnel round trip);"
            f" CONCURRENT(16) {t_per_q*1e3:.2f} ms/query throughput,"
            f" p50 latency under load {t_conc_p50*1e3:.2f} ms"
        )
        if not cpu_fb:
            _, t_64, _ = measure_query(
                ex, "i", tq, check_topn, n_serial=0, n_conc=128, threads=64
            )
            log(
                f"e2e executor TopN(n=100) CONCURRENT(64): {t_64*1e3:.2f}"
                f" ms/query throughput"
            )

        # --- tier 4: MULTI-SLICE TopN with a src bitmap -----------------
        # 64 slices x 128 ranked candidates, scored against a src row:
        # the fused scorer reads candidate and src rows straight from
        # the resident plane mirrors — one program + one fetch per
        # query where a per-slice protocol would pay 64 dispatches and
        # 64 src uploads (reference workload: Tanimoto similarity
        # search, docs/tutorials.md:333-342).
        MS_SLICES, MS_ROWS = 64, 128
        fm = idx.create_frame("m", cache_size=512)
        vm = fm.create_view_if_not_exists("standard")
        mrows = rng.integers(
            0, 2**32, size=(MS_SLICES, MS_ROWS, bpl.WORDS_PER_SLICE),
            dtype=np.uint32,
        )
        for s in range(MS_SLICES):
            prime_fragment(
                vm.create_fragment_if_not_exists(s), mrows[s], bpl.pad_rows
            )
        mq = parse_string("TopN(Bitmap(rowID=0, frame=m), frame=m, n=100)")
        (mwarm,) = ex.execute("i", mq)
        assert len(mwarm) == 100
        # Bit-exactness anchor: row 0's total must equal the host sum.
        want0 = int(
            sum(
                np.bitwise_count(mrows[s, 0] & mrows[s, 0]).sum()
                for s in range(MS_SLICES)
            )
        )
        got0 = {p.id: p.count for p in mwarm}[0]
        assert got0 == want0, f"multi-slice TopN exactness: {got0} != {want0}"

        def check_ms(res):
            pairs = res[0]
            assert len(pairs) == 100 and pairs[0].count >= pairs[-1].count

        m_p50, m_per_q, _ = measure_query(
            ex, "i", mq, check_ms, n_conc=8 if cpu_fb else 32, **trim
        )
        log(
            f"e2e executor TopN(src) over {MS_SLICES} slices x {MS_ROWS}"
            f" candidates (fused plane scorer): sync p50 {m_p50*1e3:.2f} ms"
            f" (incl. tunnel round trip); CONCURRENT(16)"
            f" {m_per_q*1e3:.2f} ms/query throughput"
        )
        if not cpu_fb:
            # The prep cache leaves dispatch+fetch+selection per query;
            # more threads overlap the fetch RTTs further (same ladder
            # logic as the Count tier).
            _, m_32, _ = measure_query(
                ex, "i", mq, check_ms, n_serial=0, n_conc=96, threads=32
            )
            log(
                f"e2e executor TopN(src) CONCURRENT(32): {m_32*1e3:.2f}"
                f" ms/query throughput"
            )

        # Per-stage TopN(src) breakdown (prep / dispatch / plane fetch /
        # host winner-selection): the measurement groundwork for the
        # 5-7 ms warm residual (ROADMAP 5) — each warm query runs under
        # its own root trace and the topn.* span means land in the
        # artifact.
        stage_ms: dict[str, list] = {}
        for _ in range(5 if cpu_fb else 20):
            root = tr.start_trace("bench.topn")
            with root:
                ex.execute("i", mq)
            rec = tr.finish_root(root)
            for sp in (rec or {}).get("spans", []):
                if sp["name"].startswith("topn."):
                    stage_ms.setdefault(sp["name"], []).append(
                        sp["duration_ms"]
                    )
        topn_breakdown = {
            name: round(sorted(v)[len(v) // 2], 3)
            for name, v in sorted(stage_ms.items())
        }
        if topn_breakdown:
            log(
                "TopN(src) per-stage p50 ms: "
                + ", ".join(
                    f"{k.split('.', 1)[1]} {v}"
                    for k, v in topn_breakdown.items()
                )
            )
        ex.close()
        co.close()
        holder.close()
    coalesce_stats["total"] = co.snapshot()
    log(
        f"coalesce total: {coalesce_stats['total']['launches']} launches"
        f" for {coalesce_stats['total']['queries']} coalesced queries"
        f" (mean occupancy {coalesce_stats['total']['mean_occupancy']},"
        f" max {coalesce_stats['total']['max_occupancy']},"
        f" pad rows {coalesce_stats['total']['pad_rows']})"
    )
    return e2e_s, coalesce_stats, topn_breakdown


if __name__ == "__main__":
    main()
