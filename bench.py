"""Benchmark: 1B-column PQL Intersect+Count throughput (BASELINE.json
north_star / configs[3]-shaped workload).

Builds ~954 slices (1B columns) of two-row fragments, measures the fused
AND+popcount query throughput on the accelerator, and compares against
the host-CPU popcount path (numpy ``bitwise_count``, the stand-in for
the reference's Go/amd64 POPCNT roaring loop — reference:
roaring/assembly_amd64.s).  Goal: >=10x (BASELINE.md).

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
Progress goes to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def wait_for_backend(attempts: int = 8, delay_s: float = 60.0) -> None:
    """Probe accelerator init in SUBPROCESSES until one succeeds.

    The axon TPU tunnel can be wedged for many minutes after an earlier
    killed process (leaked session grant); a failed in-process backend
    init is cached by JAX, so probing must happen out-of-process.  Turns
    a transiently-wedged tunnel into a delayed bench instead of a
    crashed one (round-1 BENCH artifact failure mode)."""
    import subprocess
    import time as _time

    for i in range(attempts):
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('OK')"],
            capture_output=True,
            text=True,
            timeout=900,
        )
        if probe.returncode == 0 and "OK" in probe.stdout:
            return
        tail = (probe.stderr or probe.stdout).strip().splitlines()
        log(
            f"backend probe {i + 1}/{attempts} failed"
            f" ({tail[-1] if tail else 'no output'}); retrying in {delay_s:.0f}s"
        )
        _time.sleep(delay_s)
    log("backend never came up; proceeding (the real error will surface)")


def main() -> None:
    wait_for_backend()

    import jax
    import jax.numpy as jnp

    from pilosa_tpu.exec import plan
    from pilosa_tpu.ops.bitplane import SLICE_WIDTH, WORDS_PER_SLICE
    from pilosa_tpu.pql.parser import parse_string

    total_columns = 1_000_000_000
    n_slices = (total_columns + SLICE_WIDTH - 1) // SLICE_WIDTH  # 954
    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    log(f"building {n_slices} slices x 2 rows x {WORDS_PER_SLICE} words (~50% density)")

    rng = np.random.default_rng(7)
    leaves = rng.integers(
        0, 2**32, size=(n_slices, 2, WORDS_PER_SLICE), dtype=np.uint32
    )

    # --- host-CPU baseline: the reference's popcntAndSlice loop shape ---
    a, b = leaves[:, 0], leaves[:, 1]
    t0 = time.perf_counter()
    host_count = int(np.bitwise_count(a & b).sum())
    host_s = time.perf_counter() - t0
    log(f"host AND+popcount: {host_s:.3f}s -> {host_count}")

    # --- device: fused Intersect+Count, batched over all slices ---
    q = parse_string("Count(Intersect(Bitmap(rowID=1), Bitmap(rowID=2)))")
    expr, _ = plan.decompose(q.calls[0].children[0])

    dev = jnp.asarray(leaves)
    jax.block_until_ready(dev)

    def time_variant(name: str, fn) -> float:
        out = jax.block_until_ready(fn(dev))  # warmup/compile
        got = int(np.asarray(out, dtype=np.int64).sum())
        assert got == host_count, f"bit-exactness ({name}): {got} != {host_count}"
        iters = 20
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(dev)
        jax.block_until_ready(out)
        s = (time.perf_counter() - t0) / iters
        log(f"device {name} Intersect+Count: {s*1e3:.2f} ms/query (x{iters})")
        return s

    # Keep-or-kill evidence for the fused Pallas kernel path: time it
    # against the plain-XLA formulation on the same data (VERDICT r1
    # item 4) and take the better one as the headline.
    plain_s = time_variant("plain-XLA", plan.compiled_batched(expr, "count", fused=False))
    variants = {"plain-XLA": plain_s}
    from pilosa_tpu.ops.bitplane import _use_pallas

    if _use_pallas():
        variants["fused-pallas"] = time_variant(
            "fused-pallas", plan.compiled_batched(expr, "count", fused=True)
        )
        ratio = plain_s / variants["fused-pallas"]
        log(f"fused-pallas vs plain-XLA speedup: {ratio:.3f}x")
    best = min(variants, key=variants.get)
    dev_s = variants[best]
    log(f"headline variant: {best}")

    # --- secondary: TopN(n=100) scoring latency (BASELINE configs[2]) ---
    # 2048 candidate rows scored against a src row in one batched kernel;
    # p50 over 20 queries, logged to stderr (the driver records only the
    # primary metric line).
    from pilosa_tpu.ops import bitplane as bpl

    cand = jnp.asarray(
        rng.integers(0, 2**32, size=(2048, bpl.WORDS_PER_SLICE), dtype=np.uint32)
    )
    src = jnp.asarray(leaves[0, 0])
    warm = bpl.top_counts(cand, src)
    jax.block_until_ready(bpl.top_k(warm, 100))  # compile both stages
    lat = []
    for _ in range(20):
        t0 = time.perf_counter()
        counts = bpl.top_counts(cand, src)
        topc, topi = bpl.top_k(counts, 100)
        jax.block_until_ready((topc, topi))
        lat.append(time.perf_counter() - t0)
    p50 = sorted(lat)[len(lat) // 2]
    log(f"TopN(n=100) over 2048 rows: p50 {p50*1e3:.2f} ms")

    cols_per_s = total_columns / dev_s
    vs = host_s / dev_s
    print(
        json.dumps(
            {
                "metric": "intersect_count_1b_columns",
                "value": round(cols_per_s / 1e9, 3),
                "unit": "Gcols/s",
                "vs_baseline": round(vs, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
