"""Read-repair — version-checked replica reads, convergence on demand.

A read at consistency quorum/all version-checks each touched slice
across its replica set BEFORE executing: R replicas must answer, and if
their per-slice write versions disagree the coordinator synchronously
repairs — checksum comparison first (equal checksums mean only the
version counters drifted: stamp them forward, copy nothing), then a
newest->stale push through the rebalance subsystem's transition-
independent delta machinery (bulk fragment tar over the chunked data
plane + delta-log replay to checksum agreement) when content actually
diverged.  The router may then hand the slice to ANY replica — all of
them now carry the quorum-agreed state, which is what makes
read-your-writes hold at W+R > N.

The same ``push_slice`` is the hint replayer's escalation path when a
drained hint stream fails its post-replay checksum verification.
"""

from __future__ import annotations

from pilosa_tpu.net import resilience

# Replay-verify rounds per repair push before giving up (mirrors the
# rebalance coordinator's copy loop).
_REPAIR_ROUNDS = 3


class RepairError(RuntimeError):
    pass


def check_versions(rep, index: str, slices, level: str):
    """Version-check ``slices`` at R = required_acks(level, N).

    Returns ``[(slice, owners, versions_by_host), ...]`` for slices
    whose reachable replicas DISAGREE; raises
    :class:`~pilosa_tpu.replicate.quorum.ReadConsistencyError` when
    fewer than R replicas of some slice answer.  One versions RPC per
    replica host covers every slice it owns (batched, not per-slice).
    """
    from pilosa_tpu.replicate.quorum import ReadConsistencyError, required_acks

    owners_of: dict[int, list] = {}
    host_slices: dict[str, list[int]] = {}
    for s in slices:
        owners = rep.cluster.fragment_nodes(index, s)
        if len(owners) < 2:
            continue  # single replica: nothing to agree with
        owners_of[int(s)] = owners
        for node in owners:
            host_slices.setdefault(node.host, []).append(int(s))
    if not owners_of:
        return []

    got: dict[str, dict[int, int]] = {}
    for host, hs in host_slices.items():
        if host == rep.host:
            got[host] = rep.versions.get_many(index, hs)
            continue
        try:
            got[host] = rep.client_factory(host).replicate_versions(index, hs)
        except Exception as e:  # noqa: BLE001 — replica boundary
            if not resilience.is_node_failure(e):
                raise
            rep.stats.count("cluster.replication.versionCheckFailures")

    diverged = []
    for s, owners in sorted(owners_of.items()):
        need = required_acks(level, len(owners))
        by_host = {
            n.host: got[n.host][s]
            for n in owners
            if n.host in got and s in got[n.host]
        }
        if len(by_host) < need:
            raise ReadConsistencyError(level, index, s, len(by_host), need)
        if len(set(by_host.values())) > 1:
            diverged.append((s, owners, by_host))
    rep.stats.count("cluster.replication.versionChecks", len(owners_of))
    return diverged


def repair_slice(rep, index: str, slice_i: int, owners, by_host) -> str:
    """Converge one diverged slice; returns the repair cause
    (``"version-only"`` or ``"content"``).

    Checksums gate the copy: replicas whose version counters drifted
    (crash-reset, missed stamp) but whose CONTENT agrees just get their
    versions stamped forward — no bytes move.  Real divergence copies
    newest -> each stale replica through the delta machinery.
    """
    reachable = [h for h in by_host]
    checks: dict[str, dict[str, str]] = {}
    for host in reachable:
        try:
            if host == rep.host:
                checks[host] = rep.local_checksums(index, slice_i)
            else:
                checks[host] = rep._delta(
                    host,
                    {"index": index, "slice": slice_i, "action": "checksum"},
                )["checksums"]
        except Exception as e:  # noqa: BLE001 — replica boundary
            if not resilience.is_node_failure(e):
                raise

    max_ver = max(by_host.values())
    distinct = {tuple(sorted(c.items())) for c in checks.values()}
    if len(distinct) <= 1:
        cause = "version-only"
    else:
        cause = "content"
        # Newest replica wins; break version ties toward the replica-set
        # order (the primary).
        source = next(
            h
            for h in sorted(
                by_host, key=lambda h: (-by_host[h], _owner_rank(owners, h))
            )
            if h in checks
        )
        for target in reachable:
            if target == source or checks.get(target) == checks.get(source):
                continue
            push_slice(rep, source, target, index, slice_i)
    for host in reachable:
        _stamp_version(rep, host, index, slice_i, max_ver)
    rep.stats.count_with_custom_tags(
        "cluster.replication.readRepairs", 1, [f"cause:{cause}"]
    )
    rep.logger(
        f"replicate: read-repair of {index}/{slice_i} ({cause}; "
        f"versions {by_host})"
    )
    return cause


def push_slice(rep, src: str, dst: str, index: str, slice_i: int) -> None:
    """Push ``src``'s slice state onto ``dst`` to checksum agreement:
    open the copy window (delta log) on the source, stream every view's
    fragment tar through the chunked data plane, then replay writes that
    raced the stream until source/target checksums agree — the PR-10
    migration copy loop, scoped to one repair."""
    base = {"index": index, "slice": int(slice_i)}
    throttle = rep.hint_replay_throttle_mbps * 1e6 / 8.0
    try:
        for _attempt in range(2):
            rep._delta(src, {**base, "action": "start"})
            rep._delta(
                src,
                {
                    **base,
                    "action": "copy",
                    "target": dst,
                    "throttleBytesPerSec": throttle,
                },
            )
            for _round in range(_REPAIR_ROUNDS):
                r = rep._delta(
                    src, {**base, "action": "replay", "target": dst}
                )
                if r.get("overflowed"):
                    break  # write storm outran the log: recopy
                cks = rep._delta(src, {**base, "action": "checksum"})[
                    "checksums"
                ]
                ckt = rep._delta(dst, {**base, "action": "checksum"})[
                    "checksums"
                ]
                if all(ckt.get(k) == v for k, v in cks.items()):
                    rep.stats.count("cluster.replication.repairPushes")
                    return
        raise RepairError(
            f"repair push {index}/{slice_i} {src} -> {dst} failed to "
            "checksum-verify"
        )
    finally:
        try:
            rep._delta(src, {**base, "action": "stop"})
        except Exception:  # noqa: BLE001 — window close is best-effort
            pass


def _stamp_version(rep, host: str, index: str, slice_i: int, version: int):
    try:
        if host == rep.host:
            rep.versions.observe(index, slice_i, version)
        else:
            rep.client_factory(host).observe_version(index, slice_i, version)
    except Exception as e:  # noqa: BLE001 — stamping is additive
        if not resilience.is_node_failure(e):
            raise


def _owner_rank(owners, host: str) -> int:
    for i, n in enumerate(owners):
        if n.host == host:
            return i
    return len(owners)
