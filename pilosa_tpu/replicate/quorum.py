"""Quorum replication coordinator — tunable W-of-N writes.

One :class:`Replication` per server composes the subsystem: the
per-slice version store (``versions.py``), the hinted-handoff log
(``hints.py``), the background hint replayer, and the read-repair
driver (``repair.py``).  The executor's write fan-out routes through
:meth:`Replication.coordinate_write`; reads at quorum/all consistency
route through :meth:`ensure_read_consistency`.

Write contract (Dynamo-style, DeCandia et al. SOSP'07):

* N = the slice's replica set; W = ``required_acks(consistency, N)``
  with consistency one/quorum/all (``[cluster] write-consistency``,
  per-request ``X-Write-Consistency``).
* The coordinator applies locally first (capturing the exact per-view
  deltas), stamps its post-apply slice version onto every remote leg
  (``X-Write-Version`` — replicas max-merge), and fans out.
* Every UNREACHABLE replica gets the write queued as a hint; acks <
  W raises :class:`QuorumWriteError` LOUDLY — but the hints are already
  queued, so a failed request that partially applied still converges.
* Hints replay when the target's circuit breaker re-admits traffic
  (open -> half-open, ``net/resilience.py``), on the internal admission
  lane, throttled by ``[cluster] hint-replay-throttle-mbps``; each
  drained slice checksum-verifies against the target and escalates to
  a full delta-machinery push on disagreement.
"""

from __future__ import annotations

import json
import os
import threading
import time

from pilosa_tpu.net import resilience
from pilosa_tpu.replicate import hints as hints_mod
from pilosa_tpu.replicate import repair as repair_mod
from pilosa_tpu.replicate.hints import HintLog
from pilosa_tpu.replicate.versions import VersionStore

CONSISTENCY_LEVELS = ("one", "quorum", "all")

# Remote write legs carry the coordinator's post-apply slice version as
# "<slice>:<version>"; the replica handler max-merges it.
WRITE_VERSION_HEADER = "X-Write-Version"
# Per-request consistency overrides on /query (and the import client).
WRITE_CONSISTENCY_HEADER = "X-Write-Consistency"
READ_CONSISTENCY_HEADER = "X-Read-Consistency"


def required_acks(level: str, n: int) -> int:
    """W for a consistency level over ``n`` replicas: one=1,
    quorum=floor(n/2)+1, all=n (never below 1, never above n)."""
    n = max(int(n), 1)
    if level == "one":
        return 1
    if level == "all":
        return n
    if level == "quorum":
        return n // 2 + 1
    raise ValueError(f"unknown consistency level: {level!r}")


def validate_level(level: str, what: str = "consistency") -> str:
    if level not in CONSISTENCY_LEVELS:
        raise ValueError(
            f"invalid {what}: {level!r} (expected one of "
            f"{'/'.join(CONSISTENCY_LEVELS)})"
        )
    return level


class QuorumWriteError(RuntimeError):
    """A write gathered fewer than W acknowledgements.  The acked
    replicas (and the coordinator's hints for the failed ones) keep the
    write durable — the request fails loudly so the CLIENT knows the
    consistency contract was not met and can retry (replays are
    idempotent set/clear)."""

    def __init__(self, level: str, acks: int, needed: int, n: int, failures):
        self.level = level
        self.acks = acks
        self.needed = needed
        self.replicas = n
        self.failures = list(failures)
        detail = "; ".join(f"{h}: {e}" for h, e in self.failures)
        super().__init__(
            f"write acknowledged by {acks} of {n} replicas "
            f"(need {needed} at consistency={level})"
            + (f": {detail}" if detail else "")
        )


class ReadConsistencyError(RuntimeError):
    """Fewer than R replicas answered a version-checked read."""

    def __init__(self, level: str, index: str, slice_i: int, got: int, need: int):
        super().__init__(
            f"read at consistency={level} reached {got} of {need} required "
            f"replicas for {index}/{slice_i}"
        )


class Replication:
    """The server's replication wiring in one handle."""

    def __init__(
        self,
        host: str = "",
        cluster=None,
        holder=None,
        client_factory=None,
        breakers=None,
        rebalancer=None,
        tracer=None,
        stats=None,
        logger=None,
        data_dir: str = "",
        write_consistency: str = "quorum",
        read_consistency: str = "one",
        hint_cap: int = 10_000,
        hint_replay_throttle_mbps: float = 0.0,
    ):
        from pilosa_tpu.obs import trace
        from pilosa_tpu.obs.stats import NopStatsClient

        self.host = host
        self.cluster = cluster
        self.holder = holder
        self.client_factory = client_factory
        self.breakers = breakers
        # The server's Rebalancer: its transition-independent
        # /rebalance/delta actions (start/copy/replay/checksum/stop)
        # ARE the repair data plane — read-repair and hint-replay
        # escalation drive them instead of growing a second one.
        self.rebalancer = rebalancer
        self.tracer = tracer or trace.NOP_TRACER
        self.stats = stats or NopStatsClient()
        self.logger = logger or (lambda m: None)
        self.data_dir = data_dir
        self.write_consistency = validate_level(
            write_consistency, "write-consistency"
        )
        self.read_consistency = validate_level(
            read_consistency, "read-consistency"
        )
        self.versions = VersionStore(stats=self.stats)
        self.hints = HintLog(cap=hint_cap, stats=self.stats)
        self.hint_replay_throttle_mbps = float(hint_replay_throttle_mbps)
        # Hint replay cadence; the breaker's open->half-open transition
        # gates the actual push, this only bounds discovery latency.
        self.replay_interval_s = 2.0
        self._closing = threading.Event()
        self._replay_thread: threading.Thread | None = None
        self._replay_mu = threading.Lock()  # one replay pass at a time
        self._versions_flushed = 0  # bump-count at last persist

    # -- lifecycle -----------------------------------------------------

    def _state_path(self) -> str:
        return os.path.join(self.data_dir, ".replication.json")

    def open(self) -> None:
        """Restore persisted versions and start the replayer."""
        if self.data_dir:
            try:
                with open(self._state_path()) as f:
                    self.versions.load_doc(json.load(f).get("versions", {}))
            except (OSError, ValueError):
                pass
        self._closing.clear()
        self._replay_thread = threading.Thread(
            target=self._replay_loop, daemon=True, name=f"hint-replay:{self.host}"
        )
        self._replay_thread.start()

    def close(self) -> None:
        self._closing.set()
        self._persist_versions()

    def _persist_versions(self) -> None:
        if not self.data_dir:
            return
        try:
            os.makedirs(self.data_dir, exist_ok=True)
            tmp = self._state_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"versions": self.versions.to_doc()}, f)
            os.replace(tmp, self._state_path())
        except OSError as e:
            self.logger(f"replicate: version persist failed: {e}")

    # -- write-listener leg (registered by the server) -----------------

    def on_local_write(
        self, frag, set_rows, set_cols, clear_rows, clear_cols, exact=True
    ):
        """Fragment write hook: advance the slice's version and feed the
        coordinator's capture scope (``exact`` is irrelevant: version
        bumps and hint capture are idempotent per bit).  Called under
        the fragment lock — leaf locks only.

        The listener registry is PROCESS-global while servers are
        per-node: in-process multi-server setups (tests, benches) would
        otherwise count every server's writes in every store — only
        fragments under THIS node's data dir are ours."""
        if self.data_dir and not str(getattr(frag, "path", "")).startswith(
            self.data_dir
        ):
            return
        self.versions.bump(frag.index, frag.slice)
        hints_mod.record_local_write(
            frag, set_rows, set_cols, clear_rows, clear_cols
        )

    # -- the quorum write path (executor._write_one_view) --------------

    def write_consistency_for(self, opt) -> str:
        level = getattr(opt, "write_consistency", "") or self.write_consistency
        return validate_level(level, "write-consistency")

    def coordinate_write(
        self, executor, index, c, opt, view, write_fn, row_id, col_id,
        slice_i, targets,
    ) -> bool:
        """W-of-N write: local apply (captured) -> stamped remote
        fan-out -> hints for unreachable replicas -> loud sub-W failure.
        Returns the write's changed-bit like the legacy path."""
        from pilosa_tpu.pql.parser import Query

        level = self.write_consistency_for(opt)
        # W derives from the slice's replica set; during a rebalance
        # transition ``targets`` additionally carries the new ring's
        # owners (dual-write) whose acks count toward W.
        n = len(self.cluster.fragment_nodes(index, slice_i)) or len(targets)
        need = required_acks(level, n)
        acks = 0
        ret = False
        failures: list[tuple[str, Exception]] = []
        captured: list = []
        local = next((nd for nd in targets if nd.host == self.host), None)
        remotes = [nd for nd in targets if nd.host != self.host]
        with self.tracer.span(
            "replicate", consistency=level, replicas=n, targets=len(targets)
        ) as sp:
            if local is not None:
                with hints_mod.capture(captured):
                    if write_fn(view, row_id, col_id):
                        ret = True
                acks += 1
            # Stamp AFTER the local apply so the version covers it.
            ver = self.versions.get(index, slice_i)
            headers = {WRITE_VERSION_HEADER: f"{slice_i}:{ver}"}
            for node in remotes:
                try:
                    res = executor._exec_remote(
                        node, index, Query(calls=[c]), None, opt,
                        extra_headers=headers,
                    )
                    acks += 1
                    if res and res[0]:
                        ret = True
                except resilience.DeadlineExceeded:
                    raise
                except Exception as e:  # noqa: BLE001 — replica boundary
                    if not resilience.is_node_failure(e):
                        raise
                    failures.append((node.host, e))
            for host, _e in failures:
                if captured:
                    queued = self.hints.queue_views(host, captured)
                else:
                    # Coordinator does not replicate the slice: queue
                    # the call itself; PQL replays through the target's
                    # whole write path (all views, timestamps intact).
                    queued = int(
                        self.hints.queue_pql(host, index, slice_i, str(c))
                    )
                if queued:
                    self.stats.count(
                        "cluster.replication.hintsQueued", queued
                    )
            sp.annotate(acks=acks, needed=need, hinted=len(failures))
            self.stats.count_with_custom_tags(
                "cluster.replication.acks", acks, [f"class:{level}"]
            )
            if acks < need:
                self.stats.count("cluster.replication.writeFailures")
                sp.annotate(error="sub-quorum")
                raise QuorumWriteError(level, acks, need, n, failures)
        return ret

    # -- version-checked reads (executor.execute) ----------------------

    def read_consistency_for(self, opt) -> str:
        level = getattr(opt, "read_consistency", "") or self.read_consistency
        return validate_level(level, "read-consistency")

    def ensure_read_consistency(self, index: str, slices, level: str) -> int:
        """Version-check ``slices`` across their replica sets at R =
        required_acks(level); synchronously read-repair any diverged
        slice (push newest -> stale through the delta machinery) so the
        serving replica — whichever the router picks — answers with the
        quorum-agreed state.  Returns the number of slices repaired."""
        diverged = repair_mod.check_versions(self, index, slices, level)
        repaired = 0
        for slice_i, owners, by_host in diverged:
            self.stats.count("cluster.replication.divergence")
            repair_mod.repair_slice(self, index, slice_i, owners, by_host)
            repaired += 1
        return repaired

    # -- delta-machinery access (shared with repair.py) ----------------

    def _delta(self, host: str, payload: dict) -> dict:
        """One /rebalance/delta action against ``host`` — direct when it
        is this node (no self-HTTP), POSTed otherwise."""
        if host == self.host and self.rebalancer is not None:
            return self.rebalancer.delta_action(payload)
        client = self.client_factory(host)
        client.timeout = max(client.timeout, 600.0)
        status, data = client._request(
            "POST", "/rebalance/delta", body=json.dumps(payload).encode()
        )
        return json.loads(client._check(status, data) or b"{}")

    def local_checksums(self, index: str, slice_i: int) -> dict[str, str]:
        if self.rebalancer is not None:
            return self.rebalancer.delta_action(
                {"index": index, "slice": slice_i, "action": "checksum"}
            )["checksums"]
        return {}

    def replicates_locally(self, index: str, slice_i: int) -> bool:
        """Whether this node holds fragments of the slice (a hint holder
        that does can checksum-verify its replay)."""
        idx = self.holder.index(index) if self.holder is not None else None
        if idx is None:
            return False
        for frame in idx.frames().values():
            for view in frame.views().values():
                if view.fragment(slice_i) is not None:
                    return True
        return False

    # -- hint replay ---------------------------------------------------

    def _replay_loop(self) -> None:
        while not self._closing.wait(self.replay_interval_s):
            try:
                self.replay_tick()
            except Exception as e:  # noqa: BLE001 — replayer must survive
                self.logger(f"replicate: replay tick error: {e}")

    def replay_tick(self) -> dict[str, int]:
        """One discovery pass: attempt a replay for every target with a
        backlog.  The first RPC rides the shared per-host breaker gate
        (``InternalClient._prepare``), so while the target's breaker is
        OPEN the attempt fails in microseconds — and the attempt that
        lands after ``open_s`` IS the half-open probe: the PR-5
        open -> half-open transition is the replay trigger, and a
        successful replay doubles as the probe that closes the breaker.
        Persists versions opportunistically."""
        out: dict[str, int] = {}
        for target in self.hints.targets():
            if self._closing.is_set():
                break
            out[target] = self.replay_target(target)
        self._persist_versions()
        return out

    def replay_now(self, target: str | None = None) -> dict[str, int]:
        """Synchronous replay (ops / tests): bypasses the breaker gate —
        the operator asserted the target is back."""
        out = {}
        for t in [target] if target else self.hints.targets():
            out[t] = self.replay_target(t)
        return out

    def replay_target(self, target: str) -> int:
        """Drain and push one target's hints in application order on the
        internal admission lane; a push that dies mid-way requeues the
        unapplied tail.  After each slice drains, checksum-verify
        against the target (when this node replicates the slice) and
        escalate to a full delta-machinery push on disagreement; then
        stamp the target's version forward."""
        with self._replay_mu:
            return self._replay_target_locked(target)

    def _replay_target_locked(self, target: str) -> int:
        client = self.client_factory(target)
        replayed = 0
        throttle = _Throttle(self.hint_replay_throttle_mbps * 1e6 / 8.0)
        groups = self.hints.drain(target)
        for g, (index, slice_i, entries, overflowed) in enumerate(groups):
            for i, entry in enumerate(entries):
                try:
                    throttle.charge(_entry_bytes(entry))
                    self._apply_entry(client, index, slice_i, entry)
                except Exception as e:  # noqa: BLE001 — target boundary
                    # A dead push must not lose ANYTHING drained: the
                    # current group's unapplied tail AND every
                    # not-yet-touched group go back head-first.
                    self.hints.requeue(target, index, slice_i, entries[i:])
                    for r_index, r_slice, r_entries, _r_of in groups[g + 1 :]:
                        self.hints.requeue(target, r_index, r_slice, r_entries)
                    self.hints.note_replay(target, replayed, error=str(e))
                    self.stats.count(
                        "cluster.replication.hintsReplayed", replayed
                    )
                    return replayed
            replayed += len(entries)
            try:
                # An overflowed group lost hints: force the checksum
                # reconciliation (full push on disagreement) instead of
                # trusting the partial stream.
                self._verify_replay(
                    client, target, index, slice_i, force=overflowed
                )
            except Exception as e:  # noqa: BLE001 — verification is additive
                self.logger(
                    f"replicate: post-replay verify of {index}/{slice_i} "
                    f"on {target} failed: {e}"
                )
        self.hints.note_replay(target, replayed)
        if replayed:
            self.stats.count("cluster.replication.hintsReplayed", replayed)
            self.logger(
                f"replicate: replayed {replayed} hint(s) to {target}"
            )
        return replayed

    def _apply_entry(self, client, index: str, slice_i: int, entry: tuple):
        kind = entry[0]
        if kind == "views":
            _, frame, view, sr, sc, cr, cc = entry
            client.import_view_bits(
                index, frame, view, slice_i, (sr, sc), (cr, cc)
            )
        elif kind == "pql":
            client.execute_query(index, entry[1], remote=True)
        elif kind == "import":
            client.import_raw(entry[1])
        elif kind == "import-value":
            client.import_value_raw(entry[1])
        else:
            raise ValueError(f"unknown hint entry kind: {kind!r}")

    def _verify_replay(
        self, client, target: str, index: str, slice_i: int,
        force: bool = False,
    ):
        """Replay-to-checksum-agreement: when this node replicates the
        slice, its state is the reference — disagreement after a full
        drain means the target missed MORE than the hints covered
        (overflow, pre-hint divergence), so escalate to the full
        delta-machinery push.  ``force`` marks an overflowed group
        (hints were dropped): verification is mandatory there."""
        if not force and not self.replicates_locally(index, slice_i):
            return
        if force and not self.replicates_locally(index, slice_i):
            # Nothing local to compare against: hand convergence to
            # anti-entropy/read-repair, loudly.
            self.logger(
                f"replicate: hint overflow for {index}/{slice_i} on "
                f"{target} with no local replica; anti-entropy owns it"
            )
            return
        local = self.local_checksums(index, slice_i)
        remote = self._delta(
            target, {"index": index, "slice": slice_i, "action": "checksum"}
        )["checksums"]
        if any(remote.get(k) != v for k, v in local.items()):
            repair_mod.push_slice(self, self.host, target, index, slice_i)
            self.stats.count("cluster.replication.replayEscalations")
        client.observe_version(
            index, slice_i, self.versions.get(index, slice_i)
        )

    # -- observability -------------------------------------------------

    def snapshot(self) -> dict:
        """The ``GET /debug/replication`` document."""
        t = self._replay_thread
        return {
            "node": self.host,
            "writeConsistency": self.write_consistency,
            "readConsistency": self.read_consistency,
            "hints": self.hints.snapshot(),
            "versions": self.versions.snapshot(),
            "replay": {
                "intervalS": self.replay_interval_s,
                "throttleMbps": self.hint_replay_throttle_mbps,
                "running": bool(t is not None and t.is_alive()),
            },
        }


class _Throttle:
    """Token throttle on replay bytes (``hint-replay-throttle-mbps``):
    bulk hint drains must not saturate a recovering node's uplink."""

    def __init__(self, bytes_per_sec: float):
        self._rate = float(bytes_per_sec)
        self._sent = 0
        self._t0 = time.monotonic()

    def charge(self, nbytes: int) -> None:
        if self._rate <= 0:
            return
        self._sent += nbytes
        ahead = self._sent / self._rate - (time.monotonic() - self._t0)
        if ahead > 0:
            time.sleep(min(ahead, 1.0))


def _entry_bytes(entry: tuple) -> int:
    kind = entry[0]
    if kind == "views":
        return 16 * (len(entry[3]) + len(entry[5])) or 16
    if kind in ("import", "import-value"):
        return len(entry[1])
    return len(entry[1])
