"""Hinted handoff — bounded per-(replica, slice) write hints.

When a quorum write cannot reach a replica, the coordinator queues the
write's effects here, destined for that replica, and the replayer pushes
them when the replica's circuit breaker re-admits traffic (open ->
half-open).  This is the delta-log idiom from the rebalance subsystem
(``rebalance/deltalog.py``) re-keyed by TARGET HOST: entries preserve
application order, the log is bounded per (target, index, slice), and an
overflow drops the slice's hints LOUDLY (counted) — anti-entropy and
read-repair then own convergence for that slice, bounded memory over
unbounded correctness.

Entry kinds (all idempotent to replay):

* ``("views", frame, view, set_rows, set_cols, clear_rows, clear_cols)``
  — exact per-view deltas captured from the fragment write-listener
  during the coordinator's local apply (absolute column ids, the
  ``/fragment/import-view`` wire shape); replays standard, inverse, and
  time views byte-exactly.
* ``("pql", query)`` — the original write call text, for coordinators
  that do not replicate the slice themselves (nothing local to
  capture); replays through the target's whole write path.
* ``("import", payload)`` / ``("import-value", payload)`` — raw
  ``/import`` (protobuf) / ``/import-value`` (JSON) bodies queued by
  the client-side import fan-out via ``POST /replicate/hint``.
"""

from __future__ import annotations

import contextvars
import threading
import time

# Active capture buffer: while a coordinator applies a write locally,
# the write-listener appends (index, slice, frame, view, sets, clears)
# tuples here so failed replicas get the exact local effects as hints.
_capture: "contextvars.ContextVar[list | None]" = contextvars.ContextVar(
    "pilosa_hint_capture", default=None
)


class _CaptureScope:
    def __init__(self, buf: list):
        self._buf = buf
        self._token = None

    def __enter__(self) -> list:
        self._token = _capture.set(self._buf)
        return self._buf

    def __exit__(self, *exc) -> None:
        _capture.reset(self._token)


def capture(buf: list | None = None) -> _CaptureScope:
    """Scope within which local fragment writes record into ``buf``."""
    return _CaptureScope(buf if buf is not None else [])


def record_local_write(frag, set_rows, set_cols, clear_rows, clear_cols) -> None:
    """Write-listener leg: feed the active capture scope (no-op — one
    contextvar read — when no coordinator is capturing)."""
    buf = _capture.get()
    if buf is None:
        return
    buf.append(
        (
            frag.index,
            frag.slice,
            frag.frame,
            frag.view,
            [int(r) for r in set_rows],
            [int(c) for c in set_cols],
            [int(r) for r in clear_rows],
            [int(c) for c in clear_cols],
        )
    )


def entry_bits(entry: tuple) -> int:
    """Cap accounting: logged bits for a views entry, 1 for pql, row
    count for import payloads (pre-computed by the queuer)."""
    kind = entry[0]
    if kind == "views":
        return len(entry[3]) + len(entry[5])
    if kind in ("import", "import-value"):
        return int(entry[2])
    return 1


class HintLog:
    """Ordered hint queues keyed (target_host, index, slice), each
    bounded at ``cap`` bits.  Leaf lock — holders never call out."""

    def __init__(self, cap: int = 10_000, stats=None):
        from pilosa_tpu.obs.stats import NopStatsClient

        self.cap = int(cap)
        self.stats = stats or NopStatsClient()
        self._mu = threading.Lock()
        # (target, index, slice) -> {"entries": [...], "bits": int}
        self._logs: dict[tuple[str, str, int], dict] = {}
        # target -> {"lastReplay": ts, "lastError": str, "replayed": n}
        self._targets: dict[str, dict] = {}
        self.dropped = 0  # hints lost to cap overflow (slices count once)

    # -- queueing ------------------------------------------------------

    def _queue(self, target: str, index: str, slice_i: int, entry: tuple) -> bool:
        n = entry_bits(entry)
        with self._mu:
            log = self._logs.setdefault(
                (target, index, int(slice_i)),
                {"entries": [], "bits": 0, "overflowed": False},
            )
            if log["overflowed"] or log["bits"] + n > self.cap:
                # Overflow: drop the slice's whole backlog and stop
                # accepting until the next replay drain — a PARTIAL
                # hint stream replays to a state that is neither the
                # old nor the new one; the drain's overflow marker
                # makes the replayer checksum-reconcile (full push)
                # instead.
                dropped = len(log["entries"]) + 1
                log["entries"] = []
                log["bits"] = 0
                log["overflowed"] = True
                self.dropped += dropped
                self.stats.count("cluster.replication.hintsDropped", dropped)
                self._targets.setdefault(target, {})
                return False
            log["entries"].append(entry)
            log["bits"] += n
            self._targets.setdefault(target, {})
        return True

    def queue_views(self, target: str, captured: list) -> int:
        """Queue captured local write effects (see :func:`capture`);
        returns entries queued."""
        queued = 0
        for index, slice_i, frame, view, sr, sc, cr, cc in captured:
            if self._queue(
                target, index, slice_i, ("views", frame, view, sr, sc, cr, cc)
            ):
                queued += 1
        return queued

    def queue_pql(self, target: str, index: str, slice_i: int, query: str) -> bool:
        return self._queue(target, index, slice_i, ("pql", query))

    def queue_payload(
        self, target: str, index: str, slice_i: int, kind: str,
        payload: bytes, rows: int,
    ) -> bool:
        """An /import or /import-value body destined for ``target``."""
        if kind not in ("import", "import-value"):
            raise ValueError(f"unknown hint payload kind: {kind!r}")
        return self._queue(target, index, slice_i, (kind, payload, int(rows)))

    # -- replay side ---------------------------------------------------

    def targets(self) -> list[str]:
        """Hosts with a non-empty (or overflowed) backlog."""
        with self._mu:
            return sorted(
                {
                    t
                    for (t, _, _), log in self._logs.items()
                    if log["entries"] or log["overflowed"]
                }
            )

    def drain(self, target: str) -> list[tuple[str, int, list, bool]]:
        """Atomically take every (index, slice, entries, overflowed)
        queued for one target, in application order; the queues stay
        open (and un-overflowed) so writes racing the replay land in
        the next drain.  An overflowed group's entries are empty — the
        replayer must checksum-reconcile that slice instead."""
        out = []
        with self._mu:
            for (t, index, slice_i), log in sorted(self._logs.items()):
                if t != target or not (log["entries"] or log["overflowed"]):
                    continue
                out.append(
                    (index, slice_i, log["entries"], log["overflowed"])
                )
                log["entries"] = []
                log["bits"] = 0
                log["overflowed"] = False
        return out

    def requeue(self, target: str, index: str, slice_i: int, entries: list) -> None:
        """Head-requeue a replay's unapplied tail (push died mid-way)."""
        if not entries:
            return
        with self._mu:
            log = self._logs.setdefault(
                (target, index, int(slice_i)),
                {"entries": [], "bits": 0, "overflowed": False},
            )
            log["entries"] = list(entries) + log["entries"]
            log["bits"] += sum(entry_bits(e) for e in entries)

    def note_replay(self, target: str, replayed: int, error: str = "") -> None:
        with self._mu:
            st = self._targets.setdefault(target, {})
            st["lastReplay"] = time.time()
            st["replayed"] = st.get("replayed", 0) + replayed
            if error:
                st["lastError"] = error
            else:
                st.pop("lastError", None)
            if not error:
                # Fully drained + clean: forget empty queues so the
                # backlog map doesn't grow one key per ever-failed host.
                for key in [
                    k
                    for k, log in self._logs.items()
                    if k[0] == target and not log["entries"]
                ]:
                    del self._logs[key]

    def backlog(self, target: str | None = None) -> int:
        """Queued entry count (one target, or total)."""
        with self._mu:
            return sum(
                len(log["entries"])
                for (t, _, _), log in self._logs.items()
                if target is None or t == target
            )

    def snapshot(self) -> dict:
        """The ``/debug/replication`` hints block: per-target backlog
        (entries/bits/slices), last replay outcome, drop total."""
        with self._mu:
            by_target: dict[str, dict] = {}
            for (t, index, slice_i), log in sorted(self._logs.items()):
                ent = by_target.setdefault(
                    t, {"entries": 0, "bits": 0, "slices": []}
                )
                if log["entries"]:
                    ent["entries"] += len(log["entries"])
                    ent["bits"] += log["bits"]
                    ent["slices"].append(f"{index}/{slice_i}")
                if log["overflowed"]:
                    ent.setdefault("overflowed", []).append(
                        f"{index}/{slice_i}"
                    )
            for t, st in self._targets.items():
                by_target.setdefault(
                    t, {"entries": 0, "bits": 0, "slices": []}
                ).update(st)
            return {"cap": self.cap, "dropped": self.dropped, "targets": by_target}
