"""Quorum replication: tunable W-of-N writes, hinted handoff, and
read-repair (Dynamo-class consistency; DeCandia et al. SOSP'07,
Lakshman & Malik 2010).

Modules: ``versions`` (per-slice monotonic write versions),
``hints`` (bounded per-(replica, slice) hinted-handoff log),
``quorum`` (the W-of-N write coordinator + hint replayer),
``repair`` (version-checked reads with newest->stale convergence).
"""

from pilosa_tpu.replicate.hints import HintLog
from pilosa_tpu.replicate.quorum import (
    CONSISTENCY_LEVELS,
    READ_CONSISTENCY_HEADER,
    WRITE_CONSISTENCY_HEADER,
    WRITE_VERSION_HEADER,
    QuorumWriteError,
    ReadConsistencyError,
    Replication,
    required_acks,
    validate_level,
)
from pilosa_tpu.replicate.repair import RepairError
from pilosa_tpu.replicate.versions import VersionStore

__all__ = [
    "CONSISTENCY_LEVELS",
    "READ_CONSISTENCY_HEADER",
    "WRITE_CONSISTENCY_HEADER",
    "WRITE_VERSION_HEADER",
    "HintLog",
    "QuorumWriteError",
    "ReadConsistencyError",
    "RepairError",
    "Replication",
    "VersionStore",
    "required_acks",
    "validate_level",
]
