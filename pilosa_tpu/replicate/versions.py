"""Per-slice monotonic write versions — the replication staleness oracle.

Every locally-applied fragment write bumps the owning (index, slice)'s
version; the quorum coordinator stamps its post-apply version onto each
remote write leg (``X-Write-Version``), and replicas MAX-MERGE the stamp
into their own counter.  Two replicas that received the same write
stream therefore converge to the same number, and a replica that missed
writes (down, partitioned, shed) sits visibly behind — the read path's
version check and the syncer's skip-if-agree fast path both key on
exactly this comparison, and hint replay closes the gap it exposes.

Versions are advisory (checksums stay the authoritative divergence
detector): equal versions short-circuit work, unequal versions trigger a
checksum comparison, never a blind copy.  The store persists to
``<data-dir>/.replication.json`` at close and on replay ticks so a
cleanly-restarted replica still compares; a crash resets to the last
flush, which reads as stale and costs one checksum agreement round.
"""

from __future__ import annotations

import threading


class VersionStore:
    """Monotonic per-(index, slice) write version counters.

    ``_mu`` is a LEAF lock: bump/observe run inside the fragment write
    path (under the fragment lock via the write-listener hook), so this
    store must never call out while holding it.
    """

    def __init__(self, stats=None):
        from pilosa_tpu.obs.stats import NopStatsClient

        self._mu = threading.Lock()
        self._versions: dict[tuple[str, int], int] = {}
        self.stats = stats or NopStatsClient()

    def bump(self, index: str, slice_i: int) -> int:
        """One locally-applied write: advance and return the version."""
        key = (index, int(slice_i))
        with self._mu:
            v = self._versions.get(key, 0) + 1
            self._versions[key] = v
            return v

    def bump_many(self, index: str, slice_i: int, n: int) -> int:
        """Advance by ``n`` locally-applied writes under ONE lock
        acquisition — WAL recovery replays thousands of ops and stamps
        them in a single call so quorum accounting catches up without
        a per-op lock storm.  Returns the resulting version."""
        if n <= 0:
            return self.get(index, slice_i)
        key = (index, int(slice_i))
        with self._mu:
            v = self._versions.get(key, 0) + int(n)
            self._versions[key] = v
            return v

    def observe(self, index: str, slice_i: int, version: int) -> int:
        """Max-merge a coordinator-stamped (or repair-pushed) version;
        returns the resulting local version.  Never moves backwards."""
        key = (index, int(slice_i))
        version = int(version)
        with self._mu:
            v = self._versions.get(key, 0)
            if version > v:
                v = version
                self._versions[key] = v
            return v

    def get(self, index: str, slice_i: int) -> int:
        with self._mu:
            return self._versions.get((index, int(slice_i)), 0)

    def get_many(self, index: str, slices) -> dict[int, int]:
        with self._mu:
            return {
                int(s): self._versions.get((index, int(s)), 0) for s in slices
            }

    def drop_index(self, index: str) -> None:
        with self._mu:
            self._versions = {
                k: v for k, v in self._versions.items() if k[0] != index
            }

    # -- persistence (.replication.json) -------------------------------

    def to_doc(self) -> dict:
        with self._mu:
            return {f"{i}/{s}": v for (i, s), v in self._versions.items()}

    def load_doc(self, doc: dict) -> None:
        """Restore persisted versions (max-merged, so a partial flush
        can never regress a live counter)."""
        for key, v in (doc or {}).items():
            index, _, slice_s = key.rpartition("/")
            try:
                self.observe(index, int(slice_s), int(v))
            except (TypeError, ValueError):
                continue

    def snapshot(self, per_slice_cap: int = 256) -> dict:
        """The ``/debug/replication`` versions block: per-index summary
        plus the per-slice map (capped — a 10k-slice index summarizes)."""
        with self._mu:
            items = sorted(self._versions.items())
        by_index: dict[str, dict] = {}
        for (index, slice_i), v in items:
            ent = by_index.setdefault(
                index, {"slices": 0, "max": 0, "bySlice": {}}
            )
            ent["slices"] += 1
            ent["max"] = max(ent["max"], v)
            if len(ent["bySlice"]) < per_slice_cap:
                ent["bySlice"][str(slice_i)] = v
        return by_index
