"""Host (numpy) query evaluation — the degraded-mode data plane.

The host planes are AUTHORITATIVE (fragments write host-side and mirror
to HBM), and every device kernel in the system has a backend-generic
numpy formulation: the fold algebra and BSI ripple evaluate through
``plan.eval_expr_np`` (the same ``bsi/ripple.py`` code the fused XLA
programs embed), and TopN scoring is a popcount of ``row AND src`` per
candidate.  So when the accelerator is quarantined
(device/health.py), a node can keep answering BYTE-IDENTICALLY from
host memory — slower, but correct by construction.

This module is that fallback path, production-grade rather than
test-only:

* ``rows`` / ``count`` / ``agg_partials`` cover the Count/Bitmap
  algebra, Range/BSI comparisons (± predicates, between), and the BSI
  aggregates' partial vectors — op-for-op the arrays the device
  programs produce, decoded by the same executor code.
* ``score_topn_parts`` fills the folded TopN scorer's dense count
  vectors from ``Fragment._row_words_host`` rows, matching
  ``bp.score_planes`` exactly (popcount of candidate-row AND src).

Degraded throughput is admission-classed for free: the gates sit in
FRONT of the executor and their shed decision keys on the EWMA of
observed service time per class (net/admission.py), so when host
evaluation stretches service times the node sheds 429+Retry-After at
the door instead of collapsing into queue timeouts.  The
``exec.hostEval.*`` counters and the ``hosteval`` trace span make the
fallback visible per query.
"""

from __future__ import annotations

import time

import numpy as np

from pilosa_tpu.exec import plan
from pilosa_tpu.obs import perf as perf_mod
from pilosa_tpu.ops import bitplane as bp


def popcount_words(arr: np.ndarray) -> int:
    """Popcount of a uint32 word array (numpy>=2 bitwise_count, else
    unpackbits) — the host analog of the fused popcount reduce."""
    if hasattr(np, "bitwise_count"):
        return int(np.bitwise_count(arr).sum())
    return int(np.unpackbits(arr.view(np.uint8)).sum())


class HostEvaluator:
    """Evaluates bitmap call trees over an executor's authoritative
    host planes.  Stateless beyond the executor handle — safe to share
    across request threads."""

    def __init__(self, executor):
        self.ex = executor

    def _count(self, what: str, n: int = 1) -> None:
        self.ex.holder.stats.count_with_custom_tags(
            "exec.hostEval.queries", n, [f"kind:{what}"]
        )

    def _slice_rows(self, index: str, c, slices, reduce: str = "row"):
        """Per-slice evaluated result rows (uint32[words] or None) for
        an already-BSI-rewritten call tree.  The pass streams each
        slice's leaf rows once, so it records into the launch telemetry
        as a ``hosteval`` site launch (the degraded-mode row of the
        /debug/perf roofline table)."""
        expr, leaves = plan.decompose(c)
        t0 = time.monotonic()
        out = {}
        for s in slices:
            rows = [
                self.ex._leaf_row_host(index, leaf, s) for leaf in leaves
            ]
            out[s] = plan.eval_expr_np(expr, rows, bp.WORDS_PER_SLICE)
        n_rows = len(slices) * len(leaves)
        if perf_mod.enabled():
            perf_mod.record_launch(
                "hosteval",
                reduce=reduce,
                rows=n_rows,
                n_bytes=perf_mod.plane_bytes(n_rows, bp.WORDS_PER_SLICE),
                total_ms=(time.monotonic() - t0) * 1e3,
                trace_id=perf_mod.current_trace_id(),
            )
        return out

    def rows(self, index: str, c, slices: list[int]) -> dict:
        """``{slice: uint32[words] | None}`` — the host analog of the
        "row" reduce (None = identically-zero result)."""
        with self.ex.tracer.span("hosteval", kind="row", slices=len(slices)):
            self._count("row")
            return self._slice_rows(index, self.ex._rewrite_bsi(index, c), slices)

    def counts(self, index: str, c, slices: list[int]) -> dict:
        """``{slice: int}`` per-slice popcounts ("count" reduce)."""
        with self.ex.tracer.span("hosteval", kind="count", slices=len(slices)):
            self._count("count")
            rows = self._slice_rows(
                index, self.ex._rewrite_bsi(index, c), slices,
                reduce="count",
            )
            return {
                s: (0 if r is None else popcount_words(r))
                for s, r in rows.items()
            }

    def count_total(self, index: str, c, slices: list[int]) -> int:
        """Count(tree) summed over ``slices`` — the host analog of the
        limb total-count (host Python ints are unbounded, so no limb
        split is needed; totals are identical)."""
        return sum(self.counts(index, c, slices).values())

    def agg_partials(self, index: str, rc, slices: list[int]) -> dict:
        """``{slice: int32 partial vector}`` for a rewritten BSI
        aggregate call (BsiSum/BsiMin/BsiMax) — ``ripple.sum_vec`` /
        ``minmax_vec`` through the numpy backend produce the exact
        arrays the fused "agg" programs return, so the executor's
        decode loop is shared verbatim.  Slices with no planes at all
        are omitted (their device batch rows would be all-zero; the
        all-zero partial vector decodes to "no data" identically, so
        emitting it would be equivalent — omission just skips work)."""
        with self.ex.tracer.span("hosteval", kind="agg", slices=len(slices)):
            self._count("agg")
            expr, leaves = plan.decompose(rc)
            t0 = time.monotonic()
            out = {}
            for s in slices:
                rows = [
                    self.ex._leaf_row_host(index, leaf, s) for leaf in leaves
                ]
                if all(
                    r is None
                    for r, leaf in zip(rows, leaves)
                    if leaf.name not in plan.NEUTRAL_LEAVES
                ):
                    continue
                out[s] = np.asarray(
                    plan.eval_expr_np(expr, rows, bp.WORDS_PER_SLICE)
                )
            n_rows = len(slices) * len(leaves)
            if perf_mod.enabled():
                perf_mod.record_launch(
                    "hosteval",
                    reduce="agg",
                    rows=n_rows,
                    n_bytes=perf_mod.plane_bytes(n_rows, bp.WORDS_PER_SLICE),
                    total_ms=(time.monotonic() - t0) * 1e3,
                    trace_id=perf_mod.current_trace_id(),
                )
            return out

    # ------------------------------------------------------------------
    # TopN scoring
    # ------------------------------------------------------------------

    def score_topn_parts(self, parts) -> None:
        """Fill each TopState's dense count vector HOST-side.

        ``parts``: the executor's score entries ``(st, sub_ref,
        src_words, src_slot, frag)``.  For every dense candidate (the
        positions ``st.dense_pos`` indexes, ids in candidate order),
        the count is ``popcount(row AND src)`` over the fragment's
        authoritative host rows — the arithmetic ``bp.score_planes``
        runs on device, so ``top_score_arrays`` sees identical
        vectors."""
        with self.ex.tracer.span("hosteval", kind="topn", parts=len(parts)):
            self._count("topn")
            t0 = time.monotonic()
            n_rows = 0
            for st, sub_ref, srcw, _slot, frag in parts:
                if sub_ref is None or st.dense_pos is None:
                    continue
                src = np.asarray(srcw, dtype=np.uint32)
                ids = st.cand_ids[st.dense_pos]
                counts = np.zeros(len(ids), dtype=np.int32)
                for i, rid in enumerate(ids):
                    row = frag._row_words_host(int(rid))
                    if row is not None:
                        counts[i] = popcount_words(row & src)
                st.counts = counts
                n_rows += len(ids)
            if perf_mod.enabled():
                perf_mod.record_launch(
                    "hosteval",
                    reduce="topn",
                    rows=n_rows,
                    n_bytes=perf_mod.plane_bytes(n_rows, bp.WORDS_PER_SLICE),
                    total_ms=(time.monotonic() - t0) * 1e3,
                    trace_id=perf_mod.current_trace_id(),
                )
