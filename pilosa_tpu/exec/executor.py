"""Executor — the distributed PQL control plane.

Behavior parity with the reference executor (reference: executor.go):
per-call dispatch, slice-list construction from the index's max slice,
map/reduce over cluster nodes with replica failover, write fan-out to all
replicas, two-phase TopN, bulk-SetRowAttrs fast path, attr broadcast.

TPU-native execution differs in structure, not results:

* A bitmap call tree is compiled to **one fused XLA program per tree
  shape** (exec/plan.py); per slice the leaves are device rows gathered
  from fragment HBM planes, so ``Count(Intersect(a, b))`` runs as a
  single fused bitwise+popcount kernel with no intermediate rows —
  replacing the reference's per-container roaring merges
  (reference: executor.go:438-505 + roaring kernels).
* The local "mapper" batches all local slices' leaves into one stacked
  device array and evaluates the tree **vmapped over slices** in a
  single device program, instead of a goroutine per slice
  (reference: executor.go:1246-1282 mapperLocal).
* Cross-node fan-out keeps the reference's HTTP+protobuf shape via an
  injectable client; intra-host multi-device reduces ride ICI
  collectives (parallel/mesh.py).
"""

from __future__ import annotations

import contextvars
import functools
import threading
import time
import queue
from collections import OrderedDict
from concurrent.futures import FIRST_COMPLETED, Future, wait
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass, field, replace
from datetime import datetime

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu import bsi
from pilosa_tpu import device as device_mod
from pilosa_tpu.bsi import ripple
from pilosa_tpu.device import health as health_mod
from pilosa_tpu.cluster import topology as topo
from pilosa_tpu.cluster.topology import Cluster, Node
from pilosa_tpu.parallel import mesh as pmesh
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.bitmap import RowBitmap
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.core import fragment as fragment_mod
from pilosa_tpu.core.fragment import TopOptions
from pilosa_tpu.core.view import VIEW_INVERSE, VIEW_STANDARD
from pilosa_tpu.exec import coalesce as coalesce_mod
from pilosa_tpu.exec import hosteval as hosteval_mod
from pilosa_tpu.exec import plan
from pilosa_tpu.exec import warmup
from pilosa_tpu.net import resilience
from pilosa_tpu.obs import perf as perf_mod
from pilosa_tpu.obs import trace
from pilosa_tpu.testing import faults
from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.pql.parser import Call, Query

# Absent-row stand-in for anchored count leaf batches: an all-sentinel
# sparse payload at the bucket floor (membership False on every real
# position).  Read-only module constant.
_EMPTY_SPARSE_PAYLOAD = np.full(
    bp.PAYLOAD_BUCKET_FLOOR, bp.FMT_SENTINEL, dtype=np.uint32
)

# reference: executor.go:33-40
DEFAULT_FRAME = "general"
MIN_THRESHOLD = 1
# reference: pilosa.go:107-108
TIME_FORMAT = "%Y-%m-%dT%H:%M"
# reference: config.go (max-writes-per-request default)
DEFAULT_MAX_WRITES_PER_REQUEST = 5000

WRITE_CALLS = frozenset({"SetBit", "ClearBit", "SetRowAttrs", "SetColumnAttrs"})


class ExecutorError(RuntimeError):
    pass


class IndexNotFoundError(ExecutorError):
    def __init__(self):
        super().__init__("index not found")


class FrameNotFoundError(ExecutorError):
    def __init__(self):
        super().__init__("frame not found")


class TooManyWritesError(ExecutorError):
    def __init__(self):
        super().__init__("too many write commands")


class SliceUnavailableError(ExecutorError):
    def __init__(self):
        super().__init__("slice unavailable")


class SlicesUnavailableError(ExecutorError):
    """Every replica for ``slices`` is down or circuit-broken and the
    query did not opt into partial results — fail fast WITH the slice
    list, so the caller knows exactly what it would have lost."""

    def __init__(self, slices, cause: Exception | None = None):
        self.slices = sorted({int(s) for s in slices})
        msg = f"slices unavailable: {self.slices}"
        if cause is not None:
            msg += f" (last error: {cause})"
        super().__init__(msg)


@dataclass
class ExecOptions:
    """reference: executor.go:1302-1304 (+ resilience extensions)"""

    remote: bool = False
    # Per-request consistency overrides (pilosa_tpu/replicate): "" means
    # the server-configured [cluster] write-consistency /
    # read-consistency default; one|quorum|all otherwise.  Ignored when
    # no Replication is wired (bare library executors).
    write_consistency: str = ""
    read_consistency: str = ""
    # Graceful degradation: when every replica for a slice is down or
    # circuit-broken, reduce over the surviving slices and record the
    # lost ones in ``missing_slices`` instead of failing the query.
    allow_partial: bool = False
    # OUT parameter — filled by _map_reduce when allow_partial dropped
    # slices; the handler surfaces it as the partial/missing_slices
    # response marker.  Sorted, deduplicated.
    missing_slices: list[int] = field(default_factory=list)
    # Originating tenant (net/admission.py TenantRegistry): set by the
    # handler after API-key resolution and forwarded as X-Tenant on
    # every remote map leg, so a coordinator's fan-out is charged to
    # the tenant that sent the query on every node it touches.  A
    # field rather than a contextvar: map legs run on pool threads
    # that don't inherit the handler's context.
    tenant: str = ""


@dataclass
class _MapResponse:
    node: Node | None = None
    slices: list[int] = field(default_factory=list)
    result: object = None
    error: Exception | None = None


def needs_slices(calls: list[Call]) -> bool:
    """reference: executor.go:1326-1343"""
    if not calls:
        return False
    return any(c.name not in WRITE_CALLS for c in calls)


def isin_sorted(values: np.ndarray, sorted_ref: np.ndarray) -> np.ndarray:
    """Membership of ``values`` in SORTED-unique ``sorted_ref`` via one
    binary search — np.isin's sort-based path costs ~80 us/call even on
    tiny arrays, and the folded TopN's phase-2 pays it once per slice
    per query."""
    if not len(sorted_ref):
        return np.zeros(len(values), dtype=bool)
    idx = np.searchsorted(sorted_ref, values)
    idx[idx == len(sorted_ref)] = len(sorted_ref) - 1
    return sorted_ref[idx] == values


def merge_counts_by_id(parts):
    """Sum (ids, counts) array pairs by id — Pairs.Add semantics
    (reference: cache.go:312-334), the ONE array implementation of the
    TopN cross-slice reduce.  Returns (uids_sorted_asc, sums) or None
    when empty."""
    parts = [p for p in parts if len(p[0])]
    if not parts:
        return None
    cat_ids = np.concatenate([i for i, _ in parts])
    cat_cnts = np.concatenate([c for _, c in parts])
    uids, inv = np.unique(cat_ids, return_inverse=True)
    sums = np.zeros(len(uids), np.int64)
    np.add.at(sums, inv, cat_cnts)
    return uids, sums


class _DaemonPool:
    """Minimal thread pool with DAEMON workers.

    Stock ThreadPoolExecutor workers are non-daemon and joined at
    interpreter exit, so one mapper wedged inside a device call (an XLA
    runtime fault) turns into a process that never exits.  Query
    fan-out must degrade to a failed query, not a hung shutdown —
    daemon workers die with the process.  Futures are the ordinary
    concurrent.futures kind, so wait()/as_completed compose."""

    def __init__(self, max_workers: int, stats=None):
        from pilosa_tpu.obs.stats import NopStatsClient

        self._max_workers = max_workers
        self._work: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads: list[threading.Thread] = []
        self._idle = 0
        self._mu = threading.Lock()
        self._shutdown = False
        self._cancel_pending = False
        # Pool visibility (/metrics): queued-but-unclaimed items, items
        # being run right now, and total worker threads ever spawned —
        # without these the pool's contribution to query latency is
        # unattributable (and coalescing wins invisible).
        self.stats = stats or NopStatsClient()
        self._depth = 0
        self._active = 0
        # Zero-publish up front: an idle pool is visible in /metrics
        # from boot, not only after its first fan-out.
        self._publish()

    def _publish(self) -> None:
        # Advisory reads outside _mu: gauges are monotonic snapshots,
        # and a stats backend must never extend the pool's critical
        # section.
        self.stats.gauge("exec.pool.queueDepth", float(self._depth))
        self.stats.gauge("exec.pool.activeWorkers", float(self._active))

    def submit(self, fn, *args, **kwargs) -> Future:
        fut: Future = Future()
        # Carry the submitter's contextvars into the worker so trace
        # spans started in a mapper attach to the submitting request's
        # trace (obs/trace.py keeps the current span in a ContextVar).
        ctx = contextvars.copy_context()
        spawned = False
        with self._mu:
            if self._shutdown:
                raise RuntimeError("cannot submit after shutdown")
            self._work.put((fut, ctx, fn, args, kwargs))
            self._depth += 1
            # Spawn only when no idle worker can take the item (the
            # counter is advisory; a race costs one extra thread, never
            # a lost task).
            if self._idle == 0 and len(self._threads) < self._max_workers:
                t = threading.Thread(
                    target=self._worker, daemon=True, name="exec-pool"
                )
                self._threads.append(t)
                t.start()
                spawned = True
        if spawned:
            self.stats.count("exec.pool.spawned")
        self._publish()
        return fut

    def _worker(self) -> None:
        while True:
            with self._mu:
                self._idle += 1
            item = self._work.get()
            with self._mu:
                self._idle -= 1
            if item is None:  # retire (shutdown)
                return
            fut, ctx, fn, args, kwargs = item
            with self._mu:
                self._depth -= 1
                self._active += 1
            self._publish()
            try:
                if self._cancel_pending:
                    fut.cancel()
                    continue
                if not fut.set_running_or_notify_cancel():
                    continue
                try:
                    fut.set_result(ctx.run(fn, *args, **kwargs))
                except BaseException as e:  # noqa: BLE001 — crosses the future
                    fut.set_exception(e)
            finally:
                with self._mu:
                    self._active -= 1
                self._publish()

    def shutdown(self, wait: bool = True, cancel_futures: bool = False) -> None:
        with self._mu:
            if self._shutdown:
                return
            self._shutdown = True
            self._cancel_pending = cancel_futures
            threads = list(self._threads)
        for _ in threads:
            self._work.put(None)
        if wait:
            for t in threads:
                t.join()


class Executor:
    """Executes PQL queries against a holder, fanning out across a cluster.

    ``client_factory(node) -> client`` supplies the inter-node data plane;
    the client must expose ``execute_query(index, query, slices, remote)
    -> list`` (see net/client.py).  Single-node setups never invoke it.
    """

    def __init__(
        self,
        holder,
        host: str = "",
        cluster: Cluster | None = None,
        client_factory=None,
        max_writes_per_request: int = DEFAULT_MAX_WRITES_PER_REQUEST,
        tracer=None,
        prefetcher=None,
        coalescer=None,
        replication=None,
        device_health=None,
    ):
        self.holder = holder
        self.host = host
        self.cluster = cluster or Cluster(nodes=[Node(host=host)])
        self.client_factory = client_factory
        self.max_writes_per_request = max_writes_per_request
        self.tracer = tracer or trace.NOP_TRACER
        # Quorum replication (pilosa_tpu/replicate): when wired (Server
        # does), write fan-out becomes W-of-N with hinted handoff and
        # reads at quorum/all consistency version-check their replicas
        # (read-repair on divergence).  None = the legacy best-effort
        # fan-out (bare library use, remote legs).
        self.replication = replication
        # Async HBM mirror prefetcher (device/prefetch.py): when wired
        # (Server does, gated on [device] prefetch), a query's cold leaf
        # mirrors re-materialize concurrently while planning proceeds.
        # None = disabled (bare library use stays fully deterministic).
        self.prefetcher = prefetcher
        # Durable-ingest manager (pilosa_tpu/ingest): when wired (Server
        # does, gated on [ingest] wal), point-write acks block on the
        # WAL group commit — the write returns only after its op record
        # is fsynced (or captured by a completed snapshot).  None =
        # the historical op-buf durability (bare library use).
        self.ingest = None
        # Cross-query coalescing scheduler (exec/coalesce.py): when
        # wired (Server does, gated on [exec] coalesce), concurrent
        # queries sharing a compile key ride ONE fused launch.  The
        # scheduler is OWNED by whoever wired it (Server.close /
        # bench), not by this executor — several executors may share
        # one.  None = every query dispatches its own launch.
        self.coalescer = coalescer
        # Device-health subsystem (device/health.py): classifies launch
        # failures, drives the per-device/collective quarantine state
        # machine, and owns the hung-collective watchdog.  The Server
        # wires a configured instance (shared with its coalescer and
        # gossiped to peers); bare library executors build a default so
        # device-fault tolerance is never off.
        self._owns_health = device_health is None
        self.device_health = device_health or health_mod.DeviceHealth(
            stats=getattr(holder, "stats", None)
        )
        # Host (numpy) evaluator over the authoritative host planes —
        # the degraded-mode data plane a quarantined device falls back
        # to, byte-identical by construction (exec/hosteval.py).
        self.hosteval = hosteval_mod.HostEvaluator(self)
        # (expr, reduce, batch shape) programs this executor has already
        # dispatched — distinguishes compile-bearing first calls from
        # pure execution in the device span annotations.
        self._seen_programs: set = set()
        self._pool = _DaemonPool(
            max_workers=16, stats=getattr(holder, "stats", None)
        )
        self._zero_rows: dict = {}  # device -> cached all-zero leaf row
        # (value, bucket, device) -> packed BSI predicate row on device.
        self._pred_rows: dict = {}
        # Assembled leaf-batch LRU (see _cached_batch); executors serve
        # concurrent HTTP request threads, so access is lock-guarded.
        self._batch_cache: "OrderedDict[tuple, dict]" = OrderedDict()
        self._batch_mu = threading.Lock()
        # Folded-TopN prep LRU (see _topn_folded_entry) — candidate
        # walks, union assembly, and gather prep cached per (query,
        # slice set), validated like _batch_cache entries.
        self._topn_cache: "OrderedDict[tuple, dict]" = OrderedDict()
        # slice->node grouping LRU (see _slices_by_node) — host-only
        # dicts, no device bytes, so unlike the two caches above it is
        # NOT a residency-pool tenant; the count cap bounds it.
        self._slice_group_cache: "OrderedDict[tuple, dict]" = OrderedDict()
        # A fragment leaving service (delete/teardown) must release the
        # TopN prep entries pinning its HBM plane snapshots now, not at
        # LRU displacement (held weakly — see fragment._close_listeners).
        fragment_mod.register_close_listener(self._drop_closed_fragment)

    def close(self) -> None:
        fragment_mod.unregister_close_listener(self._drop_closed_fragment)
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self._owns_health:
            self.device_health.close()
        # Deregister every cache entry from the residency pool so a
        # closed executor's device arrays stop counting as resident.
        pool = device_mod.pool()
        with self._batch_mu:
            batch_keys = list(self._batch_cache)
            topn_keys = list(self._topn_cache)
            self._batch_cache.clear()
            self._topn_cache.clear()
        for k in batch_keys:
            pool.remove(self._batch_pool_key(k))
        for k in topn_keys:
            pool.remove(self._topn_pool_key(k))

    def _drop_closed_fragment(self, frag) -> None:
        with self._batch_mu:
            stale = [
                k
                for k, e in self._topn_cache.items()
                if any(p[0] is frag for p in e.get("parts", ()))
            ]
            for k in stale:
                del self._topn_cache[k]
        for k in stale:
            device_mod.pool().remove(self._topn_pool_key(k))

    # ------------------------------------------------------------------
    # HBM residency-pool tenancy (device/pool.py): both device-holding
    # caches are byte-accounted pool tenants — the pool's LRU eviction
    # (not just the entry-count caps) bounds their device footprint.
    # ------------------------------------------------------------------

    def _batch_pool_key(self, key: tuple) -> tuple:
        return ("exec", id(self), "batch", key)

    def _topn_pool_key(self, key: tuple) -> tuple:
        return ("exec", id(self), "topn", key)

    def _register_cache_entry(self, pool_key, arrays, info, evict):
        """Admit a cache entry's device arrays to the residency pool;
        returns the pool key, or None when nothing lives on device."""
        bbd: dict = {}
        for arr in arrays:
            for d, n in device_mod.bytes_by_device(arr).items():
                bbd[d] = bbd.get(d, 0) + n
        if not bbd:
            return None
        device_mod.pool().admit(
            pool_key, bbd, evict, category="cache", info=info
        )
        return pool_key

    def _evict_batch_key(self, key: tuple) -> bool:
        """Pool eviction hook for a batch-cache entry.  Non-blocking:
        the pool invokes this under ITS lock while request threads
        hold ``_batch_mu`` around cache reads/inserts (pool tenancy
        itself is registered outside ``_batch_mu`` — see
        _cached_batch_build), so a blocking acquire here could still
        deadlock through that interleaving — skipping a busy cache is
        always safe.  The lock-order analyzer (pilosa_tpu/analyze)
        tracks this as a non-blocking edge."""
        if not self._batch_mu.acquire(blocking=False):
            return False
        try:
            self._batch_cache.pop(key, None)
            return True
        finally:
            self._batch_mu.release()

    def _evict_topn_key(self, key: tuple) -> bool:
        if not self._batch_mu.acquire(blocking=False):
            return False
        try:
            self._topn_cache.pop(key, None)
            return True
        finally:
            self._batch_mu.release()

    # ------------------------------------------------------------------
    # entry point (reference: executor.go:65-151)
    # ------------------------------------------------------------------

    def execute(
        self,
        index: str,
        q: Query,
        slices: list[int] | None = None,
        opt: ExecOptions | None = None,
    ) -> list:
        if not index:
            raise ExecutorError("index required")
        if (
            self.max_writes_per_request > 0
            and q.write_call_n() > self.max_writes_per_request
        ):
            raise TooManyWritesError()
        opt = opt or ExecOptions()

        slices = list(slices) if slices else []
        inverse_slices: list[int] = []
        column_label = "columnID"
        want_slices = needs_slices(q.calls)
        # Inverse orientation only swaps in the inverse slice list when this
        # node computed the lists itself; a coordinator-provided list (remote
        # leg) already has the right orientation and must be used as-is.
        computed_lists = False
        if not slices and want_slices:
            idx = self.holder.index(index)
            if idx is None:
                raise IndexNotFoundError()
            slices = list(range(idx.max_slice() + 1))
            inverse_slices = list(range(idx.max_inverse_slice() + 1))
            column_label = idx.column_label
            computed_lists = True

        # Cost-class accounting (exec/plan.py cost_class): the same
        # classification the admission layer gates on, counted here so
        # the executor-side mix is visible even for direct library use
        # (no HTTP front) — dashboards correlate exec.class.* against
        # net.admission.* to see what the gates actually passed.
        class_tags = [f"class:{plan.cost_class(q.calls)}"]
        if opt.tenant:
            # Tenant-tagged only when QoS resolved one: untagged
            # (library / single-tenant) deployments keep the exact
            # class-only series their dashboards already chart.
            class_tags.append(f"tenant:{opt.tenant}")
        self.holder.stats.count_with_custom_tags("exec.class", 1, class_tags)

        # Bulk attribute-insert fast path (reference: executor.go:119-122).
        if q.calls and all(c.name == "SetRowAttrs" for c in q.calls):
            return self._execute_bulk_set_row_attrs(index, q.calls, opt)

        # Version-checked replica reads (pilosa_tpu/replicate): at
        # read consistency quorum/all the touched slices' replica
        # versions must agree before execution — divergence triggers a
        # synchronous read-repair (newest -> stale, checksum-verified),
        # which is what makes read-your-writes hold at W+R > N.  The
        # default level "one" costs nothing here.
        if (
            self.replication is not None
            and not opt.remote
            and slices
            and any(c.name not in WRITE_CALLS for c in q.calls)
        ):
            level = self.replication.read_consistency_for(opt)
            if level != "one":
                with self.tracer.span(
                    "replicate.read", consistency=level
                ) as sp:
                    repaired = self.replication.ensure_read_consistency(
                        index, slices, level
                    )
                    sp.annotate(repaired=repaired)

        # Async HBM prefetch: kick cold leaf-mirror uploads for the whole
        # query now, so host->device staging overlaps the per-call
        # planning below (per-fragment locks synchronize the rendezvous).
        if self.prefetcher is not None and slices:
            self._prefetch_query(index, q.calls, slices)

        results = []
        for call in q.calls:
            # Per-call deadline gate: a multi-call query whose budget
            # ran out mid-way fails with 504 rather than starting the
            # next call's fan-out.
            resilience.check_deadline(f"before call {call.name}")
            call_slices = slices
            if call.supports_inverse() and want_slices and computed_lists:
                frame = call.args.get("frame") or DEFAULT_FRAME
                f = self.holder.frame(index, frame)
                if f is None:
                    raise FrameNotFoundError()
                if call.is_inverse(f.row_label, column_label):
                    call_slices = inverse_slices
            with self.tracer.span(f"call.{call.name}", index=index):
                results.append(
                    self._execute_call(index, call, call_slices, opt)
                )
        return results

    def _prefetch_query(self, index: str, calls, slices: list[int]) -> None:
        """Walk the query's leaf fragments (exec/plan tree + TopN frame)
        and schedule cold-mirror uploads on the prefetcher.  Strictly
        best-effort: any resolution error here is swallowed — the call's
        own execution raises the authoritative error.  Frame/view
        resolution is hoisted out of the per-slice loop and only COLD
        fragments collect, so the all-warm steady state costs one dict
        lookup + two attribute compares per existing fragment."""
        frags: list = []
        seen: set[int] = set()

        def add_view(frame_name: str, view_name: str) -> None:
            v = self.holder.view(index, frame_name, view_name)
            if v is None:
                return
            have = v.fragment_slices()
            for s in slices:
                if s not in have:
                    continue
                frag = v.fragment(s)
                if frag is None or id(frag) in seen:
                    continue
                # Advisory cold check (no lock): a racing writer only
                # flips a mirror cold; the worker re-checks under the
                # fragment lock.
                if (
                    frag._device is None
                    or frag._device_version != frag._version
                ):
                    seen.add(id(frag))
                    frags.append(frag)

        try:
            idx = self.holder.index(index)
            if idx is None:
                return
            for call in calls:
                if call.name in WRITE_CALLS:
                    continue
                for leaf in plan.collect_leaf_calls(call):
                    if leaf.name == "Range" and leaf.conditions():
                        # BSI Range: warm the field view's plane mirrors.
                        frame = leaf.args.get("frame") or DEFAULT_FRAME
                        for field_name in leaf.conditions():
                            add_view(frame, bsi.field_view_name(field_name))
                        continue
                    if leaf.name != "Bitmap":
                        continue
                    frame = leaf.args.get("frame") or DEFAULT_FRAME
                    _, col_ok = _uint_arg(leaf, idx.column_label)
                    add_view(
                        frame, VIEW_INVERSE if col_ok else VIEW_STANDARD
                    )
                if call.name == "TopN":
                    add_view(*self._topn_frame_view(call))
                if call.name in ("Sum", "Min", "Max") and isinstance(
                    call.args.get("field"), str
                ):
                    add_view(
                        call.args.get("frame") or DEFAULT_FRAME,
                        bsi.field_view_name(call.args["field"]),
                    )
        except Exception:  # noqa: BLE001 — prefetch must never fail a query
            return
        if frags:
            with self.tracer.span("prefetch", fragments=len(frags)):
                self.prefetcher.prefetch(frags)

    # ------------------------------------------------------------------
    # dispatch (reference: executor.go:156-182)
    # ------------------------------------------------------------------

    def _execute_call(self, index: str, c: Call, slices: list[int], opt: ExecOptions):
        name = c.name
        if name == "ClearBit":
            return self._execute_clear_bit(index, c, opt)
        if name == "SetBit":
            return self._execute_set_bit(index, c, opt)
        if name == "SetRowAttrs":
            self._execute_set_row_attrs(index, c, opt)
            return None
        if name == "SetColumnAttrs":
            self._execute_set_column_attrs(index, c, opt)
            return None
        # Read calls count per call name with the index tag (reference:
        # executor.go:163-181) — the per-query stats surface dashboards
        # key on.
        self.holder.stats.count_with_custom_tags(name, 1, [f"index:{index}"])
        if name == "Count":
            return self._execute_count(index, c, slices, opt)
        if name == "TopN":
            return self._execute_topn(index, c, slices, opt)
        if name in ("Sum", "Min", "Max"):
            return self._execute_bsi_agg(index, c, slices, opt)
        return self._execute_bitmap_call(index, c, slices, opt)

    # ------------------------------------------------------------------
    # bitmap call trees — fused device programs
    # ------------------------------------------------------------------

    def _leaf_row_device(self, index: str, c: Call, slice_i: int):
        """Fetch one leaf row as a device (or None=empty) uint32[32768]."""
        if c.name == "Bitmap":
            frag, row_id = self._resolve_bitmap_leaf(index, c, slice_i)
            if frag is None:
                return None
            return frag.device_row(row_id)
        if c.name == "Range":
            return self._range_row_device(index, c, slice_i)
        if c.name == "BsiPlane":
            frag = self._bsi_plane_fragment(index, c, slice_i)
            if frag is None:
                return None
            return frag.device_row(c.args["row"])
        if c.name == "BsiPred":
            return self._pred_row_device(c, slice_i)
        if c.name == "BsiZero":
            return None
        raise plan.PlanError(f"unknown call: {c.name}")

    def _bsi_plane_fragment(self, index: str, c: Call, slice_i: int):
        return self.holder.fragment(
            index, c.args["frame"], bsi.field_view_name(c.args["field"]), slice_i
        )

    def _pred_row_device(self, c: Call, slice_i: int):
        """A packed predicate row on a slice's home device, cached per
        (value, bucket, device) — predicates repeat across slices and
        across queries, so the upload happens once."""
        import jax

        dev = bp.home_device(slice_i)
        key = (c.args["v"], c.args["d"], dev)
        row = self._pred_rows.get(key)
        if row is None:
            row = jax.device_put(bsi.pred_row(c.args["v"], c.args["d"]), dev)
            if len(self._pred_rows) >= 256:
                self._pred_rows.clear()
            self._pred_rows[key] = row
        return row

    def _resolve_bitmap_leaf(self, index: str, c: Call, slice_i: int):
        """Frame/row/orientation resolution for a Bitmap() leaf
        (reference: executor.go:438-484 executeBitmapSlice)."""
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError()
        column_label = idx.column_label
        frame = c.args.get("frame") or DEFAULT_FRAME
        f = self.holder.frame(index, frame)
        if f is None:
            raise FrameNotFoundError()
        row_label = f.row_label

        row_id, row_ok = _uint_arg(c, row_label)
        col_id, col_ok = _uint_arg(c, column_label)
        if row_ok and col_ok:
            raise ExecutorError(
                f"Bitmap() cannot specify both {row_label} and {column_label} values"
            )
        if not row_ok and not col_ok:
            raise ExecutorError(
                f"Bitmap() must specify either {row_label} or {column_label} values"
            )
        view, id_ = VIEW_STANDARD, row_id
        if col_ok:
            view, id_ = VIEW_INVERSE, col_id
            if not f.inverse_enabled:
                raise ExecutorError(
                    "Bitmap() cannot retrieve columns unless inverse storage enabled"
                )
        frag = self.holder.fragment(index, frame, view, slice_i)
        return frag, id_

    def _resolve_range(self, idx, f, c: Call):
        """Shared Range() argument resolution for the device and host
        row paths: (view_name, id, start, end, quantum)."""
        column_label = idx.column_label
        row_label = f.row_label
        col_id, col_ok = _uint_arg(c, column_label)
        row_id, row_ok = _uint_arg(c, row_label)
        if col_ok and row_ok:
            raise ExecutorError(
                f'Range() cannot contain both "{column_label}" and "{row_label}"'
            )
        if not col_ok and not row_ok:
            raise ExecutorError(
                f'Range() must specify either "{column_label}" or "{row_label}"'
            )
        view_name, id_ = (VIEW_INVERSE, col_id) if col_ok else (VIEW_STANDARD, row_id)
        return view_name, id_, _time_arg(c, "start"), _time_arg(c, "end"), f.time_quantum

    def _range_row_device(self, index: str, c: Call, slice_i: int):
        """Union of rows across time views (reference: executor.go:507-589)."""
        frame = c.args.get("frame") or DEFAULT_FRAME
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError()
        f = idx.frame(frame)
        if f is None:
            raise FrameNotFoundError()
        view_name, id_, start, end, quantum = self._resolve_range(idx, f, c)
        if not quantum:
            return None

        acc = None
        for view in tq.views_by_time_range(view_name, start, end, quantum):
            frag = self.holder.fragment(index, frame, view, slice_i)
            if frag is None:
                continue
            row = frag.device_row(id_)
            if row is None:
                continue
            acc = row if acc is None else (acc | row)
        return acc

    # ------------------------------------------------------------------
    # BSI rewrite — Range(field > x) / Sum / Min / Max expansion
    # ------------------------------------------------------------------

    def _bsi_resolve_field(self, index: str, c: Call):
        """(frame name, BSIField) for a BSI call — schema errors surface
        here, before any leaf machinery runs."""
        frame = c.args.get("frame") or DEFAULT_FRAME
        f = self.holder.frame(index, frame)
        if f is None:
            raise FrameNotFoundError()
        if not f.range_enabled:
            raise ExecutorError(
                f"frame {frame!r} does not support range queries"
            )
        return frame, f

    def _bsi_field_leaves(self, frame: str, fld) -> tuple[list[Call], int]:
        """The plane leaves of one field, padded to its depth bucket:
        exists, sign, ``depth`` magnitude planes, then all-zero pads —
        so every field in a bucket shares one compile shape (and one
        coalescer compile key) per op kind."""
        depth = fld.bit_depth
        bucket = bsi.pad_depth(depth)
        leaves = [
            Call("BsiPlane", {"frame": frame, "field": fld.name, "row": r})
            for r in (bsi.ROW_EXISTS, bsi.ROW_SIGN)
        ]
        leaves += [
            Call(
                "BsiPlane",
                {"frame": frame, "field": fld.name, "row": bsi.ROW_BIT_BASE + k},
            )
            for k in range(depth)
        ]
        leaves += [Call("BsiZero") for _ in range(bucket - depth)]
        return leaves, bucket

    def _rewrite_bsi(self, index: str, c: Call) -> Call:
        """Expand BSI Range calls (a comparison arg present) anywhere in
        a call tree into synthetic ``BsiCmp`` nodes over plane/predicate
        leaves; returns the ORIGINAL object when nothing changed, so
        non-BSI queries keep their cache keys byte-identical.  Runs on
        the node that executes the slices (map_fn side) — remote
        forwarding ships the un-rewritten PQL text, and each node
        re-expands against its own schema."""
        if c.name == "Range" and c.conditions():
            return self._rewrite_bsi_range(index, c)
        new_children = [self._rewrite_bsi(index, ch) for ch in c.children]
        if all(nc is oc for nc, oc in zip(new_children, c.children)):
            return c
        return Call(name=c.name, args=dict(c.args), children=new_children)

    def _rewrite_bsi_range(self, index: str, c: Call) -> Call:
        conds = c.conditions()
        if len(conds) != 1:
            raise ExecutorError(
                "Range() supports exactly one field comparison"
                " (use >< for between)"
            )
        (field_name, cond), = conds.items()
        frame, f = self._bsi_resolve_field(index, c)
        fld = f.bsi_field(field_name)
        if fld is None:
            raise ExecutorError(f"unknown field: {field_name!r}")
        op = bsi.OPS.get(cond.op)
        if op is None:
            raise ExecutorError(f"unknown comparison: {cond.op!r}")
        depth = fld.bit_depth
        leaves, bucket = self._bsi_field_leaves(frame, fld)
        if op == "between":
            v = cond.value
            if (
                not isinstance(v, list)
                or len(v) != 2
                or any(isinstance(x, bool) or not isinstance(x, int) for x in v)
            ):
                raise ExecutorError("between (><) requires a two-int list")
            lo, hi = bsi.clamp_between(v[0], v[1], depth)
            leaves.append(Call("BsiPred", {"v": lo, "d": bucket}))
            leaves.append(Call("BsiPred", {"v": hi, "d": bucket}))
        else:
            v = cond.value
            if isinstance(v, bool) or not isinstance(v, int):
                raise ExecutorError(
                    f"Range() comparison value must be an integer, got {v!r}"
                )
            op, v = bsi.clamp_predicate(op, v, depth)
            leaves.append(Call("BsiPred", {"v": v, "d": bucket}))
        return Call("BsiCmp", {"op": op}, children=leaves)

    def _rewrite_bsi_agg(self, index: str, c: Call) -> Call:
        """Expand Sum/Min/Max(frame=, field=, [filter child]) into the
        synthetic aggregate node the plan layer compiles (one fused
        program per (kind, depth bucket, filter-present))."""
        if len(c.children) > 1:
            raise ExecutorError(f"{c.name}() can only have one input bitmap")
        field_name = c.args.get("field")
        if not isinstance(field_name, str):
            raise ExecutorError(f"{c.name}() field required")
        frame, f = self._bsi_resolve_field(index, c)
        fld = f.bsi_field(field_name)
        if fld is None:
            raise ExecutorError(f"unknown field: {field_name!r}")
        leaves, bucket = self._bsi_field_leaves(frame, fld)
        has_filter = bool(c.children)
        if has_filter:
            leaves.append(self._rewrite_bsi(index, c.children[0]))
        return Call(
            "Bsi" + c.name,
            {"filter": has_filter, "nplanes": bucket},
            children=leaves,
        )

    def _leaf_row_host(self, index: str, c: Call, slice_i: int):
        """Host-side (numpy) variant of _leaf_row_device: one leaf row's
        words, or None when the row has no bits."""
        if c.name == "Bitmap":
            frag, row_id = self._resolve_bitmap_leaf(index, c, slice_i)
            if frag is None:
                return None
            return frag._row_words_host(row_id)
        if c.name == "Range":
            return self._range_row_host(index, c, slice_i)
        if c.name == "BsiPlane":
            frag = self._bsi_plane_fragment(index, c, slice_i)
            if frag is None:
                return None
            return frag._row_words_host(c.args["row"])
        if c.name == "BsiPred":
            return bsi.pred_row(c.args["v"], c.args["d"])
        if c.name == "BsiZero":
            return None
        raise plan.PlanError(f"unknown call: {c.name}")

    def _range_row_host(self, index: str, c: Call, slice_i: int):
        frame = c.args.get("frame") or DEFAULT_FRAME
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError()
        f = idx.frame(frame)
        if f is None:
            raise FrameNotFoundError()
        view_name, id_, start, end, quantum = self._resolve_range(idx, f, c)
        if not quantum:
            return None
        acc = None
        for view in tq.views_by_time_range(view_name, start, end, quantum):
            frag = self.holder.fragment(index, frame, view, slice_i)
            if frag is None:
                continue
            row = frag._row_words_host(id_)
            if row is None:
                continue
            acc = row if acc is None else (acc | row)
        return acc

    def _assemble_host_batch(self, index: str, leaves, slices: list[int]):
        """Assemble the single-device batch HOST-SIDE: one numpy fill
        plus ONE device transfer, instead of ~2 device dispatches per
        (slice, leaf) — at bench scale (954 slices) the dispatch-per-leaf
        cold path costs thousands of round trips, which a remote-tunnel
        TPU amplifies badly.  The host plane is authoritative, so this
        is always coherent.  Returns (batch, kept, empties)."""
        n_leaves = len(leaves)
        rows_buf = np.zeros(
            (len(slices), n_leaves, bp.WORDS_PER_SLICE), dtype=np.uint32
        )
        kept: list[int] = []
        empties: list[int] = []
        i = 0
        for s in slices:
            any_set = False
            for j, leaf in enumerate(leaves):
                w = self._leaf_row_host(index, leaf, s)
                if w is not None:
                    rows_buf[i, j] = w
                    if leaf.name not in plan.NEUTRAL_LEAVES:
                        any_set = True
            if not leaves or not any_set:
                # an empty slice writes nothing, so position i stays
                # zero-initialized for the next kept slice
                empties.append(s)
            else:
                kept.append(s)
                i += 1
        if not kept:
            return None, kept, empties
        bucket = plan.slice_bucket(len(kept))
        if bucket <= rows_buf.shape[0]:
            # positions past the last kept slice were never written
            batch_np = rows_buf[:bucket]
        else:
            batch_np = np.zeros(
                (bucket, n_leaves, bp.WORDS_PER_SLICE), dtype=np.uint32
            )
            batch_np[: len(kept)] = rows_buf[: len(kept)]
        return jnp.asarray(batch_np), kept, empties

    def _gather_leaf_stacks(self, index: str, c: Call, slices: list[int]):
        """Fetch every slice's leaf rows onto its home device.

        Returns ``(expr, stacks, kept_slices, empties)``: ``stacks[i]``
        is uint32[n_leaves, 32768] for ``kept_slices[i]`` (device-local);
        ``empties`` are slices where no leaf has any bits (their result
        is identically zero for every tree shape)."""
        expr, leaves = plan.decompose(c)
        stacks: list[object] = []
        kept_slices: list[int] = []
        empties: list[int] = []
        for s in slices:
            rows = []
            any_set = False
            for leaf in leaves:
                r = self._leaf_row_device(index, leaf, s)
                if r is None:
                    r = self._zero_row(s)
                elif leaf.name not in plan.NEUTRAL_LEAVES:
                    any_set = True
                rows.append(r)
            if not leaves or not any_set:
                empties.append(s)
                continue
            # All of a slice's leaves live on its home device, so this
            # stack stays device-local.
            stacks.append(jnp.stack(rows))
            kept_slices.append(s)
        return expr, stacks, kept_slices, empties

    # Assembled leaf batches kept per (index, canonical call, slice set):
    # the working set of a hot query is one entry.  Each entry holds
    # device memory comparable to the queried planes, so entries are
    # byte-accounted residency-pool tenants (device/pool.py) — under an
    # HBM budget the pool's LRU eviction, not this count cap, is the
    # operative bound; the cap remains as the unbounded-budget backstop.
    _BATCH_CACHE_CAP = 4

    def _cached_batch(self, index: str, c: Call, slices: list[int]):
        """Traced wrapper over :meth:`_cached_batch_build` — the "plan"
        stage of a query trace (tree decomposition + leaf batch
        assembly), annotated with whether the batch cache served it."""
        with self.tracer.span("plan", slices=len(slices)) as sp:
            return self._cached_batch_build(index, c, slices, sp)

    def _cached_batch_build(self, index: str, c: Call, slices: list[int], sp):
        """The assembled device batch for a bitmap call tree over
        ``slices``, CACHED across queries.

        At bench scale the per-slice Python loop in _gather_leaf_stacks
        costs ~2 device dispatches per (slice, leaf) — thousands of
        host-side operations before the fused program runs, where the
        reference's goroutine-per-slice mapperLocal amortizes to ~zero
        (reference: executor.go:1246-1282).  Repeated query shapes skip
        it entirely: entries validate in O(1) against the global
        fragment write epoch, then (only when some fragment changed
        anywhere) against the per-fragment version vector.  Range
        leaves' validity entries additionally carry the frame's time
        quantum and every time-view fragment's version (the view set
        depends on the quantum; set_time_quantum bumps the write epoch
        so the O(1) fast path stays sound)."""
        c = self._rewrite_bsi(index, c)
        expr, leaves = plan.decompose(c)
        cacheable = all(leaf.name in plan.LEAF_CALLS for leaf in leaves)
        key = (index, str(c), tuple(slices))
        if cacheable:
            with self._batch_mu:
                ent = self._batch_cache.get(key)
            if ent is not None:
                epoch = fragment_mod.write_epoch()
                if ent["epoch"] == epoch or ent[
                    "versions"
                ] == self._leaf_versions(index, leaves, slices):
                    ent["epoch"] = epoch
                    with self._batch_mu:
                        if key in self._batch_cache:
                            self._batch_cache.move_to_end(key)
                    device_mod.pool().touch(self._batch_pool_key(key))
                    sp.annotate(batch_cache="hit")
                    return ent

        sp.annotate(batch_cache="miss")
        # Capture validity BEFORE building: a concurrent write during
        # assembly leaves the entry conservatively stale.  The same
        # sweep counts mirror-less fragments for the cold-path choice.
        epoch = fragment_mod.write_epoch()
        versions = None
        n_frag = n_cold = 0
        if cacheable:
            versions, n_frag, n_cold = self._leaf_versions(
                index, leaves, slices, with_cold=True
            )
        mesh = pmesh.default_slices_mesh()
        ent = {
            "batch": None,
            "pos_of": {},
            "mesh": None,
            "epoch": epoch,
            "versions": versions,
        }
        if mesh is None:
            # Single device: assemble HOST-side (one numpy fill + one
            # transfer; the slice axis pads to a power of two — one
            # compiled program per (tree shape, bucket), SURVEY.md §7
            # shape bucketing).
            batch, kept_slices, empties = self._assemble_host_batch(
                index, leaves, slices
            )
            ent.update(
                expr=expr,
                empties=empties,
                kept=kept_slices,
                batch=batch,
                pos_of={s: i for i, s in enumerate(kept_slices)},
            )
        elif cacheable and n_cold * 2 > n_frag:
            # MOSTLY-cold fragments: assemble per-device blocks HOST-
            # side from the authoritative planes — one transfer per
            # device instead of ~2 device dispatches per (slice, leaf),
            # and no full-plane uploads just to gather two rows.  A
            # mostly-WARM set (e.g. one fragment invalidated by a write)
            # keeps the device-gather path, which re-uploads only the
            # changed planes.
            batch, pos_of, kept_slices, empties = self._assemble_mesh_batch_host(
                index, leaves, slices, mesh
            )
            ent.update(expr=expr, empties=empties, kept=kept_slices)
            if batch is not None:
                ent.update(
                    batch=batch,
                    pos_of=pos_of,
                    mesh=mesh if len(kept_slices) > 1 else None,
                )
        else:
            # Warm device mirrors (or Range trees): gather rows straight
            # from HBM-resident planes — nothing crosses host<->device.
            expr, stacks, kept_slices, empties = self._gather_leaf_stacks(
                index, c, slices
            )
            ent.update(expr=expr, empties=empties, kept=kept_slices)
            if len(kept_slices) > 1:
                batch, pos_of = self._assemble_mesh_batch(
                    stacks, kept_slices, mesh
                )
                ent.update(batch=batch, pos_of=pos_of, mesh=mesh)
            elif kept_slices:
                ent.update(
                    batch=jnp.stack(stacks),
                    pos_of={s: i for i, s in enumerate(kept_slices)},
                )
        # Per-column leaf identity keys for union-leaf fusion
        # (coalesce._launch_interp): equal keys guarantee byte-identical
        # columns — same leaf call, same kept-slice geometry, and the
        # same validation epoch (entries sharing an epoch were built
        # from the same plane state; a refresh never rewrites content).
        # Predicate/zero columns are slice-invariant and share globally.
        kept_sig = tuple(ent.get("kept") or ())
        ent["leaf_keys"] = tuple(
            ("zero",)
            if leaf.name == "BsiZero"
            else ("pred", leaf.args["v"], leaf.args["d"])
            if leaf.name == "BsiPred"
            else (index, str(leaf), kept_sig, epoch)
            for leaf in leaves
        )
        if cacheable:
            displaced = []
            with self._batch_mu:
                self._batch_cache[key] = ent
                while len(self._batch_cache) > self._BATCH_CACHE_CAP:
                    displaced.append(self._batch_cache.popitem(last=False)[0])
            # Pool tenancy OUTSIDE _batch_mu: admission may evict other
            # tenants, whose callbacks take _batch_mu non-blocking.
            pool = device_mod.pool()
            for k in displaced:
                pool.remove(self._batch_pool_key(k))
            ent["pool_key"] = self._register_cache_entry(
                self._batch_pool_key(key),
                [ent["batch"]],
                {"cache": "batch", "index": index, "query": str(c)},
                functools.partial(self._evict_batch_key, key),
            )
        return ent

    def _assemble_mesh_batch_host(self, index: str, leaves, slices, mesh):
        """Host-side mesh batch assembly for COLD fragments: read leaf
        rows from the authoritative numpy planes, group by home device
        (slice mod n_devices, same placement as _assemble_mesh_batch,
        including balanced-chunk spill), and ship ONE block per device.
        Returns (batch, pos_of, kept, empties); batch is None when
        nothing is set, and a plain single-device array when only one
        slice survives (callers then run the non-collective path)."""
        n_leaves = len(leaves)
        rows_of: dict[int, np.ndarray] = {}
        kept: list[int] = []
        empties: list[int] = []
        for s in slices:
            buf = None
            any_set = False
            for j, leaf in enumerate(leaves):
                w = self._leaf_row_host(index, leaf, s)
                if w is not None:
                    if buf is None:
                        buf = np.zeros(
                            (n_leaves, bp.WORDS_PER_SLICE), dtype=np.uint32
                        )
                    buf[j] = w
                    if leaf.name not in plan.NEUTRAL_LEAVES:
                        any_set = True
            if not any_set:
                empties.append(s)
            else:
                kept.append(s)
                rows_of[s] = buf
        if not kept:
            return None, {}, kept, empties
        if len(kept) == 1:
            return (
                jnp.asarray(rows_of[kept[0]][None]),
                {kept[0]: 0},
                kept,
                empties,
            )

        n_dev = int(mesh.devices.size)
        groups, chunk = self._mesh_placement(kept, n_dev)
        blocks = []
        pos_of: dict[int, int] = {}
        for d in range(n_dev):
            block = np.zeros(
                (chunk, n_leaves, bp.WORDS_PER_SLICE), dtype=np.uint32
            )
            for i, s in enumerate(groups[d]):
                block[i] = rows_of[s]
                pos_of[s] = d * chunk + i
            blocks.append(jax.device_put(block, mesh.devices.flat[d]))
        return pmesh.assemble_sharded_batch(blocks, mesh), pos_of, kept, empties

    @staticmethod
    def _mesh_placement(kept: list[int], n_dev: int):
        """Slice -> device placement shared by BOTH batch assemblers
        (device gather and cold host blocks): home device = slice mod
        n_devices (matching fragment plane placement), chunk = balanced
        power-of-two (pow2 >= ceil(n/n_devices)), clustered overflow
        spilled to devices with free rows.  Returns ({device: [slices]},
        chunk); the two assemblers MUST produce identical pos_of layouts
        for the same kept set, since their outputs share the batch
        cache."""
        groups: dict[int, list[int]] = {d: [] for d in range(n_dev)}
        for s in kept:
            groups[s % n_dev].append(s)
        chunk = plan.slice_bucket((len(kept) + n_dev - 1) // n_dev)
        spill: list[int] = []
        for d in range(n_dev):
            while len(groups[d]) > chunk:
                spill.append(groups[d].pop())
        for d in range(n_dev):
            while spill and len(groups[d]) < chunk:
                groups[d].append(spill.pop())
        return groups, chunk

    def _leaf_versions(
        self, index: str, leaves, slices: list[int], with_cold: bool = False
    ):
        """(fragment identity, version) per (slice, leaf) — the cache
        validity vector.  Pure dict lookups; no device work.  With
        ``with_cold`` also returns (n_fragments, n_without_device_mirror)
        from the same sweep, so callers never resolve the pairs twice."""
        # Range resolution (frame lookup, timestamp parsing, time-view
        # enumeration) is slice-invariant — hoist it out of the
        # per-slice loop (954 slices at bench scale revalidate after
        # every write anywhere).
        range_ctx: dict[int, tuple | None] = {
            j: self._range_leaf_context(index, leaf)
            for j, leaf in enumerate(leaves)
            if leaf.name == "Range"
        }
        out = []
        n_frag = n_cold = 0
        for s in slices:
            for j, leaf in enumerate(leaves):
                if j in range_ctx:
                    ctx = range_ctx[j]
                    if ctx is None:
                        out.append(("range", None))
                        continue
                    frame, quantum, views = ctx
                    vers = []
                    for view in views:
                        frag = self.holder.fragment(index, frame, view, s)
                        if frag is None:
                            vers.append(None)
                        else:
                            vers.append((frag._serial, frag._version))
                            n_frag += 1
                            if frag._device is None:
                                n_cold += 1
                    out.append(("range", quantum, tuple(vers)))
                    continue
                if leaf.name in plan.NEUTRAL_LEAVES:
                    # Slice-invariant data rows: identity is fully
                    # captured by the canonical call string in the key.
                    out.append(("const",))
                    continue
                if leaf.name == "BsiPlane":
                    frag = self._bsi_plane_fragment(index, leaf, s)
                else:
                    frag, _ = self._resolve_bitmap_leaf(index, leaf, s)
                if frag is None:
                    out.append(None)
                else:
                    out.append((frag._serial, frag._version))
                    n_frag += 1
                    if frag._device is None:
                        n_cold += 1
        if with_cold:
            return tuple(out), n_frag, n_cold
        return tuple(out)

    def _range_leaf_context(self, index: str, c: Call):
        """Slice-invariant validity context for one Range leaf:
        ``(frame, quantum, views)`` — the frame's time quantum (the view
        set depends on it) and the resolved time-view names — or None
        when the leaf cannot resolve (no frame / no quantum)."""
        frame = c.args.get("frame") or DEFAULT_FRAME
        idx = self.holder.index(index)
        f = idx.frame(frame) if idx is not None else None
        if f is None or not f.time_quantum:
            return None
        view_name, _, start, end, quantum = self._resolve_range(idx, f, c)
        views = list(tq.views_by_time_range(view_name, start, end, quantum))
        return frame, str(quantum), views

    def _fault_check_launch(self, site: str) -> None:
        """Chaos hook at a device-launch site (testing/faults.py),
        fired once per participating device so a rule can target ONE
        flaky device of the mesh.  The exception is annotated with the
        matched device ordinal, letting the health layer narrow the
        blame to that device's path."""
        host = self.host or None
        for i in range(len(self.device_health.device_paths())):
            try:
                faults.check(
                    "device.launch", host=host, path=site, device=i
                )
            except Exception as e:
                if getattr(e, "fault_device", None) is None:
                    try:
                        e.fault_device = i
                    except Exception:  # noqa: BLE001 — slots-only excs
                        pass
                raise

    def _launch_guarded(self, paths, mode, device_fn, retry_fn, host_fn):
        """Run one device launch under the health gate: classify a
        failure (device/health.classify — non-device exceptions
        re-raise unchanged), retry ONCE via ``retry_fn`` for transient
        runtime errors, drive the quarantine state machine, and fall
        back to ``host_fn`` (the byte-identical host evaluator) when
        the launch finally fails.  ``mode`` is the pre-acquired
        admission mode (possibly a half-open probe)."""
        health = self.device_health
        probe = mode == health_mod.MODE_PROBE
        try:
            res = device_fn()
        except Exception as e:
            kind = health_mod.classify(e)
            if kind is None:
                raise
            dev = getattr(e, "fault_device", None)
            if (
                kind == health_mod.KIND_ERROR
                and not probe
                and retry_fn is not None
            ):
                # Transient runtime errors get ONE immediate retry
                # before counting against the breaker — a single
                # glitch must not start the quarantine clock.
                self.holder.stats.count("device.launch.retries")
                try:
                    res = retry_fn()
                except Exception as e2:
                    kind2 = health_mod.classify(e2)
                    if kind2 is None:
                        raise
                    health.failure(
                        paths,
                        kind2,
                        probe=probe,
                        device=getattr(e2, "fault_device", dev),
                    )
                    return host_fn()
                health.success(paths, probe=probe)
                return res
            health.failure(paths, kind, probe=probe, device=dev)
            return host_fn()
        health.success(paths, probe=probe)
        return res

    def _device_span(self, ent: dict, reduce: str):
        """Span for one fused device program dispatch+fetch, annotated
        with compile-vs-execute visibility: ``warm`` is whether this
        executor already dispatched the same (tree shape, reduce, batch
        shape) program — a cold call bears XLA compilation unless the
        persistent compile cache (exec/warmup.py) serves it, which
        ``persistent_cache`` records."""
        shape = None if ent["batch"] is None else tuple(ent["batch"].shape)
        key = (ent["expr"], reduce, shape)
        warm = key in self._seen_programs
        self._seen_programs.add(key)
        return self.tracer.span(
            "exec.device",
            reduce=reduce,
            warm=warm,
            persistent_cache=bool(warmup.enabled_cache_dir()),
        )

    def _record_direct_launch(
        self, ent: dict, reduce: str, t0, t_disp, t1, site: str = "direct"
    ) -> None:
        """Launch telemetry for an uncoalesced executor launch
        (obs/perf.py): logical plane bytes from the KEPT slice rows
        (pad slices are bucketing overhead, not plane traffic) times
        the batch's leaf x word geometry."""
        if not perf_mod.enabled():
            return
        geom = ent.get("perf_geom")
        if geom is None:
            # Computed once per (cached) batch entry: poking a sharded
            # device array's shape metadata costs tens of microseconds,
            # which would land on every query of a hot cached batch.
            # Benign race — the value is idempotent.
            batch = ent["batch"]
            rows = len(ent.get("pos_of") or ()) or int(batch.shape[0])
            words = int(np.prod(batch.shape[1:]))
            ent["perf_geom"] = geom = (rows, words)
        rows, words = geom
        perf_mod.record_launch(
            site,
            reduce=reduce,
            rows=rows,
            n_bytes=perf_mod.plane_bytes(rows, words),
            dispatch_ms=(t_disp - t0) * 1e3,
            total_ms=(t1 - t0) * 1e3,
            trace_id=perf_mod.current_trace_id(),
        )

    def _coalesce_eval(self, ent: dict, reduce: str):
        """Route one assembled batch through the coalescing scheduler;
        returns the host result rows for THIS entry (``[n, words]`` for
        "row", int32 ``[n]`` partials for "count"), or None when the
        scheduler is closed (callers fall back to a direct launch).

        The per-query ``coalesce`` span covers queue wait + the shared
        launch and carries the launch's batch stats (occupancy, rows,
        padding) — the trace-level evidence that N queries rode one
        dispatch.  Compile-warmth bookkeeping matches _device_span so a
        coalesced first launch is as visible as a direct one."""
        # Chaos hook: an injected fault here surfaces exactly like a
        # coalesced launch error — the waiter's health guard classifies
        # it and fails over PER WAITER, never poisoning the shared
        # batch.
        self._fault_check_launch("coalesce")
        shape = tuple(ent["batch"].shape)
        pkey = (ent["expr"], reduce, shape)
        warm = pkey in self._seen_programs
        self._seen_programs.add(pkey)
        with self.tracer.span("coalesce", reduce=reduce, warm=warm) as sp:
            try:
                fut = self.coalescer.submit(
                    ent["expr"],
                    reduce,
                    ent["batch"],
                    pin_keys=(ent.get("pool_key"),),
                    leaf_keys=ent.get("leaf_keys"),
                )
            except coalesce_mod.CoalesceClosed:
                sp.annotate(fallback="closed")
                return None
            # The wait honors the query deadline: a flat RESULT_TIMEOUT_S
            # here once made every waiter ride out 600 s regardless of
            # its budget.  On expiry the waiter DETACHES — the shared
            # launch is never cancelled, so the batch keeps serving its
            # other waiters and the scheduler stays healthy.
            timeout = coalesce_mod.RESULT_TIMEOUT_S
            dl = resilience.current_deadline()
            if dl is not None:
                timeout = dl.clamp(timeout)
            try:
                res, info = fut.result(timeout=timeout)
            except FuturesTimeoutError:
                sp.annotate(deadline="expired")
                # The detached waiter will never call result() again,
                # so a batch-level launch error landing later would sit
                # unobserved (GC logs "exception was never retrieved"
                # per abandoned waiter).  Hand the future a consumer
                # that retrieves and COUNTS it instead.
                fut.add_done_callback(
                    coalesce_mod.consume_abandoned(self.holder.stats)
                )
                if dl is not None and dl.expired:
                    raise resilience.DeadlineExceeded(
                        "deadline expired waiting for coalesced launch"
                    ) from None
                raise
            sp.annotate(**info)
            if info.get("fused"):
                # The `fuse` span: this query rode a multi-query
                # interpreter launch — its batch composition (trees,
                # ops, subtree-dedup hits) lands in the trace and the
                # slow-query log's `fuse` block.
                with self.tracer.span(
                    "fuse",
                    **{
                        k: info[k]
                        for k in (
                            "batch_queries",
                            "programs",
                            "ops",
                            "dedup_hits",
                            "batch_rows",
                            "pad_leaves",
                        )
                        if k in info
                    },
                ):
                    pass
        return res

    def _eval_tree_slices(
        self, index: str, c: Call, slices: list[int], reduce: str
    ) -> dict[int, object]:
        """Evaluate a bitmap call tree over local slices as one batched
        device program: leaves for all slices stack into a
        uint32[n_slices, n_leaves, 32768] array and the jitted tree fn is
        vmapped over the slice axis — the TPU-shaped replacement for the
        reference's goroutine-per-slice mapperLocal.

        The launch rides the device-health gate: a quarantined device
        answers from the authoritative host planes (byte-identical, no
        device batch assembled at all), and a launch failure classifies,
        retries once for transient errors, then quarantine-drives the
        state machine and falls over to the host evaluator."""
        out: dict[int, object] = {}
        if not slices:
            return out
        paths = self.device_health.device_paths()
        mode = self.device_health.acquire(paths)
        if mode == health_mod.MODE_DENY:
            if reduce == "count":
                return self.hosteval.counts(index, c, slices)
            return self.hosteval.rows(index, c, slices)
        ent = self._cached_batch(index, c, slices)

        for s in ent["empties"]:
            out[s] = 0 if reduce == "count" else None
        if ent["batch"] is None:
            if mode == health_mod.MODE_PROBE:
                self.device_health.cancel_probe(paths)
            return out

        def direct():
            # Pin lease for the duration of the fused program: the pool
            # may not evict the batch out from under the dispatch+fetch.
            with device_mod.pool().pinned(
                ent.get("pool_key")
            ), self._device_span(ent, reduce):
                self._fault_check_launch("direct")
                t0 = time.monotonic()
                if ent["mesh"] is not None:
                    # plain-XLA formulation: partitions cleanly under SPMD
                    out_dev = plan.compiled_batched(ent["expr"], reduce)(
                        ent["batch"]
                    )
                    t_disp = time.monotonic()
                    res = jax.device_get(out_dev)
                else:
                    res = plan.compiled_batched(ent["expr"], reduce)(
                        ent["batch"]
                    )
                    t_disp = time.monotonic()
                    if reduce == "row":
                        # Every consumer of row results materializes them
                        # on the host (client responses, merges), so fetch
                        # the WHOLE batch in ONE transfer — per-slice lazy
                        # slices would each pay a device round trip when
                        # coerced.
                        res = np.asarray(res)
                t1 = time.monotonic()
                self._record_direct_launch(ent, reduce, t0, t_disp, t1)
                return res

        def device_fn():
            # Coalesced path: concurrent queries sharing this compile key
            # ride one launch; the scheduler pins every batch in the
            # launch and scatters this entry's rows back.
            if self.coalescer is not None:
                res = self._coalesce_eval(ent, reduce)
                if res is not None:
                    return res
            return direct()

        kept = list(ent["pos_of"])
        res = self._launch_guarded(
            paths,
            mode,
            device_fn,
            retry_fn=direct,
            host_fn=lambda: (
                self.hosteval.counts(index, c, kept)
                if reduce == "count"
                else self.hosteval.rows(index, c, kept)
            ),
        )
        if isinstance(res, dict):
            out.update(res)
        else:
            out.update({s: res[p] for s, p in ent["pos_of"].items()})
        return out

    def _eval_tree_slices_host(
        self, index: str, c: Call, slices: list[int]
    ) -> dict[int, object]:
        """HOST (numpy) evaluation of a bitmap tree per slice — for
        consumers that need host words (TopN src).  Authoritative planes
        are host-resident, so this touches no device state."""
        c = self._rewrite_bsi(index, c)
        expr, leaves = plan.decompose(c)
        out: dict[int, object] = {}
        for s in slices:
            rows = [self._leaf_row_host(index, leaf, s) for leaf in leaves]
            out[s] = plan.eval_expr_np(expr, rows, bp.WORDS_PER_SLICE)
        return out

    # ------------------------------------------------------------------
    # anchored position-domain count (compressed-plane fast path)
    # ------------------------------------------------------------------

    # Anchor-cardinality routing ceiling, in positions.  Past one dense
    # row's worth of words (32768 positions = 3.1% of a slice) the
    # position-domain gathers cost more than streaming the dense words,
    # so denser anchors keep the batched word-domain path.
    ANCHORED_MAX_POSITIONS = bp.WORDS_PER_SLICE

    @staticmethod
    def _expr_fold_only(expr: tuple) -> bool:
        """True when the decomposed tree is pure set algebra (leaves +
        Intersect/Union/Difference/Xor) — membership masks compose
        pointwise only for those, never for the BSI interiors."""
        if expr[0] == "leaf":
            return True
        if expr[0] not in plan.FOLD_CALLS:
            return False
        return all(Executor._expr_fold_only(ch) for ch in expr[1:])

    @staticmethod
    def _anchor_candidates(expr: tuple) -> set:
        """Leaf indices guaranteed to be SUPERSETS of the expression
        result: the result of an Intersect is a subset of every child's
        result, a Difference of its FIRST child's — so any leaf
        reachable from the root through only those edges bounds the
        result, and counting inside its position set is exact."""
        if expr[0] == "leaf":
            return {expr[1]}
        if expr[0] == "Intersect":
            out: set = set()
            for ch in expr[1:]:
                out |= Executor._anchor_candidates(ch)
            return out
        if expr[0] == "Difference" and len(expr) > 1:
            return Executor._anchor_candidates(expr[1])
        return set()

    def _try_anchored_count(self, index: str, c: Call, slices: list[int]):
        """Compressed-plane Count: when the tree is fold-only over
        Bitmap leaves and some AND-dominating leaf is sparse, evaluate
        the expression POINTWISE over that anchor leaf's positions
        against each leaf's container payload (plan.anchored_count_exec)
        — device bytes proportional to cardinality, not to leaves x
        128 KiB.  Returns the exact total, or None to decline (the
        caller falls through to the batched word-domain path; any
        failure here also declines, so the guarded path retains its
        retry/host-fallback semantics)."""
        if bp.PLANE_FORMAT == "dense":
            return None
        try:
            expr, leaves = plan.decompose(self._rewrite_bsi(index, c))
        except Exception:  # noqa: BLE001 — let the main path raise it
            return None
        if not leaves or any(leaf.name != "Bitmap" for leaf in leaves):
            return None
        if not self._expr_fold_only(expr):
            return None
        cands = self._anchor_candidates(expr)
        if not cands:
            return None
        try:
            # Per-slice leaf resolution + anchor pick, grouped by the
            # per-leaf container-format signature (formats may differ
            # per slice; each signature is its own compiled wrapper).
            groups: dict[tuple, list] = {}
            any_compressed = False
            for s in slices:
                resolved = [
                    self._resolve_bitmap_leaf(index, leaf, s)
                    for leaf in leaves
                ]
                best = None
                for i in sorted(cands):
                    frag, rid = resolved[i]
                    card = frag.row_count(rid) if frag is not None else 0
                    if best is None or card < best[0]:
                        best = (card, i)
                card, ai = best
                if card == 0:
                    continue  # empty anchor bounds the slice count at 0
                if card > self.ANCHORED_MAX_POSITIONS:
                    return None  # too dense: whole query keeps one path
                afrag, arid = resolved[ai]
                anchor = afrag.row_positions(arid)
                if anchor is None or len(anchor) == 0:
                    continue
                fmts: list[int] = []
                payloads: list = []
                eff = 4 * len(anchor)
                for frag, rid in resolved:
                    hp = (
                        frag.host_payload(rid) if frag is not None else None
                    )
                    if hp is None:
                        # Absent row: all-sentinel sparse payload, so
                        # membership answers False on every real lane.
                        fmts.append(bp.FMT_SPARSE)
                        payloads.append(_EMPTY_SPARSE_PAYLOAD)
                        eff += _EMPTY_SPARSE_PAYLOAD.nbytes
                    else:
                        fmt, payload, nbytes, _ = hp
                        fmts.append(fmt)
                        payloads.append(payload)
                        eff += nbytes
                        if fmt != bp.FMT_DENSE:
                            any_compressed = True
                groups.setdefault(tuple(fmts), []).append(
                    (anchor, payloads, eff)
                )
            if not any_compressed:
                # Every leaf is a full dense plane: the position-domain
                # gathers save no bytes, and the batched word-domain
                # path keeps its cache/coalesce behavior.  (Dense-tier
                # corpora — the default budget — always land here.)
                return None
            total = 0
            for fmts, items in groups.items():
                total += self._anchored_launch(expr, fmts, items)
            return int(total)
        except Exception:  # noqa: BLE001 — decline, main path decides
            return None

    def _anchored_launch(
        self, expr: tuple, fmts: tuple, items: list
    ) -> int:
        """One vmapped anchored launch for a group of slices sharing a
        container-format signature.  Every axis is pow2-bucketed (slice
        axis to plan.slice_bucket, anchor/payload axes to
        bp.payload_bucket) with sentinel padding, so the jit key stays
        pure geometry."""
        n = len(items)
        n_leaves = len(fmts)
        sb = plan.slice_bucket(n)
        pb = max(bp.payload_bucket(len(a)) for a, _, _ in items)
        anchor_np = np.full((sb, pb), bp.FMT_SENTINEL, dtype=np.uint32)
        for si, (anchor, _, _) in enumerate(items):
            anchor_np[si, : len(anchor)] = anchor
        payload_np = []
        for li in range(n_leaves):
            cols = [it[1][li] for it in items]
            if fmts[li] == bp.FMT_DENSE:
                arr = np.zeros((sb, bp.WORDS_PER_SLICE), dtype=np.uint32)
            elif fmts[li] == bp.FMT_SPARSE:
                lb = max(p.shape[0] for p in cols)
                arr = np.full((sb, lb), bp.FMT_SENTINEL, dtype=np.uint32)
            else:
                lb = max(p.shape[0] for p in cols)
                arr = np.full(
                    (sb, lb, 2), bp.FMT_SENTINEL, dtype=np.uint32
                )
            for si, p in enumerate(cols):
                arr[si, : p.shape[0]] = p
            payload_np.append(arr)
        logical = n * n_leaves * bp.WORDS_PER_SLICE * 4
        eff = sum(it[2] for it in items)
        t0 = time.monotonic()
        out = plan.anchored_count_exec(
            expr, fmts, jnp.asarray(anchor_np),
            [jnp.asarray(a) for a in payload_np],
        )
        t_disp = time.monotonic()
        res = jax.device_get(out)
        t1 = time.monotonic()
        if perf_mod.enabled():
            perf_mod.record_launch(
                "anchored",
                reduce="count",
                rows=n * n_leaves,
                n_bytes=logical,
                eff_bytes=eff,
                dispatch_ms=(t_disp - t0) * 1e3,
                total_ms=(t1 - t0) * 1e3,
                trace_id=perf_mod.current_trace_id(),
            )
        return int(sum(int(x) for x in res[:n]))

    def _count_slices_total(self, index: str, c: Call, slices: list[int]) -> int:
        """Count(tree) over local slices with the cross-slice reduce ON
        DEVICE.

        On a multi-device mesh the per-slice popcount partials sum
        across the sharded slice axis inside the jitted program — XLA
        inserts the all-reduce (psum over ICI) and only the limb pair
        comes back to the host, the collective replacement for the
        reference's HTTP fan-in reduce (reference: executor.go:1176-
        1207).  Falls back to the per-slice host sum (int64) beyond the
        limb partial budget or on single-device hosts."""
        if not slices:
            return 0
        paths = self.device_health.device_paths()
        mode = self.device_health.acquire(paths)
        if mode == health_mod.MODE_DENY:
            # Quarantined accelerator: host popcount over the
            # authoritative planes, no device batch assembled.
            return self.hosteval.count_total(index, c, slices)
        if mode == health_mod.MODE_OK:
            # Compressed-plane fast path: a fold-only tree with a
            # sparse AND-dominating anchor counts in the position
            # domain, reading bytes proportional to cardinality.
            # Declines (None) fall through to the batched word-domain
            # path unchanged.  Healthy devices only: a granted probe
            # must resolve through the guarded launch below.
            anchored = self._try_anchored_count(index, c, slices)
            if anchored is not None:
                return anchored
        ent = self._cached_batch(index, c, slices)
        if ent["batch"] is None:
            if mode == health_mod.MODE_PROBE:
                self.device_health.cancel_probe(paths)
            return 0
        kept_slices = ent["kept"]
        health = self.device_health
        fits_limbs = len(kept_slices) <= plan.MAX_ONDEVICE_COUNT_PARTIALS

        # Coalesced path.  A MESH-SHARDED entry within the limb budget
        # rides the "total" reduce: the cross-slice sum happens ON
        # DEVICE inside the (possibly fused multi-query) launch as an
        # all-reduce over ICI, and only an int32[2] (hi, lo) limb pair
        # crosses the tunnel per query.  Zero pad slices contribute
        # nothing to either limb, and entries fused into one
        # interpreter pass read only their own leaf registers, so the
        # on-device total equals the per-position host sum
        # byte-for-byte.  Unsharded entries keep the per-slice "count"
        # partials (int32-exact, <= 2^20 bits per slice-row; host sums
        # in unbounded Python ints — identical totals): the on-device
        # reduce buys them only a smaller fetch, while their batches'
        # committed-ness varies between the cold (host-assembled,
        # uncommitted) and warm (device-gathered, committed) builders —
        # distinct jit cache entries for one geometry, which would
        # break the totalCount family's hard cardinality bound.
        # A quarantined or watchdog-tripped COLLECTIVE path falls back
        # to the per-slice partials launch — single-device semantics on
        # the same sharded batch, no psum rendezvous to hang on.
        def coalesced():
            if (
                ent["mesh"] is not None
                and fits_limbs
                and health.collective_allowed()
            ):
                try:
                    res = self._coalesce_eval(ent, "total")
                except (
                    health_mod.LaunchWatchdogTimeout,
                    health_mod.CollectiveUnavailable,
                ):
                    res = None  # collective quarantined: partials below
                else:
                    if res is not None:
                        return plan.recombine_count_limbs(res)
            res = self._coalesce_eval(ent, "count")
            if res is not None:
                return sum(int(res[p]) for p in ent["pos_of"].values())
            return None

        def direct():
            with device_mod.pool().pinned(
                ent.get("pool_key")
            ), self._device_span(ent, "count"):
                self._fault_check_launch("direct")
                if ent["mesh"] is not None:
                    # Zero pad slices contribute nothing, so the budget
                    # is on the real slice count, not the padded batch
                    # size.
                    if fits_limbs and health.collective_allowed():
                        # The program psums over the mesh: one
                        # collective launch in flight per process,
                        # serialized AND watchdogged
                        # (health.run_collective wraps
                        # plan.collective_launch) — a hung all-reduce
                        # trips instead of wedging the process.  The
                        # chaos checkpoint sits INSIDE the watched body
                        # so an injected kind=hang wedges where a real
                        # rendezvous would.
                        def _collective_body():
                            self._fault_check_launch("collective")
                            t0 = time.monotonic()
                            out = plan.compiled_total_count(
                                ent["expr"], ent["mesh"]
                            )(ent["batch"])
                            t_disp = time.monotonic()
                            res = jax.device_get(out)
                            self._record_direct_launch(
                                ent, "total", t0, t_disp,
                                time.monotonic(), site="collective",
                            )
                            return res

                        try:
                            limbs = health.run_collective(_collective_body)
                            return plan.recombine_count_limbs(limbs)
                        except (
                            health_mod.LaunchWatchdogTimeout,
                            health_mod.CollectiveUnavailable,
                        ):
                            pass  # mesh path quarantined: partials
                    t0 = time.monotonic()
                    out = plan.compiled_batched(ent["expr"], "count")(
                        ent["batch"]
                    )
                    t_disp = time.monotonic()
                    res = jax.device_get(out)
                    self._record_direct_launch(
                        ent, "count", t0, t_disp, time.monotonic()
                    )
                    return int(
                        sum(int(res[p]) for p in ent["pos_of"].values())
                    )

                # Single device: same limb total-count program, no
                # collective — 8 bytes home instead of a per-slice
                # partial vector (zero pad slices contribute nothing).
                if fits_limbs:
                    t0 = time.monotonic()
                    limbs = plan.compiled_total_count(ent["expr"])(
                        ent["batch"]
                    )
                    t_disp = time.monotonic()
                    limbs = jax.device_get(limbs)
                    self._record_direct_launch(
                        ent, "total", t0, t_disp,
                        time.monotonic(), site="total",
                    )
                    return plan.recombine_count_limbs(limbs)
                t0 = time.monotonic()
                res = plan.compiled_batched(ent["expr"], "count")(ent["batch"])
                t_disp = time.monotonic()
                res = jax.device_get(res)
                self._record_direct_launch(
                    ent, "count", t0, t_disp, time.monotonic()
                )
                return sum(int(res[p]) for p in ent["pos_of"].values())

        def device_fn():
            if self.coalescer is not None:
                total = coalesced()
                if total is not None:
                    return total
            return direct()

        kept = list(ent["pos_of"])
        return self._launch_guarded(
            paths,
            mode,
            device_fn,
            retry_fn=direct,
            host_fn=lambda: self.hosteval.count_total(index, c, kept),
        )

    def _assemble_mesh_batch(self, stacks, kept_slices, mesh):
        """Group slices by home device (slice mod n_devices, matching
        fragment plane placement), pad per-device blocks to one
        power-of-two chunk, and assemble the global batch shard-local
        (parallel/mesh.assemble_sharded_batch).  Returns ``(batch,
        pos_of)`` with ``pos_of[slice]`` the slice's row in the global
        batch.

        The chunk is sized for a BALANCED distribution (pow2 >=
        ceil(n/n_devices)); when the queried slice set is clustered mod
        n_devices, the overflow spills to devices with free rows (one
        plane transfer per spilled slice) instead of inflating every
        device's padding to the largest group — at pod scale, mostly-
        zero compute costs more than the occasional spill copy."""
        n_dev = int(mesh.devices.size)
        stack_of = dict(zip(kept_slices, stacks))
        groups, chunk = self._mesh_placement(kept_slices, n_dev)

        blocks = []
        pos_of: dict[int, int] = {}
        for d in range(n_dev):
            dev = mesh.devices.flat[d]
            entries = []
            for i, s in enumerate(groups[d]):
                st = stack_of[s]
                if s % n_dev != d:  # spilled here: one plane-row move
                    st = jax.device_put(st, dev)
                entries.append(st)
                pos_of[s] = d * chunk + i
            if len(entries) < chunk:
                zero_stack = jnp.stack(
                    [self._zero_row_on(dev)] * stacks[0].shape[0]
                )
                entries = entries + [zero_stack] * (chunk - len(entries))
            blocks.append(jnp.stack(entries))

        return pmesh.assemble_sharded_batch(blocks, mesh), pos_of

    def _zero_row(self, slice_i: int):
        """An all-zero leaf row on a slice's home device."""
        return self._zero_row_on(pmesh.home_device(slice_i))

    def _zero_row_on(self, dev):
        """An all-zero leaf row committed to ``dev`` (cached per device)."""
        z = self._zero_rows.get(dev)
        if z is None:
            z = jax.device_put(
                np.zeros(bp.WORDS_PER_SLICE, dtype=np.uint32), dev
            )
            self._zero_rows[dev] = z
        return z

    def _execute_bitmap_call(
        self, index: str, c: Call, slices: list[int], opt: ExecOptions
    ) -> RowBitmap:
        """reference: executor.go:203-261"""

        def map_fn(local_slices: list[int]):
            rows = self._eval_tree_slices(index, c, local_slices, "row")
            bm = RowBitmap()
            for s, row in rows.items():
                if row is not None:
                    bm.set_segment(s, row)
            return bm

        def reduce_fn(prev, v):
            if prev is None:
                prev = RowBitmap()
            prev.merge(v)
            return prev

        bm = self._map_reduce(index, slices, c, opt, map_fn, reduce_fn)
        if bm is None:
            bm = RowBitmap()

        # Attach attributes for Bitmap() calls (reference: executor.go:226-258).
        if c.name == "Bitmap":
            idx = self.holder.index(index)
            if idx is not None:
                column_label = idx.column_label
                col_id, col_ok = _uint_arg(c, column_label)
                if col_ok:
                    bm.attrs = idx.column_attr_store.attrs(col_id)
                else:
                    # Raw frame arg, NOT defaulted: with frame omitted the
                    # reference attaches no row attrs (executor.go:244-258).
                    frame = c.args.get("frame") or ""
                    f = idx.frame(frame) if frame else None
                    if f is not None:
                        row_id, row_ok = _uint_arg(c, f.row_label)
                        if row_ok and f.row_attr_store is not None:
                            bm.attrs = f.row_attr_store.attrs(row_id)
        return bm

    def _execute_count(
        self, index: str, c: Call, slices: list[int], opt: ExecOptions
    ) -> int:
        """reference: executor.go:611-639"""
        if len(c.children) == 0:
            raise ExecutorError("Count() requires an input bitmap")
        if len(c.children) > 1:
            raise ExecutorError("Count() only accepts a single bitmap input")
        child = c.children[0]

        def map_fn(local_slices: list[int]):
            return self._count_slices_total(index, child, local_slices)

        def reduce_fn(prev, v):
            return (prev or 0) + v

        n = self._map_reduce(index, slices, c, opt, map_fn, reduce_fn)
        return int(n or 0)

    # ------------------------------------------------------------------
    # BSI aggregates — Sum / Min / Max over integer fields
    # ------------------------------------------------------------------

    @staticmethod
    def _normalize_valcount(v):
        """Map a local (ValCount | None) or remote-decoded ([Pair] | 0)
        partial to ValCount | None.  A remote node with no valued
        columns answers an empty result that decodes to 0 — legitimate
        partials are ALWAYS Pair lists (even all-zero ones survive the
        protobuf round trip), so bare ints mean "no data"."""
        if isinstance(v, bsi.ValCount):
            return v
        if isinstance(v, list) and v:
            p = v[0]
            val = int(p.id) & 0xFFFFFFFFFFFFFFFF
            if val >= 1 << 63:  # sign-extend the u64 wire wrap
                val -= 1 << 64
            return bsi.ValCount(value=val, count=int(p.count))
        return None

    def _execute_bsi_agg(self, index: str, c: Call, slices: list[int], opt):
        """Sum/Min/Max(…, frame=f, field=q): per-slice int32 partial
        vectors from ONE fused program over the field's planes (plus an
        optional filter bitmap tree), weighted/combined in Python ints,
        reduced across nodes through the ordinary map/reduce — exactly
        like Count.  Cross-node partials ride the Pairs wire shape
        (value u64-wrapped, count), so negatives survive protobuf."""
        name = c.name

        def map_fn(local_slices: list[int]):
            return self._bsi_agg_slices(index, c, local_slices)

        def reduce_fn(prev, v):
            v = self._normalize_valcount(v)
            if v is None:
                return prev
            prev = self._normalize_valcount(prev)
            if prev is None:
                return v
            if name == "Sum":
                return bsi.ValCount(prev.value + v.value, prev.count + v.count)
            if v.value == prev.value:
                return bsi.ValCount(prev.value, prev.count + v.count)
            if name == "Min":
                return v if v.value < prev.value else prev
            return v if v.value > prev.value else prev

        res = self._map_reduce(index, slices, c, opt, map_fn, reduce_fn)
        res = self._normalize_valcount(res)
        if res is None and name == "Sum":
            res = bsi.ValCount(0, 0)
        return res

    def _bsi_agg_slices(self, index: str, c: Call, slices: list[int]):
        """One node's aggregate partial over its local slices:
        ValCount, or None when no slice holds a valued column.  Rides
        the device-health gate like the Count path: a quarantined (or
        finally-failed) launch decodes host-computed partial vectors —
        the same ripple arithmetic through the numpy backend."""
        if not slices:
            return None
        rc = self._rewrite_bsi_agg(index, c)
        bucket = int(rc.args["nplanes"])
        paths = self.device_health.device_paths()
        mode = self.device_health.acquire(paths)
        if mode == health_mod.MODE_DENY:
            parts = self.hosteval.agg_partials(index, rc, slices)
            return self._decode_agg_parts(c, bucket, parts.values())
        ent = self._cached_batch(index, rc, slices)
        if ent["batch"] is None:
            if mode == health_mod.MODE_PROBE:
                self.device_health.cancel_probe(paths)
            return None

        def direct():
            with device_mod.pool().pinned(
                ent.get("pool_key")
            ), self._device_span(ent, "agg"):
                self._fault_check_launch("direct")
                return np.asarray(
                    jax.device_get(
                        plan.compiled_batched(ent["expr"], "agg")(ent["batch"])
                    )
                )

        def device_fn():
            if self.coalescer is not None:
                res = self._coalesce_eval(ent, "agg")
                if res is not None:
                    return np.asarray(res)
            return direct()

        kept = list(ent["pos_of"])
        res = self._launch_guarded(
            paths,
            mode,
            device_fn,
            retry_fn=direct,
            host_fn=lambda: self.hosteval.agg_partials(index, rc, kept),
        )
        if isinstance(res, dict):
            vecs = list(res.values())
        else:
            res = np.asarray(res)
            vecs = [res[p] for p in ent["pos_of"].values()]
        return self._decode_agg_parts(c, bucket, vecs)

    @staticmethod
    def _decode_agg_parts(c: Call, bucket: int, vecs):
        """Reduce per-slice aggregate partial vectors (device OR host
        produced — identical layout) into one ValCount."""
        if c.name == "Sum":
            total = 0
            count = 0
            for vec in vecs:
                part, n = ripple.decode_sum(vec, bucket)
                total += part
                count += n
            return bsi.ValCount(total, count) if count else None
        best = None
        for vec in vecs:
            decoded = ripple.decode_minmax(vec, bucket)
            if decoded is None:
                continue
            val, n = decoded
            if best is None:
                best = (val, n)
            elif val == best[0]:
                best = (val, best[1] + n)
            elif (c.name == "Min") == (val < best[0]):
                best = (val, n)
        return bsi.ValCount(*best) if best is not None else None

    # ------------------------------------------------------------------
    # TopN (reference: executor.go:281-415) — two-phase
    # ------------------------------------------------------------------

    def _execute_topn(
        self, index: str, c: Call, slices: list[int], opt: ExecOptions
    ) -> list[Pair]:
        ids_arg = _uint_slice_arg(c, "ids")
        n = _uint_arg(c, "n")[0]

        # Folded single-round-trip path: when every slice is owned
        # locally (single node — the common and benchmarked shape), both
        # phases compute from ONE union scoring pass with ONE device
        # fetch; results are identical to the two-phase protocol below.
        if not ids_arg and not opt.remote and len(slices) > 1:
            if self._all_slices_local(index, slices):
                return self._execute_topn_folded(index, c, slices, opt)

        pairs = self._execute_topn_slices(index, c, slices, opt)
        # Phase 2 refetch only on the originating node (reference:
        # executor.go:301-321).
        if not pairs or ids_arg or opt.remote:
            return pairs
        # Phase 2 exists to get EXACT counts for winners that missed
        # some slice's local candidate list; with a single slice the
        # phase-1 scores are already exact and complete, so the refetch
        # would recompute identical counts at double the latency.
        if len(slices) <= 1:
            return pairs[:n] if n and n < len(pairs) else pairs
        return self._topn_refetch(index, c, slices, opt, n, pairs)

    def _execute_topn_two_phase(
        self, index: str, c: Call, slices: list[int], opt: ExecOptions, n: int
    ) -> list[Pair]:
        """The reference's two-round protocol, used when the folded
        path's union guard trips."""
        pairs = self._execute_topn_slices(index, c, slices, opt)
        if not pairs:
            return pairs
        return self._topn_refetch(index, c, slices, opt, n, pairs)

    def _topn_refetch(
        self,
        index: str,
        c: Call,
        slices: list[int],
        opt: ExecOptions,
        n: int,
        pairs: list[Pair],
    ) -> list[Pair]:
        """Phase 2: exact counts for the phase-1 winner union."""
        other = c.clone()
        other.args["ids"] = sorted({p.id for p in pairs})
        trimmed = self._execute_topn_slices(index, other, slices, opt)
        if n and n < len(trimmed):
            trimmed = trimmed[:n]
        return trimmed

    def _score_topn_parts(self, parts) -> None:
        """Score many fragments' TopN parts with as FEW device
        operations and host<->device transfers as possible and fill
        each ``TopState.counts``.

        ``parts``: list of (TopState, SubRef, src_words, src_spec,
        fragment) — the first three from the ``*_parts`` fragment APIs,
        ``src_spec`` from ``_attach_dev_src`` (None when the src tree
        is not a plain Bitmap leaf), ``fragment`` for the host scoring
        fallback.  Entries with a SubRef group by program shape
        (sub shape, plane rows, home device); each group runs ONE fused
        program (bp.score_planes) that reads candidate AND src rows
        straight from the fragments' resident HBM mirrors — no stacked
        copy, no src upload — and is fetched as ONE array.  The
        per-fragment path paid a dispatch + a 128 KiB src upload + a
        fetch PER SLICE: 444 ms/query at 100 slices through the
        tunnel.

        Rides the device-health gate: a quarantined device (or a
        finally-failed scorer launch) fills the count vectors from the
        fragments' authoritative host rows instead
        (hosteval.score_topn_parts) — identical arithmetic, identical
        vectors.

        The ``topn.dispatch`` / ``topn.fetch`` spans split the device
        cost: dispatch covers gather prep + the async program launches,
        fetch the blocking device->host transfer — with ``topn.select``
        in the callers, the per-stage TopN(src) breakdown ROADMAP 5
        needs before attacking the 5-7 ms residual."""
        live = [e for e in parts if e[1] is not None]
        if not live:
            return
        paths = self.device_health.device_paths()
        mode = self.device_health.acquire(paths)
        if mode == health_mod.MODE_DENY:
            self.hosteval.score_topn_parts(live)
            return

        def device_fn():
            groups: dict[tuple, list] = {}
            for entry in live:
                ref = entry[1]
                groups.setdefault(
                    (ref.shape, ref.plane_rows, ref.device), []
                ).append(entry)
            dev_outs = []  # (device array, [states]) fetched in one pass
            t0 = time.monotonic()
            with self.tracer.span("topn.dispatch", groups=len(groups)):
                self._fault_check_launch("topn")
                for _gkey, members in groups.items():
                    # Pad the group to a power-of-two bucket by repeating
                    # the last member (the row dimension is already
                    # pad_rows-bucketed): an unpadded group size would
                    # compile a fresh XLA program per distinct slice count.
                    # Surplus rows are simply not consumed when the fetched
                    # scores distribute.
                    n_pad = 1 << (len(members) - 1).bit_length()
                    padded = members + [members[-1]] * (n_pad - len(members))
                    planes = tuple(m[1].plane for m in padded)
                    slots = np.stack([m[1].slots for m in padded])
                    # Same-plane src slot for every member -> zero src bytes
                    # cross the host boundary (and no extra leaf shapes in
                    # the jit key); otherwise one stacked host-snapshot
                    # transfer for the group.
                    if all(m[3] is not None for m in padded):
                        src_slots = np.asarray(
                            [m[3] for m in padded], dtype=np.int32
                        )
                        out = bp.score_planes(
                            planes, slots, src_slots=src_slots
                        )
                    else:
                        srcs = np.stack([m[2] for m in padded])
                        out = bp.score_planes(planes, slots, srcs=srcs)
                    dev_outs.append((out, [m[0] for m in members]))
            t_disp = time.monotonic()
            with self.tracer.span("topn.fetch", arrays=len(dev_outs)) as sp:
                fetched = self._shared_fetch([o for o, _ in dev_outs], sp)
            for arr, (_, sts) in zip(fetched, dev_outs):
                arr = np.asarray(arr)
                for i, st in enumerate(sts):
                    st.counts = arr[i]
            # Scorer roofline accounting: each live member's fused
            # scoring pass streams its whole plane snapshot (group pad
            # repeats are bucketing, not counted).
            if perf_mod.enabled():
                perf_mod.record_launch(
                    "topn",
                    reduce="topn",
                    rows=sum(int(e[1].plane_rows) for e in live),
                    n_bytes=sum(
                        perf_mod.plane_bytes(
                            int(e[1].plane_rows), bp.WORDS_PER_SLICE
                        )
                        for e in live
                    ),
                    dispatch_ms=(t_disp - t0) * 1e3,
                    total_ms=(time.monotonic() - t0) * 1e3,
                    trace_id=perf_mod.current_trace_id(),
                )
            return True

        self._launch_guarded(
            paths,
            mode,
            device_fn,
            retry_fn=device_fn,
            host_fn=lambda: self.hosteval.score_topn_parts(live),
        )

    def _shared_fetch(self, arrays, sp):
        """Fetch device arrays to the host, batching the BLOCKING
        device->host round trip with other queries' concurrent fetches
        through the coalescer's fetch lane (submit_fetch) — the TopN
        fetch residual is one round trip per drain instead of one per
        query.  Dispatches already happened (async); only the wait
        folds.  Falls back to a direct ``jax.device_get`` without a
        coalescer or when it is closed."""
        co = self.coalescer
        if co is not None and hasattr(co, "submit_fetch"):
            try:
                fut = co.submit_fetch(arrays)
            except coalesce_mod.CoalesceClosed:
                fut = None
            if fut is not None:
                timeout = coalesce_mod.RESULT_TIMEOUT_S
                dl = resilience.current_deadline()
                if dl is not None:
                    timeout = dl.clamp(timeout)
                try:
                    res, info = fut.result(timeout=timeout)
                except FuturesTimeoutError:
                    sp.annotate(deadline="expired")
                    # Same abandoned-waiter contract as _coalesce_eval:
                    # the eventual fetch error must be consumed, not
                    # left for GC log spam.
                    fut.add_done_callback(
                        coalesce_mod.consume_abandoned(self.holder.stats)
                    )
                    if dl is not None and dl.expired:
                        raise resilience.DeadlineExceeded(
                            "deadline expired waiting for shared fetch"
                        ) from None
                    raise
                sp.annotate(**info)
                return res
        return jax.device_get(arrays)

    def _attach_dev_src(self, index: str, c: Call, frag, part):
        """Extend a fragment's (st, SubRef, src_words) TopN part with
        the src row's SLOT in the member's own plane snapshot when the
        TopN src is a plain Bitmap leaf on the SAME fragment (the
        common ``TopN(Bitmap(frame=f), frame=f)`` shape): the fused
        scorer then reads the src row from the already-grouped plane —
        zero src bytes host->device and no extra leaf shapes in the jit
        key.  Anything else (different src frame, sparse-tier src row,
        a mirror refresh since the prepare snapshot, non-Bitmap tree)
        returns None, falling the group back to the one host-snapshot
        src transfer — always consistent, just not transfer-free."""
        st, sub_ref, srcw = part
        slot = None
        if (
            sub_ref is not None
            and len(c.children) == 1
            and c.children[0].name == "Bitmap"
            and not c.children[0].children
        ):
            sfrag, row_id = self._resolve_bitmap_leaf(
                index, c.children[0], frag.slice
            )
            if sfrag is frag:
                with sfrag._mu:
                    s = sfrag._slot_of.get(row_id)
                    # The slot is only valid against the snapshot the
                    # prepare captured; a refresh since then (writes)
                    # may have reordered the slot layout.
                    if s is not None and sfrag.device_plane() is sub_ref.plane:
                        slot = int(s)
        return st, sub_ref, srcw, slot

    def _existing_topn_slices(
        self, index: str, c: Call, slices: list[int]
    ) -> list[int]:
        """Subset of ``slices`` whose fragment of the TopN frame/view
        actually exists.  A missing fragment contributes nothing
        (``_topn_options_for_slice`` returns None for it), so skipping
        turns the per-slice host walk from O(max_slice) into
        O(existing fragments) — at bench scale (954 index slices, one
        frame fragment) that walk dominated warm TopN host time."""
        frame, view = self._topn_frame_view(c)
        idx = self.holder.index(index)
        f = idx.frame(frame) if idx is not None else None
        v = f.view(view) if f is not None else None
        if v is None:
            return []
        have = v.fragment_slices()
        return [s for s in slices if s in have]

    def _all_slices_local(self, index: str, slices: list[int]) -> bool:
        rn = getattr(self.cluster, "route_nodes", None)
        nodes = rn() if rn is not None else list(self.cluster.nodes)
        try:
            m = self._slices_by_node(nodes, index, slices)
        except SliceUnavailableError:
            return False
        return set(m.keys()) == {self.host}

    # Folded-TopN prep entries kept per (index, query, slice set): the
    # working set of a hot dashboard is a handful of repeated queries.
    _TOPN_CACHE_CAP = 8

    def _topn_versions(self, index: str, c: Call, slices: list[int]):
        """Validity vector for a folded-TopN prep entry: the TopN
        frame's fragment versions over the ORIGINAL slice list (a
        fragment springing into existence must invalidate) plus, when a
        src tree exists, the versions of every fragment its leaves
        resolve to (the src rows were host-evaluated at prep time)."""
        frame, view = self._topn_frame_view(c)
        out: list = []
        for s in slices:
            frag = self.holder.fragment(index, frame, view, s)
            out.append(
                None if frag is None else (frag._serial, frag._version)
            )
        if len(c.children) == 1:
            try:
                _, leaves = plan.decompose(
                    self._rewrite_bsi(index, c.children[0])
                )
            except (plan.PlanError, ExecutorError):
                leaves = []
            out.append(tuple(self._leaf_versions(index, leaves, slices)))
        return tuple(out)

    def _topn_folded_entry(self, index: str, c: Call, slices: list[int]) -> dict:
        """The folded path's prep — candidate walks, union assembly,
        foreign-count resolution, src evaluation, and gather prep —
        CACHED per (index, query, slice set) and validated exactly like
        _cached_batch entries (O(1) against the global write epoch, then
        against the version vector).  At 64 slices the prep is ~50 ms of
        host-side numpy per query; repeated queries skip all of it and
        pay only dispatch + fetch + winner selection.

        Attr-filtered queries (filterField) are NOT cached: the attr
        store has no version vector, so a SetRowAttrs would serve stale
        candidates."""
        key = (index, str(c), tuple(slices))
        cacheable = not self._topn_parsed_args(c)[3]  # "" = no filterField
        if cacheable:
            now = time.monotonic()
            with self._batch_mu:
                # Purge entries past their lifetime: they can never be
                # served again (the expiry below), and each pins an HBM
                # plane snapshot via its SubRefs — dead entries must not
                # hold device memory until LRU displacement.
                expired = [
                    k
                    for k, e in self._topn_cache.items()
                    if now - e["built_at"] >= cache_mod.RECALCULATE_INTERVAL_S
                ]
                for k in expired:
                    del self._topn_cache[k]
                ent = self._topn_cache.get(key)
            for k in expired:
                device_mod.pool().remove(self._topn_pool_key(k))
            # Entries also EXPIRE on the rank caches' re-sort throttle:
            # candidate counts come from the ranked caches, whose
            # throttled re-sort (RECALCULATE_INTERVAL_S) happens inside
            # the candidate walk this cache skips — without the expiry a
            # hot read-only query would freeze its candidate counts
            # forever instead of the old path's <= 10 s of staleness.
            cur_versions = None
            if ent is not None and (
                time.monotonic() - ent["built_at"]
                < cache_mod.RECALCULATE_INTERVAL_S
            ):
                epoch = fragment_mod.write_epoch()
                if ent["epoch"] != epoch:
                    cur_versions = self._topn_versions(index, c, slices)
                if ent["epoch"] == epoch or ent["versions"] == cur_versions:
                    ent["epoch"] = epoch
                    with self._batch_mu:
                        if key in self._topn_cache:
                            self._topn_cache.move_to_end(key)
                    device_mod.pool().touch(self._topn_pool_key(key))
                    return ent
                # Version validation failed: the entry can never serve
                # again (a deleted or rewritten fragment), yet its
                # SubRefs pin HBM plane snapshots — drop it NOW, before
                # the rebuild, so a failing build can't resurrect it.
                with self._batch_mu:
                    if self._topn_cache.get(key) is ent:
                        del self._topn_cache[key]
                device_mod.pool().remove(self._topn_pool_key(key))
        # Capture validity BEFORE building: a concurrent write during
        # the build leaves the entry conservatively stale.  The vector
        # computed for the failed validation (if any) is reused — it
        # predates the build, which is exactly the conservative bar.
        epoch = fragment_mod.write_epoch()
        versions = None
        if cacheable:
            versions = (
                cur_versions
                if cur_versions is not None
                else self._topn_versions(index, c, slices)
            )
        ent = self._topn_folded_build(index, c, slices)
        ent["epoch"] = epoch
        ent["versions"] = versions
        ent["built_at"] = time.monotonic()
        if cacheable:
            displaced = []
            with self._batch_mu:
                self._topn_cache[key] = ent
                while len(self._topn_cache) > self._TOPN_CACHE_CAP:
                    displaced.append(self._topn_cache.popitem(last=False)[0])
            pool = device_mod.pool()
            for k in displaced:
                pool.remove(self._topn_pool_key(k))
            # Byte-account the entry's HBM plane snapshots (SubRefs):
            # the pool, not the entry-count cap, now bounds how much
            # device memory TopN prep keeps alive.
            self._register_cache_entry(
                self._topn_pool_key(key),
                [p[5].plane for p in ent.get("parts", ()) if p[5] is not None],
                {"cache": "topn", "index": index, "query": str(c)},
                functools.partial(self._evict_topn_key, key),
            )
        return ent

    def _topn_folded_build(self, index: str, c: Call, slices: list[int]) -> dict:
        """Build a folded-TopN prep entry (see _topn_folded_entry for
        the caching contract).  Entry shapes: ``{"empty": True}``,
        ``{"two_phase": True}``, or ``{"parts": [(frag, topt, cand_ids,
        cand_mask, st_proto, sub_ref, src_words, src_slot), ...]}``
        where st_proto is the UNSCORED TopState (cloned per query) and
        cand_mask pre-resolves ``np.isin(union_order_ids, cand_ids)``
        for phase-1 winner selection."""
        has_src = len(c.children) == 1

        # Only slices whose fragment exists can contribute; restricting
        # up front turns every per-slice walk below into O(fragments).
        slices = self._existing_topn_slices(index, c, slices)

        # Pass 1 (host-only): per-slice candidate (ids, cached counts)
        # arrays, WITHOUT evaluating the src tree yet — the union guard
        # below must be able to fall back before any src work is spent.
        # A src only shrinks candidate lists (tanimoto count-window), so
        # the src-free walk is a conservative union estimate.
        per: list[tuple] = []
        for s in slices:
            prep = self._topn_options_for_slice(index, c, s, None)
            if prep is None:
                continue
            frag, topt = prep
            per.append((frag, topt) + frag.top_candidates_arrays(topt))
        if not per:
            return {"empty": True}
        # Guard against disjoint caches: every slice scores the WHOLE
        # union, so when the union dwarfs the largest per-slice candidate
        # list the folded pass does more device gather+score work than
        # the two saved round trips are worth — use the two-phase
        # protocol instead.  Overlapping hot rows (the common shape)
        # keep union ~= per-slice candidates and stay folded.
        union = np.unique(np.concatenate([ids for _, _, ids, _ in per]))
        if not len(union):
            return {"empty": True}
        max_cand = max(len(ids) for _, _, ids, _ in per)
        if len(union) > max(2 * max_cand, 512):
            return {"two_phase": True}

        if has_src:
            src_rows = self._eval_tree_slices_host(index, c.children[0], slices)
            if _uint_arg(c, "tanimotoThreshold")[0] > 0:
                # Tanimoto count-windows depend on the src count, so
                # re-derive candidates (and the union) with the real src.
                per = []
                for s in slices:
                    prep = self._topn_options_for_slice(index, c, s, src_rows)
                    if prep is None:
                        continue
                    frag, topt = prep
                    per.append((frag, topt) + frag.top_candidates_arrays(topt))
                if not per:
                    return {"empty": True}
                union = np.unique(
                    np.concatenate([ids for _, _, ids, _ in per])
                )
            else:
                # Without tanimoto, candidate filtering never reads the
                # src — only the scorer does.  Attach it to the pass-1
                # options instead of re-walking every candidate list.
                attached = []
                for frag, topt, ids, cnts in per:
                    src = RowBitmap()
                    row = src_rows.get(frag.slice)
                    if row is not None:
                        src.set_segment(frag.slice, row)
                    attached.append((frag, replace(topt, src=src), ids, cnts))
                per = attached
        if not len(union):
            return {"empty": True}

        # Gather prep: the union scoring pass per fragment, WITHOUT the
        # kernel dispatch (all fragments score the same union, so the
        # gathered submatrices share a shape).  Reuses each slice's
        # candidate arrays, resolving counts only for the foreign
        # winners (top_prepare_union_parts).
        parts: list[tuple] = []
        for frag, topt, cand_ids, cand_cnts in per:
            st, sub_ref, srcw = frag.top_prepare_union_parts(
                union, cand_ids, cand_cnts, topt
            )
            _, _, _, src_slot = self._attach_dev_src(
                index, c, frag, (st, sub_ref, srcw)
            )
            cand_mask = (
                np.isin(st.cand_ids, cand_ids, assume_unique=True)
                if st.cand_ids is not None
                else None
            )
            parts.append(
                (frag, topt, cand_ids, cand_mask, st, sub_ref, srcw, src_slot)
            )
        # "scores" memoizes the fetched count vectors for as long as
        # the ENTRY validates (fragments unchanged since build =>
        # scores unchanged); "score_event" single-flights the fused
        # scorer across concurrent queries of this entry (leader
        # scores, everyone else waits on the event — never on a lock),
        # so a 32-query storm of one TopN shape pays ONE
        # dispatch+fetch, not 32 — the topn.fetch residual ROADMAP 5
        # names.
        return {"parts": parts}

    def _execute_topn_folded(
        self, index: str, c: Call, slices: list[int], opt: ExecOptions
    ) -> list[Pair]:
        """Both TopN phases from one scoring pass (reference protocol:
        executor.go:281-321 — two map/reduce rounds; here the cross-slice
        candidate union is known after a host-only cache walk, so every
        slice scores the WHOLE union once and the phase-1 winner
        selection plus the phase-2 exact counts both read those scores.
        One device round trip instead of two.)  The prep (candidates,
        union, gather layout) comes from the validated per-query cache
        (_topn_folded_entry); per query only the dispatch, the ONE
        fetch, and the winner selection run."""
        n = _uint_arg(c, "n")[0]
        if len(c.children) > 1:
            raise ExecutorError("TopN() can only have one input bitmap")
        # Canonicalize through the parsed tree BEFORE keying the prep
        # cache: the single-flighted score sharing keyed on the exact
        # query string, so semantically identical TopN(src) queries
        # whose src trees merely commute (Intersect(A,B) vs
        # Intersect(B,A)) each paid their own dispatch+fetch.  AND/OR/
        # XOR commute bit for bit, so results stay byte-identical.
        c = plan.canonicalize_call(c)
        with self.tracer.span("topn.prep", slices=len(slices)):
            ent = self._topn_folded_entry(index, c, slices)
        if ent.get("empty"):
            return []
        if ent.get("two_phase"):
            return self._execute_topn_two_phase(index, c, slices, opt, n)

        # Clone the unscored states (the prep is shared across
        # concurrent queries; scores are per-query), dispatch, fetch.
        states: list[tuple] = []
        score_parts: list[tuple] = []
        for frag, topt, cand_ids, cand_mask, st_proto, sub_ref, srcw, src_slot in ent[
            "parts"
        ]:
            st = replace(st_proto, counts=None, dev_counts=None)
            states.append((frag, topt, cand_ids, cand_mask, st))
            score_parts.append((st, sub_ref, srcw, src_slot, frag))
        # Score ONCE per validated entry: concurrent queries of the
        # same TopN shape single-flight (one leader dispatches +
        # fetches; everyone else waits on an Event — never on a lock —
        # and reuses the fetched count vectors).  Scores stay valid
        # exactly as long as the entry does: entry validation already
        # proved the scored fragments unchanged since build.
        with self.tracer.span("topn.score", parts=len(score_parts)) as sp:
            scores = None
            leader = False
            ev = None
            with self._batch_mu:
                scores = ent.get("scores")
                if scores is None:
                    ev = ent.get("score_event")
                    if ev is None:
                        ev = ent["score_event"] = threading.Event()
                        leader = True
            if scores is None and not leader:
                # A leader is scoring right now; its fetched vectors
                # arrive with the event.  A failed leader leaves
                # scores unset — fall through and score directly.
                ev.wait(timeout=coalesce_mod.RESULT_TIMEOUT_S)
                with self._batch_mu:
                    scores = ent.get("scores")
            if scores is None:
                try:
                    # Pin the prep entry and every scored fragment's
                    # mirror for the fused scorer's dispatch+fetch: the
                    # pool may evict none of the planes this program
                    # reads mid-query.
                    pin_keys = [
                        self._topn_pool_key((index, str(c), tuple(slices)))
                    ]
                    pin_keys += [p[0]._pool_key for p in ent["parts"]]
                    with device_mod.pool().pinned(*pin_keys):
                        self._score_topn_parts(score_parts)
                    with self._batch_mu:
                        ent["scores"] = [p[0].counts for p in score_parts]
                    sp.annotate(score_cache="computed")
                finally:
                    if leader:
                        ev.set()
            else:
                for part, cnts in zip(score_parts, scores):
                    part[0].counts = cnts
                sp.annotate(score_cache="shared")
                self.holder.stats.count("exec.topn.scoreShared")

        # Phase-1 winner selection per slice, from the same scores the
        # two-phase protocol's first round would have produced for the
        # slice's own candidates (cand_ids is a subset of the union) —
        # all in numpy: at union scale, Pair-object bookkeeping in
        # Python dominated warm TopN host time.  The ``topn.select``
        # span is the host-winner-selection leg of the per-stage
        # TopN(src) breakdown (with topn.dispatch/topn.fetch).
        with self.tracer.span("topn.select", parts=len(states)):
            winner_ids: list[np.ndarray] = []
            fulls: list[tuple[np.ndarray, np.ndarray]] = []
            for frag, topt, cand_ids, cand_mask, st in states:
                ids, cnts, keep, short = frag.top_score_arrays(st)
                fulls.append((ids[keep], cnts[keep]))
                if topt.src is None:
                    winner_ids.append(
                        cand_ids[: topt.n] if topt.n else cand_ids
                    )
                elif short:
                    # Scoring short-circuited (e.g. no src segment
                    # here): the subset selection would short-circuit
                    # identically.
                    winner_ids.append(ids)
                else:
                    sel_ids, _ = frag.select_winners(
                        ids, cnts, keep, cand_ids, topt.n, cand_mask=cand_mask
                    )
                    winner_ids.append(sel_ids)
            ids2 = (
                np.unique(np.concatenate(winner_ids))
                if winner_ids
                else np.empty(0, np.int64)
            )
            if not len(ids2):
                return []

            # Phase-2 equivalent: exact counts for the winner union,
            # already in hand; counts SUM across slices (reference
            # reduce: Pairs.Add, cache.go:312-334).
            kept = []
            for i, cts in fulls:
                m = isin_sorted(i, ids2)
                kept.append((i[m], cts[m]))
            merged = merge_counts_by_id(kept)
            if merged is None:
                return []
            uids, sums = merged
            order = np.lexsort((uids, -sums))
            if n and n < len(order):
                order = order[:n]
            return [Pair(int(uids[k]), int(sums[k])) for k in order]

    def _execute_topn_slices(
        self, index: str, c: Call, slices: list[int], opt: ExecOptions
    ) -> list[Pair]:
        def map_fn(local_slices: list[int]):
            # Missing fragments contribute nothing — walk only slices
            # that materialized one (O(fragments), not O(max_slice)).
            local_slices = self._existing_topn_slices(index, c, local_slices)
            # The src bitmap (if any) evaluates HOST-side per slice: the
            # scorer needs host words anyway (sparse probing + transfer
            # to the gather kernel), so a device program here would add
            # a sync round trip per query for no compute win.
            src_rows = None
            if len(c.children) == 1:
                src_rows = self._eval_tree_slices_host(
                    index, c.children[0], local_slices
                )
            elif len(c.children) > 1:
                raise ExecutorError("TopN() can only have one input bitmap")
            # Two passes: prepare every slice (candidates + gathered
            # scorer inputs), then score all slices in as few batched
            # programs as their shapes allow, fetched in one transfer —
            # one round trip per node per phase however many slices it
            # owns, the TPU shape of the reference's goroutine-per-slice
            # mapperLocal fan-in (reference: executor.go:1246-1282).
            prepped = [
                self._prepare_topn_slice(index, c, s, src_rows=src_rows)
                for s in local_slices
            ]
            states = [p for p in prepped if p is not None]
            with device_mod.pool().pinned(
                *[frag._pool_key for frag, _ in states]
            ):
                self._score_topn_parts(
                    [
                        (*self._attach_dev_src(index, c, frag, part), frag)
                        for frag, part in states
                    ]
                )
            states = [(frag, part[0]) for frag, part in states]
            # Merge all slices' results in one numpy pass (counts sum
            # by id — Pairs.Add semantics, reference: cache.go:312-334);
            # Pairs materialize once at the protocol boundary.
            parts = []
            for frag, st in states:
                ids, cnts, keep, short = frag.top_score_arrays(st)
                if short:
                    parts.append((ids, cnts))
                else:
                    sel = keep
                    ids, cnts = ids[sel], cnts[sel]
                    if st.n and st.n < len(ids):
                        order = np.lexsort((ids, -cnts))[: st.n]
                        ids, cnts = ids[order], cnts[order]
                    parts.append((ids, cnts))
            merged = merge_counts_by_id(parts)
            if merged is None:
                return []
            uids, sums = merged
            return [Pair(int(i), int(cnt)) for i, cnt in zip(uids, sums)]

        def reduce_fn(prev, v):
            return cache_mod.add_pairs(prev or [], v)

        pairs = self._map_reduce(index, slices, c, opt, map_fn, reduce_fn) or []
        return cache_mod.sort_pairs(pairs)

    @staticmethod
    def _topn_frame_view(c: Call) -> tuple[str, str]:
        """The (frame, view) a TopN call targets — the single resolution
        point shared by option building and the existing-slice filter."""
        frame = c.args.get("frame") or DEFAULT_FRAME
        view = VIEW_INVERSE if bool(c.args.get("inverse", False)) else VIEW_STANDARD
        return frame, view

    @staticmethod
    def _topn_parsed_args(c: Call):
        """Slice-invariant TopN argument parsing (reference:
        executor.go:346-415), hoisted out of the per-slice loop — at
        hundreds of slices the repeated arg walks dominated option
        building.  Memoized ON the Call instance (clone() builds fresh
        objects, so a mutated clone — e.g. the phase-2 refetch's ids=
        — never sees a stale parse)."""
        cached = getattr(c, "_topn_parsed", None)
        if cached is not None:
            return cached
        frame, view = Executor._topn_frame_view(c)
        n = _uint_arg(c, "n")[0]
        fld = c.args.get("field", "") or ""
        row_ids = _uint_slice_arg(c, "ids")
        min_threshold = _uint_arg(c, "threshold")[0]
        if min_threshold <= 0:
            min_threshold = MIN_THRESHOLD
        filters = c.args.get("filters")
        tanimoto = _uint_arg(c, "tanimotoThreshold")[0]
        cached = (
            frame,
            view,
            n,
            fld,
            tuple(row_ids) if row_ids else None,
            min_threshold,
            tuple(filters) if filters else None,
            tanimoto,
        )
        c._topn_parsed = cached
        return cached

    def _topn_options_for_slice(self, index: str, c: Call, slice_i: int, src_rows=None):
        """reference: executor.go:346-415.  ``src_rows`` carries the
        host-evaluated src rows from _execute_topn_slices.  Returns
        ``(fragment, TopOptions)``, or None when the fragment does not
        exist."""
        (
            frame,
            view,
            n,
            fld,
            row_ids,
            min_threshold,
            filters,
            tanimoto,
        ) = self._topn_parsed_args(c)

        src = None
        if src_rows is not None:
            src = RowBitmap()
            row = src_rows.get(slice_i)
            if row is not None:
                src.set_segment(slice_i, row)

        f = self.holder.fragment(index, frame, view, slice_i)
        if f is None:
            return None
        # Validated AFTER the fragment-existence early return, matching
        # the reference's ordering (executor.go:346-415): a bad tanimoto
        # over absent fragments yields empty results, not an error.
        if tanimoto > 100:
            raise ExecutorError("Tanimoto Threshold is from 1 to 100 only")
        return f, TopOptions(
            n=n,
            src=src,
            row_ids=list(row_ids) if row_ids else None,
            filter_field=fld,
            filter_values=list(filters) if filters else None,
            min_threshold=min_threshold,
            tanimoto_threshold=tanimoto,
        )

    def _prepare_topn_slice(
        self, index: str, c: Call, slice_i: int, src_rows=None
    ):
        """``(fragment, (TopState, sub, src_words))`` with the score
        kernel NOT yet dispatched (see _score_topn_parts), or None when
        the fragment does not exist."""
        prep = self._topn_options_for_slice(index, c, slice_i, src_rows)
        if prep is None:
            return None
        f, topt = prep
        return f, f.top_prepare_parts(topt)

    # ------------------------------------------------------------------
    # writes (reference: executor.go:642-840)
    # ------------------------------------------------------------------

    def _resolve_write(self, index: str, c: Call, verb: str):
        frame_name = c.args.get("frame")
        if not isinstance(frame_name, str):
            raise ExecutorError(f"{verb}() field required: frame")
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError()
        f = idx.frame(frame_name)
        if f is None:
            raise FrameNotFoundError()
        row_label = f.row_label
        column_label = idx.column_label
        row_id, ok = _uint_arg(c, row_label)
        if not ok:
            raise ExecutorError(f"{verb}() row field '{row_label}' required")
        col_id, ok = _uint_arg(c, column_label)
        if not ok:
            raise ExecutorError(f"{verb}() column field '{column_label}' required")
        return f, row_id, col_id

    def _execute_set_bit(self, index: str, c: Call, opt: ExecOptions) -> bool:
        view = c.args.get("view", "") or ""
        f, row_id, col_id = self._resolve_write(index, c, "SetBit")

        timestamp = None
        ts = c.args.get("timestamp")
        if isinstance(ts, str):
            try:
                timestamp = datetime.strptime(ts, TIME_FORMAT)
            except ValueError:
                raise ExecutorError(f"invalid date: {ts}") from None

        ret = self._write_views(
            index, c, opt, view, f,
            lambda vw, r, cl: f.set_bit(vw, r, cl, timestamp),
            row_id, col_id,
        )
        self._wait_durable(index)
        return ret

    def _execute_clear_bit(self, index: str, c: Call, opt: ExecOptions) -> bool:
        view = c.args.get("view", "") or ""
        f, row_id, col_id = self._resolve_write(index, c, "ClearBit")
        ret = self._write_views(
            index, c, opt, view, f,
            lambda vw, r, cl: f.clear_bit(vw, r, cl),
            row_id, col_id,
        )
        self._wait_durable(index)
        return ret

    def _wait_durable(self, index: str) -> None:
        """Log-before-ack: park until every WAL append THIS thread made
        while applying the write is group-commit fsynced.  Runs OUTSIDE
        every fragment lock — a slow fsync stalls only this writer's
        ack, never a concurrent reader — and covers both the
        coordinator-local leg and remote legs (each remote node's own
        executor waits before responding)."""
        if self.ingest is None:
            return
        with self.tracer.span("ingest", index=index):
            self.ingest.wait_durable()

    def _write_views(
        self, index, c, opt, view, frame, write_fn, row_id, col_id
    ) -> bool:
        """Write to standard and/or inverse views with replica fan-out
        (reference: executor.go:679-734,783-840).  For the inverse view
        the row/column roles transpose: the slice is derived from the
        rowID and the stored (row, col) swap."""
        if view == VIEW_STANDARD:
            return self._write_one_view(index, c, opt, VIEW_STANDARD, write_fn, row_id, col_id)
        if view == VIEW_INVERSE:
            return self._write_one_view(index, c, opt, VIEW_INVERSE, write_fn, col_id, row_id)
        if view == "":
            ret = self._write_one_view(index, c, opt, VIEW_STANDARD, write_fn, row_id, col_id)
            if frame.inverse_enabled:
                if self._write_one_view(index, c, opt, VIEW_INVERSE, write_fn, col_id, row_id):
                    ret = True
            return ret
        raise ExecutorError(f"invalid view: {view}")

    def _write_one_view(
        self, index, c, opt, view, write_fn, row_id, col_id
    ) -> bool:
        # write_nodes: the read owners plus, during a rebalance
        # transition, the slice's NEW-ring owners — every write is
        # applied on both rings so no write is lost whichever ring
        # ultimately serves it (the delta log covers the copy race).
        slice_i = col_id // bp.SLICE_WIDTH
        ret = False
        wn = getattr(self.cluster, "write_nodes", None)
        targets = (
            wn(index, slice_i)
            if wn is not None
            else self.cluster.fragment_nodes(index, slice_i)
        )
        if self.replication is not None and not opt.remote:
            # Quorum path (pilosa_tpu/replicate): W-of-N acknowledgement
            # at the request's consistency, hints queued for unreachable
            # replicas, sub-W failing LOUDLY — never "success because
            # someone acked".
            return self.replication.coordinate_write(
                self, index, c, opt, view, write_fn, row_id, col_id,
                slice_i, targets,
            )
        for node in targets:
            if node.host == self.host:
                if write_fn(view, row_id, col_id):
                    ret = True
                continue
            if opt.remote:
                continue
            res = self._exec_remote(node, index, Query(calls=[c]), None, opt)
            if res and res[0]:
                ret = True
        return ret

    # ------------------------------------------------------------------
    # attribute writes (reference: executor.go:843-1040)
    # ------------------------------------------------------------------

    def _execute_set_row_attrs(self, index: str, c: Call, opt: ExecOptions) -> None:
        frame_name = c.args.get("frame")
        if not isinstance(frame_name, str):
            raise ExecutorError("SetRowAttrs() frame required")
        frame = self.holder.frame(index, frame_name)
        if frame is None:
            raise FrameNotFoundError()
        row_label = frame.row_label
        row_id, ok = _uint_arg(c, row_label)
        if not ok:
            raise ExecutorError(f"SetRowAttrs() row field '{row_label}' required")
        attrs = dict(c.args)
        attrs.pop("frame", None)
        attrs.pop(row_label, None)
        frame.row_attr_store.set_attrs(row_id, attrs)
        if opt.remote:
            return
        self._broadcast_query(index, Query(calls=[c]), opt)

    def _execute_bulk_set_row_attrs(
        self, index: str, calls: list[Call], opt: ExecOptions
    ) -> list:
        """reference: executor.go:905-985"""
        by_frame: dict[str, dict[int, dict]] = {}
        for c in calls:
            frame_name = c.args.get("frame")
            if not isinstance(frame_name, str):
                raise ExecutorError("SetRowAttrs() frame required")
            f = self.holder.frame(index, frame_name)
            if f is None:
                raise FrameNotFoundError()
            row_label = f.row_label
            row_id, ok = _uint_arg(c, row_label)
            if not ok:
                raise ExecutorError(f"SetRowAttrs row field '{row_label}' required")
            attrs = dict(c.args)
            attrs.pop("frame", None)
            attrs.pop(row_label, None)
            by_frame.setdefault(frame_name, {}).setdefault(row_id, {}).update(attrs)
        for frame_name, attr_sets in by_frame.items():
            f = self.holder.frame(index, frame_name)
            f.row_attr_store.set_bulk_attrs(attr_sets)
        if not opt.remote:
            self._broadcast_query(index, Query(calls=calls), opt)
        return [None] * len(calls)

    def _execute_set_column_attrs(self, index: str, c: Call, opt: ExecOptions) -> None:
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError()
        id_, ok = _uint_arg(c, "id")
        col_name = "id"
        if not ok:
            id_, ok = _uint_arg(c, idx.column_label)
            if not ok:
                raise ExecutorError("SetColumnAttrs() id required")
            col_name = idx.column_label
        attrs = dict(c.args)
        attrs.pop(col_name, None)
        idx.column_attr_store.set_attrs(id_, attrs)
        if opt.remote:
            return
        self._broadcast_query(index, Query(calls=[c]), opt)

    def _broadcast_query(self, index: str, q: Query, opt: ExecOptions) -> None:
        """Forward a query to every other node in parallel; first error
        wins (reference: executor.go:966-985).  During a rebalance
        transition the new ring's joining nodes receive the broadcast
        too (attribute state must be complete there at cutover)."""
        rn = getattr(self.cluster, "route_nodes", None)
        all_nodes = rn() if rn is not None else self.cluster.nodes
        others = [n for n in all_nodes if n.host != self.host]
        if not others:
            return
        futures = [
            self._pool.submit(self._exec_remote, n, index, q, None, opt)
            for n in others
        ]
        for fut in futures:
            fut.result()

    # ------------------------------------------------------------------
    # map/reduce over the cluster (reference: executor.go:1131-1283)
    # ------------------------------------------------------------------

    def _slices_by_node(
        self,
        nodes: list[Node],
        index: str,
        slices: list[int],
        epoch: int | None = None,
    ) -> dict[str, tuple[Node, list[int]]]:
        """Group slices by owning node, CACHED per (routing version,
        node set, index, slice list): placement is pure in those inputs
        (fnv + jump hash, reference: cluster.go:202-244), and at bench
        scale re-hashing ~1000 slices per query costs more host time
        than the compiled query program.  Callers treat the result as
        read-only.

        The cluster's ``routing_version`` keys the cache (per-slice
        cutover flips during a rebalance change placement without an
        epoch bump) together with its ``health_version`` (a replica
        whose DEVICE is quarantined — learned via the gossip
        device-health piggyback — is deprioritized: the first
        non-degraded owner serves, falling back to the primary when
        every replica is degraded), and ``epoch`` — when the caller
        captured one at query start — is verified here: a ring mutation
        mid-query raises
        :class:`~pilosa_tpu.cluster.topology.MixedEpochError`
        loudly instead of reducing over a half-old, half-new route."""
        rv = getattr(self.cluster, "routing_version", 0)
        hv = getattr(self.cluster, "health_version", 0)
        if epoch is not None:
            cur = getattr(self.cluster, "epoch", 0)
            if cur != epoch:
                raise topo.MixedEpochError(epoch, cur)
        key = (rv, hv, tuple(n.host for n in nodes), index, tuple(slices))
        with self._batch_mu:
            hit = self._slice_group_cache.get(key)
            if hit is not None:
                self._slice_group_cache.move_to_end(key)
                return hit
        m: dict[str, tuple[Node, list[int]]] = {}
        node_hosts = {n.host for n in nodes}
        for s in slices:
            owners = [
                o
                for o in self.cluster.fragment_nodes(index, s)
                if o.host in node_hosts
            ]
            if not owners:
                raise SliceUnavailableError()
            owner = next(
                (o for o in owners if not getattr(o, "degraded", False)),
                owners[0],
            )
            m.setdefault(owner.host, (owner, []))[1].append(s)
        with self._batch_mu:
            self._slice_group_cache[key] = m
            while len(self._slice_group_cache) > 8:
                self._slice_group_cache.popitem(last=False)
        return m

    def _map_reduce(self, index, slices, c, opt, map_fn, reduce_fn):
        """Map slices over owning nodes, reduce INCREMENTALLY as each
        response lands, and fail a dead node's slices over to replicas
        the moment its error arrives (reference: executor.go:1149-1243
        reduces off a channel the same way).

        A slow or dead node therefore never delays reducing the fast
        nodes' results: completion order drives the reduce loop
        (FIRST_COMPLETED waits), and failover work is resubmitted while
        the healthy nodes' mappers are still in flight.

        Routing is EPOCH-GUARDED: the topology epoch is captured once
        here, and every (re)grouping — including failover re-placement
        — verifies it, so a ring mutation mid-query fails loudly
        instead of mixing epochs."""
        epoch0 = getattr(self.cluster, "epoch", None)
        if not opt.remote:
            # route_nodes = the read ring plus, during a rebalance
            # transition, the new ring's joining nodes (flipped slices
            # already route to them).
            rn = getattr(self.cluster, "route_nodes", None)
            nodes = rn() if rn is not None else list(self.cluster.nodes)
        else:
            me = self.cluster.node_by_host(self.host)
            nodes = [me] if me is not None else [Node(host=self.host)]
        if not nodes:
            nodes = [Node(host=self.host)]

        if not slices:
            # Sliceless execution still runs locally once.
            resp = self._map_node(Node(host=self.host), [], index, c, opt, map_fn)
            if resp.error:
                raise resp.error
            return reduce_fn(None, resp.result)

        result = None
        # future -> node list the future's slices may still fail over to
        inflight: dict = {}
        # Slices dropped under allow_partial (every replica down/open).
        missing: list[int] = []

        def _submit(avail_nodes, want) -> None:
            m = self._slices_by_node(avail_nodes, index, want, epoch=epoch0)
            for _, (node, node_slices) in m.items():
                fut = self._pool.submit(
                    self._map_node, node, node_slices, index, c, opt, map_fn
                )
                inflight[fut] = avail_nodes

        def _failover(resp, avail_nodes) -> None:
            """Re-place a failed mapper's slices on the remaining nodes.
            An exhausted DEADLINE is never a node failure — it fails the
            query (504), not the node.  Slices with no surviving replica
            either fail fast with the slice list or, under
            ``allow_partial``, drop into ``missing``.  A semantic error
            (bad frame, parse-adjacent failures) re-raises rather than
            masquerading as a dead node."""
            if isinstance(resp.error, resilience.DeadlineExceeded):
                raise resp.error
            if not resilience.is_node_failure(resp.error):
                raise resp.error
            remaining = [n for n in avail_nodes if n.host != resp.node.host]
            placeable, lost = self.cluster.split_by_owner(
                index, resp.slices, {n.host for n in remaining}
            )
            if lost:
                if not opt.allow_partial:
                    raise SlicesUnavailableError(lost, cause=resp.error)
                missing.extend(lost)
                self.holder.stats.count(
                    "exec.partial.slicesDropped", len(lost)
                )
            if placeable:
                _submit(remaining, placeable)

        m = self._slices_by_node(nodes, index, slices, epoch=epoch0)
        if len(m) == 1:
            # Single target (the whole single-node case): run the
            # mapper inline.  A pool hop would add a context switch
            # per query and cap request concurrency at the pool
            # size — the caller's own thread is the parallelism.
            ((node, node_slices),) = m.values()
            resp = self._map_node(node, node_slices, index, c, opt, map_fn)
            if resp.error is None:
                return reduce_fn(None, resp.result)
            _failover(resp, nodes)
        else:
            _submit(nodes, slices)

        while inflight:
            # Reduce-loop waits derive from the remaining deadline
            # budget, not a flat constant: when it runs out, abandon the
            # in-flight mappers (daemon pool) and 504.
            dl = resilience.current_deadline()
            timeout = None
            if dl is not None:
                timeout = dl.remaining()
                if timeout <= 0:
                    raise resilience.DeadlineExceeded(
                        "deadline exceeded awaiting map responses"
                    )
            done, _ = wait(
                list(inflight), timeout=timeout, return_when=FIRST_COMPLETED
            )
            if not done:
                raise resilience.DeadlineExceeded(
                    "deadline exceeded awaiting map responses"
                )
            for fut in done:
                avail_nodes = inflight.pop(fut)
                resp = fut.result()
                if resp.error is not None:
                    _failover(resp, avail_nodes)
                    continue
                result = reduce_fn(result, resp.result)
        if missing:
            # Merge (a query may map/reduce more than once — TopN's two
            # phases), keep sorted + deduplicated for the wire marker.
            opt.missing_slices[:] = sorted(
                set(opt.missing_slices) | set(missing)
            )
        return result

    def _map_node(self, node, node_slices, index, c, opt, map_fn) -> _MapResponse:
        resp = _MapResponse(node=node, slices=node_slices)
        try:
            # The deadline contextvar crossed into this worker with the
            # submitter's context; an exhausted budget fails the QUERY
            # (504 at the handler), never the node.
            resilience.check_deadline("before map")
            if node.host == self.host:
                with self.tracer.span(
                    "map.local", node=node.host, slices=len(node_slices)
                ):
                    resp.result = map_fn(node_slices)
            else:
                results = self._exec_remote(
                    node, index, Query(calls=[c]), node_slices, opt,
                    idempotent=True,
                )
                resp.result = results[0] if results else None
        except resilience.DeadlineExceeded:
            raise
        except Exception as e:  # noqa: BLE001 — failover boundary
            resp.error = e
        return resp

    def _exec_remote(
        self, node, index, q, slices, opt, idempotent=False,
        extra_headers=None,
    ) -> list:
        """Forward a query to a peer (reference: executor.go:1045-1129).

        The rpc span's ids travel as X-Trace-Id/X-Span-Id headers; the
        remote handler continues the trace under them and ships its
        spans back, which the client absorbs into this node's trace.

        ``idempotent`` marks the call safe to retry (read-only map
        legs); write fan-out stays single-shot, matching the client's
        retry contract.  ``extra_headers`` ride the same header channel
        (the quorum coordinator's X-Write-Version stamp)."""
        if self.client_factory is None:
            raise ExecutorError(f"no client for remote node {node.host}")
        client = self.client_factory(node)
        with self.tracer.span(
            "rpc.execute", node=node.host, slices=len(slices) if slices else 0
        ) as sp:
            headers = self.tracer.remote_headers(sp)
            if extra_headers:
                headers = {**(headers or {}), **extra_headers}
            if opt.tenant:
                headers = {**(headers or {}), "X-Tenant": opt.tenant}
            kwargs = {}
            if getattr(client, "supports_resilience", False):
                kwargs["idempotent"] = idempotent
            if headers and getattr(client, "supports_trace", False):
                return client.execute_query(
                    index,
                    str(q),
                    slices,
                    remote=True,
                    trace_headers=headers,
                    tracer=self.tracer,
                    **kwargs,
                )
            return client.execute_query(
                index, str(q), slices, remote=True, **kwargs
            )


# ---------------------------------------------------------------------------


def _uint_arg(c: Call, key: str) -> tuple[int, bool]:
    """(value, present) via Call.uint_arg (negative int64s wrap to
    uint64, so e.g. rowID=-1 reads an empty astronomically-high row
    instead of erroring), with type errors normalized to ExecutorError
    at the API boundary."""
    try:
        v = c.uint_arg(key)
    except TypeError as e:
        raise ExecutorError(str(e)) from e
    return (0, False) if v is None else (v, True)


def _uint_slice_arg(c: Call, key: str) -> list[int] | None:
    try:
        return c.uint_slice_arg(key)
    except TypeError as e:
        raise ExecutorError(str(e)) from e


def _time_arg(c: Call, key: str) -> datetime:
    v = c.args.get(key)
    if not isinstance(v, str):
        raise ExecutorError(f"Range() {key} time required")
    try:
        return datetime.strptime(v, TIME_FORMAT)
    except ValueError:
        raise ExecutorError(f"cannot parse Range() {key} time") from None
