"""Query planning: PQL call trees -> fused XLA programs.

The reference interprets a call tree per slice, materializing a roaring
bitmap at every node and dispatching per-container merge kernels
(reference: executor.go:263-278 executeBitmapCallSlice and the roaring
kernels under it).  On TPU that structure would bounce every intermediate
through HBM; instead each *tree shape* compiles once to a single jitted
function over a stack of leaf rows:

    Count(Intersect(Bitmap(a), Bitmap(b)))
      -> fn(leaves: uint32[2, 32768]) = popcount_sum(leaves[0] & leaves[1])

XLA fuses the whole expression (bitwise ops + popcount + reduce) into one
kernel, so no intermediate row ever materializes.  Shapes are static:
every leaf is one slice-row (32768 uint32 words), so one compilation per
(tree-shape, reduce-kind) serves every slice and every rowID — query
shape bucketing per SURVEY.md §7 "dynamic shapes".

Leaf calls are ``Bitmap`` and ``Range`` (row fetches); interior calls are
``Intersect``/``Union``/``Difference`` (left-fold, reference:
executor.go:418-434,486-505,621-637).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, namedtuple
from collections.abc import Callable

import jax
import jax.numpy as jnp

from pilosa_tpu.bsi import ripple
from pilosa_tpu.pql.parser import WRITE_CALLS, Call

# Calls that fetch rows (leaves of a bitmap expression).  The Bsi*
# leaves are synthetic calls the executor's BSI rewrite produces:
# BsiPlane fetches one field-view plane row, BsiPred is a packed
# predicate row (slice-invariant data), BsiZero an all-zero pad plane
# (depth bucketing).
LEAF_CALLS = frozenset({"Bitmap", "Range", "BsiPlane", "BsiPred", "BsiZero"})
# Interior set-algebra calls and their fold ops.
FOLD_CALLS = frozenset({"Intersect", "Union", "Difference", "Xor"})
# Synthetic BSI interior calls (executor._rewrite_bsi / _rewrite_bsi_agg):
# BsiCmp produces a result row (composable inside bitmap trees); the
# aggregates produce per-slice int32 partial vectors (reduce "agg").
BSI_CALLS = frozenset({"BsiCmp", "BsiSum", "BsiMin", "BsiMax"})
# Leaves that carry slice-invariant data rather than fragment content:
# they never make a slice non-empty on their own.
NEUTRAL_LEAVES = frozenset({"BsiPred", "BsiZero"})


class PlanError(ValueError):
    pass


def decompose(call: Call) -> tuple[tuple, list[Call]]:
    """Flatten a bitmap call tree into a hashable structure + leaf calls.

    Returns ``(expr, leaves)`` where ``expr`` is a nested tuple — ``("leaf",
    i)`` referencing ``leaves[i]``, or ``(op, child_exprs...)`` — usable as
    a jit cache key.
    """
    leaves: list[Call] = []

    def rec(c: Call) -> tuple:
        if c.name in LEAF_CALLS:
            idx = len(leaves)
            leaves.append(c)
            return ("leaf", idx)
        if c.name in BSI_CALLS:
            # Statics come from the synthetic call's args; depth is
            # implied by the child arity, so fields sharing a depth
            # bucket share one expr (and one compiled program) per op.
            if c.name == "BsiCmp":
                head = ("bsiCmp", c.args["op"])
            else:
                tag = {"BsiSum": "bsiSum", "BsiMin": "bsiMin", "BsiMax": "bsiMax"}
                head = (tag[c.name], bool(c.args.get("filter")))
            return head + tuple(rec(ch) for ch in c.children)
        if c.name not in FOLD_CALLS:
            raise PlanError(f"unknown call: {c.name}")
        if c.name in ("Intersect", "Difference") and not c.children:
            raise PlanError(f"empty {c.name} query is currently not supported")
        return (c.name,) + tuple(rec(ch) for ch in c.children)

    return rec(call), leaves


def collect_leaf_calls(call: Call) -> list[Call]:
    """Every Bitmap/Range leaf reachable under ``call``, crossing
    non-bitmap wrappers (Count's child, TopN's src tree) — the
    prefetcher's walk (device/prefetch.py).  Unlike :func:`decompose`
    it never raises on unknown interior calls: the prefetcher only
    needs the leaves' (frame, view, row) identities to re-materialize
    cold mirrors, not a valid bitmap expression, so anything
    unrecognized just recurses into its children."""
    out: list[Call] = []

    def rec(c: Call) -> None:
        if c.name in LEAF_CALLS:
            out.append(c)
            return
        for ch in c.children:
            rec(ch)

    rec(call)
    return out


# ---------------------------------------------------------------------------
# cost classes (net/admission.py): the admission layer's view of a plan
# ---------------------------------------------------------------------------

COST_POINT = "point"
COST_HEAVY = "heavy"
COST_WRITE = "write"

# Calls whose execution fans past a single fused row program: TopN's
# two-phase candidate walk, the BSI aggregates' per-slice partial
# vectors, and Range (time-view union / ~depth-many plane leaves per
# BSI comparison) all cost an order of magnitude more device and host
# work per slice than a point Count/Bitmap tree.
_HEAVY_CALLS = frozenset({"TopN", "Sum", "Min", "Max", "Range"})


def cost_class(calls: "list[Call]") -> str:
    """The admission cost class of a parsed query: ``write`` when any
    call mutates, else ``heavy`` when any call (at any depth) is a
    TopN/aggregate/Range, else ``point``.  Derived purely from the
    parsed plan — classification must stay cheap enough to run before
    any admission decision, let alone device work."""

    def heavy(c: Call) -> bool:
        if c.name in _HEAVY_CALLS:
            return True
        return any(heavy(ch) for ch in c.children)

    if any(c.name in WRITE_CALLS for c in calls):
        return COST_WRITE
    if any(heavy(c) for c in calls):
        return COST_HEAVY
    return COST_POINT


def canonicalize_call(c: Call) -> Call:
    """Reorder the children of commutative fold calls (Intersect/
    Union/Xor — AND/OR/XOR on bitsets) into a canonical order, bottom
    up, so semantically identical trees that differ only in argument
    ordering produce one canonical string (``str(call)`` already sorts
    keyword args).  This is the compile-key canonicalization the
    single-flighted TopN score cache keys through: without it,
    ``TopN(Intersect(A, B), ...)`` and ``TopN(Intersect(B, A), ...)``
    each paid their own dispatch+fetch.  Returns the ORIGINAL object
    when nothing changed.  Difference is not commutative and is left
    alone; results are byte-identical either way."""
    kids = [canonicalize_call(ch) for ch in c.children]
    if c.name in ("Intersect", "Union", "Xor") and len(kids) > 1:
        kids = sorted(kids, key=str)
    if len(kids) == len(c.children) and all(
        a is b for a, b in zip(kids, c.children)
    ):
        return c
    return Call(name=c.name, args=dict(c.args), children=kids)


def _popcount32(row):
    return jnp.sum(jax.lax.population_count(row).astype(jnp.int32))


def _split_bsi_rows(rows, tail: int):
    """(exists, sign, planes, tail_rows) from a BSI node's evaluated
    children — ``tail`` trailing rows are predicate/filter rows."""
    body = rows[: len(rows) - tail] if tail else rows
    return body[0], body[1], body[2:], rows[len(rows) - tail :]


def _eval_expr(expr: tuple, leaves):
    if expr[0] == "leaf":
        return leaves[expr[1]]
    name = expr[0]
    if name == "bsiCmp":
        op = expr[1]
        rows = [_eval_expr(e, leaves) for e in expr[2:]]
        npred = 2 if op == "between" else 1
        exists, sign, planes, preds = _split_bsi_rows(rows, npred)
        if op == "between":
            return ripple.between_row(
                exists, sign, planes, preds[0], preds[1], jnp
            )
        return ripple.signed_cmp(op, exists, sign, planes, preds[0], jnp)
    if name in ("bsiSum", "bsiMin", "bsiMax"):
        has_filter = expr[1]
        rows = [_eval_expr(e, leaves) for e in expr[2:]]
        exists, sign, planes, tail = _split_bsi_rows(
            rows, 1 if has_filter else 0
        )
        filt = tail[0] if has_filter else None
        if name == "bsiSum":
            return ripple.sum_vec(exists, sign, planes, filt, jnp, _popcount32)
        return ripple.minmax_vec(
            "min" if name == "bsiMin" else "max",
            exists, sign, planes, filt, jnp, _popcount32, jnp.where,
        )
    children = [_eval_expr(e, leaves) for e in expr[1:]]
    if name == "Union" and not children:
        return jnp.zeros(leaves.shape[1:], dtype=leaves.dtype)
    acc = children[0]
    for nxt in children[1:]:
        if name == "Intersect":
            acc = acc & nxt
        elif name == "Union":
            acc = acc | nxt
        elif name == "Difference":
            acc = acc & ~nxt
        elif name == "Xor":
            acc = acc ^ nxt
    return acc


def eval_expr_np(expr: tuple, leaf_rows, words: int):
    """HOST (numpy) evaluation of a decomposed tree over one slice's
    leaf rows (``leaf_rows[i]`` is uint32[words] or None = empty).

    The fold ops are the same as the device _eval_expr; numpy vectorizes
    them in one pass over 128 KiB, which beats a device dispatch for the
    side computations that feed host logic (e.g. the TopN src row: its
    consumer needs host words for sparse probing, so evaluating on
    device would buy a sync round trip for nothing — through a remote
    TPU tunnel that round trip dwarfs the query itself)."""
    import numpy as np

    def rec(e):
        if e[0] == "leaf":
            r = leaf_rows[e[1]]
            return None if r is None else np.asarray(r, dtype=np.uint32)
        name = e[0]
        if name in ("bsiCmp", "bsiSum", "bsiMin", "bsiMax"):
            rows = [rec(c) for c in e[2:]]
            rows = [
                np.zeros(words, dtype=np.uint32) if r is None else r
                for r in rows
            ]
            pops = lambda r: int(np.bitwise_count(r).sum()) if hasattr(  # noqa: E731
                np, "bitwise_count"
            ) else int(np.unpackbits(r.view(np.uint8)).sum())
            if name == "bsiCmp":
                op = e[1]
                npred = 2 if op == "between" else 1
                exists, sign, planes, preds = _split_bsi_rows(rows, npred)
                if op == "between":
                    return ripple.between_row(
                        exists, sign, planes, preds[0], preds[1], np
                    )
                return ripple.signed_cmp(op, exists, sign, planes, preds[0], np)
            has_filter = e[1]
            exists, sign, planes, tail = _split_bsi_rows(
                rows, 1 if has_filter else 0
            )
            filt = tail[0] if has_filter else None
            if name == "bsiSum":
                return ripple.sum_vec(exists, sign, planes, filt, np, pops)
            return ripple.minmax_vec(
                "min" if name == "bsiMin" else "max",
                exists, sign, planes, filt, np, pops, np.where,
            )
        children = [rec(c) for c in e[1:]]
        zeros = lambda: np.zeros(words, dtype=np.uint32)  # noqa: E731
        if name == "Union":
            live = [c for c in children if c is not None]
            if not live:
                return None
            acc = live[0]
            for nxt in live[1:]:
                acc = acc | nxt
            return acc
        acc = children[0]
        for nxt in children[1:]:
            if name == "Intersect":
                if acc is None or nxt is None:
                    return None
                acc = acc & nxt
            elif name == "Difference":
                if acc is None:
                    return None
                if nxt is not None:
                    acc = acc & ~nxt
            elif name == "Xor":
                if acc is None:
                    acc = zeros()
                acc = acc ^ (nxt if nxt is not None else zeros())
        return acc

    return rec(expr)


def _make_fn(expr: tuple, reduce: str):
    """``reduce``: ``"row"`` returns the uint32[32768] result row;
    ``"count"`` returns the int32 popcount of the result (never
    materializing it); ``"agg"`` passes the expression's own int32
    partial vector through unchanged (the BSI aggregate nodes reduce
    inside the expression)."""

    def fn(leaf_stack):
        out = _eval_expr(expr, leaf_stack)
        if reduce == "count":
            return jnp.sum(jax.lax.population_count(out).astype(jnp.int32))
        return out

    return fn


def compiled_batched(expr: tuple, reduce: str) -> "_Program":
    """One jitted program per (tree shape, reduce kind), vmapped over a
    leading slice axis — input uint32[n_slices, n_leaves, 32768].  All of
    a node's local slices evaluate in ONE device program (the TPU-shaped
    equivalent of the reference's goroutine-per-slice mapperLocal,
    reference: executor.go:1246-1282).

    XLA emits the whole expression as one fused bitwise+popcount+reduce
    pass (measured ~490 GB/s ≈ 60% of v5e HBM peak at 1B columns); a
    handwritten Pallas variant was measured decisively slower twice and
    deleted — see ops/bitplane.py."""
    return _compiled_batched(expr, reduce)


# On-device count reduce budget, in PARTIALS (one partial = one
# slice-row's popcount, <= 2^20 bits).  TPUs have no native int64, so
# the reduce runs TWO-STAGE in 16-bit limbs of the per-slice-row int32
# partials: sum(partial & 0xFFFF) stays below 2^31 for up to 2^15
# partials and sum(partial >> 16) far longer; the host recombines
# hi*2^16 + lo in Python ints.  2^15 single-row slices = ~34B columns
# per node — past BASELINE configs[4]'s 10B-column cluster shape.
# Callers fall back to the per-slice host sum (int64) beyond this.
MAX_ONDEVICE_COUNT_PARTIALS = 1 << 15


def compiled_total_count(expr: tuple, mesh=None) -> "_Program":
    """Count(tree) reduced to one replicated int32[2] = (hi, lo) limb
    pair on-device; total = (hi << 16) + lo, recombined by the caller
    (recombine_count_limbs).  ``mesh=None`` compiles the single-device
    variant: same limb math, no collective — only 8 bytes return to the
    host instead of a per-slice partial vector.

    Input: uint32[n_slices, n_leaves, *rest, words] sharded P(slices,
    None, ...) over ``mesh``.  The word axis reduces first — every
    partial covers at most one slice-row's 2^20 bits, so int32 is exact
    — then the partials limb-split and sum across ALL remaining axes
    *inside* the jitted program, so the SPMD partitioner inserts the
    cross-device all-reduce (psum riding ICI) — the collective
    replacement for the reference's streaming HTTP fan-in reduce
    (reference: executor.go:1176-1207).  Only the two scalars ever
    reach the host, and the limb math is exact for up to
    MAX_ONDEVICE_COUNT_PARTIALS slice-row partials.
    """
    return _compiled_total_count(expr, mesh)


# Collective-bearing launches (programs whose cross-slice reduce psums
# over a sharded mesh axis) must never be IN FLIGHT concurrently from
# two threads of one process: each launch enqueues on every
# participating device, and two racing dispatches can enqueue in
# different per-device orders — both all-reduces then wait forever for
# participants stuck behind the other program (observed as the CPU
# backend's cross_module rendezvous stall; the hazard is structural,
# not backend-specific).  One process-wide mutex serializes them:
# collective programs occupy the whole mesh anyway, so the lock costs
# nothing a real device would not already charge.  Collective-free
# launches (vmapped per-slice programs, single-device reduces) never
# take it.
_collective_mu = threading.Lock()


def collective_launch() -> "threading.Lock":
    """The process-wide mesh-collective launch lock; hold it across
    dispatch + fetch of any program compiled with a mesh psum
    (compiled_total_count(expr, mesh), interp "total" on sharded input,
    parallel/mesh's distributed reduces)."""
    return _collective_mu


def recombine_count_limbs(limbs):
    """(hi, lo) int32 limbs -> exact totals.

    Scalar limb pair (shape [2]) -> Python int; vector limbs (shape
    [2, n]) -> int64 ndarray.  The single recombination point for every
    limb-split device reduce (Count and TopN)."""
    import numpy as np

    limbs = np.asarray(limbs, dtype=np.int64)
    hi, lo = limbs[0], limbs[1]
    total = (hi << 16) + lo
    return int(total) if total.ndim == 0 else total


def expr_has_bsi(expr: tuple) -> bool:
    """Whether a decomposed expr contains a BSI node.  BSI nodes index
    WORDS of their predicate row and reduce internally, so they must
    evaluate per slice (vmap) — the leaf-major broadcast trick the pure
    bitwise total-count uses would hand them whole slice axes."""
    if expr[0] == "leaf":
        return False
    if expr[0] in ("bsiCmp", "bsiSum", "bsiMin", "bsiMax"):
        return True
    return any(expr_has_bsi(e) for e in expr[1:])


def slice_bucket(n: int) -> int:
    """Canonical pow2 bucket for a batch's leading slice axis — the ONE
    bucketing rule every batch assembler (executor, coalescer, warmup)
    must use, so their launches land on the same compiled programs."""
    from pilosa_tpu.ops import bitplane as bp

    return bp.pow2_bucket(n, 1)


# ---------------------------------------------------------------------------
# expression-as-data interpreter (plane-major multi-query fusion)
# ---------------------------------------------------------------------------
#
# ``compiled_batched`` compiles one program per TREE SHAPE, so a mix of
# DISTINCT concurrent queries never shares a launch and each re-streams
# its resident planes.  The interpreter generalizes the PR-6
# predicates-travel-as-data idiom (bsi.pred_row) to the expression
# itself: a register machine whose opcode/operand table is an ordinary
# int32 INPUT — K distinct trees lower to one table, the compiled
# program streams the union leaf set exactly once per dispatch, and a
# new query is a new table row, NEVER a recompile.  The jit key is pure
# geometry — (slice bucket, leaf bucket, op bucket, out bucket, reduce)
# — every axis pow2-bucketed, so the family's compiled-entry count is
# O(1) in concurrent-mix diversity (program_cache_bounds "interp").
#
# Register file layout per slice: slots [0, n_leaves) are the stacked
# leaf rows, slot n_leaves + i is instruction i's output.  Instruction
# row: (opcode, a, b, aux).

OP_AND = 0
OP_OR = 1
OP_ANDNOT = 2
OP_XOR = 3
# Broadcast of predicate word ``aux`` of register ``a``: all-ones iff
# bit 0 of that word is set — the BSI ripple's per-plane predicate mask
# (ripple.lower_magnitude_cmp), reading the packed bsi.pred_row leaf.
OP_MASKW = 4

# Opcode-table budget for one fused launch: a lowered tree past this
# falls back to the per-compile-key coalesce path (its own concat
# launch) rather than splintering the bucket grid.  Tables pad to pow2
# buckets >= FUSE_OPS_FLOOR.
FUSE_MAX_OPS = 256
FUSE_OPS_FLOOR = 8


class FuseUnsupported(PlanError):
    """The expression cannot lower to the interpreter's opcode table
    (BSI aggregates reduce inside the expression; oversized trees blow
    the op budget) — callers fall back to the per-compile-key path."""


class FuseEmitter:
    """Value-numbering opcode emitter: identical instructions (with
    commutative operand order normalized) share one register, so
    shared subtrees within a fused batch evaluate once.  ``rollback``
    restores a checkpoint when a tree fails to lower mid-way, keeping
    the shared table clean for the batch's other queries."""

    def __init__(self, n_leaves: int, max_ops: int = FUSE_MAX_OPS):
        self.n_leaves = int(n_leaves)
        self.max_ops = int(max_ops)
        self.rows: list[tuple[int, int, int, int]] = []
        self._memo: dict[tuple, int] = {}
        self.dedup_hits = 0

    def _emit(self, op: int, a: int, b: int, aux: int = 0) -> int:
        if op in (OP_AND, OP_OR, OP_XOR) and b < a:
            a, b = b, a
        key = (op, a, b, aux)
        reg = self._memo.get(key)
        if reg is not None:
            self.dedup_hits += 1
            return reg
        if len(self.rows) >= self.max_ops:
            raise FuseUnsupported(
                f"opcode table full ({self.max_ops} instructions)"
            )
        reg = self.n_leaves + len(self.rows)
        self.rows.append((int(op), int(a), int(b), int(aux)))
        self._memo[key] = reg
        return reg

    def and_(self, a: int, b: int) -> int:
        return self._emit(OP_AND, a, b)

    def or_(self, a: int, b: int) -> int:
        return self._emit(OP_OR, a, b)

    def andnot(self, a: int, b: int) -> int:
        return self._emit(OP_ANDNOT, a, b)

    def xor(self, a: int, b: int) -> int:
        return self._emit(OP_XOR, a, b)

    def maskw(self, a: int, word: int) -> int:
        return self._emit(OP_MASKW, a, a, word)

    def checkpoint(self) -> tuple:
        return len(self.rows), dict(self._memo), self.dedup_hits

    def rollback(self, cp: tuple) -> None:
        n, memo, hits = cp
        del self.rows[n:]
        self._memo = memo
        self.dedup_hits = hits


_FOLD_EMIT = {
    "Intersect": "and_",
    "Union": "or_",
    "Difference": "andnot",
    "Xor": "xor",
}


def _leaf_reg(leaf_map, i: int) -> int:
    return leaf_map + i if isinstance(leaf_map, int) else leaf_map[i]


def lower_expr(expr: tuple, leaf_map, em: FuseEmitter) -> int:
    """Lower one decomposed tree into ``em``'s opcode table; returns
    the result row's register id.  ``leaf_map`` places the tree's
    leaves in the combined register file: an int means leaves sit
    contiguously at ``base + i``; a sequence maps leaf ordinal ``i`` to
    its register — the fused union-leaf layout, where leaf columns
    SHARED between queries (same fragment row, same slice geometry)
    collapse to one register, so the emitter's value numbering dedups
    whole subtrees across distinct queries.  The emitted stream mirrors
    :func:`_eval_expr` operation for operation (the BSI ripple lowers
    through bsi/ripple.py's ``lower_*``), so interpreter results are
    byte-identical to the direct compiled tree.  Raises
    :class:`FuseUnsupported` for BSI aggregates (they reduce inside the
    expression) and when the op budget runs out."""
    if expr[0] == "leaf":
        return _leaf_reg(leaf_map, expr[1])
    name = expr[0]
    if name == "bsiCmp":
        op = expr[1]
        regs = [lower_expr(e, leaf_map, em) for e in expr[2:]]
        npred = 2 if op == "between" else 1
        body, preds = regs[: len(regs) - npred], regs[len(regs) - npred :]
        exists, sign, planes = body[0], body[1], body[2:]
        if op == "between":
            return ripple.lower_between(
                em, exists, sign, planes, preds[0], preds[1]
            )
        return ripple.lower_signed_cmp(em, op, exists, sign, planes, preds[0])
    if name in ("bsiSum", "bsiMin", "bsiMax"):
        raise FuseUnsupported(f"{name} reduces inside the expression")
    children = [lower_expr(e, leaf_map, em) for e in expr[1:]]
    if not children:
        # Empty Union: the canonical all-zero row (x ^ x).
        zero = _leaf_reg(leaf_map, 0)
        return em.xor(zero, zero)
    emit = getattr(em, _FOLD_EMIT[name])
    acc = children[0]
    for nxt in children[1:]:
        acc = emit(acc, nxt)
    return acc


def _build_interp(reduce: str):
    """One jitted interpreter per reduce kind: ``fn(leaves, prog,
    out_idx)`` with ``leaves`` uint32[n_slices, n_leaves, words],
    ``prog`` int32[n_ops, 4] instruction rows, ``out_idx`` int32[k]
    result-register selections.  A lax.scan threads the register file
    through the table (dynamic_update_index keeps the carry in place),
    vmapped over slices; ``"count"`` returns int32[n_slices, k]
    popcount partials, ``"row"`` uint32[n_slices, k, words] result
    rows, ``"total"`` int32[2, k] per-register (hi, lo) 16-bit limb
    pairs — the per-slice count partials limb-split and summed across
    the slice axis INSIDE the jitted program, so on a mesh-sharded
    batch the SPMD partitioner inserts the cross-device all-reduce
    (psum over ICI) and only 8·k bytes ever reach the host (exact up
    to MAX_ONDEVICE_COUNT_PARTIALS slice-row partials; zero pad slices
    contribute nothing to either limb).  The table and selections are
    DATA — one compiled entry per geometry bucket serves every
    expression mix."""
    inner = "count" if reduce == "total" else reduce

    def fn(leaves, prog, out_idx):
        n_leaves = leaves.shape[1]
        steps = prog.shape[0]

        def one(stack):
            regs0 = jnp.concatenate(
                [stack, jnp.zeros((steps, stack.shape[1]), dtype=stack.dtype)],
                axis=0,
            )

            def step(regs, x):
                row, i = x
                op, a, b, aux = row[0], row[1], row[2], row[3]
                ra = regs[a]
                rb = regs[b]
                val = jax.lax.switch(
                    op,
                    (
                        lambda ra, rb, aux: ra & rb,
                        lambda ra, rb, aux: ra | rb,
                        lambda ra, rb, aux: ra & ~rb,
                        lambda ra, rb, aux: ra ^ rb,
                        lambda ra, rb, aux: jnp.broadcast_to(
                            (ra[aux] & jnp.uint32(1))
                            * jnp.uint32(0xFFFFFFFF),
                            ra.shape,
                        ),
                    ),
                    ra,
                    rb,
                    aux,
                )
                return (
                    jax.lax.dynamic_update_index_in_dim(
                        regs, val, n_leaves + i, 0
                    ),
                    None,
                )

            regs, _ = jax.lax.scan(step, regs0, (prog, jnp.arange(steps)))
            outs = regs[out_idx]
            if inner == "count":
                return jnp.sum(
                    jax.lax.population_count(outs).astype(jnp.int32), axis=-1
                )
            return outs

        res = jax.vmap(one)(leaves)
        if reduce == "total":
            # Limb-split BEFORE the slice-axis sum (TPUs have no int64):
            # each partial <= 2^20, so lo/hi stay int32-exact up to 2^15
            # non-zero partials; the host recombines hi*2^16 + lo.  On
            # sharded input the sums become all-reduces over the mesh.
            lo = jnp.sum(res & 0xFFFF, axis=0)
            hi = jnp.sum(res >> 16, axis=0)
            return jnp.stack([hi, lo])
        return res

    return jax.jit(fn)


def compiled_interp(reduce: str) -> "_Program":
    """The interpreter program for one reduce kind ("count" | "row" |
    "total").  Callers bucket EVERY input axis to powers of two (coalescer
    _launch_interp / warmup.prewarm_fuse) — the compiled-entry count
    per wrapper is the product of the bucket grids, not the number of
    distinct expression mixes ever fused."""
    return _compiled_interp(reduce)


# Largest bucketed (leaf, op, out) axes ever dispatched — with the
# leading slice axis in _BUCKET_HIGHWATER["interp"], these derive the
# interp family's hard cardinality bound.  Plain dict writes: racing
# writers both store valid maxima.
_INTERP_HIGHWATER: dict[str, int] = {}


def interp_exec(reduce: str, leaves, prog, out_idx):
    """Dispatch one fused interpreter launch, recording the bucket
    high-waters the ``exec.programCache.bound[cache:interp]`` gauge
    derives from.  ``prog``/``out_idx`` may be host numpy — they are
    kilobytes of metadata riding the launch."""
    for k, v in (
        ("leaves", int(leaves.shape[1])),
        ("ops", int(prog.shape[0])),
        ("outs", int(out_idx.shape[0])),
    ):
        if v > _INTERP_HIGHWATER.get(k, 0):
            _INTERP_HIGHWATER[k] = v
    return _compiled_interp(reduce)(leaves, prog, out_idx)


class _Program:
    """Recording proxy around one jitted wrapper: records the bucketed
    leading batch axis at call time (feeding the hard-bound gauges) and
    passes ``lower`` through for AOT compile probes.  The underlying
    jit wrapper compiles once per distinct batch shape — with callers
    bucketing the slice axis to powers of two, a wrapper's compiled
    entry count is bounded by the bucket-class count, not by how many
    distinct slice sets queries touch.

    Compile-time accounting: jit compiles lazily at the first call per
    argument-shape tuple, so that FIRST call's wall time (trace + XLA
    compile + the dispatch itself) accrues to the family's cumulative
    ``exec.programCache.compileMs[cache:*]`` gauge — the online answer
    to "how much of this soak went to compilation" (a persistent-cache
    hit shows up as a near-zero first call)."""

    __slots__ = ("fn", "family", "_seen_shapes")

    def __init__(self, fn, family: str):
        self.fn = fn
        self.family = family
        self._seen_shapes: set = set()

    def __call__(self, batch, *args):
        _note_bucket(self.family, int(batch.shape[0]))
        shapes = (tuple(batch.shape),) + tuple(
            tuple(getattr(a, "shape", ())) for a in args
        )
        if shapes in self._seen_shapes:
            return self.fn(batch, *args)
        t0 = time.monotonic()
        out = self.fn(batch, *args)
        # Unlocked set add + dict accumulate: a racing duplicate first
        # call double-counts a few ms of telemetry, never corrupts.
        self._seen_shapes.add(shapes)
        _note_compile_ms(self.family, (time.monotonic() - t0) * 1e3)
        return out

    def lower(self, *args, **kwargs):
        return self.fn.lower(*args, **kwargs)


CacheInfo = namedtuple("CacheInfo", ["hits", "misses", "maxsize", "currsize"])


class _ProgramCache:
    """Bounded memo of jit wrappers keyed by compile statics, with
    ``cache_info()`` compatible with the functools.lru_cache interface
    it replaces — replaced so :func:`program_cache_stats` can walk the
    live wrappers and count their COMPILED entries (an lru_cache hides
    its values).  Eviction past ``maxsize`` drops the oldest wrapper
    (and with it, its compiled executables)."""

    def __init__(self, builder: Callable, family: str, maxsize: int = 512):
        self._builder = builder
        self._family = family
        self._maxsize = maxsize
        self._d: "OrderedDict[tuple, _Program]" = OrderedDict()
        self._mu = threading.Lock()
        self._hits = 0
        self._misses = 0

    def __call__(self, *key) -> _Program:
        with self._mu:
            prog = self._d.get(key)
            if prog is not None:
                self._hits += 1
                return prog
            self._misses += 1
        fn = self._builder(*key)
        prog = _Program(fn, self._family)
        with self._mu:
            cur = self._d.setdefault(key, prog)
            while len(self._d) > self._maxsize:
                self._d.popitem(last=False)
            return cur

    def cache_info(self) -> CacheInfo:
        with self._mu:
            return CacheInfo(self._hits, self._misses, self._maxsize, len(self._d))

    def cache_clear(self) -> None:
        with self._mu:
            progs = list(self._d.values())
            self._d.clear()
            self._hits = self._misses = 0
        for p in progs:
            try:
                p.fn.clear_cache()
            except Exception:  # noqa: BLE001 — jax version without it
                pass

    def programs(self) -> list[_Program]:
        with self._mu:
            return list(self._d.values())


def _build_total_count(expr: tuple, mesh):
    per_slice = expr_has_bsi(expr)

    def fn(batch):
        if per_slice:
            # Per-slice evaluation (vmapped): each partial covers one
            # slice-row result (<= 2^20 bits), int32-exact.
            partials = jax.vmap(
                lambda stack: jnp.sum(
                    jax.lax.population_count(
                        _eval_expr(expr, stack)
                    ).astype(jnp.int32)
                )
            )(batch)
        else:
            out = _eval_expr(expr, batch.swapaxes(0, 1))
            # Word axis first: each partial <= 2^20 bits, int32-exact.
            partials = jnp.sum(
                jax.lax.population_count(out).astype(jnp.int32), axis=-1
            )
        lo = jnp.sum(partials & 0xFFFF)
        hi = jnp.sum(partials >> 16)
        return jnp.stack([hi, lo])

    if mesh is None:
        return jax.jit(fn)
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.jit(fn, out_shardings=NamedSharding(mesh, P()))


def _build_batched(expr: tuple, reduce: str):
    return jax.jit(jax.vmap(_make_fn(expr, reduce)))


def _build_scatter():
    """Delta-scatter: apply n (slot, word, or-mask, andnot-mask) updates
    to a resident device plane as ONE fused gather/modify/scatter.  The
    update axis leads so the program-cache bucket gauges see the
    pow2-bucketed update count (callers pad to :func:`pilosa_tpu.ops.
    bitplane.pow2_bucket` by REPEATING the last real entry — duplicate
    indices then write identical values, which XLA scatter handles
    deterministically).  No buffer donation: a concurrent reader may
    still hold the old plane, which is exactly how the fragment's
    version fence gives readers old-or-new atomicity."""

    def fn(slots, words, or_m, andnot_m, plane):
        cur = plane[slots, words]
        return plane.at[slots, words].set((cur & ~andnot_m) | or_m)

    return jax.jit(fn)


_compiled_batched = _ProgramCache(_build_batched, "plan.batched")
_compiled_total_count = _ProgramCache(_build_total_count, "plan.totalCount")
_compiled_interp = _ProgramCache(_build_interp, "interp")
_compiled_scatter = _ProgramCache(_build_scatter, "plan.scatter", maxsize=1)
# Defined before _build_anchored below (builders bind lazily at call).
_compiled_anchored = _ProgramCache(
    lambda expr, fmts: _build_anchored(expr, fmts), "plan.anchored"
)


def scatter_apply(plane, slots, words, or_m, andnot_m):
    """Dispatch one fused delta-scatter launch (update axis bucketed by
    the caller); returns the NEW plane array, old left intact."""
    # The jit cache also keys on the plane's (pow2-classed) row count;
    # track its highwater so program_cache_bounds stays an invariant.
    _note_bucket("plan.scatter.rows", int(plane.shape[0]))
    return _compiled_scatter()(slots, words, or_m, andnot_m, plane)


# ---------------------------------------------------------------------------
# anchored position-domain count (compressed-plane fast path)
# ---------------------------------------------------------------------------

def _build_anchored(expr: tuple, fmts: tuple):
    """Position-domain Count: instead of streaming dense
    (leaves x 32768)-word rows, evaluate the fold expression POINTWISE
    over the anchor leaf's sentinel-padded position vector, reading
    each leaf through its container format directly (ops/bitplane
    membership_* — dense gather / sparse searchsorted / RLE run
    search).  Sound whenever the result is a subset of the anchor
    (executor._anchor_candidates), so the count is just the number of
    anchor positions whose membership mask survives.

    ``fmts`` is the per-leaf container-format tuple — a compile static
    (it selects which membership kernel each leaf traces), which is why
    it is part of the wrapper key.  Inputs are vmapped over a leading
    slice axis: anchor uint32[S, P], payload i uint32[S, Li] or
    uint32[S, Ri, 2]; all axes pow2-bucketed by the caller so the jit
    key stays pure geometry."""
    from pilosa_tpu.ops import bitplane as bp

    def one(anchor, *payloads):
        def leaf_mask(i):
            fmt = fmts[i]
            if fmt == bp.FMT_DENSE:
                return bp.membership_dense(payloads[i], anchor)
            if fmt == bp.FMT_SPARSE:
                return bp.membership_sparse(payloads[i], anchor)
            return bp.membership_rle(payloads[i], anchor)

        def rec(e):
            if e[0] == "leaf":
                return leaf_mask(e[1])
            kids = [rec(ch) for ch in e[1:]]
            if not kids:  # empty Union
                return jnp.zeros(anchor.shape, dtype=bool)
            acc = kids[0]
            for nxt in kids[1:]:
                if e[0] == "Intersect":
                    acc = acc & nxt
                elif e[0] == "Union":
                    acc = acc | nxt
                elif e[0] == "Difference":
                    acc = acc & ~nxt
                else:  # Xor
                    acc = acc ^ nxt
            return acc

        mask = rec(expr)
        valid = anchor != jnp.uint32(bp.FMT_SENTINEL)
        return jnp.sum((mask & valid).astype(jnp.int32))

    return jax.jit(jax.vmap(one))


def compiled_anchored_count(expr: tuple, fmts: tuple) -> "_Program":
    """One jitted wrapper per (tree shape, per-leaf container-format
    tuple); compiled entries inside a wrapper key on (slice bucket,
    anchor-position bucket, per-leaf payload buckets)."""
    return _compiled_anchored(expr, fmts)


# Largest payload-entry bucket ever dispatched through an anchored
# launch (anchor vector or any leaf payload) — with the slice axis in
# _BUCKET_HIGHWATER["plan.anchored"], this derives the family's hard
# cardinality bound.  Plain dict writes: racing maxima are both valid.
_ANCHORED_HIGHWATER: dict[str, int] = {}


def anchored_count_exec(expr: tuple, fmts: tuple, anchor, payloads):
    """Dispatch one anchored count launch (slice axis leading,
    everything pow2-bucketed by the caller), recording the payload
    high-waters program_cache_bounds derives from.  Returns int32[S]
    per-slice counts."""
    hw = max(
        max((int(p.shape[1]) for p in payloads), default=1),
        int(anchor.shape[1]),
    )
    if hw > _ANCHORED_HIGHWATER.get("payload", 0):
        _ANCHORED_HIGHWATER["payload"] = hw
    if len(fmts) > _ANCHORED_HIGHWATER.get("leaves", 0):
        _ANCHORED_HIGHWATER["leaves"] = len(fmts)
    return _compiled_anchored(expr, fmts)(anchor, *payloads)


# ---------------------------------------------------------------------------
# compiled-program cardinality (ROADMAP 2a: canonical keys + hard bounds)
# ---------------------------------------------------------------------------

# family -> largest bucketed leading batch axis dispatched so far.
# Plain dict writes: racing writers both store valid maxima.
_BUCKET_HIGHWATER: dict[str, int] = {}

# family -> cumulative first-call (compile-bearing) wall ms.  Plain
# dict accumulation: a lost race under-counts telemetry, nothing more.
_COMPILE_MS: dict[str, float] = {}


def _note_bucket(family: str, bucket: int) -> None:
    if bucket > _BUCKET_HIGHWATER.get(family, 0):
        _BUCKET_HIGHWATER[family] = bucket


def _note_compile_ms(family: str, ms: float) -> None:
    _COMPILE_MS[family] = _COMPILE_MS.get(family, 0.0) + ms


def program_cache_compile_ms() -> dict[str, float]:
    """Cumulative compile-bearing first-call wall ms per jit family —
    the ``exec.programCache.compileMs[cache:*]`` gauges on /metrics and
    the ``compile_ms`` column of bench artifacts' perf block."""
    return {k: round(v, 3) for k, v in _COMPILE_MS.items()}


def _jit_cache_size(fn) -> int:
    """Entry count of one jax.jit wrapper's compile cache (0 when the
    running jax version doesn't expose it)."""
    try:
        return int(fn._cache_size())
    except Exception:  # noqa: BLE001 — observability must never raise
        return 0


def program_cache_stats() -> dict[str, int]:
    """COMPILED-program counts per jit family — the
    ``exec.programCache.entries`` gauge on /metrics.  ``plan.*`` sums
    the compiled entries inside every live (tree shape, reduce)/(tree
    shape, mesh) wrapper (one entry per batch-shape bucket);
    ``bitplane.*`` counts compiled entries inside the module-level jit
    wrappers (the TopN scorer keys on per-fragment plane shapes).
    Every counted key is canonicalized — slice axes, plane rows,
    candidate slots, and fragment-group sizes all bucket to powers of
    two — so each family is hard-bounded by its bucket grid
    (:func:`program_cache_bounds`), not by schema churn."""
    from pilosa_tpu.ops import bitplane as bp

    out = {
        "plan.batched": sum(
            _jit_cache_size(p.fn) for p in _compiled_batched.programs()
        ),
        "plan.totalCount": sum(
            _jit_cache_size(p.fn) for p in _compiled_total_count.programs()
        ),
        "interp": sum(
            _jit_cache_size(p.fn) for p in _compiled_interp.programs()
        ),
        "plan.scatter": sum(
            _jit_cache_size(p.fn) for p in _compiled_scatter.programs()
        ),
        "plan.anchored": sum(
            _jit_cache_size(p.fn) for p in _compiled_anchored.programs()
        ),
        "bitplane.expand": (
            _jit_cache_size(bp._expand_sparse_xla)
            + _jit_cache_size(bp._expand_rle_xla)
        ),
        "bitplane.scorePlanes": (
            _jit_cache_size(bp._score_planes_self_src)
            + _jit_cache_size(bp._score_planes_host_src)
        ),
        "bitplane.fusedCount": _jit_cache_size(bp._fused_count_xla),
        "bitplane.topCounts": _jit_cache_size(bp._top_counts_xla),
    }
    out["total"] = sum(out.values())
    return out


def _scatter_floor() -> int:
    # Lazy: ingest.scatter imports this module inside apply().
    from pilosa_tpu.ingest import scatter as ingest_scatter

    return ingest_scatter.UPDATE_BUCKET_FLOOR


def program_cache_bounds() -> dict[str, int]:
    """Hard per-family cardinality bounds implied by the pow2 bucket
    grids at the LARGEST shapes observed so far (``exec.programCache.
    bound`` on /metrics).  ``entries <= bound`` is an invariant: a
    family exceeding its bound means some caller stopped canonicalizing
    its compile key — exactly what the churny-schema regression test
    asserts.  Families whose keys carry arbitrary caller shapes
    (``bitplane.fusedCount``) have no derivable bound and are omitted."""
    from pilosa_tpu.ops import bitplane as bp

    hw = bp.shape_highwater()
    rb = bp.ROW_BLOCK

    def slice_classes(family: str) -> int:
        return bp.bucket_classes(max(_BUCKET_HIGHWATER.get(family, 1), 1))

    return {
        # distinct wrappers x slice-bucket classes per wrapper
        "plan.batched": (
            _compiled_batched.cache_info().currsize
            * slice_classes("plan.batched")
        ),
        "plan.totalCount": (
            _compiled_total_count.cache_info().currsize
            * slice_classes("plan.totalCount")
        ),
        # reduce-kind wrappers x slice x leaf x op-table x out classes —
        # pure geometry: the bound does NOT grow with how many distinct
        # expression mixes ever fused, which is the whole point.
        "interp": (
            _compiled_interp.cache_info().currsize
            * slice_classes("interp")
            * bp.bucket_classes(max(_INTERP_HIGHWATER.get("leaves", 1), 1))
            * bp.bucket_classes(
                max(_INTERP_HIGHWATER.get("ops", FUSE_OPS_FLOOR), FUSE_OPS_FLOOR),
                FUSE_OPS_FLOOR,
            )
            * bp.bucket_classes(max(_INTERP_HIGHWATER.get("outs", 1), 1))
        ),
        # one wrapper x update-count bucket classes (floor
        # ingest.scatter.UPDATE_BUCKET_FLOOR) x plane-row shape classes
        # (planes pad rows to pow2, floor ROW_BLOCK; the word axis is
        # uniform, so it contributes no classes)
        "plan.scatter": (
            _compiled_scatter.cache_info().currsize
            * bp.bucket_classes(
                max(_BUCKET_HIGHWATER.get("plan.scatter", _scatter_floor()),
                    _scatter_floor()),
                _scatter_floor(),
            )
            * bp.bucket_classes(
                max(_BUCKET_HIGHWATER.get("plan.scatter.rows", rb), rb), rb
            )
        ),
        # (self-src + host-src) x fragment-group classes x plane-row
        # classes x candidate-slot classes
        "bitplane.scorePlanes": (
            2
            * bp.bucket_classes(max(hw.get("score_frags", 1), 1))
            * bp.bucket_classes(max(hw.get("score_rows", rb), rb), rb)
            * bp.bucket_classes(max(hw.get("score_slots", rb), rb), rb)
        ),
        "bitplane.topCounts": bp.bucket_classes(
            max(hw.get("top_rows", rb), rb), rb
        ),
        # (tree shape x container-format tuple) wrappers x slice-bucket
        # classes x payload-length bucket classes raised to the leaf
        # count — the container-length bucketing rule: every anchor /
        # payload axis pads to payload_bucket (floor
        # PAYLOAD_BUCKET_FLOOR), so per-leaf length variation compiles
        # at most one entry per bucket class, and format variation
        # lands in DISTINCT wrappers (counted by currsize), never in
        # unbounded jit keys.
        "plan.anchored": (
            _compiled_anchored.cache_info().currsize
            * slice_classes("plan.anchored")
            * bp.bucket_classes(
                max(
                    _ANCHORED_HIGHWATER.get(
                        "payload", bp.PAYLOAD_BUCKET_FLOOR
                    ),
                    bp.PAYLOAD_BUCKET_FLOOR,
                ),
                bp.PAYLOAD_BUCKET_FLOOR,
            )
            # +1: the anchor-position axis keys alongside the per-leaf
            # payload axes.
            ** (max(_ANCHORED_HIGHWATER.get("leaves", 1), 1) + 1)
        ),
        # (sparse + rle) expansion wrappers x payload bucket classes
        "bitplane.expand": 2 * bp.bucket_classes(
            max(
                hw.get("expand_payload", bp.PAYLOAD_BUCKET_FLOOR),
                bp.PAYLOAD_BUCKET_FLOOR,
            ),
            bp.PAYLOAD_BUCKET_FLOOR,
        ),
    }


def program_cache_entries() -> int:
    """Total compiled-program cache entries (the headline gauge)."""
    return program_cache_stats()["total"]


def clear_program_caches() -> None:
    """Drop every compiled program and the bucket high-water marks —
    test isolation for the cardinality regression suite (a process
    that already ran queries would otherwise leak entries into another
    test's gauge assertions)."""
    from pilosa_tpu.ops import bitplane as bp

    _compiled_batched.cache_clear()
    _compiled_total_count.cache_clear()
    _compiled_interp.cache_clear()
    _compiled_scatter.cache_clear()
    _compiled_anchored.cache_clear()
    _BUCKET_HIGHWATER.clear()
    _INTERP_HIGHWATER.clear()
    _ANCHORED_HIGHWATER.clear()
    _COMPILE_MS.clear()
    bp._SHAPE_HIGHWATER.clear()
    for fn in (
        bp._score_planes_self_src,
        bp._score_planes_host_src,
        bp._fused_count_xla,
        bp._top_counts_xla,
        bp._expand_sparse_xla,
        bp._expand_rle_xla,
    ):
        try:
            fn.clear_cache()
        except Exception:  # noqa: BLE001 — jax version without it
            pass
