"""Cross-query coalescing: one device launch for many concurrent queries.

BENCH_r05: the fused kernel answers Intersect+Count in 0.64 ms, yet 128
client threads only reach 0.88 ms/query end to end — every query
dispatches its OWN fused-XLA launch, so per-launch dispatch overhead,
GIL contention, and host assembly dominate, not compute.  The idiom that
closes this gap in production inference stacks is continuous
micro-batching, and the compile model here is already shaped for it:
``plan.compiled_batched`` keys programs by (tree shape, reduce kind) and
vmaps over a leading batch axis, so concurrent queries that share that
compile key can ride ONE launch by concatenating along the axis the
program already batches over.

Scheduling is CONTINUOUS, not windowed: a lone query on an idle device
dispatches immediately (``max_wait_us`` is only an optional accumulation
backstop, default 0); while a launch is in flight on the dispatcher
thread, new arrivals accumulate in per-compile-key queues and the next
drain takes them all.  Under serial load every query gets its own launch
at native latency; under concurrent load occupancy rises automatically
to whatever the arrival rate sustains.

Batch construction, per drained key:

* **Identity dedup.**  Waiters whose leaf batches are the SAME assembled
  array (the batch-cache hot path: a query storm over one cached entry)
  share one segment — and when the drain is one segment, the launch runs
  directly on that array with zero extra device work.  N queries, one
  launch, no copies.
* **Concatenation.**  Distinct single-device batches with the same
  compile key (expr shape, reduce kind, leaf count, words, device)
  concatenate along the leading axis, padded with cached all-zero rows
  to a power-of-two bucket so the jit cache stays bounded (one program
  per (tree shape, reduce, bucket)).  Pad rows are never scattered back
  to any waiter, so they need no masking out of per-slice reduces; the
  coalescer always launches the per-slice ``compiled_batched`` program
  (its "count" partials are int32-exact — one slice-row is <= 2^20
  bits — and each waiter host-sums only its own positions in unbounded
  Python ints, byte-identical to the limb total-count path).
* **Sharded batches dedup only.**  Mesh-sharded entries (multi-device
  hosts) still amortize duplicate waiters over one launch, but distinct
  sharded arrays are never concatenated — cross-sharding concatenation
  would move shards between devices mid-query.
* **Program-key fusion.**  Concatenation only merges queries sharing a
  compile key, so a realistic mix of DISTINCT Count/Range/Bitmap trees
  never batched and each re-streamed its planes.  With ``fuse`` on, a
  drain additionally pulls every other queue whose entries share the
  PROGRAM key (reduce kind, word geometry, device), lowers the distinct
  trees into one opcode/operand table (plan.lower_expr — expressions
  travel as DATA, like BSI predicates), and evaluates all of them in
  ONE interpreter pass (plan.interp_exec) over the union leaf set:
  K distinct queries, one launch, one pass over the resident planes.
  Identical queries share a lowered program, the emitter's value
  numbering dedups shared subtrees, and a tree that cannot lower (BSI
  aggregates, op-budget overflow) falls back to its own concat launch.
  Fused "count" results are the same per-slice int32 partials as the
  concat path — byte-identical totals.
* **Shared fetches.**  ``submit_fetch`` batches concurrent blocking
  device->host fetches (the folded TopN scorer's dominant residual)
  into one ``jax.device_get`` per drain, so DISTINCT concurrent TopN
  queries share a round trip the way PR-10's single-flight shared it
  for identical ones.

Every fragment-plane-bearing pool key in a drained batch is pinned via
the PR-3 residency pool for the launch's dispatch+fetch, so LRU eviction
can never drop a mirror out from under a coalesced program.

Observability: ``exec.coalesce.launches`` / ``coalescedQueries`` /
``padWaste`` counters and an ``exec.coalesce.batchOccupancy`` histogram;
the executor's per-query ``coalesce`` trace span carries the launch's
occupancy and row stats (and through it the slow-query log's batch
stats).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass

import numpy as np

from pilosa_tpu import device as device_mod
from pilosa_tpu.obs import perf as perf_mod
from pilosa_tpu.obs.stats import NopStatsClient

DEFAULT_MAX_BATCH = 64
DEFAULT_MAX_WAIT_US = 0
# Most DISTINCT expression programs one fused interpreter launch may
# carry ([exec] fuse-max-programs); < 2 disables fusion entirely.
DEFAULT_FUSE_MAX_PROGRAMS = 16
# Leaf-row budget for one fused launch's combined array: segment sets
# past it split into further launches (the leaf-axis analogue of
# MAX_CONCAT_ROWS — the concat materializes a transient copy, so this
# bounds device memory, not correctness).  64 leaves x 128 KiB = 8 MiB
# per batch row.
MAX_FUSE_LEAVES = 64
# Reduce kinds the interpreter can evaluate; "agg" trees reduce inside
# the expression (BSI aggregates) and stay on the per-compile-key path.
# "total" is the ICI-reduced count: per-register limb pairs summed
# across the slice axis ON DEVICE (psum over the mesh for sharded
# batches), so a fused launch of K distinct Count queries returns 8·K
# bytes instead of K per-slice partial vectors.
_FUSABLE_REDUCES = frozenset({"count", "row", "total"})
# Sentinel queue key for shared device->host fetches (submit_fetch):
# concurrent TopN score fetches drain in ONE jax.device_get round trip.
_FETCH_KEY = ("__fetch__",)
# Row budget for one concatenated launch: segments beyond it split into
# further launches.  Entry batches are already pow2-padded per query, so
# this bounds transient device memory (concatenation materializes a
# copy), not correctness.
MAX_CONCAT_ROWS = 4096
# Backstop bound on a waiter's Future wait: a wedged device call must
# surface as a failed query, not a hung request thread.  Waiters with a
# query deadline clamp this to their REMAINING budget and detach on
# expiry without cancelling the shared launch (executor._coalesce_eval)
# — an expired waiter never poisons the batch for the others.
RESULT_TIMEOUT_S = 600.0


class CoalesceClosed(RuntimeError):
    """Raised by submit() after close(); callers fall back to a direct
    (uncoalesced) launch."""


def consume_abandoned(stats):
    """Done-callback for a coalesce future whose waiter detached on
    deadline expiry: retrieves the eventual batch-level launch error so
    it is COUNTED (``exec.coalesce.abandonedErrors``) instead of
    surfacing as per-future "exception was never retrieved" GC log spam
    — with every waiter detached, nothing else would ever observe it."""

    def _cb(fut):
        try:
            exc = fut.exception()
        except Exception:  # noqa: BLE001 — cancelled futures
            return
        if exc is not None and stats is not None:
            stats.count("exec.coalesce.abandonedErrors")

    return _cb


@dataclass
class _Item:
    batch: object
    future: Future
    pin_keys: tuple
    # Leaf identity keys (executor._cached_batch leaf_keys): one per
    # batch column, equal keys <=> byte-identical columns.  The fused
    # launch collapses shared columns into ONE union register, so the
    # pass streams each distinct plane row once however many queries
    # reference it.  None = no identities known (columns stay unique).
    leaf_keys: "tuple | None" = None
    # Submitting query's trace id, captured at submit time: the
    # dispatcher thread has no trace contextvar, so the launch
    # telemetry's slowest-launch attribution rides the item.
    trace_id: str = ""


def _placement(batch) -> tuple:
    """Hashable placement token for the compile key: single-device
    batches group (and concatenate) per device; sharded batches group by
    their full sharding and are marked concat-ineligible."""
    try:
        devs = list(batch.devices())
    except Exception:  # noqa: BLE001 — non-jax stand-ins in unit tests
        devs = []
    if len(devs) == 1:
        return (str(devs[0]), False)
    try:
        return (repr(batch.sharding), True)
    except Exception:  # noqa: BLE001
        return (tuple(sorted(str(d) for d in devs)), True)


class CoalesceScheduler:
    """Per-compile-key batch queues + one dispatcher thread.

    ``submit(expr, reduce, batch, pin_keys)`` returns a Future resolving
    to ``(results, info)`` where ``results`` is the host ndarray of this
    entry's rows of the launch output (``[n_rows, words]`` for "row",
    ``[n_rows]`` int32 partials for "count") and ``info`` the launch's
    batch stats for trace annotation.
    """

    def __init__(
        self,
        max_batch: int = DEFAULT_MAX_BATCH,
        max_wait_us: int = DEFAULT_MAX_WAIT_US,
        stats=None,
        fuse: bool = True,
        fuse_max_programs: int = DEFAULT_FUSE_MAX_PROGRAMS,
        health=None,
    ):
        self.max_batch = max(1, int(max_batch))
        self.max_wait_us = max(0, int(max_wait_us))
        # Device-health manager (device/health.py), wired by the Server
        # alongside the executor's: collective-bearing launches run
        # under its hung-collective watchdog and the collective path's
        # quarantine breaker; errors cross the waiter futures, where
        # each waiter's guard fails over to the host evaluator
        # independently.  None = plain serialized collectives.
        self.health = health
        # Multi-query fusion ([exec] fuse): a drain additionally pulls
        # every other queue whose entries share this key's PROGRAM key
        # (reduce kind, word geometry, device), lowers the distinct
        # trees to one opcode table, and evaluates them all in ONE
        # interpreter pass over the union leaf set (plan.interp_exec).
        self.fuse = bool(fuse) and int(fuse_max_programs) >= 2
        self.fuse_max_programs = max(1, int(fuse_max_programs))
        self.stats = stats or NopStatsClient()
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        # key -> deque[_Item]; OrderedDict gives FIFO across keys (the
        # key whose first item arrived earliest drains first).
        self._queues: "OrderedDict[tuple, deque]" = OrderedDict()
        self._closed = False
        # device -> {(pad, tail...): cached all-zero pad rows}
        self._zeros: dict = {}
        # counters (mirrored to self.stats; kept here for snapshot()/bench)
        self._launches = 0
        self._queries = 0
        self._pad_rows = 0
        self._launched_rows = 0
        self._max_occupancy = 0
        # fusion counters (exec.interp.*)
        self._fused_launches = 0
        self._fused_queries = 0
        self._fused_programs = 0
        self._fused_ops = 0
        self._fuse_dedup_hits = 0
        self._fuse_shared_leaves = 0
        self._fuse_fallbacks = 0
        self._fetch_launches = 0
        self._fetch_arrays = 0
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="exec-coalesce"
        )
        self._thread.start()

    # ------------------------------------------------------------------
    # producer side
    # ------------------------------------------------------------------

    def submit(
        self, expr: tuple, reduce: str, batch, pin_keys=(), leaf_keys=None
    ) -> Future:
        """Enqueue one assembled leaf batch (``uint32[n, n_leaves,
        words]``) for a coalesced ``compiled_batched(expr, reduce)``
        launch.  ``leaf_keys`` (optional) are per-column identity
        tokens enabling union-leaf sharing in fused launches."""
        key = (expr, reduce, tuple(batch.shape[1:]), _placement(batch))
        fut: Future = Future()
        if leaf_keys is not None and len(leaf_keys) != int(batch.shape[1]):
            leaf_keys = None
        item = _Item(
            batch=batch,
            future=fut,
            pin_keys=tuple(k for k in pin_keys if k is not None),
            leaf_keys=leaf_keys,
            trace_id=perf_mod.current_trace_id(),
        )
        with self._cv:
            if self._closed:
                raise CoalesceClosed("coalescer closed")
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = deque()
            q.append(item)
            self._cv.notify()
        return fut

    def submit_fetch(self, arrays) -> Future:
        """Enqueue a device->host fetch of ``arrays`` (a list of device
        arrays); resolves to ``(host_arrays, info)``.  All fetch items
        pending at a drain share ONE ``jax.device_get`` round trip —
        the TopN(src) fetch residual folds across DISTINCT concurrent
        queries this way (PR-10's single-flight only covered identical
        ones)."""
        fut: Future = Future()
        item = _Item(
            batch=list(arrays),
            future=fut,
            pin_keys=(),
            trace_id=perf_mod.current_trace_id(),
        )
        with self._cv:
            if self._closed:
                raise CoalesceClosed("coalescer closed")
            q = self._queues.get(_FETCH_KEY)
            if q is None:
                q = self._queues[_FETCH_KEY] = deque()
            q.append(item)
            self._cv.notify()
        return fut

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            pending = [it for q in self._queues.values() for it in q]
            self._queues.clear()
            self._cv.notify_all()
        for it in pending:
            if not it.future.done():
                it.future.set_exception(CoalesceClosed("coalescer closed"))
        self._thread.join(timeout=10)

    def snapshot(self) -> dict:
        """Counters for bench artifacts and tests."""
        with self._mu:
            launches = self._launches
            queries = self._queries
            fused_launches = self._fused_launches
            return {
                "launches": launches,
                "queries": queries,
                "pad_rows": self._pad_rows,
                "launched_rows": self._launched_rows,
                "max_occupancy": self._max_occupancy,
                "mean_occupancy": (
                    round(queries / launches, 3) if launches else None
                ),
                "fused_launches": fused_launches,
                "fused_queries": self._fused_queries,
                "fused_programs": self._fused_programs,
                "fused_ops": self._fused_ops,
                "fuse_dedup_hits": self._fuse_dedup_hits,
                "fuse_shared_leaves": self._fuse_shared_leaves,
                "fuse_fallbacks": self._fuse_fallbacks,
                "mean_fused_per_launch": (
                    round(self._fused_queries / fused_launches, 3)
                    if fused_launches
                    else None
                ),
                "fetch_launches": self._fetch_launches,
                "fetch_arrays": self._fetch_arrays,
            }

    # ------------------------------------------------------------------
    # dispatcher
    # ------------------------------------------------------------------

    def _drain_locked(self, key, items: list) -> None:
        q = self._queues.get(key)
        while q and len(items) < self.max_batch:
            items.append(q.popleft())
        if q is None:
            return
        if not q:
            del self._queues[key]
        else:
            # max_batch left items behind: rotate the key behind the
            # others so one hot query shape cannot starve the rest.
            self._queues.move_to_end(key)

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._closed and not self._queues:
                    self._cv.wait()
                if self._closed:
                    return
                key = next(iter(self._queues))
                items: list = []
                self._drain_locked(key, items)
            if self.max_wait_us and len(items) < self.max_batch:
                # Optional accumulation backstop: linger at most
                # max_wait_us for same-key company before launching.
                # 0 (the default) launches immediately — the in-flight
                # launch below is the only accumulation window.
                deadline = time.monotonic() + self.max_wait_us / 1e6
                with self._cv:
                    while len(items) < self.max_batch and not self._closed:
                        if key in self._queues:
                            self._drain_locked(key, items)
                            continue
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(timeout=remaining)
            # Program-key tier: a fusable drain additionally pulls every
            # OTHER queue whose entries share this key's program key
            # (reduce, word geometry, device) — the mixed batch of
            # distinct trees the interpreter evaluates in one pass.
            extra: list = []
            fk = self._fuse_key(key)
            if fk is not None:
                with self._cv:
                    for k2 in list(self._queues):
                        if 1 + len(extra) >= self.fuse_max_programs:
                            break
                        if len(items) + sum(
                            len(its) for _, its in extra
                        ) >= self.max_batch:
                            break
                        if k2 == key or self._fuse_key(k2) != fk:
                            continue
                        its: list = []
                        self._drain_locked(k2, its)
                        if its:
                            extra.append((k2, its))
            try:
                # The launch (dispatch + fetch) runs HERE, on the
                # dispatcher thread — while it is in flight, new
                # arrivals queue up and the next iteration drains them
                # in one batch.  That in-flight window IS the
                # continuous-batching accumulation.
                self._launch(key, items, extra)
            except BaseException as e:  # noqa: BLE001 — crosses futures
                exc = e if isinstance(e, Exception) else RuntimeError(repr(e))
                for it in items + [it for _, its in extra for it in its]:
                    if not it.future.done():
                        it.future.set_exception(exc)

    def _run_collective(self, fn):
        """One collective-bearing dispatch+fetch: watchdogged through
        the health manager when wired (errors and trips cross the
        waiter futures), plain serialized otherwise.  The chaos
        checkpoint (``device.launch`` path=``collective``) sits inside
        the watched body so an injected kind=hang wedges exactly where
        a real all-reduce rendezvous would."""
        from pilosa_tpu.testing import faults

        def body():
            faults.check("device.launch", path="collective")
            return fn()

        if self.health is not None:
            return self.health.run_collective(body)
        from pilosa_tpu.exec import plan

        with plan.collective_launch():
            return body()

    def _fuse_key(self, key) -> tuple | None:
        """The program-key tier's grouping token: queues whose entries
        share it may lower into ONE interpreter launch.  None = not
        fusable (fusion off, fetch items, "agg" reduce).  Sharded
        batches ARE fusable with each other when their sharding token
        matches: the fused concat runs along the LEAF axis, which
        leaves the slice-axis sharding untouched — unlike the concat
        path's slice-axis merge, no shard ever moves devices."""
        if not self.fuse or key == _FETCH_KEY:
            return None
        _expr, reduce, tail, placement = key
        if reduce not in _FUSABLE_REDUCES:
            return None
        # words + full placement token (device, or the sharding repr):
        # the geometry every fused segment must share (the leading
        # slice axis groups later, per launch).
        return (reduce, tail[-1], placement)

    def _launch(self, key, items: list, extra=()) -> None:
        if key == _FETCH_KEY:
            self._launch_fetch(items)
            return
        expr, reduce, _tail, placement = key
        if extra:
            self._launch_fused(reduce, [(key, items)] + list(extra))
            return
        if (
            reduce == "total"
            and self.fuse
            and len({id(it.batch) for it in items}) > 1
        ):
            # Same-compile-key Count entries over DISTINCT batches
            # cannot concatenate under "total" (each launch reduces to
            # one scalar limb pair) — the interpreter evaluates them as
            # distinct programs in ONE pass instead, preserving the
            # concat path's one-launch sharing.
            self._launch_fused(reduce, [(key, items)])
            return
        self._fallback_launch(key, items)

    def _fallback_launch(self, key, items: list) -> None:
        """The per-compile-key launch semantics fusion falls back to:
        concat for single-device batches, identity-dedup-only for
        sharded ones (cross-array slice-axis concatenation would move
        shards between devices mid-query).  "total" reduces to one
        scalar limb pair per batch, so it can never concatenate —
        identity dedup only, through the limb total-count program."""
        expr, reduce, _tail, placement = key
        if reduce == "total":
            groups: "OrderedDict[int, list]" = OrderedDict()
            for it in items:
                groups.setdefault(id(it.batch), []).append(it)
            for grp in groups.values():
                self._launch_total(expr, grp)
            return
        if not placement[1]:
            self._launch_concat(expr, reduce, items)
            return
        groups = OrderedDict()
        for it in items:
            groups.setdefault(id(it.batch), []).append(it)
        for grp in groups.values():
            self._launch_concat(expr, reduce, grp)

    def _launch_total(self, expr, items: list) -> None:
        """One identity-deduped batch through the limb total-count
        program (plan.compiled_total_count): the cross-slice reduce
        runs on device — as an all-reduce over ICI when the batch is
        mesh-sharded — and every waiter receives the SAME int32[2]
        (hi, lo) limb pair, recombined executor-side."""
        import jax

        from pilosa_tpu.exec import plan

        batch = items[0].batch
        mesh = None
        try:
            from jax.sharding import NamedSharding

            sh = batch.sharding
            if isinstance(sh, NamedSharding) and len(batch.devices()) > 1:
                mesh = sh.mesh
        except Exception:  # noqa: BLE001 — non-jax stand-ins, old arrays
            mesh = None
        pins = {k for it in items for k in it.pin_keys}
        t0 = time.monotonic()
        t_disp = [t0]  # set when the async dispatch returns (pre-fetch)
        with device_mod.pool().pinned(*pins):
            if mesh is not None:
                # The program psums over the mesh: serialize with every
                # other collective launch in the process (see
                # plan.collective_launch — racing dispatches can
                # deadlock the all-reduce rendezvous).  With a health
                # manager wired, the serialized dispatch+fetch also
                # rides the launch watchdog: a hung rendezvous trips,
                # fails the waiters (who fall over to the host path
                # per-waiter), and quarantines the collective path.
                def _body():
                    out = plan.compiled_total_count(expr, mesh)(batch)
                    t_disp[0] = time.monotonic()
                    return np.asarray(jax.device_get(out))

                res = self._run_collective(_body)
            else:
                out = plan.compiled_total_count(expr, mesh)(batch)
                t_disp[0] = time.monotonic()
                res = np.asarray(jax.device_get(out))
        t1 = time.monotonic()
        launch_ms = (t1 - t0) * 1e3
        if perf_mod.enabled():
            perf_mod.record_launch(
                "collective" if mesh is not None else "total",
                reduce="total",
                queries=len(items),
                rows=int(batch.shape[0]),
                n_bytes=perf_mod.plane_bytes(
                    int(batch.shape[0]), int(np.prod(batch.shape[1:]))
                ),
                dispatch_ms=(t_disp[0] - t0) * 1e3,
                total_ms=launch_ms,
                trace_id=items[0].trace_id,
            )
        with self._mu:
            self._launches += 1
            self._queries += len(items)
            self._launched_rows += int(batch.shape[0])
            if len(items) > self._max_occupancy:
                self._max_occupancy = len(items)
            launch_n = self._launches
        self.stats.count("exec.coalesce.launches")
        self.stats.count("exec.coalesce.coalescedQueries", len(items))
        self.stats.histogram("exec.coalesce.batchOccupancy", float(len(items)))
        info = {
            "launch": launch_n,
            "total": True,
            "batch_queries": len(items),
            "batch_segments": 1,
            "batch_rows": int(batch.shape[0]),
            "pad_rows": 0,
            "launch_ms": round(launch_ms, 3),
        }
        for it in items:
            it.future.set_result((res, info))

    def _launch_concat(self, expr, reduce, items: list) -> None:
        # Identity dedup: one segment per DISTINCT batch array.
        segs: list = []
        seg_of: dict[int, int] = {}
        seg_items: list[list] = []
        for it in items:
            i = seg_of.get(id(it.batch))
            if i is None:
                i = len(segs)
                seg_of[id(it.batch)] = i
                segs.append(it.batch)
                seg_items.append([])
            seg_items[i].append(it)
        # Greedy row-budget chunks over the distinct segments.
        lo = 0
        while lo < len(segs):
            hi = lo + 1
            rows = int(segs[lo].shape[0])
            while (
                hi < len(segs)
                and rows + int(segs[hi].shape[0]) <= MAX_CONCAT_ROWS
            ):
                rows += int(segs[hi].shape[0])
                hi += 1
            self._launch_one(
                expr,
                reduce,
                segs[lo:hi],
                [it for sub in seg_items[lo:hi] for it in sub],
                seg_items[lo:hi],
            )
            lo = hi

    def _launch_one(self, expr, reduce, segs, items, seg_items) -> None:
        import jax
        import jax.numpy as jnp

        from pilosa_tpu.exec import plan

        n_rows = [int(b.shape[0]) for b in segs]
        total = sum(n_rows)
        pad = 0
        if len(segs) == 1:
            dev_in = segs[0]
        else:
            # The canonical slice-axis bucket (plan.slice_bucket): the
            # concatenated launch lands on the same compiled program a
            # direct query over that bucket would.
            bucket = plan.slice_bucket(total)
            pad = bucket - total
            parts = list(segs)
            if pad:
                parts.append(self._pad_zeros(pad, segs[0]))
            dev_in = jnp.concatenate(parts, axis=0)
        pins = {k for it in items for k in it.pin_keys}
        t0 = time.monotonic()
        with device_mod.pool().pinned(*pins):
            out = plan.compiled_batched(expr, reduce)(dev_in)
            t_disp = time.monotonic()
            res = np.asarray(jax.device_get(out))
        t1 = time.monotonic()
        launch_ms = (t1 - t0) * 1e3
        # Logical bytes are the PRE-pad rows: pad rows are bucketing
        # overhead, not useful plane traffic.
        if perf_mod.enabled():
            perf_mod.record_launch(
                "coalesce",
                reduce=reduce,
                queries=len(items),
                rows=total,
                n_bytes=perf_mod.plane_bytes(
                    total, int(np.prod(segs[0].shape[1:]))
                ),
                dispatch_ms=(t_disp - t0) * 1e3,
                total_ms=launch_ms,
                trace_id=items[0].trace_id,
            )
        with self._mu:
            self._launches += 1
            self._queries += len(items)
            self._pad_rows += pad
            self._launched_rows += total + pad
            if len(items) > self._max_occupancy:
                self._max_occupancy = len(items)
            launch_n = self._launches
        self.stats.count("exec.coalesce.launches")
        self.stats.count("exec.coalesce.coalescedQueries", len(items))
        if pad:
            self.stats.count("exec.coalesce.padWaste", pad)
        self.stats.histogram("exec.coalesce.batchOccupancy", float(len(items)))
        info = {
            "launch": launch_n,
            "batch_queries": len(items),
            "batch_segments": len(segs),
            "batch_rows": total,
            "pad_rows": pad,
            "launch_ms": round(launch_ms, 3),
        }
        start = 0
        for rows, sub in zip(n_rows, seg_items):
            seg_res = res[start : start + rows]
            start += rows
            for it in sub:
                it.future.set_result((seg_res, info))

    # ------------------------------------------------------------------
    # multi-query fusion (plane-major interpreter launches)
    # ------------------------------------------------------------------

    def _launch_fused(self, reduce, buckets: list) -> None:
        """Launch a mixed drain of per-compile-key buckets
        (``[(key, items), ...]``, all sharing one program key) as
        interpreter passes.  Queries fused into one pass must share the
        leading slice-axis length (their result rows scatter back
        row-for-row), so items group by it; groups that end up with
        fewer than two distinct (tree, segment) programs — or whose
        trees refuse to lower — fall back to the ordinary
        per-compile-key concat launch, never fail."""
        by_n: "OrderedDict[int, list]" = OrderedDict()
        for key, its in buckets:
            for it in its:
                by_n.setdefault(int(it.batch.shape[0]), []).append((key, it))
        for n_rows, pairs in by_n.items():
            self._launch_interp(reduce, n_rows, pairs)

    def _fallback_by_key(self, reduce, fallback: "OrderedDict") -> None:
        for key, its in fallback.items():
            with self._mu:
                self._fuse_fallbacks += len(its)
            self.stats.count("exec.interp.fallbacks", len(its))
            self._fallback_launch(key, its)

    def _launch_interp(self, reduce, n_rows: int, pairs: list) -> None:
        import jax
        import jax.numpy as jnp

        from pilosa_tpu.exec import plan
        from pilosa_tpu.ops import bitplane as bp

        # Segments: the distinct entry batches (identity dedup — a
        # query storm repeating K distinct queries contributes K
        # segments however many waiters ride them).
        segs: list = []
        seg_keys: list = []
        seg_of: dict[int, int] = {}
        for _key, it in pairs:
            if id(it.batch) not in seg_of:
                seg_of[id(it.batch)] = len(segs)
                lk = it.leaf_keys
                if lk is None:
                    # No identities: every column is unique to this
                    # segment (no cross-segment sharing possible).
                    lk = tuple(
                        ("anon", id(it.batch), j)
                        for j in range(int(it.batch.shape[1]))
                    )
                seg_keys.append(lk)
                segs.append(it.batch)
        l_tot = sum(int(b.shape[1]) for b in segs)
        if l_tot > MAX_FUSE_LEAVES and len(segs) > 1:
            # Leaf budget exceeded: greedy segment chunks, each its own
            # fused launch (a lone oversized segment proceeds whole —
            # it would be just as big on the unfused path).
            chunk_of: dict[int, int] = {}
            chunk = rows = 0
            for si, b in enumerate(segs):
                ln = int(b.shape[1])
                if rows and rows + ln > MAX_FUSE_LEAVES:
                    chunk += 1
                    rows = 0
                chunk_of[si] = chunk
                rows += ln
            parts: dict[int, list] = {}
            for key, it in pairs:
                parts.setdefault(chunk_of[seg_of[id(it.batch)]], []).append(
                    (key, it)
                )
            for sub in parts.values():
                self._launch_interp(reduce, n_rows, sub)
            return

        # Union leaf layout: first occurrence of each identity key
        # claims a register; later references — within one query, or
        # across DISTINCT queries — collapse onto it, so the fused pass
        # streams each distinct plane row ONCE per dispatch (the
        # plane-major amortization this tier exists for).
        union: "OrderedDict[tuple, int]" = OrderedDict()
        src_of: list[tuple[int, int]] = []  # union register -> (seg, col)
        for si, lk in enumerate(seg_keys):
            for j, k in enumerate(lk):
                if k not in union:
                    union[k] = len(src_of)
                    src_of.append((si, j))
        l_union = len(src_of)
        l_bucket = bp.pow2_bucket(l_union, 1)
        leaf_maps = [[union[k] for k in lk] for lk in seg_keys]

        # Lower each DISTINCT (tree, leaf layout) once; identical
        # queries share the lowered program (the "identical leaf sets
        # evaluated once" dedup), and — with shared leaf columns
        # collapsed — the emitter's value numbering dedups shared
        # subtrees ACROSS queries too.  A tree that cannot lower (BSI
        # aggregate node, op budget) rolls the table back and routes
        # its items to the concat fallback by ORIGINAL compile key.
        em = plan.FuseEmitter(l_bucket, plan.FUSE_MAX_OPS)
        out_of: dict[tuple, int] = {}
        failed: set = set()
        fused: list = []  # (item, out_reg)
        fallback: "OrderedDict[tuple, list]" = OrderedDict()
        for key, it in pairs:
            expr = key[0]
            lmap = leaf_maps[seg_of[id(it.batch)]]
            pk = (expr, tuple(lmap))
            reg = out_of.get(pk)
            if reg is None and pk not in failed:
                cp = em.checkpoint()
                try:
                    reg = out_of[pk] = plan.lower_expr(expr, lmap, em)
                except plan.FuseUnsupported:
                    em.rollback(cp)
                    failed.add(pk)
            if reg is None:
                fallback.setdefault(key, []).append(it)
            else:
                fused.append((it, reg))

        # Fewer than two distinct programs fused = nothing to fuse;
        # the concat path handles identity dedup with zero copies.
        if fused and len(out_of) < 2:
            for it, _reg in fused:
                fallback.setdefault(
                    next(k for k, i2 in pairs if i2 is it), []
                ).append(it)
            fused = []

        if fused:
            # Combined leaf array: each segment contributes only the
            # union columns it FIRST provided (duplicates — within a
            # query or across queries — never re-copy, never
            # re-stream).  A single full-contribution pow2 segment is
            # used as-is: zero copies, the hot repeated-mix case.
            parts = []
            for si, seg in enumerate(segs):
                cols = [j for s2, j in src_of if s2 == si]
                if not cols:
                    continue
                if cols == list(range(int(seg.shape[1]))):
                    parts.append(seg)
                else:
                    parts.append(seg[:, jnp.asarray(cols, dtype=jnp.int32)])
            if l_bucket > l_union:
                parts.append(
                    self._leaf_pad_zeros(n_rows, l_bucket - l_union, segs[0])
                )
            # Leaf-axis concat: slice-axis sharding (if any) is
            # untouched — each shard concatenates locally.
            combined = (
                parts[0]
                if len(parts) == 1
                else jnp.concatenate(parts, axis=1)
            )
            n_ops = len(em.rows)
            p_bucket = bp.pow2_bucket(max(n_ops, 1), plan.FUSE_OPS_FLOOR)
            prog = np.zeros((p_bucket, 4), dtype=np.int32)
            if n_ops:
                prog[:n_ops] = np.asarray(em.rows, dtype=np.int32)
            out_regs = list(dict.fromkeys(reg for _it, reg in fused))
            pos_of_reg = {r: i for i, r in enumerate(out_regs)}
            k_bucket = bp.pow2_bucket(len(out_regs), 1)
            out_idx = np.asarray(
                out_regs + [out_regs[-1]] * (k_bucket - len(out_regs)),
                dtype=np.int32,
            )
            pins = {k for it, _ in fused for k in it.pin_keys}
            try:
                sharded = len(combined.devices()) > 1
            except Exception:  # noqa: BLE001 — unit-test stand-ins
                sharded = False
            t0 = time.monotonic()
            t_disp = [t0]
            with device_mod.pool().pinned(*pins):
                if reduce == "total" and sharded:
                    # The slice-axis limb sums psum over the mesh —
                    # serialize with other collective launches (and,
                    # with a health manager, run under the launch
                    # watchdog; see _launch_total).
                    def _body():
                        out = plan.interp_exec(
                            reduce, combined, prog, out_idx
                        )
                        t_disp[0] = time.monotonic()
                        return np.asarray(jax.device_get(out))

                    res = self._run_collective(_body)
                else:
                    out = plan.interp_exec(reduce, combined, prog, out_idx)
                    t_disp[0] = time.monotonic()
                    res = np.asarray(jax.device_get(out))
            t1 = time.monotonic()
            launch_ms = (t1 - t0) * 1e3
            # Logical bytes: the deduped union leaf set (streamed once
            # per pass), pad leaves excluded.
            if perf_mod.enabled():
                perf_mod.record_launch(
                    "collective" if (reduce == "total" and sharded) else "interp",
                    reduce=reduce,
                    queries=len(fused),
                    rows=n_rows * l_union,
                    n_bytes=perf_mod.plane_bytes(
                        n_rows * l_union, int(combined.shape[-1])
                    ),
                    dispatch_ms=(t_disp[0] - t0) * 1e3,
                    total_ms=launch_ms,
                    trace_id=fused[0][0].trace_id,
                )
            with self._mu:
                self._launches += 1
                self._queries += len(fused)
                self._launched_rows += n_rows
                if len(fused) > self._max_occupancy:
                    self._max_occupancy = len(fused)
                self._fused_launches += 1
                self._fused_queries += len(fused)
                self._fused_programs += len(out_of)
                self._fused_ops += n_ops
                self._fuse_dedup_hits += em.dedup_hits
                self._fuse_shared_leaves += l_tot - l_union
                launch_n = self._launches
            self.stats.count("exec.coalesce.launches")
            self.stats.count("exec.coalesce.coalescedQueries", len(fused))
            self.stats.histogram(
                "exec.coalesce.batchOccupancy", float(len(fused))
            )
            self.stats.count("exec.interp.launches")
            self.stats.count("exec.interp.fusedQueries", len(fused))
            self.stats.histogram("exec.interp.opsPerLaunch", float(n_ops))
            if l_tot > l_union:
                self.stats.count(
                    "exec.interp.sharedLeaves", l_tot - l_union
                )
            info = {
                "launch": launch_n,
                "fused": True,
                "batch_queries": len(fused),
                "programs": len(out_of),
                "ops": n_ops,
                "dedup_hits": em.dedup_hits,
                "batch_rows": n_rows,
                "leaf_rows": l_union,
                "shared_leaves": l_tot - l_union,
                "pad_leaves": l_bucket - l_union,
                "launch_ms": round(launch_ms, 3),
            }
            for it, reg in fused:
                it.future.set_result((res[:, pos_of_reg[reg]], info))

        self._fallback_by_key(reduce, fallback)

    def _leaf_pad_zeros(self, n_rows: int, pad: int, like):
        """Cached all-zero LEAF-axis pad block matching ``like``'s
        placement (single device, or the identical sharding for mesh
        batches) — bucketing the combined leaf axis of a fused launch
        (pow2 gaps, so the cache stays small like the row-pad one)."""
        import jax

        words = int(like.shape[-1])
        devs = list(like.devices())
        if len(devs) == 1:
            target = devs[0]
            token = str(target)
        else:
            target = like.sharding
            token = repr(target)
        zkey = ("leafpad", n_rows, pad, words, token)
        z = self._zeros.get(zkey)
        if z is None:
            z = jax.device_put(
                np.zeros((n_rows, pad, words), dtype=np.uint32), target
            )
            self._zeros[zkey] = z
        return z

    def _launch_fetch(self, items: list) -> None:
        """Drain pending fetch items with ONE blocking device->host
        round trip: dispatches stay with their submitters (they are
        already async); only the value fetch — the dominant TopN(src)
        residual — batches here."""
        import jax

        arrays: list = []
        spans: list[tuple[int, int]] = []
        for it in items:
            arrs = it.batch
            spans.append((len(arrays), len(arrs)))
            arrays.extend(arrs)
        t0 = time.monotonic()
        fetched = jax.device_get(arrays)
        fetch_ms = (time.monotonic() - t0) * 1e3
        if perf_mod.enabled():
            perf_mod.record_launch(
                "fetch",
                reduce="fetch",
                queries=len(items),
                n_bytes=sum(int(getattr(a, "nbytes", 0) or 0) for a in arrays),
                total_ms=fetch_ms,
                trace_id=items[0].trace_id,
            )
        with self._mu:
            self._fetch_launches += 1
            self._fetch_arrays += len(arrays)
            n = self._fetch_launches
        self.stats.count("exec.interp.fetchLaunches")
        self.stats.count("exec.interp.fetchedArrays", len(arrays))
        info = {
            "fetch_launch": n,
            "fetch_items": len(items),
            "fetch_arrays": len(arrays),
            "fetch_ms": round(fetch_ms, 3),
        }
        for it, (lo, cnt) in zip(items, spans):
            it.future.set_result((fetched[lo : lo + cnt], info))

    def _pad_zeros(self, pad: int, like):
        """Cached all-zero pad rows on ``like``'s device — the pad set
        is small (pow2 gaps under MAX_CONCAT_ROWS), so the cache stays
        bounded in practice."""
        import jax

        dev = list(like.devices())[0]
        zkey = (pad,) + tuple(int(d) for d in like.shape[1:]) + (str(dev),)
        z = self._zeros.get(zkey)
        if z is None:
            z = jax.device_put(
                np.zeros((pad,) + tuple(like.shape[1:]), dtype=np.uint32), dev
            )
            self._zeros[zkey] = z
        return z
