"""Cross-query coalescing: one device launch for many concurrent queries.

BENCH_r05: the fused kernel answers Intersect+Count in 0.64 ms, yet 128
client threads only reach 0.88 ms/query end to end — every query
dispatches its OWN fused-XLA launch, so per-launch dispatch overhead,
GIL contention, and host assembly dominate, not compute.  The idiom that
closes this gap in production inference stacks is continuous
micro-batching, and the compile model here is already shaped for it:
``plan.compiled_batched`` keys programs by (tree shape, reduce kind) and
vmaps over a leading batch axis, so concurrent queries that share that
compile key can ride ONE launch by concatenating along the axis the
program already batches over.

Scheduling is CONTINUOUS, not windowed: a lone query on an idle device
dispatches immediately (``max_wait_us`` is only an optional accumulation
backstop, default 0); while a launch is in flight on the dispatcher
thread, new arrivals accumulate in per-compile-key queues and the next
drain takes them all.  Under serial load every query gets its own launch
at native latency; under concurrent load occupancy rises automatically
to whatever the arrival rate sustains.

Batch construction, per drained key:

* **Identity dedup.**  Waiters whose leaf batches are the SAME assembled
  array (the batch-cache hot path: a query storm over one cached entry)
  share one segment — and when the drain is one segment, the launch runs
  directly on that array with zero extra device work.  N queries, one
  launch, no copies.
* **Concatenation.**  Distinct single-device batches with the same
  compile key (expr shape, reduce kind, leaf count, words, device)
  concatenate along the leading axis, padded with cached all-zero rows
  to a power-of-two bucket so the jit cache stays bounded (one program
  per (tree shape, reduce, bucket)).  Pad rows are never scattered back
  to any waiter, so they need no masking out of per-slice reduces; the
  coalescer always launches the per-slice ``compiled_batched`` program
  (its "count" partials are int32-exact — one slice-row is <= 2^20
  bits — and each waiter host-sums only its own positions in unbounded
  Python ints, byte-identical to the limb total-count path).
* **Sharded batches dedup only.**  Mesh-sharded entries (multi-device
  hosts) still amortize duplicate waiters over one launch, but distinct
  sharded arrays are never concatenated — cross-sharding concatenation
  would move shards between devices mid-query.

Every fragment-plane-bearing pool key in a drained batch is pinned via
the PR-3 residency pool for the launch's dispatch+fetch, so LRU eviction
can never drop a mirror out from under a coalesced program.

Observability: ``exec.coalesce.launches`` / ``coalescedQueries`` /
``padWaste`` counters and an ``exec.coalesce.batchOccupancy`` histogram;
the executor's per-query ``coalesce`` trace span carries the launch's
occupancy and row stats (and through it the slow-query log's batch
stats).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass

import numpy as np

from pilosa_tpu import device as device_mod
from pilosa_tpu.obs.stats import NopStatsClient

DEFAULT_MAX_BATCH = 64
DEFAULT_MAX_WAIT_US = 0
# Row budget for one concatenated launch: segments beyond it split into
# further launches.  Entry batches are already pow2-padded per query, so
# this bounds transient device memory (concatenation materializes a
# copy), not correctness.
MAX_CONCAT_ROWS = 4096
# Backstop bound on a waiter's Future wait: a wedged device call must
# surface as a failed query, not a hung request thread.  Waiters with a
# query deadline clamp this to their REMAINING budget and detach on
# expiry without cancelling the shared launch (executor._coalesce_eval)
# — an expired waiter never poisons the batch for the others.
RESULT_TIMEOUT_S = 600.0


class CoalesceClosed(RuntimeError):
    """Raised by submit() after close(); callers fall back to a direct
    (uncoalesced) launch."""


@dataclass
class _Item:
    batch: object
    future: Future
    pin_keys: tuple


def _placement(batch) -> tuple:
    """Hashable placement token for the compile key: single-device
    batches group (and concatenate) per device; sharded batches group by
    their full sharding and are marked concat-ineligible."""
    try:
        devs = list(batch.devices())
    except Exception:  # noqa: BLE001 — non-jax stand-ins in unit tests
        devs = []
    if len(devs) == 1:
        return (str(devs[0]), False)
    try:
        return (repr(batch.sharding), True)
    except Exception:  # noqa: BLE001
        return (tuple(sorted(str(d) for d in devs)), True)


class CoalesceScheduler:
    """Per-compile-key batch queues + one dispatcher thread.

    ``submit(expr, reduce, batch, pin_keys)`` returns a Future resolving
    to ``(results, info)`` where ``results`` is the host ndarray of this
    entry's rows of the launch output (``[n_rows, words]`` for "row",
    ``[n_rows]`` int32 partials for "count") and ``info`` the launch's
    batch stats for trace annotation.
    """

    def __init__(
        self,
        max_batch: int = DEFAULT_MAX_BATCH,
        max_wait_us: int = DEFAULT_MAX_WAIT_US,
        stats=None,
    ):
        self.max_batch = max(1, int(max_batch))
        self.max_wait_us = max(0, int(max_wait_us))
        self.stats = stats or NopStatsClient()
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        # key -> deque[_Item]; OrderedDict gives FIFO across keys (the
        # key whose first item arrived earliest drains first).
        self._queues: "OrderedDict[tuple, deque]" = OrderedDict()
        self._closed = False
        # device -> {(pad, tail...): cached all-zero pad rows}
        self._zeros: dict = {}
        # counters (mirrored to self.stats; kept here for snapshot()/bench)
        self._launches = 0
        self._queries = 0
        self._pad_rows = 0
        self._launched_rows = 0
        self._max_occupancy = 0
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="exec-coalesce"
        )
        self._thread.start()

    # ------------------------------------------------------------------
    # producer side
    # ------------------------------------------------------------------

    def submit(self, expr: tuple, reduce: str, batch, pin_keys=()) -> Future:
        """Enqueue one assembled leaf batch (``uint32[n, n_leaves,
        words]``) for a coalesced ``compiled_batched(expr, reduce)``
        launch."""
        key = (expr, reduce, tuple(batch.shape[1:]), _placement(batch))
        fut: Future = Future()
        item = _Item(
            batch=batch,
            future=fut,
            pin_keys=tuple(k for k in pin_keys if k is not None),
        )
        with self._cv:
            if self._closed:
                raise CoalesceClosed("coalescer closed")
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = deque()
            q.append(item)
            self._cv.notify()
        return fut

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            pending = [it for q in self._queues.values() for it in q]
            self._queues.clear()
            self._cv.notify_all()
        for it in pending:
            if not it.future.done():
                it.future.set_exception(CoalesceClosed("coalescer closed"))
        self._thread.join(timeout=10)

    def snapshot(self) -> dict:
        """Counters for bench artifacts and tests."""
        with self._mu:
            launches = self._launches
            queries = self._queries
            return {
                "launches": launches,
                "queries": queries,
                "pad_rows": self._pad_rows,
                "launched_rows": self._launched_rows,
                "max_occupancy": self._max_occupancy,
                "mean_occupancy": (
                    round(queries / launches, 3) if launches else None
                ),
            }

    # ------------------------------------------------------------------
    # dispatcher
    # ------------------------------------------------------------------

    def _drain_locked(self, key, items: list) -> None:
        q = self._queues.get(key)
        while q and len(items) < self.max_batch:
            items.append(q.popleft())
        if q is None:
            return
        if not q:
            del self._queues[key]
        else:
            # max_batch left items behind: rotate the key behind the
            # others so one hot query shape cannot starve the rest.
            self._queues.move_to_end(key)

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._closed and not self._queues:
                    self._cv.wait()
                if self._closed:
                    return
                key = next(iter(self._queues))
                items: list = []
                self._drain_locked(key, items)
            if self.max_wait_us and len(items) < self.max_batch:
                # Optional accumulation backstop: linger at most
                # max_wait_us for same-key company before launching.
                # 0 (the default) launches immediately — the in-flight
                # launch below is the only accumulation window.
                deadline = time.monotonic() + self.max_wait_us / 1e6
                with self._cv:
                    while len(items) < self.max_batch and not self._closed:
                        if key in self._queues:
                            self._drain_locked(key, items)
                            continue
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(timeout=remaining)
            try:
                # The launch (dispatch + fetch) runs HERE, on the
                # dispatcher thread — while it is in flight, new
                # arrivals queue up and the next iteration drains them
                # in one batch.  That in-flight window IS the
                # continuous-batching accumulation.
                self._launch(key, items)
            except BaseException as e:  # noqa: BLE001 — crosses futures
                exc = e if isinstance(e, Exception) else RuntimeError(repr(e))
                for it in items:
                    if not it.future.done():
                        it.future.set_exception(exc)

    def _launch(self, key, items: list) -> None:
        expr, reduce, _tail, placement = key
        sharded = placement[1]
        if not sharded:
            self._launch_concat(expr, reduce, items)
            return
        # Sharded batches: duplicate waiters share a launch, distinct
        # arrays each get their own (no cross-sharding concatenation).
        groups: "OrderedDict[int, list]" = OrderedDict()
        for it in items:
            groups.setdefault(id(it.batch), []).append(it)
        for grp in groups.values():
            self._launch_concat(expr, reduce, grp)

    def _launch_concat(self, expr, reduce, items: list) -> None:
        # Identity dedup: one segment per DISTINCT batch array.
        segs: list = []
        seg_of: dict[int, int] = {}
        seg_items: list[list] = []
        for it in items:
            i = seg_of.get(id(it.batch))
            if i is None:
                i = len(segs)
                seg_of[id(it.batch)] = i
                segs.append(it.batch)
                seg_items.append([])
            seg_items[i].append(it)
        # Greedy row-budget chunks over the distinct segments.
        lo = 0
        while lo < len(segs):
            hi = lo + 1
            rows = int(segs[lo].shape[0])
            while (
                hi < len(segs)
                and rows + int(segs[hi].shape[0]) <= MAX_CONCAT_ROWS
            ):
                rows += int(segs[hi].shape[0])
                hi += 1
            self._launch_one(
                expr,
                reduce,
                segs[lo:hi],
                [it for sub in seg_items[lo:hi] for it in sub],
                seg_items[lo:hi],
            )
            lo = hi

    def _launch_one(self, expr, reduce, segs, items, seg_items) -> None:
        import jax
        import jax.numpy as jnp

        from pilosa_tpu.exec import plan

        n_rows = [int(b.shape[0]) for b in segs]
        total = sum(n_rows)
        pad = 0
        if len(segs) == 1:
            dev_in = segs[0]
        else:
            # The canonical slice-axis bucket (plan.slice_bucket): the
            # concatenated launch lands on the same compiled program a
            # direct query over that bucket would.
            bucket = plan.slice_bucket(total)
            pad = bucket - total
            parts = list(segs)
            if pad:
                parts.append(self._pad_zeros(pad, segs[0]))
            dev_in = jnp.concatenate(parts, axis=0)
        pins = {k for it in items for k in it.pin_keys}
        t0 = time.monotonic()
        with device_mod.pool().pinned(*pins):
            out = plan.compiled_batched(expr, reduce)(dev_in)
            res = np.asarray(jax.device_get(out))
        launch_ms = (time.monotonic() - t0) * 1e3
        with self._mu:
            self._launches += 1
            self._queries += len(items)
            self._pad_rows += pad
            self._launched_rows += total + pad
            if len(items) > self._max_occupancy:
                self._max_occupancy = len(items)
            launch_n = self._launches
        self.stats.count("exec.coalesce.launches")
        self.stats.count("exec.coalesce.coalescedQueries", len(items))
        if pad:
            self.stats.count("exec.coalesce.padWaste", pad)
        self.stats.histogram("exec.coalesce.batchOccupancy", float(len(items)))
        info = {
            "launch": launch_n,
            "batch_queries": len(items),
            "batch_segments": len(segs),
            "batch_rows": total,
            "pad_rows": pad,
            "launch_ms": round(launch_ms, 3),
        }
        start = 0
        for rows, sub in zip(n_rows, seg_items):
            seg_res = res[start : start + rows]
            start += rows
            for it in sub:
                it.future.set_result((seg_res, info))

    def _pad_zeros(self, pad: int, like):
        """Cached all-zero pad rows on ``like``'s device — the pad set
        is small (pow2 gaps under MAX_CONCAT_ROWS), so the cache stays
        bounded in practice."""
        import jax

        dev = list(like.devices())[0]
        zkey = (pad,) + tuple(int(d) for d in like.shape[1:]) + (str(dev),)
        z = self._zeros.get(zkey)
        if z is None:
            z = jax.device_put(
                np.zeros((pad,) + tuple(like.shape[1:]), dtype=np.uint32), dev
            )
            self._zeros[zkey] = z
        return z
