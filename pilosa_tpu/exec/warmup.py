"""Cold-start elimination: persistent XLA compile cache + shape pre-warm.

The reference's cold path is an O(containers) mmap open
(reference: fragment.go:154-242) — a restarted node answers its first
query in milliseconds.  Our executor instead compiles one fused XLA
program per (tree shape, slice bucket), which cost ~5 s per shape on
every process restart (BENCH_r04 "e2e executor COLD").  Two fixes,
both here:

* ``enable_compile_cache(dir)`` turns on JAX's persistent compilation
  cache so every shape is compiled once per MACHINE, not once per
  process — a restart deserializes the executable from disk.
* ``prewarm()`` compiles the standard query-shape buckets (the shapes
  every fresh server will hit: Count/row over 1–2-leaf trees at small
  power-of-two slice buckets), so even the first-ever query on a new
  machine finds its program ready.  Run it in a background thread at
  server open; it only touches jit caches, which are thread-safe.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from pilosa_tpu.exec import plan
from pilosa_tpu.ops import bitplane as bp

_enabled_dir: str | None = None
_lock = threading.Lock()


def enable_compile_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Idempotent; first caller wins (the cache dir is process-global in
    JAX).  Returns True when the cache is active.  Entry criteria are
    relaxed so the multi-second fused-tree programs always land on
    disk; sub-100 ms host compiles stay out to keep the dir small.
    """
    global _enabled_dir
    with _lock:
        if _enabled_dir is not None:
            return True
        import jax

        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        except (OSError, AttributeError, ValueError):
            return False
        # The cache is ACTIVE from here on; the threshold knobs are
        # best-effort tuning (a JAX version lacking one must not make
        # us report the cache as off while it writes entries).
        for knob, val in (
            ("jax_persistent_cache_min_compile_time_secs", 0.1),
            ("jax_persistent_cache_min_entry_size_bytes", 0),
        ):
            try:
                jax.config.update(knob, val)
            except (AttributeError, ValueError):
                pass
        _enabled_dir = cache_dir
        return True


def enabled_cache_dir() -> str | None:
    return _enabled_dir


# The tree shapes every fresh node serves immediately: bare row fetch,
# Count(Bitmap), and the 2-leaf Intersect/Union/Difference counts —
# the reference's headline query mix (executor.go:418-505).
_LEAF = ("leaf", 0)
_STANDARD_EXPRS = (
    _LEAF,
    ("Intersect", ("leaf", 0), ("leaf", 1)),
    ("Union", ("leaf", 0), ("leaf", 1)),
    ("Difference", ("leaf", 0), ("leaf", 1)),
)


def _n_leaves(expr) -> int:
    if expr[0] == "leaf":
        return 1
    return sum(_n_leaves(e) for e in expr[1:])


# Bucket sizes the coalescer's concatenated launches land on: entry
# batches are pow2-padded per query, and distinct-entry concatenation
# re-pads the total to the next power of two (exec/coalesce.py).  The
# coalescer always runs the per-slice vmapped "count" program (NOT the
# limb total-count), so those jit keys need their own warm.
_COALESCE_BUCKETS = (1, 2, 4, 8, 16)


def prewarm_coalesce(
    buckets=_COALESCE_BUCKETS, exprs=_STANDARD_EXPRS[1:3]
) -> int:
    """Compile the coalescer's (tree shape x bucket) "count" programs —
    by default the Intersect/Union 2-leaf Count shapes, the headline
    concurrent query mix.  The "row" programs at small buckets are
    already covered by :func:`prewarm`; larger coalesced row buckets
    compile on first use (a row result that size is dominated by its
    own fetch, not the compile)."""
    warmed = 0
    for expr in exprs:
        nl = _n_leaves(expr)
        for bucket in buckets:
            batch = np.zeros((bucket, nl, bp.WORDS_PER_SLICE), dtype=np.uint32)
            plan.compiled_batched(expr, "count")(batch).block_until_ready()
            warmed += 1
    return warmed


# Interpreter geometry buckets the first fused launches land on:
# (leaf bucket, op-table bucket, out bucket) for the common small mixed
# batches — 2-leaf trees fusing in pairs/quads.  Larger geometries
# (BSI ripples push the op table toward 64-128 rows) compile on first
# use; a batch that size is dominated by its own pass, not the compile.
_FUSE_SHAPES = ((2, 8, 2), (4, 8, 2), (4, 8, 4), (8, 16, 8))


def prewarm_fuse(
    slice_buckets=(1, 2, 4, 8), shapes=_FUSE_SHAPES,
    reduces=("count", "total"),
) -> int:
    """Compile the multi-query interpreter's smallest geometry buckets
    (plan.compiled_interp — "count" for the mixed-storm hot path and
    "total" for the on-device-reduced Count storm).  The program is
    expression-INDEPENDENT (opcode tables are data), so these few
    compiles cover every query mix of their geometry."""
    warmed = 0
    for n_leaves, p_bucket, k_bucket in shapes:
        prog = np.zeros((p_bucket, 4), dtype=np.int32)
        out = np.zeros(k_bucket, dtype=np.int32)
        for n in slice_buckets:
            leaves = np.zeros(
                (n, n_leaves, bp.WORDS_PER_SLICE), dtype=np.uint32
            )
            for reduce in reduces:
                plan.interp_exec(
                    reduce, leaves, prog, out
                ).block_until_ready()
                warmed += 1
    return warmed


def prewarm_topn(
    row_buckets=(bp.ROW_BLOCK, 2 * bp.ROW_BLOCK), group_buckets=(1,)
) -> int:
    """Compile the fused TopN scorer's smallest bucket shapes — the
    self-src variant of ``bp.score_planes`` (the common
    ``TopN(Bitmap(frame=f), frame=f)`` shape) at the first plane-row /
    candidate-slot classes.  Every dimension of the scorer's jit key is
    pow2-bucketed (ops/bitplane.py), so this warms the exact programs a
    fresh node's first TopN queries hit."""
    warmed = 0
    for rows in row_buckets:
        for n in group_buckets:
            planes = tuple(
                np.zeros((rows, bp.WORDS_PER_SLICE), dtype=np.uint32)
                for _ in range(n)
            )
            slots = np.zeros((n, rows), dtype=np.int32)
            src_slots = np.zeros(n, dtype=np.int32)
            bp.score_planes(
                planes, slots, src_slots=src_slots
            ).block_until_ready()
            warmed += 1
    return warmed


def prewarm(buckets=(1, 2, 4, 8), exprs=_STANDARD_EXPRS, coalesce=False) -> int:
    """Compile the standard (tree shape x slice bucket) programs.

    Triggers real compilations by calling each program on a zero batch
    of the bucketed shape — with the persistent cache enabled this both
    fills the in-process jit cache and writes the executables to disk.
    Covers the same jit keys the executor hits (executor.py:687-770):
    single-device count AND row reduces at every bucket (row queries
    evaluate over the whole power-of-two batch, not per slice), and on
    a multi-device host the MESH variants too — sharded-input keys
    differ from the single-device ones, so each must warm on its own.
    Returns the number of programs warmed.  Safe to run concurrently
    with serving: jit compilation is thread-safe and zero inputs are
    discarded.
    """
    import jax

    from pilosa_tpu.parallel import mesh as pmesh

    mesh = pmesh.default_slices_mesh()
    warmed = 0
    for expr in exprs:
        nl = _n_leaves(expr)
        for bucket in buckets:
            batch = np.zeros((bucket, nl, bp.WORDS_PER_SLICE), dtype=np.uint32)
            plan.compiled_total_count(expr)(batch).block_until_ready()
            plan.compiled_batched(expr, "row")(batch).block_until_ready()
            warmed += 2
        if mesh is not None:
            # First queries over >1 slice on a mesh host: per-device
            # chunk 1 covers up to n_devices slices, chunk 2 to 2x.
            for chunk in (1, 2):
                blocks = [
                    jax.device_put(
                        np.zeros(
                            (chunk, nl, bp.WORDS_PER_SLICE), dtype=np.uint32
                        ),
                        d,
                    )
                    for d in mesh.devices.flat
                ]
                batch = pmesh.assemble_sharded_batch(blocks, mesh)
                # No compiled_batched(expr, "count") here: the executor
                # only takes that fallback past the 2^15-partial budget
                # (executor.py:758), never at these chunk sizes.
                plan.compiled_total_count(expr, mesh)(batch).block_until_ready()
                plan.compiled_batched(expr, "row")(batch).block_until_ready()
                warmed += 2
    warmed += prewarm_topn()
    if coalesce:
        warmed += prewarm_coalesce()
        warmed += prewarm_fuse()
    return warmed


def prewarm_async(logger=None, coalesce=False) -> threading.Thread:
    """Run :func:`prewarm` on a daemon thread (server open must not
    block on compiles); returns the thread for tests to join."""

    def run():
        try:
            n = prewarm(coalesce=coalesce)
            if logger is not None:
                logger(f"prewarm: {n} standard query programs compiled")
        except Exception as e:  # pragma: no cover - diagnostics only
            if logger is not None:
                logger(f"prewarm failed: {e}")

    t = threading.Thread(target=run, daemon=True, name="prewarm")
    t.start()
    return t
