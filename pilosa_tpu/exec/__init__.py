"""Execution layer: the distributed PQL query engine.

reference: executor.go
"""

from pilosa_tpu.exec.executor import (
    ExecOptions,
    Executor,
    ExecutorError,
    FrameNotFoundError,
    IndexNotFoundError,
    SliceUnavailableError,
    SlicesUnavailableError,
    TooManyWritesError,
)

__all__ = [
    "Executor",
    "ExecOptions",
    "ExecutorError",
    "IndexNotFoundError",
    "FrameNotFoundError",
    "TooManyWritesError",
    "SliceUnavailableError",
    "SlicesUnavailableError",
]
