"""Roaring file-format codec, bit-compatible with the reference.

The on-disk format (reference: roaring/roaring.go:507-660) is the
framework's checkpoint format — keeping it byte-compatible means the
reference's ``pilosa check`` / ``pilosa inspect`` tools and backup tars
work unchanged against our data files, and golden files cut from either
implementation validate the other.

Layout (all little-endian):

    u32 cookie = 12346
    u32 containerCount                  # non-empty containers only
    containerCount * { u64 key, u32 n-1 }
    containerCount * { u32 offset }     # absolute byte offset of payload
    payloads:
        n <= 4096  -> n * u32 sorted low-bits ("array" container)
        n >  4096  -> 1024 * u64 bitmap words ("bitmap" container)
    op-log, repeated until EOF:
        u8 type (0=add, 1=remove), u64 value, u32 FNV-1a(first 9 bytes)

A container covers 2^16 bit-positions; its key is ``value >> 16``
(reference: roaring/roaring.go:1786-1787).  Decoding is TIERED like the
reference's in-memory forms (roaring/roaring.go:893-906): bitmap
containers materialize as uint64[1024] word arrays, array containers
stay as sorted uint32 value arrays (pay-per-bit), and encoding chooses
the payload form by the same ArrayMaxSize = 4096 rule regardless of the
in-memory tier (reference: roaring/roaring.go:893).
"""

from __future__ import annotations

import io
import struct
from dataclasses import dataclass, field

import numpy as np

COOKIE = 12346
HEADER_SIZE = 8
ARRAY_MAX_SIZE = 4096
CONTAINER_BITS = 1 << 16
CONTAINER_WORDS64 = CONTAINER_BITS // 64  # 1024 u64 words ("bitmapN")
OP_SIZE = 13

OP_ADD = 0
OP_REMOVE = 1

_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193


def fnv1a32(data: bytes) -> int:
    """32-bit FNV-1a (stdlib has no FNV; matches Go's hash/fnv.New32a)."""
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFF
    return h


class CorruptError(ValueError):
    pass


@dataclass
class ContainerInfo:
    """Stats for one container (reference: roaring.ContainerInfo,
    roaring/roaring.go:669-683) — powers the ``inspect`` CLI."""

    key: int
    type: str  # "array" | "bitmap"
    n: int
    alloc: int


@dataclass
class BitmapInfo:
    ops: int
    containers: list[ContainerInfo] = field(default_factory=list)


def decode(data: bytes) -> dict[int, np.ndarray]:
    """Decode a roaring file into {container_key: uint64[1024] words},
    applying the trailing op-log (reference: roaring/roaring.go:567-646).

    Dispatches to the C++ codec (pilosa_tpu/native) when available; the
    Python path is the fallback and parity oracle."""
    return decode_with_ops(data)[0]


def decode_with_ops(data: bytes) -> tuple[dict[int, np.ndarray], int]:
    """decode() plus the replayed op count — one parse serves both the
    containers and Fragment.open's op-counter bookkeeping."""
    from pilosa_tpu import native

    try:
        res = native.decode(data)
    except native.NativeCorruptError as e:
        raise CorruptError(str(e)) from e
    if res is not None:
        return res
    containers, ops_offset, _ = _decode_containers(data)
    op_n = _apply_ops(containers, data, ops_offset)
    return containers, op_n


def decode_tiered(
    data,
) -> tuple[dict[int, np.ndarray], dict[int, np.ndarray], int]:
    """Decode keeping each container in its cheapest form:
    ``(words, arrays, op_n)`` where ``words[key]`` is uint64[1024] (bitmap
    containers) and ``arrays[key]`` is a SORTED uint32 value array (array
    containers, pay-per-bit — never materialized to 8 KiB).  This is the
    loading path for tall-sparse fragments (e.g. inverse views with one
    array container per row), where materializing every container would
    cost rows x 8 KiB (reference keeps the same two forms in memory,
    roaring/roaring.go:893-906).

    ``data`` may be bytes or any readable buffer (mmap, memoryview):
    both decoders read it in place and every returned array is a fresh
    copy, so the buffer can be closed immediately after (reference
    analog: zero-copy container attach straight out of the mmap,
    roaring/roaring.go:567-620 — here tiers are materialized instead,
    but the FILE bytes are never duplicated in memory).

    Dispatches to the C++ tiered decoder when available; the pure-Python
    path below is the fallback and parity oracle."""
    from pilosa_tpu import native

    try:
        res = native.decode_tiered(data)
    except native.NativeCorruptError as e:
        raise CorruptError(str(e)) from e
    if res is not None:
        return res
    words, arrays, ops_offset, _ = _decode_containers_tiered(data)
    op_n = _apply_ops_tiered(words, arrays, data, ops_offset)
    return words, arrays, op_n


def _parse_header_tables(data):
    """Vectorized header parse shared by the tiered decoder and
    :func:`ops_region_offset` — the ONE place the header layout and the
    container payload-size rule (n <= 4096 -> 4n-byte array, else
    8 KiB bitmap) live.  Returns ``(keys u64[], ns i64[], offs i64[],
    plens i64[], ops_base)``; a tall-sparse file has one container per
    row (hundreds of thousands of entries), so the key and offset
    tables read as one structured view each."""
    if len(data) < HEADER_SIZE:
        raise CorruptError("data too small")
    cookie, key_n = struct.unpack_from("<II", data, 0)
    if cookie != COOKIE:
        raise CorruptError("invalid roaring file")
    if HEADER_SIZE + key_n * 16 > len(data):
        raise CorruptError(
            f"header claims {key_n} containers but file is {len(data)} bytes"
        )
    ktab = np.frombuffer(
        data,
        dtype=np.dtype([("key", "<u8"), ("n1", "<u4")]),
        count=key_n,
        offset=HEADER_SIZE,
    )
    keys = ktab["key"]
    ns = ktab["n1"].astype(np.int64) + 1
    # The format writes containers in strictly ascending key order
    # (encoder sorts; reference roaring.go:507-531 iterates sorted) and
    # every consumer here — the streaming fragment loader's grouping,
    # the sparse tier's binary searches — depends on it, so fail fast
    # instead of silently mis-answering on an out-of-order file.
    if key_n > 1 and (np.diff(keys.astype(np.int64)) <= 0).any():
        raise CorruptError("container keys are not sorted/unique")
    offs = np.frombuffer(
        data, dtype="<u4", count=key_n, offset=HEADER_SIZE + key_n * 12
    ).astype(np.int64)
    plens = np.where(ns <= ARRAY_MAX_SIZE, ns * 4, CONTAINER_WORDS64 * 8)
    return keys, ns, offs, plens, HEADER_SIZE + key_n * 16


# Public alias: the fragment's streaming loader parses the header
# tables itself to fill its storage tiers straight from the mmap.
parse_header_tables = _parse_header_tables


def _decode_containers_tiered(data: bytes):
    """Parse into (words, arrays, ops_offset, infos): bitmap containers
    as uint64[1024] words, array containers as sorted uint32 values."""
    keys, ns, offs, plens, ops_base = _parse_header_tables(data)
    words_out: dict[int, np.ndarray] = {}
    arrays_out: dict[int, np.ndarray] = {}
    ops_offset = ops_base
    infos: list[ContainerInfo] = []
    for i in range(len(keys)):
        offset = int(offs[i])
        if offset >= len(data):
            raise CorruptError(f"offset out of bounds: off={offset}, len={len(data)}")
        n = int(ns[i])
        key = int(keys[i])
        payload_len = int(plens[i])
        if offset + payload_len > len(data):
            raise CorruptError(
                f"container payload out of bounds: off={offset}, "
                f"need={payload_len}, len={len(data)}"
            )
        if n <= ARRAY_MAX_SIZE:
            values = np.frombuffer(data, dtype="<u4", count=n, offset=offset)
            if values.size and int(values.max()) >= CONTAINER_BITS:
                raise CorruptError(
                    f"array value out of range in container key={key}: "
                    f"{int(values.max())}"
                )
            # The format requires strictly-ascending array values; the
            # sparse tier's binary searches depend on it, so fail fast
            # instead of silently mis-answering on corrupt input.
            if values.size > 1 and (np.diff(values.astype(np.int64)) <= 0).any():
                raise CorruptError(
                    f"array container key={key} is not sorted/unique"
                )
            arrays_out[key] = values.astype(np.uint32)
            end = offset + n * 4
            infos.append(ContainerInfo(key, "array", n, n * 4))
        else:
            words_out[key] = np.frombuffer(
                data, dtype="<u8", count=CONTAINER_WORDS64, offset=offset
            ).copy()
            end = offset + CONTAINER_WORDS64 * 8
            infos.append(ContainerInfo(key, "bitmap", n, CONTAINER_WORDS64 * 8))
        ops_offset = max(ops_offset, end)
    return words_out, arrays_out, ops_offset, infos


def values_to_words(values: np.ndarray) -> np.ndarray:
    """Sorted uint32 container values -> uint64[1024] words."""
    words = np.zeros(CONTAINER_WORDS64, dtype=np.uint64)
    if len(values):
        widx = (values // 64).astype(np.int64)
        masks = np.uint64(1) << (values % 64).astype(np.uint64)
        np.bitwise_or.at(words, widx, masks)
    return words


def words_to_values(words: np.ndarray) -> np.ndarray:
    """uint64[1024] words -> sorted uint32 container values."""
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    (positions,) = np.nonzero(bits)
    return positions.astype(np.uint32)


def _decode_containers(data: bytes):
    words_out, arrays_out, ops_offset, infos = _decode_containers_tiered(data)
    containers = words_out
    for key, values in arrays_out.items():
        containers[key] = values_to_words(values)
    return containers, ops_offset, infos


def ops_region_offset(data) -> int:
    """Byte offset where the op-log begins (one past the last container
    payload), computed from the header tables alone — no payload is
    materialized, so this is cheap even on multi-hundred-MB files.
    Used by torn-tail recovery, which must locate the op region of a
    file whose op-log no longer parses."""
    keys, ns, offs, plens, base = _parse_header_tables(data)
    if len(keys) == 0:
        return base
    end = int((offs + plens).max())
    if end > len(data):
        raise CorruptError(
            f"container payload out of bounds: end={end}, len={len(data)}"
        )
    return max(base, end)


def _read_op(data, pos: int):
    """THE parser of the 13-byte op wire record (reference:
    roaring/roaring.go:1746-1762): returns ``(typ, value, problem)``
    where ``problem`` is None for a valid record — shared by op replay
    (:func:`_iter_ops`) and torn-tail scanning so record validity can
    never diverge between them."""
    typ = data[pos]
    (value,) = struct.unpack_from("<Q", data, pos + 1)
    (chk,) = struct.unpack_from("<I", data, pos + 9)
    want = fnv1a32(bytes(data[pos : pos + 9]))
    if chk != want:
        return typ, value, f"checksum mismatch: exp={want:08x}, got={chk:08x}"
    if typ not in (OP_ADD, OP_REMOVE):
        return typ, value, f"invalid op type: {typ}"
    return typ, value, None


def _op_record_valid(data, pos: int) -> bool:
    return _read_op(data, pos)[2] is None


# Group-commit flush threshold for op-log appends — owned here, next to
# the record format, so the torn-tail bound below can never drift from
# the writer's actual flush size (fragment._OP_FLUSH_BYTES aliases it).
OP_FLUSH_BYTES = 64 << 10

# A process crash can tear at most one group-commit flush buffer off the
# op-log tail (plus the record that tripped the threshold).  An invalid
# tail LARGER than this cannot be crash residue — it is at-rest damage
# to committed data and must refuse to load rather than silently
# truncate.
MAX_TORN_TAIL = OP_FLUSH_BYTES + 2 * OP_SIZE


def scan_torn_tail(data, max_tail: int = MAX_TORN_TAIL) -> tuple[int, str] | None:
    """Decide whether an unparseable op-log is a TORN TAIL — the residue
    of a crash mid-append — and if so where the committed prefix ends.

    Returns ``(valid_end, reason)`` when the file's op region consists of
    a run of valid records followed ONLY by invalid bytes (a partial
    record at EOF, or full-size records that all fail their FNV check —
    what an interrupted group-commit ``write()`` leaves, since appends
    are sequential).  Returns ``None`` when the op-log is healthy OR when
    a VALID record exists beyond the first invalid one: that shape means
    mid-log damage to committed data (e.g. a flipped bit at rest), which
    must never be silently truncated away.

    The reference's recovery window is one 13-byte record (it appends
    per-op, fragment.go:379-418); group commit widens the torn window to
    the flush buffer, so recovery must handle a multi-record tail — but
    never one larger than ``max_tail`` (see :data:`MAX_TORN_TAIL`).
    Analog: roaring/roaring.go:622-646 (op replay on open).
    """
    ops_offset = ops_region_offset(data)
    pos = ops_offset
    n = len(data)
    # Only the final max_tail window can be torn, and records are a
    # fixed 13 bytes from ops_offset, so the scan can fast-forward to
    # the record boundary nearest (n - max_tail): identical accept /
    # refuse outcomes — damage before the window makes the caller's
    # committed-prefix decode refuse — at O(64 KiB) cost instead of
    # O(op-log) per-byte Python FNV on a multi-hundred-MB log.
    if n - pos > max_tail:
        pos += ((n - max_tail - pos) // OP_SIZE) * OP_SIZE
    while pos < n:
        if n - pos < OP_SIZE:
            return pos, f"partial {n - pos}-byte op record at EOF"
        if not _op_record_valid(data, pos):
            # First bad record.  Torn iff nothing after it validates —
            # scan the remaining aligned windows (a random 13-byte blob
            # passes the 32-bit FNV check with p ~= 2^-32) — and the
            # invalid run fits inside one flush buffer.
            if n - pos > max_tail:
                return None
            q = pos + OP_SIZE
            while q + OP_SIZE <= n:
                if _op_record_valid(data, q):
                    return None
                q += OP_SIZE
            return pos, f"unchecksummed {n - pos}-byte op-log tail"
        pos += OP_SIZE
    return None


def _iter_ops(data: bytes, ops_offset: int):
    """Validate and yield (typ, value) op-log records — the single
    parser of the 13-byte wire record, shared by both appliers."""
    pos = ops_offset
    while pos < len(data):
        if len(data) - pos < OP_SIZE:
            raise CorruptError(f"op data out of bounds: len={len(data) - pos}")
        typ, value, problem = _read_op(data, pos)
        if problem is not None:
            raise CorruptError(problem)
        yield typ, value
        pos += OP_SIZE


def _apply_ops(containers: dict[int, np.ndarray], data: bytes, ops_offset: int) -> int:
    """Replay the op-log over words-form containers; returns the number
    of ops applied."""
    op_n = 0
    for typ, value in _iter_ops(data, ops_offset):
        key = value >> 16
        word, shift = divmod(value & 0xFFFF, 64)
        if key not in containers:
            containers[key] = np.zeros(CONTAINER_WORDS64, dtype=np.uint64)
        mask = np.uint64(1) << np.uint64(shift)
        if typ == OP_ADD:
            containers[key][word] |= mask
        else:
            containers[key][word] &= ~mask
        op_n += 1
    return op_n


def _apply_ops_tiered(
    words: dict[int, np.ndarray],
    arrays: dict[int, np.ndarray],
    data: bytes,
    ops_offset: int,
) -> int:
    """Op-log replay over tiered containers; array containers mutate in
    value form (sorted insert/remove) without materialization."""
    op_n = 0
    for typ, value in _iter_ops(data, ops_offset):
        key = value >> 16
        low = np.uint32(value & 0xFFFF)
        if key in words:
            word, shift = divmod(int(low), 64)
            mask = np.uint64(1) << np.uint64(shift)
            if typ == OP_ADD:
                words[key][word] |= mask
            else:
                words[key][word] &= ~mask
        else:
            vals = arrays.get(key)
            if vals is None:
                vals = np.empty(0, dtype=np.uint32)
            i = int(np.searchsorted(vals, low))
            present = i < len(vals) and vals[i] == low
            if typ == OP_ADD and not present:
                arrays[key] = np.insert(vals, i, low)
            elif typ == OP_REMOVE and present:
                arrays[key] = np.delete(vals, i)
            elif key not in arrays:
                arrays[key] = vals
        op_n += 1
    return op_n


def encode(containers: dict[int, np.ndarray]) -> bytes:
    """Serialize {container_key: uint64[1024]} to the reference file format.

    Empty containers are dropped (reference: roaring/roaring.go:510-531
    skips c.n == 0).  Containers with <= 4096 bits are written in array
    form, else bitmap form.  Dispatches to the C++ codec when available.
    """
    return encode_tiered(containers, {})


def encode_packed(
    keys: np.ndarray,
    words2d: np.ndarray,
    arrays: dict[int, np.ndarray] | None = None,
) -> bytes:
    """Serialize a PACKED dense tier — ``keys`` ascending container
    keys, ``words2d[i]`` the 1024-u64 payload of ``keys[i]`` — plus an
    optional sparse-arrays tier.  The all-dense case hands the buffers
    straight to the C++ codec with no per-container Python; mixed or
    native-less cases fall back to the general dict path."""
    from pilosa_tpu import native

    if not arrays:
        res = native.encode_packed(keys, words2d)
        if res is not None:
            return res
    words = {int(k): words2d[i] for i, k in enumerate(keys)}
    return encode_tiered(words, arrays or {})


def encode_tiered(
    words: dict[int, np.ndarray], arrays: dict[int, np.ndarray]
) -> bytes:
    """Serialize tiered containers (see decode_tiered) to the reference
    file format, choosing array vs bitmap payload form by the SAME
    n <= 4096 rule regardless of the in-memory form; empty containers
    are dropped.  Peak transient memory is one container.  All-words
    inputs (the dense-fragment case) dispatch to the C++ codec."""
    from pilosa_tpu import native

    if not arrays:
        res = native.encode(words)
        if res is not None:
            return res
    entries: list[tuple[int, int, object, bool]] = []  # key, n, src, is_vals
    for key, vals in arrays.items():
        if key in words:
            raise ValueError(f"container key={key} present in both tiers")
        if len(vals):
            entries.append((int(key), len(vals), vals, True))
    for key, w in words.items():
        n = _words_count(w)
        if n:
            entries.append((int(key), n, w, False))
    entries.sort()

    payloads: list[bytes] = []
    for key, n, src, is_vals in entries:
        if n <= ARRAY_MAX_SIZE:
            vals = src if is_vals else words_to_values(src)
            payloads.append(np.asarray(vals, dtype="<u4").tobytes())
        else:
            w = values_to_words(src) if is_vals else src
            payloads.append(np.asarray(w, dtype="<u8").tobytes())

    # Vectorized key/offset tables (a tall-sparse fragment serializes
    # hundreds of thousands of containers).
    ktab = np.zeros(len(entries), dtype=np.dtype([("key", "<u8"), ("n1", "<u4")]))
    ktab["key"] = [key for key, _, _, _ in entries]
    ktab["n1"] = [n - 1 for _, n, _, _ in entries]
    plens = np.asarray([len(p) for p in payloads], dtype=np.int64)
    base = HEADER_SIZE + 12 * len(entries) + 4 * len(entries)
    otab = (base + np.concatenate(([0], np.cumsum(plens[:-1])))
            if len(entries) else np.empty(0, np.int64)).astype("<u4")

    out = io.BytesIO()
    out.write(struct.pack("<II", COOKIE, len(entries)))
    out.write(ktab.tobytes())
    out.write(otab.tobytes())
    for p in payloads:
        out.write(p)
    return out.getvalue()


def encode_op(typ: int, value: int) -> bytes:
    """One 13-byte op-log record (reference: roaring/roaring.go:1746-1762)."""
    buf = struct.pack("<BQ", typ, value)
    return buf + struct.pack("<I", fnv1a32(buf))


def _words_count(words: np.ndarray) -> int:
    return int(np.unpackbits(words.view(np.uint8)).sum())


def info(data: bytes) -> BitmapInfo:
    """Container stats + op count for ``inspect`` (reference:
    roaring.Bitmap.Info, roaring/roaring.go:669-683, ctl/inspect.go).
    Runs on the tiered parse — array containers are never materialized,
    so tall-sparse files inspect in O(file size)."""
    words, arrays, ops_offset, infos = _decode_containers_tiered(data)
    op_n = sum(1 for _ in _iter_ops(data, ops_offset))
    return BitmapInfo(ops=op_n, containers=infos)


def check(data: bytes) -> list[str]:
    """Consistency check (reference: roaring.Bitmap.Check,
    roaring/roaring.go:686-706, driven by ctl/check.go).  Returns a list
    of problem strings, empty when healthy.  Array containers are
    validated during the tiered parse (range + sortedness, and their
    header n IS their length); bitmap containers verify n against the
    actual popcount; the op-log replays through the shared record
    parser."""
    errs: list[str] = []
    try:
        words, arrays, ops_offset, infos = _decode_containers_tiered(data)
    except CorruptError as e:
        return [str(e)]
    for ci in infos:
        if ci.type == "bitmap":
            actual = _words_count(words[ci.key])
            if ci.n != actual:
                errs.append(
                    f"container key={ci.key} count mismatch: n={ci.n}, count={actual}"
                )
    try:
        for _ in _iter_ops(data, ops_offset):
            pass
    except CorruptError as e:
        errs.append(str(e))
    return errs


# ---------------------------------------------------------------------------
# Bridges between the container dict and the dense slice-row planes used by
# pilosa_tpu.core.fragment.  A fragment file covers bit positions
# row*SLICE_WIDTH + (column % SLICE_WIDTH); container key k covers positions
# [k*2^16, (k+1)*2^16) — i.e. 16 consecutive containers per row.
# ---------------------------------------------------------------------------


def containers_to_plane(containers: dict[int, np.ndarray], slice_width: int) -> np.ndarray:
    """Densify into a (rows, slice_width/32) uint32 plane."""
    per_row = slice_width // CONTAINER_BITS
    max_key = max(containers.keys(), default=-1)
    rows = (max_key // per_row) + 1 if max_key >= 0 else 0
    plane = np.zeros((max(rows, 1), slice_width // 32), dtype=np.uint32)
    words32_per_container = CONTAINER_BITS // 32
    for key, words in containers.items():
        row, cidx = divmod(key, per_row)
        lo = cidx * words32_per_container
        plane[row, lo : lo + words32_per_container] = words.view("<u4").astype(np.uint32)
    return plane


def plane_to_containers(plane: np.ndarray, slice_width: int) -> dict[int, np.ndarray]:
    """Sparsify a (rows, slice_width/32) plane into the container dict."""
    per_row = slice_width // CONTAINER_BITS
    words32_per_container = CONTAINER_BITS // 32
    out: dict[int, np.ndarray] = {}
    nz_rows = np.nonzero(plane.any(axis=1))[0]
    for row in nz_rows:
        for cidx in range(per_row):
            lo = cidx * words32_per_container
            chunk = plane[row, lo : lo + words32_per_container]
            if chunk.any():
                out[int(row) * per_row + cidx] = np.ascontiguousarray(chunk).view(
                    np.uint64
                ).copy()
    return out


