"""Dense bit-plane representation and XLA bitmap ops.

The unit of storage is a *slice-row*: one row of one fragment, covering
SLICE_WIDTH = 2^20 columns, stored as 32,768 uint32 words (128 KiB).  A
fragment is a plane of shape (rows, WORDS_PER_SLICE).  Bit ``i`` of a
slice-row (column ``slice*SLICE_WIDTH + i``) lives at word ``i >> 5``,
bit ``i & 31`` (little-endian within the word, matching the reference's
roaring bitmap-container layout where word ``w`` holds values
``[w*64, w*64+64)`` — we use uint32 words because TPUs have no uint64).

These functions replace the reference's per-container sorted-merge kernels
and popcount assembly (reference: roaring/roaring.go:1259-1716,
roaring/assembly_amd64.s) with whole-row vector ops: XLA fuses the bitwise
op into the popcount reduce, so ``count_and`` etc. never materialize the
intermediate row in HBM as one fused bitwise+popcount+reduce pass.

All counts are returned as int32 device scalars (a slice-row holds at most
2^20 bits, and a full plane reduce stays far below 2^31); callers accumulate
cross-slice totals in Python ints.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

# Matches the reference: SliceWidth = 2^20 (reference: fragment.go:47).
SLICE_WIDTH = 1 << 20
WORD_BITS = 32
WORDS_PER_SLICE = SLICE_WIDTH // WORD_BITS  # 32768 words = 128 KiB
# A roaring container spans 2^16 bits (reference: roaring/roaring.go:36).
CONTAINER_BITS = 1 << 16
WORDS_PER_CONTAINER = CONTAINER_BITS // WORD_BITS  # 2048
CONTAINERS_PER_SLICE = SLICE_WIDTH // CONTAINER_BITS  # 16

# Rows are padded to power-of-two shape classes (floor ROW_BLOCK) so
# query shapes bucket into a LOG-bounded set of compiled programs.  The
# former multiple-of-8 padding kept single-row growth from recompiling,
# but a churny schema still minted a fresh XLA program every 8 rows
# (~326 ms each, VERDICT item 3): plane mirrors and candidate slot
# arrays both enter jit keys by shape, so their shape-class count IS the
# compiled-program cardinality.  pow2 classes bound it at
# log2(rows/ROW_BLOCK)+1 regardless of how many distinct fragment
# shapes the schema produces.
ROW_BLOCK = 8


def row_shape() -> tuple[int]:
    return (WORDS_PER_SLICE,)


def empty_row() -> np.ndarray:
    return np.zeros(WORDS_PER_SLICE, dtype=np.uint32)


def empty_plane(rows: int) -> np.ndarray:
    return np.zeros((rows, WORDS_PER_SLICE), dtype=np.uint32)


def pow2_bucket(n: int, floor: int = 1) -> int:
    """Round ``n`` up to the next power of two, at least ``floor``."""
    if n <= floor:
        return floor
    return 1 << (n - 1).bit_length()


def bucket_classes(hi: int, floor: int = 1) -> int:
    """How many distinct pow2 shape classes cover sizes in [1, hi] —
    the hard bound on compiled-program cardinality per bucketed
    dimension (exec/plan.program_cache_bounds)."""
    if hi <= floor:
        return 1
    return (pow2_bucket(hi, floor) // floor).bit_length()


def pad_rows(rows: int) -> int:
    """Round a row count up to its pow2 shape class (floor ROW_BLOCK)."""
    return pow2_bucket(rows, ROW_BLOCK)


# ---------------------------------------------------------------------------
# Host-side (numpy) bit manipulation — the write path.  Mutations happen on
# the host-resident authoritative plane; device mirrors are refreshed lazily
# (see core/fragment.py).
# ---------------------------------------------------------------------------


def np_set_bit(plane: np.ndarray, bit: int) -> bool:
    """Set bit ``bit`` (a fragment position: row*SLICE_WIDTH + col%SLICE_WIDTH
    flattened into the plane).  Returns True if the bit changed."""
    row, offset = divmod(bit, SLICE_WIDTH)
    word, shift = divmod(offset, WORD_BITS)
    mask = np.uint32(1 << shift)
    old = plane[row, word]
    if old & mask:
        return False
    plane[row, word] = old | mask
    return True


def np_clear_bit(plane: np.ndarray, bit: int) -> bool:
    row, offset = divmod(bit, SLICE_WIDTH)
    word, shift = divmod(offset, WORD_BITS)
    mask = np.uint32(1 << shift)
    old = plane[row, word]
    if not (old & mask):
        return False
    plane[row, word] = old & ~mask
    return True


def np_contains(plane: np.ndarray, bit: int) -> bool:
    row, offset = divmod(bit, SLICE_WIDTH)
    word, shift = divmod(offset, WORD_BITS)
    return bool((int(plane[row, word]) >> shift) & 1)


def np_set_bulk(plane: np.ndarray, rows: np.ndarray, offsets: np.ndarray) -> None:
    """Bulk set: vectorized scatter-OR for imports (reference:
    fragment.go:936-1004 bulk Import path)."""
    words = offsets // WORD_BITS
    masks = (np.uint32(1) << (offsets % WORD_BITS).astype(np.uint32)).astype(np.uint32)
    np.bitwise_or.at(plane, (rows, words), masks)


def np_clear_bulk(plane: np.ndarray, rows: np.ndarray, offsets: np.ndarray) -> None:
    """Bulk clear: vectorized scatter-ANDNOT — the overwrite half of a
    columnar BSI value import (a re-imported column must drop the stale
    bits of its previous value)."""
    words = offsets // WORD_BITS
    masks = (np.uint32(1) << (offsets % WORD_BITS).astype(np.uint32)).astype(np.uint32)
    np.bitwise_and.at(plane, (rows, words), ~masks)


def np_row_to_columns(row_words: np.ndarray) -> np.ndarray:
    """Expand one slice-row's set bits into sorted uint64 column offsets
    within the slice (0 .. SLICE_WIDTH)."""
    bits = np.unpackbits(
        np.ascontiguousarray(row_words).view(np.uint8), bitorder="little"
    )
    (positions,) = np.nonzero(bits)
    return positions.astype(np.uint64)


def np_columns_to_row(offsets: np.ndarray) -> np.ndarray:
    """Inverse of np_row_to_columns: bit offsets (within slice) -> row words."""
    row = empty_row()
    if len(offsets) == 0:
        return row
    offsets = np.asarray(offsets, dtype=np.uint64)
    words = (offsets // WORD_BITS).astype(np.int64)
    masks = (np.uint32(1) << (offsets % WORD_BITS).astype(np.uint32)).astype(np.uint32)
    np.bitwise_or.at(row, words, masks)
    return row


if hasattr(np, "bitwise_count"):  # numpy >= 2.0

    def np_count(words: np.ndarray) -> int:
        """Host popcount (the CPU reference path, equivalent of the
        reference's pure-Go popcntSlice fallback, reference:
        roaring/assembly.go:21-28)."""
        return int(np.bitwise_count(words).sum())

    def np_row_counts(plane: np.ndarray) -> np.ndarray:
        """Host per-row popcounts (cache maintenance without a device trip)."""
        return np.bitwise_count(plane).sum(axis=-1, dtype=np.int64)

else:  # pragma: no cover - numpy 1.x fallback

    def np_count(words: np.ndarray) -> int:
        return int(np.unpackbits(np.ascontiguousarray(words).view(np.uint8)).sum())

    def np_row_counts(plane: np.ndarray) -> np.ndarray:
        return (
            np.unpackbits(np.ascontiguousarray(plane).view(np.uint8), axis=-1)
            .sum(axis=-1, dtype=np.int64)
        )


# ---------------------------------------------------------------------------
# Device ops (XLA).  Everything below is jit-compiled; shapes are static per
# (rows,) bucket.  These are the hot kernels: the equivalents of the
# reference's popcntAndSlice/popcntOrSlice/popcntXorSlice asm procs and the
# materializing container merges.
# ---------------------------------------------------------------------------


def _popcount_sum(words: jnp.ndarray) -> jnp.ndarray:
    """Sum of set bits over the whole array -> int32 scalar."""
    return jnp.sum(jax.lax.population_count(words).astype(jnp.int32))


@functools.lru_cache(maxsize=8)
def _parse_mesh_shape(shape: str) -> int | None:
    """Device cap from a mesh-shape string ("4", "4x2", ...); None when
    unset, malformed, or non-positive (a bad value must never silently
    disable sharding)."""
    factors = shape.lower().replace("x", " ").split()
    if not factors:
        return None
    try:
        want = 1
        for f in factors:
            want *= int(f)
    except ValueError:
        return None
    return want if want >= 1 else None


# [device] mesh-devices override (Server.open / bench / tests): the
# process-global device-count cap for slice placement and the slices
# mesh.  0 = unset (fall through to the envs, default all visible
# devices); 1 = force the single-device data plane; N caps the mesh.
_MESH_DEVICES_OVERRIDE = 0


def configure_mesh_devices(n: int) -> None:
    """Set (or with 0, clear) the process-wide ``[device] mesh-devices``
    cap.  Placement is process-global state — in-process multi-server
    setups (tests, bench grids) share whatever the last caller set."""
    global _MESH_DEVICES_OVERRIDE
    _MESH_DEVICES_OVERRIDE = max(0, int(n))


def _mesh_devices_cap() -> int | None:
    """The effective device cap: explicit configure_mesh_devices wins,
    then ``PILOSA_DEVICE_MESH_DEVICES`` (0 = all visible), then the
    legacy ``PILOSA_TPU_MESH_SHAPE`` factor product; None = uncapped.
    Malformed values never silently disable sharding."""
    if _MESH_DEVICES_OVERRIDE > 0:
        return _MESH_DEVICES_OVERRIDE
    raw = os.environ.get("PILOSA_DEVICE_MESH_DEVICES", "")
    if raw:
        try:
            v = int(raw)
            if v >= 1:
                return v
        except ValueError:
            pass
    return _parse_mesh_shape(os.environ.get("PILOSA_TPU_MESH_SHAPE", ""))


@functools.lru_cache(maxsize=16)
def _participating_devices(cap: int | None, n_local: int) -> tuple:
    """The device tuple for slice placement under a device-count cap —
    cached so the per-slice hot paths don't re-derive it."""
    n = n_local if cap is None else min(n_local, cap)
    return tuple(jax.local_devices()[:n])


def participating_devices() -> tuple:
    return _participating_devices(_mesh_devices_cap(), len(jax.local_devices()))


def mesh_device_count() -> int:
    """Local devices participating in slice placement and the slices
    mesh.  The ``[device] mesh-devices`` config (env
    ``PILOSA_DEVICE_MESH_DEVICES``; 0 = all visible, 1 = force
    single-device) caps it, as does the legacy ``tpu.mesh-shape``
    (``PILOSA_TPU_MESH_SHAPE``, e.g. "4" or "4x2" — the product of the
    factors); default all local devices.  With >1 participating device
    the mesh-sharded data plane engages BY DEFAULT
    (parallel/mesh.default_slices_mesh)."""
    return len(participating_devices())


def home_device(slice_i: int):
    """The device that owns a slice's fragment planes: ``slice mod
    n_devices`` — the in-host analog of the reference's slice->node
    placement (reference: cluster.go:202-216).  Lives here (not in
    parallel/) so the storage layer can pin planes without pulling in
    the mesh/planner machinery; parallel/mesh.py builds its sharded
    batches around the same mapping."""
    devs = participating_devices()
    return devs[slice_i % len(devs)]


# ---------------------------------------------------------------------------
# Per-plane-row container formats (the on-device roaring analog,
# ROADMAP item 2).  A sparse-tier row is encoded at write time into
# the cheapest of three layouts — mirroring the reference's
# dense-bitmap / sorted-array / run containers, selected by density
# (reference: roaring.go container conversion thresholds):
#
#   FMT_DENSE   uint32[WORDS_PER_SLICE] words           128 KiB always
#   FMT_SPARSE  sorted uint32 positions                 4 B / position
#   FMT_RLE     sorted (start, end) uint32 runs         8 B / run
#
# Sparse and RLE payloads are sentinel-padded (FMT_SENTINEL, which is
# > any slice position) up to their pow2 payload bucket so compiled
# programs key on a bounded bucket grid, never on raw cardinality.
# The fused kernels consume the payloads DIRECTLY (membership_* below
# gather against the compressed layout); dense expansion exists only
# as a transient for paths that must stack whole rows.
# ---------------------------------------------------------------------------

FMT_DENSE = 0
FMT_SPARSE = 1
FMT_RLE = 2
FMT_NAMES = {FMT_DENSE: "dense", FMT_SPARSE: "sparse", FMT_RLE: "rle"}

# Padding sentinel: all-ones is > any real position (< SLICE_WIDTH =
# 2^20) and sorts after every real payload entry.
FMT_SENTINEL = 0xFFFFFFFF

# Floor of the payload pow2 bucket grid (64 positions = 256 B, 64 runs
# = 512 B): tiny rows share one bucket instead of spraying compiles.
PAYLOAD_BUCKET_FLOOR = 64

# ``[device] plane-format``: "auto" selects per row by encoded bytes,
# "dense" disables compression (the contrast arm and the escape hatch).
# Set by Server.open from config; module-level like scatter.ENABLED so
# fragments see it without per-fragment plumbing.
PLANE_FORMAT = "auto"

# Per-row encoded-size caps ([device] plane-sparse-max-bytes /
# plane-rle-max-bytes): a format is eligible only while its BUCKETED
# payload fits the cap — the roaring "array container only below 4096
# entries" rule, expressed in bytes.  Default half a dense row, so any
# compressed row is at least a 2x save.
SPARSE_MAX_BYTES = 65536
RLE_MAX_BYTES = 65536


def configure_plane_format(
    mode: str | None = None,
    sparse_max_bytes: int | None = None,
    rle_max_bytes: int | None = None,
) -> None:
    """Apply ``[device] plane-format`` / threshold config process-wide
    (Server.open; tests and the sparse bench flip it for contrast
    arms).  Selection is write-time only: already-encoded device
    payloads keep their format until invalidated."""
    global PLANE_FORMAT, SPARSE_MAX_BYTES, RLE_MAX_BYTES
    if mode is not None:
        if mode not in ("auto", "dense"):
            raise ValueError(f"unknown plane-format {mode!r}")
        PLANE_FORMAT = mode
    if sparse_max_bytes is not None:
        SPARSE_MAX_BYTES = max(0, int(sparse_max_bytes))
    if rle_max_bytes is not None:
        RLE_MAX_BYTES = max(0, int(rle_max_bytes))


def payload_bucket(n: int) -> int:
    """Pow2 payload-length bucket (entries, not bytes) with the shared
    floor — the container-length shape class compiled programs key on."""
    return pow2_bucket(n, PAYLOAD_BUCKET_FLOOR)


def np_positions_to_runs(offsets: np.ndarray) -> np.ndarray:
    """Sorted positions -> (R, 2) uint32 half-open maximal runs."""
    o = np.asarray(offsets, dtype=np.uint32)
    if len(o) == 0:
        return np.zeros((0, 2), dtype=np.uint32)
    brk = np.nonzero(np.diff(o) != 1)[0]
    starts = o[np.concatenate(([0], brk + 1))]
    ends = o[np.concatenate((brk, [len(o) - 1]))].astype(np.uint64) + 1
    return np.stack([starts, ends.astype(np.uint32)], axis=1)


def encode_row(offsets: np.ndarray) -> tuple[int, np.ndarray, int]:
    """Write-time format selection for one sparse-tier row: encode the
    sorted in-slice positions into the cheapest eligible container and
    return ``(fmt, payload, encoded_nbytes)``.  Deterministic: minimum
    bucketed bytes wins, ties broken toward the lower format tag
    (dense < sparse < rle)."""
    offs = np.asarray(offsets, dtype=np.uint32)
    card = len(offs)
    dense_b = WORDS_PER_SLICE * 4
    cands = [(dense_b, FMT_DENSE)]
    if PLANE_FORMAT != "dense":
        sparse_b = 4 * payload_bucket(card)
        if sparse_b < dense_b and sparse_b <= SPARSE_MAX_BYTES:
            cands.append((sparse_b, FMT_SPARSE))
        runs = np_positions_to_runs(offs)
        rle_b = 8 * payload_bucket(len(runs))
        if rle_b < dense_b and rle_b <= RLE_MAX_BYTES:
            cands.append((rle_b, FMT_RLE))
    nbytes, fmt = min(cands)
    if fmt == FMT_SPARSE:
        payload = np.full(payload_bucket(card), FMT_SENTINEL, dtype=np.uint32)
        payload[:card] = offs
    elif fmt == FMT_RLE:
        runs = np_positions_to_runs(offs)
        payload = np.full(
            (payload_bucket(len(runs)), 2), FMT_SENTINEL, dtype=np.uint32
        )
        payload[: len(runs)] = runs
    else:
        payload = np_columns_to_row(offs)
    return fmt, payload, nbytes


def decode_payload(fmt: int, payload: np.ndarray) -> np.ndarray:
    """Host inverse of encode_row: any container payload -> dense row
    words (the byte-identity oracle for the codec tests)."""
    if fmt == FMT_DENSE:
        return np.asarray(payload, dtype=np.uint32)
    if fmt == FMT_SPARSE:
        p = np.asarray(payload, dtype=np.uint32)
        return np_columns_to_row(p[p != np.uint32(FMT_SENTINEL)])
    if fmt == FMT_RLE:
        p = np.asarray(payload, dtype=np.uint32).reshape(-1, 2)
        real = p[p[:, 0] != np.uint32(FMT_SENTINEL)]
        if len(real) == 0:
            return empty_row()
        pos = np.concatenate(
            [np.arange(s, e, dtype=np.uint32) for s, e in real]
        )
        return np_columns_to_row(pos)
    raise ValueError(f"unknown container format {fmt!r}")


# --- format-aware membership kernels ---------------------------------------
# Each takes one row's payload plus a sentinel-padded uint32 position
# vector and answers "is position p set?" per lane, reading only the
# compressed layout.  Sentinel lanes may answer garbage (the sparse
# kernel answers True: sentinel == sentinel pad); callers mask invalid
# lanes before reducing.  These are traced inside plan's anchored
# programs (vmapped over the slice axis), never jitted standalone.


def membership_dense(row, pos):
    w = jnp.minimum(
        pos >> jnp.uint32(5), jnp.uint32(WORDS_PER_SLICE - 1)
    ).astype(jnp.int32)
    return ((row[w] >> (pos & jnp.uint32(31))) & jnp.uint32(1)).astype(bool)


def membership_sparse(payload, pos):
    i = jnp.searchsorted(payload, pos)
    i = jnp.minimum(i, payload.shape[0] - 1)
    return payload[i] == pos


def membership_rle(payload, pos):
    starts = payload[:, 0]
    i = jnp.searchsorted(starts, pos, side="right").astype(jnp.int32) - 1
    ic = jnp.maximum(i, 0)
    return (i >= 0) & (pos < payload[ic, 1])


# --- transient dense expansion ---------------------------------------------
# For paths that must stack whole rows (the mesh gather path batches
# device_row results into dense leaf stacks), a resident compressed
# payload expands on device in one jitted scatter; the expansion is
# NEVER cached — the pool holds only the payload bytes.  Compiles key
# on the payload bucket (bounded grid, see program_cache_bounds).


@jax.jit
def _expand_sparse_xla(payload):
    idx = (payload >> jnp.uint32(5)).astype(jnp.int32)
    masks = jnp.uint32(1) << (payload & jnp.uint32(31))
    # Positions are unique, so per-word masks have disjoint bits and
    # scatter-add equals scatter-or; sentinel lanes index past the row
    # and drop.
    return jnp.zeros(WORDS_PER_SLICE, dtype=jnp.uint32).at[idx].add(
        masks, mode="drop"
    )


def _rle_lowmask(n):
    """uint32 mask of the low ``n`` bits, n in [0, 32]."""
    n32 = n.astype(jnp.uint32)
    return jnp.where(
        n32 >= jnp.uint32(32),
        jnp.uint32(0xFFFFFFFF),
        (jnp.uint32(1) << n32) - jnp.uint32(1),
    )


@jax.jit
def _expand_rle_xla(payload):
    s = payload[:, 0]
    e = payload[:, 1]
    w0 = (s >> jnp.uint32(5)).astype(jnp.int32)
    wl = ((e - jnp.uint32(1)) >> jnp.uint32(5)).astype(jnp.int32)
    b0 = s & jnp.uint32(31)
    bl = (e - jnp.uint32(1)) & jnp.uint32(31)
    same = w0 == wl
    # Boundary-word masks; runs are disjoint and maximal so masks
    # landing in a shared word have disjoint bits (add == or).
    # Sentinel runs (start == end == FMT_SENTINEL) produce zero masks
    # and out-of-range indices, which drop.
    m0 = _rle_lowmask(
        jnp.where(same, bl + jnp.uint32(1), jnp.uint32(32))
    ) & ~_rle_lowmask(b0)
    ml = jnp.where(same, jnp.uint32(0), _rle_lowmask(bl + jnp.uint32(1)))
    row = jnp.zeros(WORDS_PER_SLICE, dtype=jnp.uint32)
    row = row.at[w0].add(m0, mode="drop")
    row = row.at[wl].add(ml, mode="drop")
    # Interior full words via a +1/-1 difference array over word index.
    has_interior = (wl > w0 + 1).astype(jnp.int32)
    d = jnp.zeros(WORDS_PER_SLICE + 1, dtype=jnp.int32)
    d = d.at[w0 + 1].add(has_interior, mode="drop")
    d = d.at[wl].add(-has_interior, mode="drop")
    cover = jnp.cumsum(d)[:WORDS_PER_SLICE] > 0
    return row | jnp.where(cover, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))


def expand_payload(fmt: int, payload):
    """Transient dense expansion of a device-resident compressed
    payload (mesh gather path).  FMT_DENSE payloads pass through."""
    if fmt == FMT_DENSE:
        return payload
    _note_shape(expand_payload=int(payload.shape[0]))
    if fmt == FMT_SPARSE:
        return _expand_sparse_xla(payload)
    if fmt == FMT_RLE:
        return _expand_rle_xla(payload)
    raise ValueError(f"unknown container format {fmt!r}")


# Pallas history (BASELINE.md "Pallas keep-or-kill"): the r02 tile-naive
# kernels measured 4x slower than XLA's fused popcount+reduce and the
# r03 restructured kernels (tile-aligned (8,128) lane partials) measured
# 0.068x plain XLA, so the experiment was deleted.  BENCH_r05 then
# showed the XLA path itself leaving bandwidth on the table (raw
# and+popcount 390.5 GB/s = 64.8% of the measured 602.8 GB/s stream
# floor), which re-chartered the attempt with two specific fixes the
# killed kernels lacked (ROADMAP item 2): (a) the reduce is
# restructured into per-chunk int32 limb partials so the accumulator
# lives in registers instead of a materialized full-size popcount
# array, and (b) the hand kernel keeps whole 128 KiB slice-rows per
# VMEM block (grid-pipelined HBM->VMEM double buffering) rather than
# (8,128) lane tiles.  The Pallas variant engages ONLY where the
# backend supports it (TPU, or forced via DENSE_KERNEL) and any
# lowering failure permanently falls back to XLA for the process —
# CPU/GPU and older jaxlibs never see it.

# "auto" = Pallas on TPU backends, XLA elsewhere; "xla" / "pallas"
# force one path (bench contrast arms; PILOSA_DENSE_KERNEL env via
# Server wiring is not needed — this is a perf toggle, not semantics).
DENSE_KERNEL = "auto"
_PALLAS_FAILED = False

# Words per limb partial in the restructured count reduce: one roaring
# container (2048 words = 2^16 bits) per int32 partial keeps every
# accumulator exact and register-resident.
_COUNT_CHUNK = WORDS_PER_CONTAINER

# Slice-rows per Pallas VMEM block: 8 x 128 KiB x 2 operands = 2 MiB
# resident per grid step, well under v5e's ~16 MiB VMEM with double
# buffering.
_PALLAS_TILE_ROWS = 8


def _popcount_sum_chunked(words: jnp.ndarray) -> jnp.ndarray:
    """Restructured popcount reduce: per-chunk int32 limb partials
    (each <= 2^16 bits, register-accumulated) then one small partial
    sum — no full-size popcount intermediate between the bitwise op
    and the reduce.  Falls back to the flat reduce for shapes that
    don't tile by _COUNT_CHUNK (tiny probe arrays)."""
    flat = words.reshape(-1)
    n = flat.shape[0]
    if n <= _COUNT_CHUNK or n % _COUNT_CHUNK:
        return _popcount_sum(flat)
    limbs = jnp.sum(
        jax.lax.population_count(flat.reshape(-1, _COUNT_CHUNK)).astype(
            jnp.int32
        ),
        axis=1,
    )
    return jnp.sum(limbs)


@jax.jit
def _count_xla(words):
    return _popcount_sum_chunked(words)


def count(words):
    """Popcount of a row/plane (reference: popcntSliceAsm)."""
    return _count_xla(words)


@functools.partial(jax.jit, static_argnames=("op",))
def _fused_count_xla(a, b, op):
    if op == "and":
        return _popcount_sum_chunked(a & b)
    if op == "or":
        return _popcount_sum_chunked(a | b)
    if op == "xor":
        return _popcount_sum_chunked(a ^ b)
    if op == "andnot":
        return _popcount_sum_chunked(a & ~b)
    raise ValueError(f"unknown fused-count op {op!r}")


def _pallas_count_kernel(op: str):
    def kernel(a_ref, b_ref, o_ref):
        a = a_ref[...]
        b = b_ref[...]
        if op == "and":
            x = a & b
        elif op == "or":
            x = a | b
        elif op == "xor":
            x = a ^ b
        else:
            x = a & ~b
        o_ref[0, 0] = jnp.sum(jax.lax.population_count(x).astype(jnp.int32))

    return kernel


@functools.partial(jax.jit, static_argnames=("op",))
def _fused_count_pallas(a, b, op):
    """Hand-written and+popcount reduce: whole slice-rows stream
    HBM->VMEM per grid step (Pallas double-buffers the blocks), the
    bitwise op + popcount + block reduce run on the resident block,
    and one int32 partial per step lands in HBM.  Raises for shapes
    that don't tile into whole slice-rows — the caller falls back."""
    from jax.experimental import pallas as pl

    n = a.size
    if n % WORDS_PER_SLICE:
        raise ValueError("pallas count needs whole slice-rows")
    rows = n // WORDS_PER_SLICE
    tile = min(_PALLAS_TILE_ROWS, rows)
    if rows % tile:
        raise ValueError("pallas count needs a row multiple of the tile")
    a2 = a.reshape(rows, WORDS_PER_SLICE)
    b2 = b.reshape(rows, WORDS_PER_SLICE)
    grid = rows // tile
    partials = pl.pallas_call(
        _pallas_count_kernel(op),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((tile, WORDS_PER_SLICE), lambda i: (i, 0)),
            pl.BlockSpec((tile, WORDS_PER_SLICE), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((grid, 1), jnp.int32),
    )(a2, b2)
    return jnp.sum(partials)


def _use_pallas() -> bool:
    if DENSE_KERNEL == "xla" or _PALLAS_FAILED:
        return False
    if DENSE_KERNEL == "pallas":
        return True
    return jax.default_backend() == "tpu"


def _fused_count(a, b, op):
    global _PALLAS_FAILED
    if _use_pallas():
        try:
            return _fused_count_pallas(a, b, op)
        except Exception:  # noqa: BLE001 — lowering/backend failure
            # One-time demotion: the XLA path is byte-identical, so a
            # backend that can't lower the hand kernel silently keeps
            # the fallback for the rest of the process.
            _PALLAS_FAILED = True
    return _fused_count_xla(a, b, op)


def count_and(a, b):
    """|a AND b| without materializing (reference: intersectionCount*,
    roaring/roaring.go:1259-1347, popcntAndSliceAsm)."""
    return _fused_count(a, b, "and")


def count_or(a, b):
    return _fused_count(a, b, "or")


def count_xor(a, b):
    return _fused_count(a, b, "xor")


def count_andnot(a, b):
    """|a AND NOT b| (reference: popcntMaskSliceAsm / differenceCount)."""
    return _fused_count(a, b, "andnot")


# Materializing set algebra (reference: roaring/roaring.go:345-474 dispatch,
# 1349-1716 kernels) — a single vector op on the dense plane.


@jax.jit
def and_(a, b):
    return a & b


@jax.jit
def or_(a, b):
    return a | b


@jax.jit
def xor(a, b):
    return a ^ b


@jax.jit
def andnot(a, b):
    return a & ~b


def _range_mask(n: int, start, end) -> jnp.ndarray:
    """uint32[n] word masks selecting bit positions in [start, end).

    Built word-by-word (not per-bit) so XLA fuses it into the consuming
    bitwise op.  start/end fit comfortably in int32 (SLICE_WIDTH = 2^20).
    """
    lo = jnp.arange(n, dtype=jnp.int32) * WORD_BITS
    s = jnp.clip(start - lo, 0, WORD_BITS).astype(jnp.uint32)
    e = jnp.clip(end - lo, 0, WORD_BITS).astype(jnp.uint32)
    width = jnp.maximum(e.astype(jnp.int32) - s.astype(jnp.int32), 0).astype(jnp.uint32)
    full = jnp.uint32(0xFFFFFFFF)
    base = jnp.where(width == 32, full, (jnp.uint32(1) << width) - jnp.uint32(1))
    return (base << s).astype(jnp.uint32)


@jax.jit
def flip_range(words, start, end):
    """Negate bits in [start, end) of a flat word array (reference:
    roaring.Bitmap.Flip, roaring/roaring.go:708-734)."""
    return words ^ _range_mask(words.shape[-1], start, end)


@jax.jit
def count_range(words, start, end):
    """Count set bits with positions in [start, end) (reference:
    roaring.Bitmap.CountRange, roaring/roaring.go:195-249)."""
    return _popcount_sum(words & _range_mask(words.shape[-1], start, end))


@jax.jit
def row_counts(plane):
    """Per-row popcounts of a plane -> int32[rows] (rebuilds the ranked
    cache after imports; reference: fragment.go:244-282 openCache recount)."""
    return jnp.sum(jax.lax.population_count(plane).astype(jnp.int32), axis=-1)


@jax.jit
def _top_counts_xla(plane, src_row):
    return jnp.sum(
        jax.lax.population_count(plane & src_row[None, :]).astype(jnp.int32), axis=-1
    )


# Largest bucketed dimension each scorer family has seen — the inputs to
# the hard cardinality bounds (exec/plan.program_cache_bounds): every
# dimension below is pow2-bucketed by the callers, so a family's compiled
# entry count can never exceed the product of its dimensions' class
# counts.  Plain dict writes (no lock): racing writers both store valid
# maxima and the bound is re-derived per read.
_SHAPE_HIGHWATER: dict[str, int] = {}


def _note_shape(**dims: int) -> None:
    for k, v in dims.items():
        if v > _SHAPE_HIGHWATER.get(k, 0):
            _SHAPE_HIGHWATER[k] = v


def shape_highwater() -> dict[str, int]:
    return dict(_SHAPE_HIGHWATER)


def top_counts(plane, src_row):
    """Per-row |row AND src| -> int32[rows]: the batched TopN(Src=...) scorer.

    The reference prunes candidates sequentially with cache-threshold early
    termination (reference: fragment.go:601-627); on TPU we instead score
    every row in one fused batched kernel and select on the host — same
    results, hardware-shaped loop structure.
    """
    _note_shape(top_rows=int(plane.shape[0]))
    return _top_counts_xla(plane, src_row)


@jax.jit
def _score_planes_self_src(planes, slots, src_slots):
    outs = []
    for f in range(len(planes)):
        rows = planes[f][slots[f]]
        src = planes[f][src_slots[f]]
        outs.append(
            jnp.sum(
                jax.lax.population_count(rows & src[None, :]).astype(jnp.int32),
                axis=-1,
            )
        )
    return jnp.stack(outs)


@jax.jit
def _score_planes_host_src(planes, slots, srcs):
    outs = []
    for f in range(len(planes)):
        rows = planes[f][slots[f]]
        outs.append(
            jnp.sum(
                jax.lax.population_count(rows & srcs[f][None, :]).astype(
                    jnp.int32
                ),
                axis=-1,
            )
        )
    return jnp.stack(outs)


def score_planes(planes, slots, src_slots=None, srcs=None):
    """Cross-fragment TopN scorer that reads STRAIGHT from the
    fragments' HBM-resident plane mirrors — no stacked candidate copy
    ever materializes (a stacked batch doubled the candidate rows'
    device footprint and tripped OOM at 100 slices x 256 candidates).

    ``planes``: tuple of uint32[plane_rows, words] device mirror
    SNAPSHOTS; ``slots``: int32[n_frag, rows] candidate slot indices
    (one small transfer); the src is either ``src_slots`` int32[n_frag]
    — the src row's slot in the SAME plane (the common
    TopN(Bitmap(frame=f), frame=f) shape; zero src bytes host->device,
    and no extra leaf shapes enter the jit key) — or ``srcs``
    uint32[n_frag, words] host-snapshot rows.  Gathers fuse into the
    popcount reduce, so each candidate row is read once.  Returns
    int32[n_frag, rows].  One dispatch + one fetch per query where the
    per-fragment path paid a dispatch, a src transfer, and a fetch PER
    SLICE (444 ms/query at 100 slices through the tunnel).

    Every dimension of the jit key is pow2-bucketed by the callers —
    fragment count (executor group padding), plane rows (pad_rows at
    plane allocation), candidate slots (pad_rows at prepare) — so the
    compiled-program count is bounded by the product of the classes,
    not by how many distinct fragment shapes the schema churns through.
    """
    _note_shape(
        score_frags=len(planes),
        score_rows=max(int(p.shape[0]) for p in planes),
        score_slots=int(slots.shape[-1]),
    )
    if srcs is None:
        return _score_planes_self_src(planes, slots, src_slots)
    return _score_planes_host_src(planes, slots, srcs)


@functools.partial(jax.jit, static_argnames=("k",))
def top_k(counts, k: int):
    """Top-k (count, rowID) by count descending — ties broken by smaller row
    id first, matching the reference's Pair sort (reference: cache.go:316-330).
    """
    kk = min(k, counts.shape[0])
    # lax.top_k breaks ties toward the lower index, which matches the
    # reference's Pair ordering (count desc, then smaller row id).
    topc, topidx = jax.lax.top_k(counts, kk)
    return topc, topidx


def batch_rows(rows: list[np.ndarray]) -> np.ndarray:
    """Stack slice-rows for batched device transfer."""
    return np.stack(rows) if rows else np.zeros((0, WORDS_PER_SLICE), np.uint32)


def np_group_by(keys: np.ndarray, *arrays: np.ndarray):
    """Yield ``(key, (aligned subarrays...))`` per unique key: ONE stable
    sort plus contiguous slicing — O(n log n) regardless of key
    cardinality, where a per-key boolean mask would re-scan the full
    array per key.  Used by the bulk-import slice grouping."""
    order = np.argsort(keys, kind="stable")
    sk = keys[order]
    sorted_arrays = [a[order] for a in arrays]
    uniq, starts = np.unique(sk, return_index=True)
    bounds = np.append(starts, len(sk))
    for i, k in enumerate(uniq):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        yield int(k), tuple(a[lo:hi] for a in sorted_arrays)
