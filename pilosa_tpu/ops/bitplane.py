"""Dense bit-plane representation and XLA bitmap ops.

The unit of storage is a *slice-row*: one row of one fragment, covering
SLICE_WIDTH = 2^20 columns, stored as 32,768 uint32 words (128 KiB).  A
fragment is a plane of shape (rows, WORDS_PER_SLICE).  Bit ``i`` of a
slice-row (column ``slice*SLICE_WIDTH + i``) lives at word ``i >> 5``,
bit ``i & 31`` (little-endian within the word, matching the reference's
roaring bitmap-container layout where word ``w`` holds values
``[w*64, w*64+64)`` — we use uint32 words because TPUs have no uint64).

These functions replace the reference's per-container sorted-merge kernels
and popcount assembly (reference: roaring/roaring.go:1259-1716,
roaring/assembly_amd64.s) with whole-row vector ops: XLA fuses the bitwise
op into the popcount reduce, so ``count_and`` etc. never materialize the
intermediate row in HBM as one fused bitwise+popcount+reduce pass.

All counts are returned as int32 device scalars (a slice-row holds at most
2^20 bits, and a full plane reduce stays far below 2^31); callers accumulate
cross-slice totals in Python ints.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

# Matches the reference: SliceWidth = 2^20 (reference: fragment.go:47).
SLICE_WIDTH = 1 << 20
WORD_BITS = 32
WORDS_PER_SLICE = SLICE_WIDTH // WORD_BITS  # 32768 words = 128 KiB
# A roaring container spans 2^16 bits (reference: roaring/roaring.go:36).
CONTAINER_BITS = 1 << 16
WORDS_PER_CONTAINER = CONTAINER_BITS // WORD_BITS  # 2048
CONTAINERS_PER_SLICE = SLICE_WIDTH // CONTAINER_BITS  # 16

# Rows are padded to power-of-two shape classes (floor ROW_BLOCK) so
# query shapes bucket into a LOG-bounded set of compiled programs.  The
# former multiple-of-8 padding kept single-row growth from recompiling,
# but a churny schema still minted a fresh XLA program every 8 rows
# (~326 ms each, VERDICT item 3): plane mirrors and candidate slot
# arrays both enter jit keys by shape, so their shape-class count IS the
# compiled-program cardinality.  pow2 classes bound it at
# log2(rows/ROW_BLOCK)+1 regardless of how many distinct fragment
# shapes the schema produces.
ROW_BLOCK = 8


def row_shape() -> tuple[int]:
    return (WORDS_PER_SLICE,)


def empty_row() -> np.ndarray:
    return np.zeros(WORDS_PER_SLICE, dtype=np.uint32)


def empty_plane(rows: int) -> np.ndarray:
    return np.zeros((rows, WORDS_PER_SLICE), dtype=np.uint32)


def pow2_bucket(n: int, floor: int = 1) -> int:
    """Round ``n`` up to the next power of two, at least ``floor``."""
    if n <= floor:
        return floor
    return 1 << (n - 1).bit_length()


def bucket_classes(hi: int, floor: int = 1) -> int:
    """How many distinct pow2 shape classes cover sizes in [1, hi] —
    the hard bound on compiled-program cardinality per bucketed
    dimension (exec/plan.program_cache_bounds)."""
    if hi <= floor:
        return 1
    return (pow2_bucket(hi, floor) // floor).bit_length()


def pad_rows(rows: int) -> int:
    """Round a row count up to its pow2 shape class (floor ROW_BLOCK)."""
    return pow2_bucket(rows, ROW_BLOCK)


# ---------------------------------------------------------------------------
# Host-side (numpy) bit manipulation — the write path.  Mutations happen on
# the host-resident authoritative plane; device mirrors are refreshed lazily
# (see core/fragment.py).
# ---------------------------------------------------------------------------


def np_set_bit(plane: np.ndarray, bit: int) -> bool:
    """Set bit ``bit`` (a fragment position: row*SLICE_WIDTH + col%SLICE_WIDTH
    flattened into the plane).  Returns True if the bit changed."""
    row, offset = divmod(bit, SLICE_WIDTH)
    word, shift = divmod(offset, WORD_BITS)
    mask = np.uint32(1 << shift)
    old = plane[row, word]
    if old & mask:
        return False
    plane[row, word] = old | mask
    return True


def np_clear_bit(plane: np.ndarray, bit: int) -> bool:
    row, offset = divmod(bit, SLICE_WIDTH)
    word, shift = divmod(offset, WORD_BITS)
    mask = np.uint32(1 << shift)
    old = plane[row, word]
    if not (old & mask):
        return False
    plane[row, word] = old & ~mask
    return True


def np_contains(plane: np.ndarray, bit: int) -> bool:
    row, offset = divmod(bit, SLICE_WIDTH)
    word, shift = divmod(offset, WORD_BITS)
    return bool((int(plane[row, word]) >> shift) & 1)


def np_set_bulk(plane: np.ndarray, rows: np.ndarray, offsets: np.ndarray) -> None:
    """Bulk set: vectorized scatter-OR for imports (reference:
    fragment.go:936-1004 bulk Import path)."""
    words = offsets // WORD_BITS
    masks = (np.uint32(1) << (offsets % WORD_BITS).astype(np.uint32)).astype(np.uint32)
    np.bitwise_or.at(plane, (rows, words), masks)


def np_clear_bulk(plane: np.ndarray, rows: np.ndarray, offsets: np.ndarray) -> None:
    """Bulk clear: vectorized scatter-ANDNOT — the overwrite half of a
    columnar BSI value import (a re-imported column must drop the stale
    bits of its previous value)."""
    words = offsets // WORD_BITS
    masks = (np.uint32(1) << (offsets % WORD_BITS).astype(np.uint32)).astype(np.uint32)
    np.bitwise_and.at(plane, (rows, words), ~masks)


def np_row_to_columns(row_words: np.ndarray) -> np.ndarray:
    """Expand one slice-row's set bits into sorted uint64 column offsets
    within the slice (0 .. SLICE_WIDTH)."""
    bits = np.unpackbits(
        np.ascontiguousarray(row_words).view(np.uint8), bitorder="little"
    )
    (positions,) = np.nonzero(bits)
    return positions.astype(np.uint64)


def np_columns_to_row(offsets: np.ndarray) -> np.ndarray:
    """Inverse of np_row_to_columns: bit offsets (within slice) -> row words."""
    row = empty_row()
    if len(offsets) == 0:
        return row
    offsets = np.asarray(offsets, dtype=np.uint64)
    words = (offsets // WORD_BITS).astype(np.int64)
    masks = (np.uint32(1) << (offsets % WORD_BITS).astype(np.uint32)).astype(np.uint32)
    np.bitwise_or.at(row, words, masks)
    return row


if hasattr(np, "bitwise_count"):  # numpy >= 2.0

    def np_count(words: np.ndarray) -> int:
        """Host popcount (the CPU reference path, equivalent of the
        reference's pure-Go popcntSlice fallback, reference:
        roaring/assembly.go:21-28)."""
        return int(np.bitwise_count(words).sum())

    def np_row_counts(plane: np.ndarray) -> np.ndarray:
        """Host per-row popcounts (cache maintenance without a device trip)."""
        return np.bitwise_count(plane).sum(axis=-1, dtype=np.int64)

else:  # pragma: no cover - numpy 1.x fallback

    def np_count(words: np.ndarray) -> int:
        return int(np.unpackbits(np.ascontiguousarray(words).view(np.uint8)).sum())

    def np_row_counts(plane: np.ndarray) -> np.ndarray:
        return (
            np.unpackbits(np.ascontiguousarray(plane).view(np.uint8), axis=-1)
            .sum(axis=-1, dtype=np.int64)
        )


# ---------------------------------------------------------------------------
# Device ops (XLA).  Everything below is jit-compiled; shapes are static per
# (rows,) bucket.  These are the hot kernels: the equivalents of the
# reference's popcntAndSlice/popcntOrSlice/popcntXorSlice asm procs and the
# materializing container merges.
# ---------------------------------------------------------------------------


def _popcount_sum(words: jnp.ndarray) -> jnp.ndarray:
    """Sum of set bits over the whole array -> int32 scalar."""
    return jnp.sum(jax.lax.population_count(words).astype(jnp.int32))


@functools.lru_cache(maxsize=8)
def _parse_mesh_shape(shape: str) -> int | None:
    """Device cap from a mesh-shape string ("4", "4x2", ...); None when
    unset, malformed, or non-positive (a bad value must never silently
    disable sharding)."""
    factors = shape.lower().replace("x", " ").split()
    if not factors:
        return None
    try:
        want = 1
        for f in factors:
            want *= int(f)
    except ValueError:
        return None
    return want if want >= 1 else None


# [device] mesh-devices override (Server.open / bench / tests): the
# process-global device-count cap for slice placement and the slices
# mesh.  0 = unset (fall through to the envs, default all visible
# devices); 1 = force the single-device data plane; N caps the mesh.
_MESH_DEVICES_OVERRIDE = 0


def configure_mesh_devices(n: int) -> None:
    """Set (or with 0, clear) the process-wide ``[device] mesh-devices``
    cap.  Placement is process-global state — in-process multi-server
    setups (tests, bench grids) share whatever the last caller set."""
    global _MESH_DEVICES_OVERRIDE
    _MESH_DEVICES_OVERRIDE = max(0, int(n))


def _mesh_devices_cap() -> int | None:
    """The effective device cap: explicit configure_mesh_devices wins,
    then ``PILOSA_DEVICE_MESH_DEVICES`` (0 = all visible), then the
    legacy ``PILOSA_TPU_MESH_SHAPE`` factor product; None = uncapped.
    Malformed values never silently disable sharding."""
    if _MESH_DEVICES_OVERRIDE > 0:
        return _MESH_DEVICES_OVERRIDE
    raw = os.environ.get("PILOSA_DEVICE_MESH_DEVICES", "")
    if raw:
        try:
            v = int(raw)
            if v >= 1:
                return v
        except ValueError:
            pass
    return _parse_mesh_shape(os.environ.get("PILOSA_TPU_MESH_SHAPE", ""))


@functools.lru_cache(maxsize=16)
def _participating_devices(cap: int | None, n_local: int) -> tuple:
    """The device tuple for slice placement under a device-count cap —
    cached so the per-slice hot paths don't re-derive it."""
    n = n_local if cap is None else min(n_local, cap)
    return tuple(jax.local_devices()[:n])


def participating_devices() -> tuple:
    return _participating_devices(_mesh_devices_cap(), len(jax.local_devices()))


def mesh_device_count() -> int:
    """Local devices participating in slice placement and the slices
    mesh.  The ``[device] mesh-devices`` config (env
    ``PILOSA_DEVICE_MESH_DEVICES``; 0 = all visible, 1 = force
    single-device) caps it, as does the legacy ``tpu.mesh-shape``
    (``PILOSA_TPU_MESH_SHAPE``, e.g. "4" or "4x2" — the product of the
    factors); default all local devices.  With >1 participating device
    the mesh-sharded data plane engages BY DEFAULT
    (parallel/mesh.default_slices_mesh)."""
    return len(participating_devices())


def home_device(slice_i: int):
    """The device that owns a slice's fragment planes: ``slice mod
    n_devices`` — the in-host analog of the reference's slice->node
    placement (reference: cluster.go:202-216).  Lives here (not in
    parallel/) so the storage layer can pin planes without pulling in
    the mesh/planner machinery; parallel/mesh.py builds its sharded
    batches around the same mapping."""
    devs = participating_devices()
    return devs[slice_i % len(devs)]


# There is NO handwritten-Pallas variant of these kernels: two rounds
# of measurement on real v5e hardware killed it.  The r02 tile-naive
# kernels measured 4x slower than XLA's fused popcount+reduce; the r03
# restructured kernels (tile-aligned (8,128) lane partials) measured
# 0.068x plain XLA (7.5 ms vs 0.51 ms per 1B-column fused
# Intersect+Count, fetch-folded slope methodology, tools/cache_probe.py).
# XLA already emits a single fused bitwise+popcount+reduce pass at
# ~490 GB/s ≈ 60% of v5e HBM peak; a hand kernel has no headroom worth
# its maintenance, so the experiment ended per the promote-or-delete
# bar (BASELINE.md "Pallas keep-or-kill").


@jax.jit
def _count_xla(words):
    return _popcount_sum(words)


def count(words):
    """Popcount of a row/plane (reference: popcntSliceAsm)."""
    return _count_xla(words)


@functools.partial(jax.jit, static_argnames=("op",))
def _fused_count_xla(a, b, op):
    if op == "and":
        return _popcount_sum(a & b)
    if op == "or":
        return _popcount_sum(a | b)
    if op == "xor":
        return _popcount_sum(a ^ b)
    if op == "andnot":
        return _popcount_sum(a & ~b)
    raise ValueError(f"unknown fused-count op {op!r}")


def _fused_count(a, b, op):
    return _fused_count_xla(a, b, op)


def count_and(a, b):
    """|a AND b| without materializing (reference: intersectionCount*,
    roaring/roaring.go:1259-1347, popcntAndSliceAsm)."""
    return _fused_count(a, b, "and")


def count_or(a, b):
    return _fused_count(a, b, "or")


def count_xor(a, b):
    return _fused_count(a, b, "xor")


def count_andnot(a, b):
    """|a AND NOT b| (reference: popcntMaskSliceAsm / differenceCount)."""
    return _fused_count(a, b, "andnot")


# Materializing set algebra (reference: roaring/roaring.go:345-474 dispatch,
# 1349-1716 kernels) — a single vector op on the dense plane.


@jax.jit
def and_(a, b):
    return a & b


@jax.jit
def or_(a, b):
    return a | b


@jax.jit
def xor(a, b):
    return a ^ b


@jax.jit
def andnot(a, b):
    return a & ~b


def _range_mask(n: int, start, end) -> jnp.ndarray:
    """uint32[n] word masks selecting bit positions in [start, end).

    Built word-by-word (not per-bit) so XLA fuses it into the consuming
    bitwise op.  start/end fit comfortably in int32 (SLICE_WIDTH = 2^20).
    """
    lo = jnp.arange(n, dtype=jnp.int32) * WORD_BITS
    s = jnp.clip(start - lo, 0, WORD_BITS).astype(jnp.uint32)
    e = jnp.clip(end - lo, 0, WORD_BITS).astype(jnp.uint32)
    width = jnp.maximum(e.astype(jnp.int32) - s.astype(jnp.int32), 0).astype(jnp.uint32)
    full = jnp.uint32(0xFFFFFFFF)
    base = jnp.where(width == 32, full, (jnp.uint32(1) << width) - jnp.uint32(1))
    return (base << s).astype(jnp.uint32)


@jax.jit
def flip_range(words, start, end):
    """Negate bits in [start, end) of a flat word array (reference:
    roaring.Bitmap.Flip, roaring/roaring.go:708-734)."""
    return words ^ _range_mask(words.shape[-1], start, end)


@jax.jit
def count_range(words, start, end):
    """Count set bits with positions in [start, end) (reference:
    roaring.Bitmap.CountRange, roaring/roaring.go:195-249)."""
    return _popcount_sum(words & _range_mask(words.shape[-1], start, end))


@jax.jit
def row_counts(plane):
    """Per-row popcounts of a plane -> int32[rows] (rebuilds the ranked
    cache after imports; reference: fragment.go:244-282 openCache recount)."""
    return jnp.sum(jax.lax.population_count(plane).astype(jnp.int32), axis=-1)


@jax.jit
def _top_counts_xla(plane, src_row):
    return jnp.sum(
        jax.lax.population_count(plane & src_row[None, :]).astype(jnp.int32), axis=-1
    )


# Largest bucketed dimension each scorer family has seen — the inputs to
# the hard cardinality bounds (exec/plan.program_cache_bounds): every
# dimension below is pow2-bucketed by the callers, so a family's compiled
# entry count can never exceed the product of its dimensions' class
# counts.  Plain dict writes (no lock): racing writers both store valid
# maxima and the bound is re-derived per read.
_SHAPE_HIGHWATER: dict[str, int] = {}


def _note_shape(**dims: int) -> None:
    for k, v in dims.items():
        if v > _SHAPE_HIGHWATER.get(k, 0):
            _SHAPE_HIGHWATER[k] = v


def shape_highwater() -> dict[str, int]:
    return dict(_SHAPE_HIGHWATER)


def top_counts(plane, src_row):
    """Per-row |row AND src| -> int32[rows]: the batched TopN(Src=...) scorer.

    The reference prunes candidates sequentially with cache-threshold early
    termination (reference: fragment.go:601-627); on TPU we instead score
    every row in one fused batched kernel and select on the host — same
    results, hardware-shaped loop structure.
    """
    _note_shape(top_rows=int(plane.shape[0]))
    return _top_counts_xla(plane, src_row)


@jax.jit
def _score_planes_self_src(planes, slots, src_slots):
    outs = []
    for f in range(len(planes)):
        rows = planes[f][slots[f]]
        src = planes[f][src_slots[f]]
        outs.append(
            jnp.sum(
                jax.lax.population_count(rows & src[None, :]).astype(jnp.int32),
                axis=-1,
            )
        )
    return jnp.stack(outs)


@jax.jit
def _score_planes_host_src(planes, slots, srcs):
    outs = []
    for f in range(len(planes)):
        rows = planes[f][slots[f]]
        outs.append(
            jnp.sum(
                jax.lax.population_count(rows & srcs[f][None, :]).astype(
                    jnp.int32
                ),
                axis=-1,
            )
        )
    return jnp.stack(outs)


def score_planes(planes, slots, src_slots=None, srcs=None):
    """Cross-fragment TopN scorer that reads STRAIGHT from the
    fragments' HBM-resident plane mirrors — no stacked candidate copy
    ever materializes (a stacked batch doubled the candidate rows'
    device footprint and tripped OOM at 100 slices x 256 candidates).

    ``planes``: tuple of uint32[plane_rows, words] device mirror
    SNAPSHOTS; ``slots``: int32[n_frag, rows] candidate slot indices
    (one small transfer); the src is either ``src_slots`` int32[n_frag]
    — the src row's slot in the SAME plane (the common
    TopN(Bitmap(frame=f), frame=f) shape; zero src bytes host->device,
    and no extra leaf shapes enter the jit key) — or ``srcs``
    uint32[n_frag, words] host-snapshot rows.  Gathers fuse into the
    popcount reduce, so each candidate row is read once.  Returns
    int32[n_frag, rows].  One dispatch + one fetch per query where the
    per-fragment path paid a dispatch, a src transfer, and a fetch PER
    SLICE (444 ms/query at 100 slices through the tunnel).

    Every dimension of the jit key is pow2-bucketed by the callers —
    fragment count (executor group padding), plane rows (pad_rows at
    plane allocation), candidate slots (pad_rows at prepare) — so the
    compiled-program count is bounded by the product of the classes,
    not by how many distinct fragment shapes the schema churns through.
    """
    _note_shape(
        score_frags=len(planes),
        score_rows=max(int(p.shape[0]) for p in planes),
        score_slots=int(slots.shape[-1]),
    )
    if srcs is None:
        return _score_planes_self_src(planes, slots, src_slots)
    return _score_planes_host_src(planes, slots, srcs)


@functools.partial(jax.jit, static_argnames=("k",))
def top_k(counts, k: int):
    """Top-k (count, rowID) by count descending — ties broken by smaller row
    id first, matching the reference's Pair sort (reference: cache.go:316-330).
    """
    kk = min(k, counts.shape[0])
    # lax.top_k breaks ties toward the lower index, which matches the
    # reference's Pair ordering (count desc, then smaller row id).
    topc, topidx = jax.lax.top_k(counts, kk)
    return topc, topidx


def batch_rows(rows: list[np.ndarray]) -> np.ndarray:
    """Stack slice-rows for batched device transfer."""
    return np.stack(rows) if rows else np.zeros((0, WORDS_PER_SLICE), np.uint32)


def np_group_by(keys: np.ndarray, *arrays: np.ndarray):
    """Yield ``(key, (aligned subarrays...))`` per unique key: ONE stable
    sort plus contiguous slicing — O(n log n) regardless of key
    cardinality, where a per-key boolean mask would re-scan the full
    array per key.  Used by the bulk-import slice grouping."""
    order = np.argsort(keys, kind="stable")
    sk = keys[order]
    sorted_arrays = [a[order] for a in arrays]
    uniq, starts = np.unique(sk, return_index=True)
    bounds = np.append(starts, len(sk))
    for i, k in enumerate(uniq):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        yield int(k), tuple(a[lo:hi] for a in sorted_arrays)
