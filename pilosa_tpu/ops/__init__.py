"""Bitmap kernel layer: dense bit-planes + fused XLA popcount kernels.

This package replaces the reference's roaring container ops and amd64
popcount assembly (reference: roaring/roaring.go:345-474,1259-1716 and
roaring/assembly_amd64.s) with TPU-native equivalents operating on dense
uint32 bit-planes.
"""

from pilosa_tpu.ops import bitplane
from pilosa_tpu.ops import roaring

__all__ = ["bitplane", "roaring"]
