"""Pallas TPU kernels for the fused bitwise-op + popcount reductions.

These are the TPU-native replacements for the reference's nine amd64
assembly procedures (reference: roaring/assembly_amd64.s: popcntSliceAsm,
popcntAndSliceAsm, popcntOrSliceAsm, popcntXorSliceAsm, popcntMaskSliceAsm
— "mask" is AND-NOT), which the Go code dispatches to via CPUID
(reference: roaring/assembly_asm.go:19-87).

A slice-row is 32,768 uint32 words = one (256, 128) tile = 128 KiB per
operand.  Kernels walk a grid of row-chunks (ROWS_PER_STEP slice-rows
per step) and emit ONE int32 partial per slice-row into a VMEM vector
output block indexed by the grid step — every step writes its own
output slot, so the pipeline never serializes through a shared
accumulator (the round-2 kernels accumulated into a single SMEM scalar,
which defeated double-buffering and measured 4x slower than plain XLA).
The cross-row partial sum happens outside the kernel where XLA fuses it
for free.

Everything here is optional: :mod:`pilosa_tpu.ops.bitplane` falls back
to pure-XLA (jnp) formulations off-TPU or when PILOSA_TPU_DISABLE_PALLAS
is set, and the two paths are asserted bit-identical in
tests/test_kernels.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128
_ROW_SUBLANES = 256  # one slice-row: 256 * 128 = 32768 words
# Preferred slice-rows per grid step: 2 operands x 4 rows x 128 KiB =
# 1 MiB of VMEM per buffer set — small enough to double-buffer, large
# enough to amortize per-step overhead.  The actual step is the largest
# of (4, 2, 1) dividing the row count, so NO operand is ever padded —
# a pad would copy the full operand through HBM on the hot path.
ROWS_PER_STEP = 4


def _chunk_for(rows: int) -> int:
    for c in (ROWS_PER_STEP, 2, 1):
        if rows % c == 0:
            return c
    raise AssertionError("unreachable")


def _interpret() -> bool:
    """Run kernels in interpreter mode off-TPU so the Pallas path is
    testable on the CPU fixture mesh."""
    return jax.default_backend() != "tpu"


def _combine(op: str, x, y):
    if op == "and":
        return x & y
    if op == "or":
        return x | y
    if op == "xor":
        return x ^ y
    if op == "andnot":
        return x & ~y
    raise ValueError(f"unknown op {op!r}")


def _row_tiles(x: jnp.ndarray) -> jnp.ndarray:
    """View a whole-slice-row-multiple word array as slice-row tiles
    (rows, 256, 128)."""
    total = x.size
    assert total % (_ROW_SUBLANES * _LANES) == 0, (
        f"operand size {total} is not a whole number of slice-rows"
    )
    return x.reshape(total // (_ROW_SUBLANES * _LANES), _ROW_SUBLANES, _LANES)


def _fused_rows_kernel(op, a_ref, b_ref, o_ref):
    w = _combine(op, a_ref[:], b_ref[:])
    o_ref[:] = jnp.sum(
        jax.lax.population_count(w).astype(jnp.int32), axis=(1, 2)
    )


def _count_rows_kernel(a_ref, o_ref):
    o_ref[:] = jnp.sum(
        jax.lax.population_count(a_ref[:]).astype(jnp.int32), axis=(1, 2)
    )


def _partials_fused(a_tiles, b_tiles, op: str) -> jnp.ndarray:
    """int32 partial per slice-row of (a OP b); grid over row chunks,
    one VMEM output slot per chunk."""
    n = a_tiles.shape[0]
    step = _chunk_for(n)
    return pl.pallas_call(
        functools.partial(_fused_rows_kernel, op),
        grid=(n // step,),
        in_specs=[
            pl.BlockSpec((step, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0)),
            pl.BlockSpec((step, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((step,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
        interpret=_interpret(),
    )(a_tiles, b_tiles)


def _partials_count(a_tiles) -> jnp.ndarray:
    n = a_tiles.shape[0]
    step = _chunk_for(n)
    return pl.pallas_call(
        _count_rows_kernel,
        grid=(n // step,),
        in_specs=[
            pl.BlockSpec((step, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0))
        ],
        out_specs=pl.BlockSpec((step,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
        interpret=_interpret(),
    )(a_tiles)


@functools.partial(jax.jit, static_argnames=("op",))
def fused_count(a: jnp.ndarray, b: jnp.ndarray, op: str) -> jnp.ndarray:
    """int32 popcount of (a OP b) over whole slice-row-multiple operands."""
    return jnp.sum(_partials_fused(_row_tiles(a), _row_tiles(b), op))


@jax.jit
def count(a: jnp.ndarray) -> jnp.ndarray:
    """int32 popcount of a (reference: popcntSliceAsm)."""
    return jnp.sum(_partials_count(_row_tiles(a)))


@functools.partial(jax.jit, static_argnames=("op",))
def fused_count_rows(a: jnp.ndarray, b: jnp.ndarray, op: str) -> jnp.ndarray:
    """Per-row popcount of (a OP b) over (rows, 32768) operands ->
    int32[rows]: the batched Count(op(x, y)) fast path — one partial per
    slice-row tile (<= 2^20 bits, always int32-safe; cross-slice totals
    sum on host in int64)."""
    rows = a.shape[0]
    at = a.reshape(rows, _ROW_SUBLANES, _LANES)
    bt = b.reshape(rows, _ROW_SUBLANES, _LANES)
    return _partials_fused(at, bt, op)


def _top_counts_kernel(p_ref, s_ref, o_ref):
    w = p_ref[:] & s_ref[:][None, :, :]
    o_ref[:] = jnp.sum(
        jax.lax.population_count(w).astype(jnp.int32), axis=(1, 2)
    )


@jax.jit
def top_counts(plane: jnp.ndarray, src_row: jnp.ndarray) -> jnp.ndarray:
    """Per-row |row AND src| over a (rows, 32768) plane -> int32[rows].

    The batched TopN(Src=...) scorer: row chunks stream through VMEM
    against a resident src tile; each grid step writes its own output
    slot (no shared accumulator)."""
    rows = plane.shape[0]
    pt = plane.reshape(rows, _ROW_SUBLANES, _LANES)
    st = src_row.reshape(_ROW_SUBLANES, _LANES)
    step = _chunk_for(rows)
    return pl.pallas_call(
        _top_counts_kernel,
        grid=(rows // step,),
        in_specs=[
            pl.BlockSpec((step, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0)),
            pl.BlockSpec((_ROW_SUBLANES, _LANES), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((step,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((rows,), jnp.int32),
        interpret=_interpret(),
    )(pt, st)
