"""Pallas TPU kernels for the fused bitwise-op + popcount reductions.

These are the TPU-native replacements for the reference's nine amd64
assembly procedures (reference: roaring/assembly_amd64.s: popcntSliceAsm,
popcntAndSliceAsm, popcntOrSliceAsm, popcntXorSliceAsm, popcntMaskSliceAsm
— "mask" is AND-NOT), which the Go code dispatches to via CPUID
(reference: roaring/assembly_asm.go:19-87).

A slice-row is 32,768 uint32 words; we view every operand as (M, 128)
lanes with M a multiple of _ROW_SUBLANES = 256 (one slice-row = one
(256, 128) tile = 128 KiB of VMEM per operand).  The grid walks slice-row
tiles sequentially, accumulating the popcount into a single SMEM scalar —
the data streams HBM -> VMEM once and the bitwise op fuses with the
popcount, so the kernels run at HBM bandwidth.

Everything here is optional: :mod:`pilosa_tpu.ops.bitplane` falls back to
pure-XLA (jnp) formulations off-TPU or when PILOSA_TPU_DISABLE_PALLAS is
set, and the two paths are asserted bit-identical in
tests/test_kernels.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_ROW_SUBLANES = 256  # one slice-row: 256 * 128 = 32768 words


def _interpret() -> bool:
    """Run kernels in interpreter mode off-TPU so the Pallas path is
    testable on the CPU fixture mesh."""
    return jax.default_backend() != "tpu"


def _combine(op: str, x, y):
    if op == "and":
        return x & y
    if op == "or":
        return x | y
    if op == "xor":
        return x ^ y
    if op == "andnot":
        return x & ~y
    raise ValueError(f"unknown op {op!r}")


def _fused_count_kernel(op, a_ref, b_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[0, 0] = 0

    w = _combine(op, a_ref[:], b_ref[:])
    out_ref[0, 0] += jnp.sum(jax.lax.population_count(w).astype(jnp.int32))


def _count_kernel(a_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[0, 0] = 0

    out_ref[0, 0] += jnp.sum(jax.lax.population_count(a_ref[:]).astype(jnp.int32))


def _as_tiles(x: jnp.ndarray) -> jnp.ndarray:
    """Reshape any word array whose size is a multiple of one slice-row
    into (M, 128)."""
    total = x.size
    assert total % (_ROW_SUBLANES * _LANES) == 0, (
        f"operand size {total} is not a whole number of slice-rows"
    )
    return x.reshape(total // _LANES, _LANES)


@functools.partial(jax.jit, static_argnames=("op",))
def fused_count(a: jnp.ndarray, b: jnp.ndarray, op: str) -> jnp.ndarray:
    """int32 popcount of (a OP b) over whole slice-row-multiple operands."""
    at, bt = _as_tiles(a), _as_tiles(b)
    m = at.shape[0]
    grid = m // _ROW_SUBLANES
    out = pl.pallas_call(
        functools.partial(_fused_count_kernel, op),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_ROW_SUBLANES, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((_ROW_SUBLANES, _LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        interpret=_interpret(),
    )(at, bt)
    return out[0, 0]


@jax.jit
def count(a: jnp.ndarray) -> jnp.ndarray:
    """int32 popcount of a (reference: popcntSliceAsm)."""
    at = _as_tiles(a)
    grid = at.shape[0] // _ROW_SUBLANES
    out = pl.pallas_call(
        _count_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((_ROW_SUBLANES, _LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        interpret=_interpret(),
    )(at)
    return out[0, 0]


def _fused_count_rows_kernel(op, a_ref, b_ref, out_ref):
    w = _combine(op, a_ref[:], b_ref[:])
    out_ref[pl.program_id(0)] = jnp.sum(
        jax.lax.population_count(w).astype(jnp.int32)
    )


@functools.partial(jax.jit, static_argnames=("op",))
def fused_count_rows(a: jnp.ndarray, b: jnp.ndarray, op: str) -> jnp.ndarray:
    """Per-row popcount of (a OP b) over (rows, 32768) operands ->
    int32[rows]: the batched Count(op(x, y)) fast path — one partial per
    slice-row tile (<= 2^20 bits, always int32-safe; cross-slice totals
    sum on host in int64)."""
    rows = a.shape[0]
    at = a.reshape(rows, _ROW_SUBLANES, _LANES)
    bt = b.reshape(rows, _ROW_SUBLANES, _LANES)
    return pl.pallas_call(
        functools.partial(_fused_count_rows_kernel, op),
        grid=(rows,),
        in_specs=[
            pl.BlockSpec((1, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((rows,), lambda i: (0,), memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((rows,), jnp.int32),
        interpret=_interpret(),
    )(at, bt)


# TopN scoring is the AND case of the fused per-row count kernel.
_top_counts_kernel = functools.partial(_fused_count_rows_kernel, "and")


@jax.jit
def top_counts(plane: jnp.ndarray, src_row: jnp.ndarray) -> jnp.ndarray:
    """Per-row |row AND src| over a (rows, 32768) plane -> int32[rows].

    The batched TopN(Src=...) scorer: one grid step per row, src tile
    revisited from VMEM each step.
    """
    rows = plane.shape[0]
    pt = plane.reshape(rows, _ROW_SUBLANES, _LANES)
    st = src_row.reshape(_ROW_SUBLANES, _LANES)
    out = pl.pallas_call(
        _top_counts_kernel,
        grid=(rows,),
        in_specs=[
            pl.BlockSpec((1, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0)),
            pl.BlockSpec((_ROW_SUBLANES, _LANES), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((rows,), lambda i: (0,), memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((rows,), jnp.int32),
        interpret=_interpret(),
    )(pt, st)
    return out
