"""Pallas TPU kernels for the fused bitwise-op + popcount reductions.

These are the TPU-native replacements for the reference's nine amd64
assembly procedures (reference: roaring/assembly_amd64.s: popcntSliceAsm,
popcntAndSliceAsm, popcntOrSliceAsm, popcntXorSliceAsm, popcntMaskSliceAsm
— "mask" is AND-NOT), which the Go code dispatches to via CPUID
(reference: roaring/assembly_asm.go:19-87).

A slice-row is 32,768 uint32 words = one (256, 128) tile = 128 KiB per
operand.  Kernels walk a grid of 8-slice-row chunks and emit LANE
PARTIALS: each step reduces popcount over the sublane axis only and
writes one (8, 128) int32 block — exactly one native TPU tile — into its
own output slot, so the pipeline never serializes through a shared
accumulator and every store is tile-aligned (Mosaic rejects rank-1
output blocks that are neither full-array nor multiples of 128, which is
what sank the round-2/3 formulations on real hardware).  The remaining
lane-axis sum happens outside the kernel where XLA fuses it for free.

Row counts that are not a multiple of 8 fall back to the pure-XLA
formulation instead of padding: fragment planes are always padded to
ROW_BLOCK = 8 rows (ops/bitplane.py:44) and query batches bucket to
powers of two, so the fallback only triggers on small ad-hoc shapes
where kernel launch overhead dominates anyway, and a pad here would
copy the full operand through HBM on the hot path.

Everything here is optional: :mod:`pilosa_tpu.ops.bitplane` falls back
to pure-XLA (jnp) formulations off-TPU or when PILOSA_TPU_DISABLE_PALLAS
is set, and the two paths are asserted bit-identical in
tests/test_kernels.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128
_ROW_SUBLANES = 256  # one slice-row: 256 * 128 = 32768 words
# Slice-rows per grid step.  8 rows x (256, 128) words is 1 MiB of VMEM
# per operand buffer — small enough to double-buffer — and makes the
# (8, 128) int32 output block exactly one native tile.
_STEP_ROWS = 8


def _interpret() -> bool:
    """Run kernels in interpreter mode off-TPU so the Pallas path is
    testable on the CPU fixture mesh."""
    return jax.default_backend() != "tpu"


def _combine(op: str, x, y):
    if op == "and":
        return x & y
    if op == "or":
        return x | y
    if op == "xor":
        return x ^ y
    if op == "andnot":
        return x & ~y
    raise ValueError(f"unknown op {op!r}")


def _popcount_reduce(w, axis=None):
    """The pure-XLA popcount+sum used by every rows-not-tile-aligned
    fallback — ONE definition so the fallbacks cannot drift from each
    other (the Pallas paths are asserted bit-identical to this in
    tests/test_kernels.py)."""
    return jnp.sum(jax.lax.population_count(w).astype(jnp.int32), axis=axis)


def _row_tiles(x: jnp.ndarray) -> jnp.ndarray:
    """View a whole-slice-row-multiple word array as slice-row tiles
    (rows, 256, 128)."""
    total = x.size
    assert total % (_ROW_SUBLANES * _LANES) == 0, (
        f"operand size {total} is not a whole number of slice-rows"
    )
    return x.reshape(total // (_ROW_SUBLANES * _LANES), _ROW_SUBLANES, _LANES)


def _fused_lanes_kernel(op, a_ref, b_ref, o_ref):
    w = _combine(op, a_ref[:], b_ref[:])
    # Reduce the sublane axis only: (8, 256, 128) -> (8, 128) lane
    # partials, one native int32 tile per grid step.
    o_ref[:] = jnp.sum(jax.lax.population_count(w).astype(jnp.int32), axis=1)


def _count_lanes_kernel(a_ref, o_ref):
    o_ref[:] = jnp.sum(
        jax.lax.population_count(a_ref[:]).astype(jnp.int32), axis=1
    )


def _lane_partials_fused(a_tiles, b_tiles, op: str) -> jnp.ndarray:
    """int32[rows, 128] lane partials of popcount(a OP b); rows % 8 == 0."""
    n = a_tiles.shape[0]
    return pl.pallas_call(
        functools.partial(_fused_lanes_kernel, op),
        grid=(n // _STEP_ROWS,),
        in_specs=[
            pl.BlockSpec((_STEP_ROWS, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0)),
            pl.BlockSpec((_STEP_ROWS, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((_STEP_ROWS, _LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, _LANES), jnp.int32),
        interpret=_interpret(),
    )(a_tiles, b_tiles)


def _lane_partials_count(a_tiles) -> jnp.ndarray:
    n = a_tiles.shape[0]
    return pl.pallas_call(
        _count_lanes_kernel,
        grid=(n // _STEP_ROWS,),
        in_specs=[
            pl.BlockSpec((_STEP_ROWS, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0))
        ],
        out_specs=pl.BlockSpec((_STEP_ROWS, _LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, _LANES), jnp.int32),
        interpret=_interpret(),
    )(a_tiles)


@functools.partial(jax.jit, static_argnames=("op",))
def fused_count(a: jnp.ndarray, b: jnp.ndarray, op: str) -> jnp.ndarray:
    """int32 popcount of (a OP b) over whole slice-row-multiple operands."""
    at, bt = _row_tiles(a), _row_tiles(b)
    if at.shape[0] % _STEP_ROWS:
        return _popcount_reduce(_combine(op, at, bt))
    return jnp.sum(_lane_partials_fused(at, bt, op))


@jax.jit
def count(a: jnp.ndarray) -> jnp.ndarray:
    """int32 popcount of a (reference: popcntSliceAsm)."""
    at = _row_tiles(a)
    if at.shape[0] % _STEP_ROWS:
        return _popcount_reduce(at)
    return jnp.sum(_lane_partials_count(at))


@functools.partial(jax.jit, static_argnames=("op",))
def fused_count_rows(a: jnp.ndarray, b: jnp.ndarray, op: str) -> jnp.ndarray:
    """Per-row popcount of (a OP b) over (rows, 32768) operands ->
    int32[rows]: the batched Count(op(x, y)) fast path — one partial per
    slice-row tile (<= 2^20 bits, always int32-safe; cross-slice totals
    sum on host in int64)."""
    rows = a.shape[0]
    at = a.reshape(rows, _ROW_SUBLANES, _LANES)
    bt = b.reshape(rows, _ROW_SUBLANES, _LANES)
    if rows % _STEP_ROWS:
        return _popcount_reduce(_combine(op, at, bt), axis=(1, 2))
    return jnp.sum(_lane_partials_fused(at, bt, op), axis=-1)


def _top_lanes_kernel(p_ref, s_ref, o_ref):
    w = p_ref[:] & s_ref[:][None, :, :]
    o_ref[:] = jnp.sum(jax.lax.population_count(w).astype(jnp.int32), axis=1)


@jax.jit
def top_counts(plane: jnp.ndarray, src_row: jnp.ndarray) -> jnp.ndarray:
    """Per-row |row AND src| over a (rows, 32768) plane -> int32[rows].

    The batched TopN(Src=...) scorer: row chunks stream through VMEM
    against a resident src tile; each grid step writes its own (8, 128)
    lane-partial tile (no shared accumulator)."""
    rows = plane.shape[0]
    pt = plane.reshape(rows, _ROW_SUBLANES, _LANES)
    st = src_row.reshape(_ROW_SUBLANES, _LANES)
    if rows % _STEP_ROWS:
        return _popcount_reduce(pt & st[None, :, :], axis=(1, 2))
    lanes = pl.pallas_call(
        _top_lanes_kernel,
        grid=(rows // _STEP_ROWS,),
        in_specs=[
            pl.BlockSpec((_STEP_ROWS, _ROW_SUBLANES, _LANES), lambda i: (i, 0, 0)),
            pl.BlockSpec((_ROW_SUBLANES, _LANES), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_STEP_ROWS, _LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        interpret=_interpret(),
    )(pt, st)
    return jnp.sum(lanes, axis=-1)
