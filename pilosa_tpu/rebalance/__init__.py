"""Elastic-cluster rebalancing — live node join/leave with background
slice migration and write-forwarding cutover.

The reference design fixes the node set at boot and punts on resharding
entirely; this subsystem makes topology changes a background operation
against a serving cluster:

* :mod:`pilosa_tpu.rebalance.plan` — the slice-ownership diff between
  the old and new jump-hash rings, as a per-slice migration plan;
* :mod:`pilosa_tpu.rebalance.deltalog` — the bounded per-slice write
  log a migration source keeps during its copy window, replayed to the
  target after the bulk copy (cutover-scoped anti-entropy);
* :mod:`pilosa_tpu.rebalance.migrate` — the coordinator state machine
  (copy -> replay -> checksum-verify -> atomic per-slice ownership
  flip -> release) plus the per-node topology-event application every
  member runs.

Reads route on the OLD ring until a slice's fragment is
checksum-verified on its new owner; writes go to BOTH rings' owners
for the whole transition (`Cluster.write_nodes`); the transition is
resumable (persisted per-slice state) and abortable (both rings stay
valid throughout).
"""

from pilosa_tpu.rebalance.deltalog import DeltaLog
from pilosa_tpu.rebalance.migrate import RebalanceError, Rebalancer
from pilosa_tpu.rebalance.plan import SliceMove, compute_plan

__all__ = [
    "DeltaLog",
    "RebalanceError",
    "Rebalancer",
    "SliceMove",
    "compute_plan",
]
