"""Rebalancer — per-node topology-event application plus the
coordinator state machine that migrates slices in the background.

Every node runs a :class:`Rebalancer` (the server owns it).  Topology
events (begin / flip / unflip / commit / abort) arrive over HTTP
fan-out at ``POST /cluster/topology`` and apply to the local
:class:`~pilosa_tpu.cluster.topology.Cluster`; each application
persists the transition snapshot to ``<data-dir>/.topology.json`` so a
node that crashes mid-transition reboots with both rings intact.

The node that receives ``POST /cluster/resize`` becomes the
COORDINATOR: it computes the slice-ownership diff
(:func:`pilosa_tpu.rebalance.plan.compute_plan`), fans the transition
to every member, and drives each slice through

    copy window opens (source starts its delta log)
      -> bulk copy: source streams every view's fragment tar to the
         target through the chunked data plane, bandwidth-throttled,
         on the internal admission lane
      -> replay rounds: the delta log drains to the target until the
         source and target fragment checksums agree
      -> FLIP: ownership cuts over atomically per slice via a
         synchronous topology fan-out (reads now route to the target)
      -> final replay drains writes that raced the flip
      -> release: sources not in the new ring drop the slice's
         fragments (HBM + disk returned)

Per-slice progress persists to ``<data-dir>/.rebalance.json`` after
every state change, so a crashed coordinator resumes from the last
completed slice when the operator re-issues the resize.  Abort
reverse-migrates any flipped slices (same machinery, rings swapped)
and then drops the transition — the old ring was never invalidated.
"""

from __future__ import annotations

import json
import os
import threading
import time

from pilosa_tpu.rebalance.deltalog import DeltaLog
from pilosa_tpu.rebalance.plan import SliceMove, compute_plan

EVENTS = ("begin", "flip", "unflip", "commit", "abort")

# Per-event fan-out attempts before the coordinator gives up (the slice
# stays in its current state — resumable).
_FANOUT_ATTEMPTS = 3
# Bulk-copy attempts per (slice, target) before the slice fails.
_COPY_ATTEMPTS = 3


class RebalanceError(RuntimeError):
    pass


class _ThrottledChunkReader:
    """File-like over a chunk generator with a bytes/sec ceiling — the
    bandwidth throttle that keeps bulk migration from starving client
    traffic on the source's uplink."""

    def __init__(self, chunks, bytes_per_sec: float = 0.0):
        self._chunks = iter(chunks)
        self._rate = float(bytes_per_sec)
        self._buf = b""
        self._sent = 0
        self._t0 = time.monotonic()
        self.bytes = 0

    def read(self, n: int = -1) -> bytes:
        while n < 0 or len(self._buf) < n:
            try:
                self._buf += next(self._chunks)
            except StopIteration:
                break
        if n < 0:
            out, self._buf = self._buf, b""
        else:
            out, self._buf = self._buf[:n], self._buf[n:]
        self.bytes += len(out)
        if self._rate > 0 and out:
            self._sent += len(out)
            ahead = self._sent / self._rate - (time.monotonic() - self._t0)
            if ahead > 0:
                time.sleep(min(ahead, 1.0))
        return out


class Rebalancer:
    """Topology-event application (every node) + migration coordination
    (the node that received the resize request)."""

    def __init__(self, server):
        self._server = server
        self.delta_log = DeltaLog(
            cap=getattr(server, "rebalance_delta_cap", 50_000),
            stats=server.holder.stats,
        )
        self._mu = threading.Lock()
        self._thread: threading.Thread | None = None
        self._starting = False  # excludes concurrent start_resize entry
        self._stop = threading.Event()
        self._state: dict | None = None  # coordinator per-slice state
        self._candidates: set[str] = set()  # gossip-announced non-members
        self._last_error = ""
        # Test seam: extra pause between slice migrations (lets tests
        # kill the coordinator mid-plan deterministically).
        self.step_delay_s = 0.0

    # -- plumbing ------------------------------------------------------

    @property
    def _cluster(self):
        return self._server.cluster

    @property
    def _holder(self):
        return self._server.holder

    @property
    def _host(self) -> str:
        return self._server.host

    @property
    def _stats(self):
        return self._holder.stats

    def _log(self, msg: str) -> None:
        self._server.logger(f"rebalance: {msg}")

    def _client(self, host: str, timeout: float | None = None):
        client = self._server._client_factory(host)
        if timeout is not None:
            client.timeout = timeout
        return client

    def _post_json(self, host: str, path: str, payload: dict) -> dict:
        client = self._client(host, timeout=600.0)
        status, data = client._request(
            "POST", path, body=json.dumps(payload).encode()
        )
        return json.loads(client._check(status, data) or b"{}")

    # -- persistence ---------------------------------------------------

    def _topology_path(self) -> str:
        return os.path.join(self._server.data_dir, ".topology.json")

    def _state_path(self) -> str:
        return os.path.join(self._server.data_dir, ".rebalance.json")

    @staticmethod
    def _write_json(path: str, doc: dict) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    @staticmethod
    def _read_json(path: str) -> dict | None:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _persist_topology(self) -> None:
        snap = self._cluster.transition_snapshot()
        if snap is None:
            try:
                os.unlink(self._topology_path())
            except OSError:
                pass
        else:
            self._write_json(self._topology_path(), snap)

    def _persist_state(self) -> None:
        if self._state is not None:
            self._write_json(self._state_path(), self._state)

    def _clear_state(self) -> None:
        self._state = None
        try:
            os.unlink(self._state_path())
        except OSError:
            pass

    def resume_from_disk(self) -> None:
        """Restore a persisted transition at boot: both rings (and the
        flipped-slice set) come back before the first query routes, so
        a node that crashed mid-migration neither loses the new ring
        nor routes reads at released fragments.  Migration itself
        resumes when the operator re-issues the resize."""
        snap = self._read_json(self._topology_path())
        if snap:
            try:
                self._cluster.restore_transition(snap)
                self._log(
                    f"restored in-flight transition epoch {snap['epoch']} "
                    f"({len(snap.get('moved', []))} slices already flipped)"
                )
            except Exception as e:  # noqa: BLE001 — boot must proceed
                self._log(f"transition restore failed: {e}")
        self._state = self._read_json(self._state_path())

    # -- topology events (every node) ----------------------------------

    def apply_event(self, ev: dict) -> dict:
        """Apply one fanned-out topology event to the local cluster.
        Idempotent per event; persists the transition snapshot."""
        kind = ev.get("event")
        epoch = int(ev.get("epoch", 0))
        if kind == "begin":
            self._cluster.begin_transition(list(ev["new"]), epoch=epoch)
            self._stats.count("cluster.rebalance.begin")
        elif kind == "flip":
            if self._cluster.flip_slice(str(ev["index"]), int(ev["slice"]), epoch):
                self._stats.count("cluster.rebalance.flips")
        elif kind == "unflip":
            self._cluster.unflip_slice(str(ev["index"]), int(ev["slice"]), epoch)
        elif kind == "commit":
            self._cluster.commit_transition(epoch)
            self._stats.count("cluster.rebalance.commit")
            if self._host not in self._cluster.hosts():
                self._log(
                    "this node left the serving ring at commit; it can "
                    "be shut down once drained traffic stops"
                )
            else:
                # A joining node needs the cluster max-slice picture
                # NOW, not at the next polling tick — a query it
                # coordinates would otherwise undercount remote-only
                # slices.
                poll = getattr(self._server, "_tick_max_slices", None)
                if poll is not None:
                    threading.Thread(
                        target=self._safe_poll, args=(poll,), daemon=True,
                        name="rebalance-maxslice-poll",
                    ).start()
        elif kind == "abort":
            self._cluster.abort_transition(epoch)
            self._stats.count("cluster.rebalance.abort")
        else:
            raise RebalanceError(f"unknown topology event: {kind!r}")
        self._persist_topology()
        return {"ok": True, "epoch": self._cluster.epoch}

    @staticmethod
    def _safe_poll(poll) -> None:
        try:
            poll()
        except Exception:  # noqa: BLE001 — advisory refresh
            pass

    def _fanout_event(self, ev: dict, hosts: list[str]) -> None:
        """Apply an event locally, then deliver it SYNCHRONOUSLY to
        every other member — correctness events (begin/flip/commit)
        must reach the whole ring before the coordinator proceeds."""
        self.apply_event(ev)
        errs = []
        for host in hosts:
            if host == self._host:
                continue
            last: Exception | None = None
            for _ in range(_FANOUT_ATTEMPTS):
                try:
                    self._post_json(host, "/cluster/topology", ev)
                    last = None
                    break
                except Exception as e:  # noqa: BLE001 — per-host retry
                    last = e
                    time.sleep(0.2)
            if last is not None:
                errs.append(f"{host}: {last}")
        if errs:
            raise RebalanceError(
                f"topology {ev.get('event')} fanout failed: " + "; ".join(errs)
            )

    # -- gossip join candidates ----------------------------------------

    def note_membership(self, host: str, state: str) -> None:
        """Track gossip-announced hosts that are not in the serving
        ring; with ``[cluster] rebalance-on-join`` the lowest-host ring
        member auto-triggers the resize that admits them."""
        ring = set(self._cluster.hosts())
        t = self._cluster.transition
        if t is not None:
            ring |= set(t.new_hosts)
        if state != "UP" or host in ring:
            self._candidates.discard(host)
            return
        if host in self._candidates:
            return
        self._candidates.add(host)
        self._log(f"gossip announced non-member {host} (join candidate)")
        if (
            getattr(self._server, "rebalance_on_join", False)
            and t is None
            and self._cluster.hosts()
            and self._host == min(self._cluster.hosts())
        ):
            target = sorted(set(self._cluster.hosts()) | self._candidates)
            threading.Thread(
                target=self._auto_resize,
                args=(target,),
                daemon=True,
                name="rebalance-on-join",
            ).start()

    def _auto_resize(self, hosts: list[str]) -> None:
        try:
            self.start_resize(hosts)
        except Exception as e:  # noqa: BLE001 — advisory trigger
            self._log(f"auto resize to {hosts} failed: {e}")

    # -- coordinator ---------------------------------------------------

    def start_resize(self, hosts: list[str]) -> dict:
        """Begin (or resume) a migration to ``hosts``.  Returns the
        status snapshot; the migration itself runs in the background.

        ``_mu`` only guards entry/exit bookkeeping — the schema push
        and begin fan-out are network round trips and run UNLOCKED
        (the ``_starting`` flag excludes concurrent entries)."""
        hosts = sorted(dict.fromkeys(hosts))
        if not hosts:
            raise RebalanceError("resize needs at least one host")
        with self._mu:
            if self._thread is not None and self._thread.is_alive():
                raise RebalanceError("a resize is already running")
            if self._starting:
                raise RebalanceError("a resize is already starting")
            self._starting = True
        try:
            t = self._cluster.transition
            old_hosts = (
                list(t.old_hosts) if t is not None else self._cluster.hosts()
            )
            if t is not None and t.new_hosts != hosts:
                raise RebalanceError(
                    f"transition to {t.new_hosts} in flight; abort it "
                    "before resizing to a different host set"
                )
            if t is None and hosts == sorted(old_hosts):
                raise RebalanceError("topology unchanged")
            state = self._state or self._read_json(self._state_path())
            if state is not None and (
                state.get("new") != hosts or state.get("completed")
            ):
                state = None
            if t is None:
                epoch = (
                    int(state["epoch"])
                    if state is not None
                    else self._cluster.epoch + 1
                )
                # Joining nodes need the schema BEFORE the transition
                # begins: dual-writes and bulk-copy restores land on
                # them from the first post-begin write.
                for host in hosts:
                    if host not in old_hosts and host != self._host:
                        self._push_schema(host)
                self._fanout_event(
                    {"event": "begin", "epoch": epoch, "new": hosts},
                    sorted(set(old_hosts) | set(hosts)),
                )
            else:
                epoch = t.epoch
            if state is None:
                state = {
                    "epoch": epoch,
                    "old": old_hosts,
                    "new": hosts,
                    "slices": {},
                    "completed": False,
                }
            state.pop("error", None)
            with self._mu:
                self._state = state
                self._last_error = ""
                self._persist_state()
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="rebalance-coordinator"
                )
                self._thread.start()
        finally:
            with self._mu:
                self._starting = False
        return self.snapshot()

    def abort(self) -> dict:
        """Stop migrating, reverse-migrate any flipped slices back to
        the old ring, and drop the transition — the cluster returns to
        its pre-resize topology with no data loss."""
        with self._mu:
            thread = self._thread
            self._stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=60.0)
        # The reversal below reuses the copy machinery, which honors
        # the stop flag — re-arm it now that the forward run is down.
        self._stop.clear()
        t = self._cluster.transition
        if t is None:
            self._clear_state()
            return self.snapshot()
        epoch = t.epoch
        all_hosts = sorted(set(t.old_hosts) | set(t.new_hosts))
        # Reverse every flipped slice: same per-slice machinery with
        # the rings swapped (the new-ring owner streams back to the
        # old-ring owners that released), then unflip.  Releases of the
        # reverse targets wait until the transition is dropped — while
        # it is active they still count as write owners.
        pending_releases: list[SliceMove] = []
        for index, s in sorted(t.moved):
            move = self._plan_for_slice(index, s)
            if move is None:
                continue
            rev = SliceMove(
                index=index,
                slice=s,
                sources=tuple(
                    h for h in move.sources if h not in move.releases
                ) + move.targets,
                targets=move.releases,
                releases=move.targets,
            )
            self._copy_slice_to_targets(rev, epoch)
            self._fanout_event(
                {"event": "unflip", "epoch": epoch, "index": index, "slice": s},
                all_hosts,
            )
            self._finalize_slice(rev, epoch, release=False)
            pending_releases.append(rev)
        self._fanout_event({"event": "abort", "epoch": epoch}, all_hosts)
        for rev in pending_releases:
            self._release_from(rev)
        self._clear_state()
        self._log("resize aborted; old ring restored")
        return self.snapshot()

    def close(self) -> None:
        self._stop.set()

    def _push_schema(self, host: str) -> None:
        """Replicate the full local schema (indexes, frames, BSI
        fields) to a JOINING node — already-existing objects are
        fine (409s pass)."""
        from pilosa_tpu.net.client import ClientError

        client = self._client(host)

        def _ignore_conflict(fn, *args) -> None:
            try:
                fn(*args)
            except ClientError as e:
                if e.status != 409 and "exists" not in str(e):
                    raise

        for name, idx in self._holder.indexes().items():
            opts: dict = {"columnLabel": idx.column_label}
            if idx.time_quantum:
                opts["timeQuantum"] = idx.time_quantum
            _ignore_conflict(client.create_index, name, opts)
            for fname, f in idx.frames().items():
                fopts: dict = {
                    "rowLabel": f.row_label,
                    "inverseEnabled": f.inverse_enabled,
                    "cacheType": f.cache_type,
                    "cacheSize": f.cache_size,
                }
                if f.time_quantum:
                    fopts["timeQuantum"] = f.time_quantum
                if f.range_enabled:
                    fopts["rangeEnabled"] = True
                _ignore_conflict(client.create_frame, name, fname, fopts)
                for fld in f.bsi_fields():
                    _ignore_conflict(
                        client.create_field,
                        name, fname, fld.name, fld.min, fld.max,
                    )
        self._log(f"schema pushed to joining node {host}")

    def _plan_for_slice(self, index: str, slice_i: int) -> SliceMove | None:
        for m in compute_plan(self._cluster, {index: slice_i}):
            if m.index == index and m.slice == slice_i:
                return m
        return None

    def _index_max_slices(self) -> dict[str, int]:
        out = {}
        for name, idx in self._holder.indexes().items():
            out[name] = max(idx.max_slice(), idx.max_inverse_slice())
        return out

    def _run(self) -> None:
        state = self._state
        try:
            # The plan must cover the CLUSTER's slice range, not just
            # what this node has seen locally — refresh the remote
            # max-slice picture synchronously before planning.
            poll = getattr(self._server, "_tick_max_slices", None)
            if poll is not None:
                self._safe_poll(poll)
            moves = compute_plan(self._cluster, self._index_max_slices())
            self._stats.gauge("cluster.rebalance.slicesPlanned", len(moves))
            self._log(
                f"epoch {state['epoch']}: {len(moves)} slice(s) to migrate "
                f"({state['old']} -> {state['new']})"
            )
            for move in moves:
                if self._stop.is_set():
                    self._log("stopped; migration state persisted for resume")
                    return
                entry = state["slices"].setdefault(move.key, {})
                if entry.get("state") == "done":
                    continue
                self._migrate_slice(move, int(state["epoch"]), entry)
                if self.step_delay_s > 0:
                    self._stop.wait(self.step_delay_s)
            if self._stop.is_set():
                return
            all_hosts = sorted(set(state["old"]) | set(state["new"]))
            self._fanout_event(
                {
                    "event": "commit",
                    "epoch": int(state["epoch"]),
                    "new": list(state["new"]),
                },
                all_hosts,
            )
            state["completed"] = True
            self._clear_state()
            self._log(f"resize complete; ring is now {state['new']}")
        except Exception as e:  # noqa: BLE001 — coordinator boundary
            self._last_error = str(e)
            if self._state is not None:
                self._state["error"] = str(e)
                self._persist_state()
            self._log(f"migration error (resumable): {e}")

    # -- per-slice state machine ---------------------------------------

    def _set_slice_state(self, entry: dict, move: SliceMove, st: str) -> None:
        entry["state"] = st
        entry["targets"] = list(move.targets)
        entry["releases"] = list(move.releases)
        self._persist_state()

    def _migrate_slice(self, move: SliceMove, epoch: int, entry: dict) -> None:
        all_hosts = sorted(
            set(self._state["old"]) | set(self._state["new"])
        )
        if entry.get("state") != "flipped":
            # A slice that crashed mid-copy restarts its copy from
            # scratch (idempotent: restore replaces the target state);
            # one that already flipped skips straight to finalize.
            self._set_slice_state(entry, move, "copying")
            self._copy_slice_to_targets(move, epoch)
        # Atomic per-slice cutover: every member flips read routing to
        # the new ring for this slice, synchronously (idempotent on
        # resume).
        self._fanout_event(
            {
                "event": "flip",
                "epoch": epoch,
                "index": move.index,
                "slice": move.slice,
            },
            all_hosts,
        )
        self._set_slice_state(entry, move, "flipped")
        self._finalize_slice(move, epoch)
        self._set_slice_state(entry, move, "done")
        self._stats.count("cluster.rebalance.slicesDone")

    def _copy_slice_to_targets(self, move: SliceMove, epoch: int) -> None:
        src = self._pick_source(move)
        for tgt in move.targets:
            self._copy_one(move, src, tgt)

    def _pick_source(self, move: SliceMove) -> str:
        states = self._cluster.node_states()
        for h in move.sources:
            if states.get(h, "UP") == "UP":
                return h
        return move.sources[0]

    def _copy_one(self, move: SliceMove, src: str, tgt: str) -> None:
        """Bulk copy + replay-until-checksums-agree for one target."""
        throttle = float(getattr(self._server, "rebalance_throttle_mbps", 0.0))
        rounds = int(getattr(self._server, "rebalance_verify_rounds", 3))
        base = {"index": move.index, "slice": move.slice}
        for _attempt in range(_COPY_ATTEMPTS):
            if self._stop.is_set():
                raise RebalanceError("stopped mid-copy")
            # (Re)open the copy window: the source logs every write to
            # this slice from before the snapshot streams.
            self._post_json(src, "/rebalance/delta", {**base, "action": "start"})
            r = self._post_json(
                src,
                "/rebalance/delta",
                {
                    **base,
                    "action": "copy",
                    "target": tgt,
                    # megabits/s -> bytes/s; 0 = unthrottled
                    "throttleBytesPerSec": throttle * 1e6 / 8.0,
                },
            )
            self._stats.count(
                "cluster.rebalance.bytesStreamed", int(r.get("bytes", 0))
            )
            for _round in range(max(rounds, 1)):
                rep = self._post_json(
                    src, "/rebalance/delta", {**base, "action": "replay", "target": tgt}
                )
                self._stats.count(
                    "cluster.rebalance.deltaReplayed", int(rep.get("entries", 0))
                )
                if rep.get("overflowed"):
                    break  # write storm outran the log: redo the copy
                cks = self._post_json(
                    src, "/rebalance/delta", {**base, "action": "checksum"}
                )["checksums"]
                ckt = self._post_json(
                    tgt, "/rebalance/delta", {**base, "action": "checksum"}
                )["checksums"]
                if all(ckt.get(k) == v for k, v in cks.items()):
                    return
                self._stats.count("cluster.rebalance.checksumRetries")
            else:
                continue  # checksums never agreed this attempt: recopy
        raise RebalanceError(
            f"slice {move.key}: copy to {tgt} failed to checksum-verify "
            f"after {_COPY_ATTEMPTS} attempts"
        )

    def _finalize_slice(
        self, move: SliceMove, epoch: int, release: bool = True
    ) -> None:
        """Post-flip: drain writes that raced the cutover, close the
        copy window, and release the slice from hosts leaving it."""
        src = self._pick_source(move)
        base = {"index": move.index, "slice": move.slice}
        for tgt in move.targets:
            self._post_json(
                src, "/rebalance/delta", {**base, "action": "replay", "target": tgt}
            )
        self._post_json(src, "/rebalance/delta", {**base, "action": "stop"})
        if release:
            self._release_from(move)

    def _release_from(self, move: SliceMove) -> None:
        delay = float(getattr(self._server, "rebalance_release_delay_ms", 0.0))
        if move.releases and delay > 0:
            # Let in-flight old-ring reads drain before their data goes.
            self._stop.wait(delay / 1000.0)
        for host in move.releases:
            try:
                self._post_json(
                    host,
                    "/rebalance/release",
                    {"index": move.index, "slice": move.slice},
                )
                self._stats.count("cluster.rebalance.releases")
            except Exception as e:  # noqa: BLE001 — release is best-effort
                # The slice is already flipped; a failed release leaves
                # orphaned (but harmless) data the operator can clean.
                self._log(f"release of {move.key} on {host} failed: {e}")

    # -- source/target-side operations (handler-invoked) ----------------

    def delta_action(self, payload: dict) -> dict:
        index = str(payload.get("index", ""))
        slice_i = int(payload.get("slice", 0))
        action = payload.get("action")
        if action == "start":
            self.delta_log.start(index, slice_i)
            return {"ok": True}
        if action == "stop":
            self.delta_log.stop(index, slice_i)
            return {"ok": True}
        if action == "replay":
            return self._replay(index, slice_i, str(payload.get("target", "")))
        if action == "copy":
            return self._copy_local_slice(
                index,
                slice_i,
                str(payload.get("target", "")),
                float(payload.get("throttleBytesPerSec", 0) or 0),
            )
        if action == "checksum":
            return {"checksums": self._checksums(index, slice_i)}
        raise RebalanceError(f"unknown delta action: {action!r}")

    def _slice_fragments(self, index: str, slice_i: int):
        idx = self._holder.index(index)
        if idx is None:
            return
        for frame in idx.frames().values():
            for view in frame.views().values():
                frag = view.fragment(slice_i)
                if frag is not None:
                    yield frame.name, view.name, frag

    def _checksums(self, index: str, slice_i: int) -> dict[str, str]:
        return {
            f"{frame}/{view}": frag.checksum().hex()
            for frame, view, frag in self._slice_fragments(index, slice_i)
        }

    def _copy_local_slice(
        self, index: str, slice_i: int, target: str, bytes_per_sec: float
    ) -> dict:
        """SOURCE side of the bulk copy: stream every view's fragment
        tar for the slice straight to the target's restore endpoint —
        chunked, throttled, never materialized.

        With a tier store configured (pilosa_tpu/tier), fragments
        whose store copy carries a CHECKSUM-FRESH logical checksum
        restore from the STORE instead: the target pulls the tar from
        shared storage (POST /tier/restore) and the source's uplink
        carries nothing — a joining node no longer hammers its peers.
        Stale/missing store copies (and targets without a tier, 501)
        fall back to peer streaming; the post-copy delta-log replay
        rounds close any gap either way."""
        if not target:
            raise RebalanceError("copy needs a target host")
        tier = getattr(self._server, "tier", None)
        client = self._client(target, timeout=600.0)
        views = 0
        nbytes = 0
        from_store = 0
        for frame, view, frag in list(self._slice_fragments(index, slice_i)):
            if tier is not None:
                restored = self._restore_via_store(
                    client, tier, index, frame, view, slice_i, frag
                )
                if restored is not None:
                    views += 1
                    nbytes += restored
                    from_store += 1
                    continue
            reader = _ThrottledChunkReader(
                frag.tar_chunks(chunk_bytes=self._server.stream_chunk_bytes),
                bytes_per_sec=bytes_per_sec,
            )
            client.restore_slice_from(
                index, frame, view, slice_i, reader, stage=True
            )
            views += 1
            nbytes += reader.bytes
        if from_store:
            self._stats.count("cluster.rebalance.storeRestores", from_store)
        return {"views": views, "bytes": nbytes, "fromStore": from_store}

    def _restore_via_store(
        self, client, tier, index: str, frame: str, view: str,
        slice_i: int, frag
    ) -> int | None:
        """One fragment's store-riding copy attempt: None = ride the
        peer stream instead (store stale/missing, target tier-less, or
        the restore failed)."""
        try:
            if tier.store_fresh_meta(frag) is None:
                return None
            return client.tier_restore(index, frame, view, slice_i)
        except Exception as e:  # noqa: BLE001 — fall back to streaming
            self._log(
                f"store-riding copy of {index}/{slice_i} {frame}/{view} "
                f"fell back to peer stream: {e}"
            )
            return None

    def _replay(self, index: str, slice_i: int, target: str) -> dict:
        """Drain the slice's delta log to the target in application
        order (cutover-scoped anti-entropy)."""
        entries, overflowed = self.delta_log.drain(index, slice_i)
        if overflowed:
            return {"entries": 0, "overflowed": True}
        if entries and not target:
            raise RebalanceError("replay needs a target host")
        client = self._client(target, timeout=600.0) if target else None
        for i, (frame, view, srows, scols, crows, ccols) in enumerate(entries):
            try:
                client.import_view_bits(
                    index, frame, view, slice_i, (srows, scols), (crows, ccols)
                )
            except Exception:
                # A push that dies mid-way must not lose the tail:
                # requeue everything unreplayed and let the coordinator
                # retry the round.
                self.delta_log.requeue(index, slice_i, entries[i:])
                raise
        return {"entries": len(entries), "overflowed": False}

    def release_slice(self, index: str, slice_i: int) -> dict:
        """Drop every local fragment of a slice this node no longer
        owns: device mirrors deregister from the HBM pool and the
        backing files are deleted — capacity actually returns.

        With a tier store configured, every fragment whose store copy
        is stale (or absent) UPLOADS before its local bytes go — the
        store stays a complete, fresh archive of released slices, so
        the next join restores from shared storage instead of peers.
        Upload failures log and count but never block the release (the
        new owners hold the data; durability-to-store is additive)."""
        if self._cluster.is_write_owner(self._host, index, slice_i):
            raise RebalanceError(
                f"refusing to release {index}/{slice_i}: this node still "
                "owns it"
            )
        tier = getattr(self._server, "tier", None)
        released = 0
        uploaded = 0
        idx = self._holder.index(index)
        if idx is not None:
            for frame in idx.frames().values():
                for view in frame.views().values():
                    if tier is not None:
                        frag = view._fragment_raw(slice_i)
                        if frag is not None:
                            try:
                                if tier.store_fresh_meta(frag) is None:
                                    tier.upload_fragment(frag)
                                    uploaded += 1
                            except Exception as e:  # noqa: BLE001
                                self._stats.count(
                                    "cluster.rebalance.releaseUploadErrors"
                                )
                                self._log(
                                    f"release upload of {index}/{slice_i} "
                                    f"{frame.name}/{view.name} failed: {e}"
                                )
                    if view.remove_fragment(slice_i):
                        released += 1
        self._stats.count("cluster.rebalance.fragmentsReleased", released)
        return {"released": released, "uploaded": uploaded}

    # -- observability --------------------------------------------------

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def snapshot(self) -> dict:
        """The ``GET /debug/rebalance`` document."""
        out: dict = {
            "node": self._host,
            "epoch": self._cluster.epoch,
            "routingVersion": self._cluster.routing_version,
            "transition": self._cluster.transition_snapshot(),
            "running": self.running(),
            "deltaLog": self.delta_log.snapshot(),
            "deltaOverflows": self.delta_log.overflow_counts(),
            "joinCandidates": sorted(self._candidates),
        }
        if self._last_error:
            out["lastError"] = self._last_error
        state = self._state
        if state is not None:
            slices = state.get("slices", {})
            by_state: dict[str, int] = {}
            for s in slices.values():
                by_state[s.get("state", "?")] = by_state.get(s.get("state", "?"), 0) + 1
            out["coordinator"] = {
                "epoch": state.get("epoch"),
                "old": state.get("old"),
                "new": state.get("new"),
                "completed": state.get("completed", False),
                "sliceStates": by_state,
                "slices": slices,
            }
            if state.get("error"):
                out["coordinator"]["error"] = state["error"]
        return out
