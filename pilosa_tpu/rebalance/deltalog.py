"""Bounded per-slice delta log — the write-forwarding half of cutover.

While a slice is being bulk-copied to its new owner, writes keep
landing on the source (reads still route there, and
``Cluster.write_nodes`` applies every write on both rings).  The copy
streams a snapshot, so writes that race the stream could be missed on
the target; the source therefore LOGS every write to a migrating slice
from the moment the coordinator opens its copy window, and the
coordinator replays the log to the target after the bulk copy — the
reference's anti-entropy protocol, scoped to one cutover instead of a
cluster-wide sweep.

The log is BOUNDED (``cap`` logged bits per slice): a write storm that
overflows it marks the slice ``overflowed`` and the coordinator redoes
the bulk copy instead of replaying — bounded memory, unbounded
correctness.  Entries preserve application order, so a set-then-clear
replays to the same final state.

The log feeds from the fragment write-listener hook
(:func:`pilosa_tpu.core.fragment.register_write_listener`): every
successful ``set_bit``/``clear_bit``/``import_bulk`` on ANY fragment of
an actively-logged (index, slice) appends one entry.  When no slice is
logging, the listener costs one dict read per write.
"""

from __future__ import annotations

import threading


class DeltaLog:
    """Per-(index, slice) ordered write log with a per-slice bit cap."""

    def __init__(self, cap: int = 50_000, stats=None):
        self.cap = cap
        self._mu = threading.Lock()
        # (index, slice) -> {"entries": list, "bits": int, "overflowed": bool}
        self._logs: dict[tuple[str, int], dict] = {}
        # Lifetime overflow count per (index, slice) — survives
        # start/stop cycles so /debug/rebalance shows WHICH slices keep
        # outrunning the cap (the subscribe engine's re-run-on-overflow
        # path and capacity planning both need the per-slice view; the
        # untagged cluster.rebalance.deltaOverflow counter only says
        # that overflows happened somewhere).
        self._overflows: dict[tuple[str, int], int] = {}
        self.stats = stats

    # -- lifecycle (driven by the coordinator via /rebalance/delta) ----

    def start(self, index: str, slice_i: int) -> None:
        """Open (or keep open) the log for one slice — idempotent, and
        re-opening RESETS it: the coordinator calls start immediately
        before each bulk copy, so stale entries from a crashed earlier
        attempt never replay."""
        with self._mu:
            self._logs[(index, slice_i)] = {
                "entries": [],
                "bits": 0,
                "overflowed": False,
            }

    def stop(self, index: str, slice_i: int) -> None:
        with self._mu:
            self._logs.pop((index, slice_i), None)

    def active(self) -> list[tuple[str, int]]:
        with self._mu:
            return sorted(self._logs)

    def drain(self, index: str, slice_i: int) -> tuple[list[tuple], bool]:
        """Atomically take the slice's logged entries (in application
        order) and whether the log overflowed since the last drain;
        the log stays OPEN and empty, so writes racing the replay land
        in the next drain."""
        with self._mu:
            log = self._logs.get((index, slice_i))
            if log is None:
                return [], False
            entries = log["entries"]
            overflowed = log["overflowed"]
            log["entries"] = []
            log["bits"] = 0
            log["overflowed"] = False
            return entries, overflowed

    def requeue(self, index: str, slice_i: int, entries: list[tuple]) -> None:
        """Put drained-but-unreplayed entries BACK at the head of the
        log (a replay push that died mid-way must not lose the tail) —
        order is preserved; the cap is deliberately ignored here (the
        entries were already admitted once)."""
        if not entries:
            return
        with self._mu:
            log = self._logs.get((index, slice_i))
            if log is None or log["overflowed"]:
                return
            log["entries"] = list(entries) + log["entries"]
            log["bits"] += sum(
                len(e[2]) + len(e[4]) for e in entries
            )

    def snapshot(self) -> dict:
        with self._mu:
            return {
                f"{i}/{s}": {
                    "entries": len(log["entries"]),
                    "bits": log["bits"],
                    "overflowed": log["overflowed"],
                    "overflows": self._overflows.get((i, s), 0),
                }
                for (i, s), log in self._logs.items()
            }

    def overflow_counts(self) -> dict:
        """Lifetime per-slice overflow counts (``{"idx/slice": n}``) —
        includes slices whose log has since closed."""
        with self._mu:
            return {f"{i}/{s}": n for (i, s), n in self._overflows.items()}

    # -- the fragment write-listener hook ------------------------------

    def record(
        self, frag, set_rows, set_cols, clear_rows, clear_cols, exact=True
    ) -> None:
        """Append one write to the slice's log (no-op when the slice is
        not migrating).  ``*_cols`` are ABSOLUTE column ids, matching
        the import-view replay wire format.  ``exact`` (the listener
        protocol's changed-bits flag) is irrelevant here: replay is
        idempotent set/clear, so already-true bits are harmless.
        Called under the fragment lock so log order equals application
        order; only takes the log lock (a leaf in the lock
        hierarchy)."""
        key = (frag.index, frag.slice)
        with self._mu:
            log = self._logs.get(key)
            if log is None or log["overflowed"]:
                return
            n = len(set_rows) + len(clear_rows)
            if n == 0:
                return
            if log["bits"] + n > self.cap:
                # Overflow: drop everything — the coordinator must redo
                # the bulk copy, which subsumes any replay.
                log["entries"] = []
                log["bits"] = 0
                log["overflowed"] = True
                self._overflows[key] = self._overflows.get(key, 0) + 1
                if self.stats is not None:
                    self.stats.count("cluster.rebalance.deltaOverflow")
                    self.stats.count_with_custom_tags(
                        "rebalance.deltalog.overflows",
                        1,
                        [f"slice:{frag.index}/{frag.slice}"],
                    )
                return
            log["entries"].append(
                (
                    frag.frame,
                    frag.view,
                    [int(r) for r in set_rows],
                    [int(c) for c in set_cols],
                    [int(r) for r in clear_rows],
                    [int(c) for c in clear_cols],
                )
            )
            log["bits"] += n
