"""Migration planner — the slice-ownership diff between two rings.

Placement is pure in (index, slice, ring), so the plan is computed
identically on any node from the transition's old/new host lists: for
every slice of every index, owners on the old ring vs owners on the new
ring; slices whose owner SET changes become one :class:`SliceMove`
(copy to the hosts gaining it, release from the hosts losing it).
Order-only changes (same owner set, different primary) need no data
movement — the commit re-routes them with the data already in place.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class SliceMove:
    """One slice's migration: stream from a ``source`` replica to every
    ``target``, then release from every ``release`` host."""

    index: str
    slice: int
    sources: tuple[str, ...]  # old-ring owners (serve reads during copy)
    targets: tuple[str, ...]  # new-ring owners that lack the slice
    releases: tuple[str, ...]  # old-ring owners not in the new ring

    @property
    def key(self) -> str:
        return f"{self.index}/{self.slice}"


def compute_plan(cluster, index_max_slices: dict[str, int]) -> list[SliceMove]:
    """Per-fragment migration plan for the cluster's ACTIVE transition.

    ``index_max_slices`` maps each index to the max slice to consider —
    callers pass ``max(max_slice, max_inverse_slice)`` since standard
    and inverse fragments of slice *i* share one placement.  Slices
    already flipped still appear in the plan (the coordinator skips
    them from its persisted per-slice state on resume)."""
    t = cluster.transition
    if t is None:
        return []
    old_ring = [cluster.node_by_host(h) or _node(h) for h in t.old_hosts]
    new_by_host = {n.host: n for n in t.new_nodes}
    new_ring = [new_by_host[h] for h in t.new_hosts]
    moves: list[SliceMove] = []
    for index in sorted(index_max_slices):
        for s in range(index_max_slices[index] + 1):
            pid = cluster.partition(index, s)
            old = [n.host for n in cluster.partition_nodes_over(pid, old_ring)]
            new = [n.host for n in cluster.partition_nodes_over(pid, new_ring)]
            if set(old) == set(new):
                continue
            moves.append(
                SliceMove(
                    index=index,
                    slice=s,
                    sources=tuple(old),
                    targets=tuple(h for h in new if h not in old),
                    releases=tuple(h for h in old if h not in new),
                )
            )
    return moves


def _node(host: str):
    from pilosa_tpu.cluster.topology import NODE_STATE_UP, Node

    return Node(host=host, state=NODE_STATE_UP)
