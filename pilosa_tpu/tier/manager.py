"""TierManager — the node-side policy engine over the object store.

Extends the PR-3 residency hierarchy one level down.  A fragment now
has three states instead of two:

    hot        local roaring file (+ optional HBM mirror)
    cold       metadata resident on the View, bytes as a tar in the
               object store
    (absent)   the slice has no data in this view

and the manager drives every transition:

* **Demand hydration** (cold → hydrating → hot): first touch through
  ``View.fragment`` / ``create_fragment_if_not_exists`` fetches the
  tar (checksum-verified twice: the store's content sha256 AND the
  tar's embedded per-entry sums), restores it, and installs the
  fragment — on the prefetcher's hydrate lane, so concurrent
  hydrations are bounded and query-lane HBM warms still win, under
  the ``[tier] hydrate-throttle-mbps`` token throttle.  Each
  hydration runs inside a ``hydrate`` trace span, so it shows up in
  the slow-query log's stage breakdown.
* **LRU demotion** (hot → cold): ``[tier] disk-budget-bytes`` bounds
  the local bytes of hot fragments (the roaring file + TopN cache —
  the bytes the page cache actually carries for an mmap'd open);
  past it, least-recently-touched fragments upload (if stale in the
  store) and flip to tar-only.  The flip is optimistic — a write
  racing it either aborts the demotion or revives the fragment by
  hydration; bits are never dropped (core/fragment.py
  ``mark_retired_if_version``).
* **Retention** (time-quantum views): expired sub-views age to the
  store past ``retention-age-s`` and DELETE past
  ``retention-delete-s`` (per-frame overrides in frame meta) — the
  time-series retention scenario the reference never had.
* **Bootstrap**: a node with an empty data dir and only ``[tier]
  store`` configured restores the schema from ``schema.json`` and
  registers every stored fragment cold — it serves the full index,
  hydrating on demand.

Counters: ``tier.hydrations`` / ``tier.demotions`` /
``tier.storeBytes`` / ``tier.storeErrors`` (+ per-op store latency
summaries from tier/store.py); full state at ``GET /debug/tier``.
"""

from __future__ import annotations

import io
import os
import threading
import time
from datetime import datetime

from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.view import VIEW_INVERSE, VIEW_STANDARD
from pilosa_tpu.obs.stats import NopStatsClient
from pilosa_tpu.obs.trace import NOP_TRACER
from pilosa_tpu.tier.store import ObjectMeta, ObjectStore, StoreError

SCHEMA_KEY = "schema.json"
FRAGMENT_PREFIX = "fragments/"

# Per-fragment state history retained for /debug/tier (the cold-boot
# test asserts the cold -> hydrating -> hot transition is visible).
_HISTORY_LIMIT = 8


class TierError(RuntimeError):
    pass


class HydrationError(TierError):
    """A cold fragment could not be hydrated from the store.  Always
    loud: the alternative is serving (or writing into) a silently
    empty fragment."""


def fragment_store_key(index: str, frame: str, view: str, slice_i: int) -> str:
    return f"{FRAGMENT_PREFIX}{index}/{frame}/{view}/{int(slice_i)}.tar"


def parse_fragment_store_key(key: str) -> tuple[str, str, str, int] | None:
    if not key.startswith(FRAGMENT_PREFIX) or not key.endswith(".tar"):
        return None
    parts = key[len(FRAGMENT_PREFIX) : -len(".tar")].split("/")
    if len(parts) != 4 or not parts[3].isdigit():
        return None
    return parts[0], parts[1], parts[2], int(parts[3])


class TierManager:
    def __init__(
        self,
        holder,
        store: ObjectStore,
        prefetcher=None,
        stats=None,
        tracer=None,
        logger=None,
        hydrate_throttle_mbps: float = 0.0,
        disk_budget_bytes: int = 0,
        retention_age_s: float = 0.0,
        retention_delete_s: float = 0.0,
    ):
        self.holder = holder
        self.store = store
        self.prefetcher = prefetcher
        self.stats = stats or NopStatsClient()
        self.tracer = tracer or NOP_TRACER
        self.logger = logger or (lambda msg: None)
        self.hydrate_throttle_mbps = float(hydrate_throttle_mbps)
        self.disk_budget_bytes = int(disk_budget_bytes)
        self.retention_age_s = float(retention_age_s)
        self.retention_delete_s = float(retention_delete_s)

        self._mu = threading.Lock()
        # key -> current state; key -> bounded transition history
        self._states: dict[str, str] = {}
        self._history: dict[str, list[str]] = {}
        # Hydration single-flight: key -> Event while a fetch is in
        # progress (two queries touching the same cold fragment fetch
        # once; waiters block on the Event, then re-check).
        self._inflight: dict[str, threading.Event] = {}
        # LRU clock for demotion: key -> last-touch monotonic time.
        # Written LOCK-FREE from View.fragment's hot path (a dict store
        # is GIL-atomic); fragments never touched rank oldest.
        self._touch: dict[str, float] = {}
        # Known store object sizes (key -> bytes) behind the
        # tier.storeBytes gauge; refreshed by puts/deletes/bootstrap.
        self._store_sizes: dict[str, int] = {}
        # Serializing token throttle for hydration reads.
        self._gate_mu = threading.Lock()
        self._gate = 0.0
        # Single-flight flag for the ASYNC disk-budget enforcement a
        # hydration schedules (uploads+demotions must not ride the
        # query's critical path; the budget is soft between passes,
        # like the page cache it accounts).
        self._enforcing = False

    # ------------------------------------------------------------------
    # keys / state bookkeeping
    # ------------------------------------------------------------------

    @staticmethod
    def _frag_key(frag) -> str:
        return fragment_store_key(frag.index, frag.frame, frag.view, frag.slice)

    def _view_key(self, view, slice_i: int) -> str:
        return fragment_store_key(view.index, view.frame, view.name, slice_i)

    def _set_state(self, key: str, state: str) -> None:
        with self._mu:
            self._states[key] = state
            hist = self._history.setdefault(key, [])
            if not hist or hist[-1] != state:
                hist.append(state)
                if len(hist) > _HISTORY_LIMIT:
                    del hist[0]

    def _drop_state(self, key: str) -> None:
        with self._mu:
            self._states.pop(key, None)
            self._history.pop(key, None)
        self._touch.pop(key, None)

    def touch(self, view, slice_i: int) -> None:
        """Hot-path LRU update from ``View.fragment`` — lock-free."""
        self._touch[self._view_key(view, slice_i)] = time.monotonic()

    def _note_store_size(self, key: str, size: int | None) -> None:
        with self._mu:
            if size is None:
                self._store_sizes.pop(key, None)
            else:
                self._store_sizes[key] = int(size)
            total = sum(self._store_sizes.values())
        self.stats.gauge("tier.storeBytes", float(total))

    # ------------------------------------------------------------------
    # hydration (cold -> hydrating -> hot)
    # ------------------------------------------------------------------

    def hydrate(self, view, slice_i: int):
        """Materialize a cold fragment.  Called by the View on first
        touch; rides the prefetcher's hydrate lane when one is wired
        (query-lane HBM warms still pop first), inline otherwise.
        Raises :class:`HydrationError` on failure — never installs a
        silently empty fragment."""
        key = self._view_key(view, slice_i)
        # Capture the CALLER's span before hopping to a prefetcher
        # worker thread: the hydrate span must parent into the query's
        # trace (and its slow-query stage breakdown), and contextvars
        # don't cross the lane's worker pool.
        parent = self.tracer.current()
        if self.prefetcher is not None:
            return self.prefetcher.run_hydration(
                lambda: self._hydrate_sync(view, slice_i, key, parent)
            )
        return self._hydrate_sync(view, slice_i, key, parent)

    def _hydrate_sync(self, view, slice_i: int, key: str, parent=None):
        while True:
            frag = view._fragment_raw(slice_i)
            if frag is not None:
                return frag  # a racing hydration won
            if view.cold_meta(slice_i) is None:
                return None  # raced a release/delete: genuinely absent
            with self._mu:
                ev = self._inflight.get(key)
                if ev is None:
                    ev = self._inflight[key] = threading.Event()
                    owner = True
                else:
                    owner = False
            if owner:
                try:
                    return self._hydrate_owner(view, slice_i, key, parent)
                finally:
                    with self._mu:
                        self._inflight.pop(key, None)
                    ev.set()
            # Another thread is fetching this key: wait it out, then
            # loop to re-check (and take over if the owner failed).
            ev.wait()

    def _hydrate_owner(self, view, slice_i: int, key: str, parent=None):
        self._set_state(key, "hydrating")
        t0 = time.monotonic()
        try:
            with self.tracer.span(
                "hydrate", parent=parent, fragment=key
            ) as sp:
                data = self.store.get(key)  # content-sha verified
                self._throttle(len(data))
                frag = view._new_fragment(slice_i)
                frag.open()
                try:
                    # read_from verifies the tar's embedded per-entry
                    # checksums before installing.
                    frag.read_from(io.BytesIO(data))
                except BaseException:
                    frag.close()
                    for path in (frag.path, frag.cache_path):
                        try:
                            os.unlink(path)
                        except OSError:
                            pass
                    raise
                view.adopt_hydrated(slice_i, frag)
                sp.annotate(bytes=len(data))
        except Exception as e:
            self._set_state(key, "cold")
            self.logger(f"tier: hydration of {key} failed: {e}")
            raise HydrationError(
                f"hydration of {key} from {self.store.url} failed: {e}"
            ) from e
        self._set_state(key, "hot")
        self._touch[key] = time.monotonic()
        self.stats.count("tier.hydrations")
        self.stats.histogram(
            "tier.hydrateMs", (time.monotonic() - t0) * 1000.0
        )
        # Budget enforcement runs in the BACKGROUND (single-flight):
        # the demotions' uploads must not ride this query's critical
        # path, so the budget is soft within a pass — like the page
        # cache it accounts.
        self._schedule_enforce(protect=key)
        return frag

    def _schedule_enforce(self, protect: str | None = None) -> None:
        if self.disk_budget_bytes <= 0:
            return
        with self._mu:
            if self._enforcing:
                return
            self._enforcing = True

        def _run() -> None:
            try:
                self.enforce_disk_budget(protect=protect)
            except Exception as e:  # noqa: BLE001 — best-effort sweep
                self.logger(f"tier: background budget sweep failed: {e}")
            finally:
                with self._mu:
                    self._enforcing = False

        threading.Thread(target=_run, daemon=True, name="tier-demote").start()

    def _throttle(self, nbytes: int) -> None:
        """Serializing token throttle: hydration reads collectively
        stay under ``hydrate-throttle-mbps`` so bulk hydration cannot
        saturate the store link while the node serves."""
        rate = self.hydrate_throttle_mbps * 1e6 / 8.0
        if rate <= 0:
            return
        with self._gate_mu:
            now = time.monotonic()
            start = max(now, self._gate)
            self._gate = start + nbytes / rate
            wait = start - now
        if wait > 0:
            time.sleep(min(wait, 60.0))

    # ------------------------------------------------------------------
    # upload / demotion (hot -> cold)
    # ------------------------------------------------------------------

    def upload_fragment(self, frag) -> ObjectMeta:
        """Archive one fragment to the store (checksummed tar; the
        fragment's LOGICAL checksum travels in the object's extra
        metadata so freshness checks never download the tar)."""
        buf = io.BytesIO()
        frag.write_to(buf)
        data = buf.getvalue()
        key = self._frag_key(frag)
        meta = self.store.put(
            key, data, extra={"checksum": frag.checksum().hex()}
        )
        self._note_store_size(key, meta.size)
        return meta

    def store_fresh_meta(self, frag) -> ObjectMeta | None:
        """The store's object metadata for ``frag`` IFF its recorded
        logical checksum matches the live fragment — the rebalance
        bulk-copy uses this to ride the store instead of peer
        streams."""
        try:
            meta = self.store.get_meta(self._frag_key(frag))
        except StoreError:
            return None
        if meta is None:
            return None
        if meta.extra.get("checksum") != frag.checksum().hex():
            return None
        return meta

    def demote(self, view, slice_i: int) -> bool:
        """Flip one hot fragment to tar-only: upload (skipped when the
        store already holds a checksum-fresh copy), then optimistically
        retire+pop — aborting if a write raced the upload — and delete
        the local files.  Returns True when the fragment went cold."""
        frag = view._fragment_raw(slice_i)
        if frag is None:
            return False
        # The view may post-date bootstrap's attach_all (created by a
        # later write): a cold entry without a hydrator would read as
        # absent, so attach before flipping anything cold.
        view.hydrator = self
        key = self._view_key(view, slice_i)
        # Exclude hydration for the whole flip + file cleanup: a
        # hydration racing the window between pop and close/unlink
        # would find the file still flock'd (or have its fresh file
        # deleted from under it).  Hydrations wait on the in-flight
        # event; a key already hydrating skips this demotion round.
        with self._mu:
            if key in self._inflight:
                return False
            ev = self._inflight[key] = threading.Event()
        try:
            version = frag._version
            try:
                meta = self.store_fresh_meta(frag)
                if meta is None:
                    meta = self.upload_fragment(frag)
            except StoreError as e:
                self.stats.count("tier.demoteErrors")
                self.logger(f"tier: demotion upload of {key} failed: {e}")
                return False
            popped = view.demote_fragment(
                slice_i, meta, expect=frag, expect_version=version
            )
            if popped is None:
                # A write landed between snapshot and flip: the upload
                # is stale — stay hot, the next sweep retries.
                self.stats.count("tier.demoteRaces")
                return False
            popped.close()
            for path in (popped.path, popped.cache_path):
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._set_state(key, "cold")
            self._touch.pop(key, None)
            self.stats.count("tier.demotions")
            return True
        finally:
            with self._mu:
                self._inflight.pop(key, None)
            ev.set()

    # -- disk budget ---------------------------------------------------

    @staticmethod
    def _fragment_local_bytes(frag) -> int:
        """On-disk bytes of one hot fragment (roaring file + TopN
        cache) — the bytes the page cache carries for its mmap'd open,
        which is what ``disk-budget-bytes`` accounts."""
        n = 0
        for path in (frag.path, frag.cache_path):
            try:
                n += os.path.getsize(path)
            except OSError:
                pass
        return n

    def _iter_hot(self):
        """(view, frag) over every hot fragment in the holder."""
        for index in self.holder.indexes().values():
            for frame in index.frames().values():
                for view in frame.views().values():
                    for frag in view.fragments():
                        yield view, frag

    def local_bytes(self) -> int:
        return sum(self._fragment_local_bytes(f) for _, f in self._iter_hot())

    def enforce_disk_budget(self, protect: str | None = None) -> int:
        """Demote least-recently-touched hot fragments until local
        bytes fit ``disk-budget-bytes``.  ``protect`` exempts one key
        (the fragment a hydration just installed — demoting it back
        immediately would thrash).  Returns the number demoted."""
        if self.disk_budget_bytes <= 0:
            return 0
        entries = []
        total = 0
        for view, frag in self._iter_hot():
            nbytes = self._fragment_local_bytes(frag)
            total += nbytes
            key = self._view_key(view, frag.slice)
            entries.append(
                (self._touch.get(key, 0.0), key, view, frag.slice, nbytes)
            )
        if total <= self.disk_budget_bytes:
            return 0
        entries.sort(key=lambda e: e[0])  # LRU first
        demoted = 0
        for _t, key, view, slice_i, nbytes in entries:
            if total <= self.disk_budget_bytes:
                break
            if key == protect:
                continue
            if self.demote(view, slice_i):
                total -= nbytes
                demoted += 1
        return demoted

    # ------------------------------------------------------------------
    # retention (time-quantum views)
    # ------------------------------------------------------------------

    def _frame_retention(self, frame) -> tuple[float, float]:
        age = getattr(frame, "retention_age_s", 0.0) or self.retention_age_s
        delete = (
            getattr(frame, "retention_delete_s", 0.0)
            or self.retention_delete_s
        )
        return float(age), float(delete)

    def sweep_retention(self, now: datetime | None = None) -> dict:
        """One retention pass: time-quantum sub-views whose period
        ended more than ``retention-age-s`` ago demote to the store;
        past ``retention-delete-s`` they delete — store objects AND
        local state.  ``now`` is injectable for tests."""
        now = now or datetime.utcnow()
        aged = deleted = 0
        for index in self.holder.indexes().values():
            for frame in index.frames().values():
                if not frame.time_quantum:
                    continue
                age_s, delete_s = self._frame_retention(frame)
                if age_s <= 0 and delete_s <= 0:
                    continue
                for view_name, view in sorted(frame.views().items()):
                    parsed = tq.parse_time_view(view_name)
                    if parsed is None:
                        continue
                    base, start, unit = parsed
                    if base not in (VIEW_STANDARD, VIEW_INVERSE):
                        continue
                    age = (
                        now - tq.view_period_end(start, unit)
                    ).total_seconds()
                    if delete_s > 0 and age > delete_s:
                        deleted += self._delete_view(frame, view)
                    elif age_s > 0 and age > age_s:
                        view.hydrator = self
                        for s in sorted(f.slice for f in view.fragments()):
                            if self.demote(view, s):
                                aged += 1
        if aged or deleted:
            self.logger(
                f"tier: retention sweep aged {aged} fragment(s) to the "
                f"store, deleted {deleted} past the horizon"
            )
        return {"aged": aged, "deleted": deleted}

    def _delete_view(self, frame, view) -> int:
        """Delete one expired view everywhere: store objects, cold
        registrations, local files.  Returns fragments removed."""
        slices = {f.slice for f in view.fragments()} | view.cold_slices()
        n = 0
        for s in sorted(slices):
            key = self._view_key(view, s)
            try:
                self.store.delete(key)
            except StoreError as e:
                self.logger(f"tier: store delete of {key} failed: {e}")
            self._note_store_size(key, None)
            self._set_state(key, "deleted")
            self._touch.pop(key, None)
            n += 1
        frame.delete_view(view.name)
        return n

    def sweep(self, now: datetime | None = None) -> dict:
        """The background tick: retention first (it can free budget),
        then disk-budget enforcement."""
        out = self.sweep_retention(now=now)
        out["demoted"] = self.enforce_disk_budget()
        return out

    # ------------------------------------------------------------------
    # bootstrap / backup
    # ------------------------------------------------------------------

    def put_schema(self) -> None:
        import json

        doc = {"indexes": self.holder.schema()}
        meta = self.store.put(
            SCHEMA_KEY, json.dumps(doc, sort_keys=True).encode()
        )
        self._note_store_size(SCHEMA_KEY, meta.size)

    def _restore_schema(self) -> int:
        import json

        try:
            meta = self.store.get_meta(SCHEMA_KEY)
        except StoreError:
            meta = None
        if meta is None:
            return 0
        doc = json.loads(self.store.get(SCHEMA_KEY).decode())
        n = 0
        for idx_doc in doc.get("indexes", []):
            opts = {}
            if idx_doc.get("columnLabel"):
                opts["column_label"] = idx_doc["columnLabel"]
            if idx_doc.get("timeQuantum"):
                opts["time_quantum"] = idx_doc["timeQuantum"]
            idx = self.holder.create_index_if_not_exists(
                idx_doc["name"], **opts
            )
            for f_doc in idx_doc.get("frames", []):
                frame = idx.create_frame_if_not_exists(
                    f_doc["name"],
                    row_label=f_doc.get("rowLabel"),
                    cache_type=f_doc.get("cacheType"),
                    cache_size=f_doc.get("cacheSize"),
                    inverse_enabled=f_doc.get("inverseEnabled"),
                    time_quantum=f_doc.get("timeQuantum"),
                    range_enabled=f_doc.get("rangeEnabled"),
                )
                if f_doc.get("retentionAgeS") or f_doc.get("retentionDeleteS"):
                    frame.set_options(
                        retention_age_s=f_doc.get("retentionAgeS"),
                        retention_delete_s=f_doc.get("retentionDeleteS"),
                    )
                have = {fld.name for fld in frame.bsi_fields()}
                for fld in f_doc.get("fields", []):
                    if fld["name"] not in have:
                        frame.create_field(
                            fld["name"], int(fld["min"]), int(fld["max"])
                        )
                n += 1
        return n

    def bootstrap(self) -> dict:
        """Cold-boot wiring: restore the schema from the store, then
        register every stored fragment the node does not hold locally
        as COLD (a local copy always wins — this node's op-log may be
        ahead; anti-entropy reconciles real divergence).  Also attaches
        the hydrator to every view so later demotions hydrate back."""
        frames_restored = self._restore_schema()
        cold = 0
        for meta in self.store.list(FRAGMENT_PREFIX):
            parsed = parse_fragment_store_key(meta.key)
            if parsed is None:
                continue
            index, frame_name, view_name, slice_i = parsed
            idx = self.holder.index(index)
            if idx is None:
                continue
            frame = idx.frame(frame_name)
            if frame is None:
                continue
            view = frame.view(view_name) or frame.create_view_if_not_exists(
                view_name
            )
            view.hydrator = self
            self._note_store_size(meta.key, meta.size)
            if view._fragment_raw(slice_i) is not None:
                self._set_state(meta.key, "hot")
                continue
            if view.register_cold(slice_i, meta):
                self._set_state(meta.key, "cold")
                cold += 1
        self.attach_all()
        if cold:
            self.logger(
                f"tier: registered {cold} cold fragment(s) from "
                f"{self.store.url}; hydration is on demand"
            )
        return {"frames": frames_restored, "cold": cold}

    def attach_all(self) -> None:
        """Attach the hydrator to every view (new cold entries created
        by demotion/retention need it, and ``View.fragment``'s touch
        hook feeds the LRU clock)."""
        for index in self.holder.indexes().values():
            for frame in index.frames().values():
                for view in frame.views().values():
                    view.hydrator = self

    def upload_all(self, include_schema: bool = True) -> int:
        """Archive the schema + every hot fragment to the store — the
        ctl ``backup --store`` engine and the rebalance-source seeding
        path."""
        if include_schema:
            self.put_schema()
        n = 0
        for _view, frag in self._iter_hot():
            self.upload_fragment(frag)
            n += 1
        return n

    def restore_from_store(
        self, index: str, frame: str, view_name: str, slice_i: int
    ) -> int:
        """Target side of store-riding rebalance bulk copy: register
        the stored fragment cold and hydrate it NOW.  Returns the
        object size; raises :class:`TierError` when the store has no
        such object."""
        idx = self.holder.index(index)
        f = idx.frame(frame) if idx is not None else None
        if f is None:
            raise TierError(f"frame not found: {index}/{frame}")
        key = fragment_store_key(index, frame, view_name, slice_i)
        meta = self.store.get_meta(key)
        if meta is None:
            raise TierError(f"store holds no object for {key}")
        view = f.create_view_if_not_exists(view_name)
        view.hydrator = self
        if view._fragment_raw(slice_i) is None:
            view.register_cold(slice_i, meta)
            self._set_state(key, "cold")
        self._note_store_size(key, meta.size)
        frag = self.hydrate(view, slice_i)
        if frag is None:
            raise TierError(f"hydration of {key} resolved no fragment")
        return meta.size

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``GET /debug/tier`` document."""
        with self._mu:
            states = dict(self._states)
            history = {k: list(v) for k, v in self._history.items()}
            store_bytes = sum(self._store_sizes.values())
        by_state: dict[str, int] = {}
        for st in states.values():
            by_state[st] = by_state.get(st, 0) + 1
        return {
            "store": self.store.snapshot(),
            "storeBytes": store_bytes,
            "diskBudgetBytes": self.disk_budget_bytes,
            "localBytes": self.local_bytes(),
            "hydrateThrottleMbps": self.hydrate_throttle_mbps,
            "retention": {
                "ageS": self.retention_age_s,
                "deleteS": self.retention_delete_s,
            },
            "countsByState": by_state,
            "fragments": {
                key: {"state": states[key], "history": history.get(key, [])}
                for key in sorted(states)
            },
        }
