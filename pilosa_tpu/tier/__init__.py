"""Tiered storage — the cold tier below the PR-3 residency hierarchy.

The residency ladder so far is HBM mirror ← mmap'd host file ← nothing:
a node cannot admit an index whose plane bytes exceed local disk+RAM,
a joining node hydrates exclusively by hammering peers, and expired
time-quantum views live forever.  This package extends the ladder one
level down to a shared OBJECT STORE holding the existing fragment tar
format from ``stream/``:

* :mod:`pilosa_tpu.tier.store` — the pluggable object store: a
  local-filesystem backend (tests/bench/smoke) and an S3-style HTTP
  backend behind one interface, with content checksums on every object
  and retry/breaker via ``net/resilience.py``.
* :mod:`pilosa_tpu.tier.manager` — the node-side policy engine:
  demand hydration of ``cold`` fragments (metadata resident, bytes in
  the store) on first touch, token-throttled through the prefetcher's
  hydrate lane; disk-budget accounting with LRU demotion back to
  tar-only; time-quantum retention (age expired views to the store,
  delete past a second horizon); and cold-boot bootstrap so a node
  with an empty data dir and only ``[tier] store`` configured serves
  the whole index.
"""

from __future__ import annotations

from pilosa_tpu.tier.store import (  # noqa: F401
    HTTPStore,
    LocalFSStore,
    ObjectMeta,
    ObjectStore,
    StoreChecksumError,
    StoreError,
    open_store,
    serve_store,
)
from pilosa_tpu.tier.manager import (  # noqa: F401
    HydrationError,
    TierError,
    TierManager,
    fragment_store_key,
    parse_fragment_store_key,
)

__all__ = [
    "HTTPStore",
    "HydrationError",
    "LocalFSStore",
    "ObjectMeta",
    "ObjectStore",
    "StoreChecksumError",
    "StoreError",
    "TierError",
    "TierManager",
    "fragment_store_key",
    "open_store",
    "parse_fragment_store_key",
    "serve_store",
]
