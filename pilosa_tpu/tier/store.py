"""Pluggable object store for the cold tier.

One interface, two backends:

* :class:`LocalFSStore` — objects as files under one root directory
  with a JSON metadata sidecar per object.  The tests/bench/smoke
  backend, and the durable half of :func:`serve_store`.
* :class:`HTTPStore` — an S3-style HTTP backend: ``PUT/GET/DELETE
  {base}/{key}`` with the content checksum in an ``X-Content-Sha256``
  header and listing via ``GET {base}/?prefix=``.  Unary calls ride
  the shared retry policy and per-host circuit breaker from
  ``net/resilience.py`` — a flapping store fails in microseconds
  instead of burning a socket timeout per op.

Every object carries a SHA-256 content checksum, written at put time
and verified on every get: a torn upload, bit rot, or a truncated
download surfaces as :class:`StoreChecksumError` (a named error) rather
than silently installing bad bytes downstream.

Store ops are timed into per-op latency histograms
(``tier.store.<op>Ms`` — summaries on ``/metrics``) and failures count
``tier.storeErrors``; both through the stats client handed to the
store, so the bare default costs nothing.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import os
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Any

from pilosa_tpu.net import resilience as rz
from pilosa_tpu.obs.stats import NopStatsClient

# Metadata sidecar suffix in the local backend; keys may not end with
# it (they would collide with their own sidecar).
META_SUFFIX = ".pmeta"

# Content-checksum and extra-metadata headers in the HTTP protocol.
SHA_HEADER = "X-Content-Sha256"
EXTRA_HEADER = "X-Store-Extra"


class StoreError(RuntimeError):
    """Any object-store failure (transport, protocol, missing key)."""


class StoreChecksumError(StoreError):
    """An object's bytes do not match its recorded content checksum —
    the named torn-bytes error the hydration/restore paths reject on
    instead of installing corrupt state."""


@dataclass
class ObjectMeta:
    """One stored object's identity: size + content checksum + opaque
    uploader-supplied ``extra`` (the tier manager records the
    fragment's logical checksum there so rebalance can judge
    freshness without downloading the tar)."""

    key: str
    size: int
    sha256: str
    mtime: float = 0.0
    extra: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "key": self.key,
            "size": self.size,
            "sha256": self.sha256,
            "mtime": self.mtime,
            "extra": self.extra,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ObjectMeta":
        return cls(
            key=str(d.get("key", "")),
            size=int(d.get("size", 0)),
            sha256=str(d.get("sha256", "")),
            mtime=float(d.get("mtime", 0.0)),
            extra=dict(d.get("extra") or {}),
        )


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def validate_key(key: str) -> str:
    """Store keys are slash-separated relative paths — no traversal,
    no absolute paths, no collision with the local backend's metadata
    sidecars."""
    if (
        not key
        or key.startswith("/")
        or key.endswith(META_SUFFIX)
        or any(part in ("", ".", "..") for part in key.split("/"))
    ):
        raise StoreError(f"invalid store key: {key!r}")
    return key


class ObjectStore:
    """Base class: public ops wrap the backend's ``_op`` methods with
    per-op latency histograms and the shared error counter."""

    #: human-readable backend location, surfaced in /debug/tier
    url: str = ""

    def __init__(self, stats=None):
        self.stats = stats or NopStatsClient()

    # -- public API (timed) -------------------------------------------

    def put(self, key: str, data: bytes, extra: dict | None = None) -> ObjectMeta:
        validate_key(key)
        return self._timed("put", lambda: self._put(key, data, extra or {}))

    def get(self, key: str) -> bytes:
        """Fetch and CHECKSUM-VERIFY one object's bytes."""
        validate_key(key)
        data, meta = self._timed("get", lambda: self._get(key))
        if meta.sha256 and sha256_hex(data) != meta.sha256:
            self.stats.count("tier.storeErrors")
            raise StoreChecksumError(
                f"store object {key!r}: content does not match its "
                f"recorded sha256 ({meta.sha256[:12]}…)"
            )
        return data

    def get_meta(self, key: str) -> ObjectMeta | None:
        """Object metadata without the bytes; None when absent."""
        validate_key(key)
        return self._timed("head", lambda: self._get_meta(key))

    def list(self, prefix: str = "") -> list[ObjectMeta]:
        return self._timed("list", lambda: self._list(prefix))

    def delete(self, key: str) -> bool:
        validate_key(key)
        return self._timed("delete", lambda: self._delete(key))

    def _timed(self, op: str, fn):
        t0 = time.monotonic()
        try:
            return fn()
        except Exception:
            self.stats.count("tier.storeErrors")
            raise
        finally:
            self.stats.histogram(
                f"tier.store.{op}Ms", (time.monotonic() - t0) * 1000.0
            )

    # -- backend hooks -------------------------------------------------

    def _put(self, key: str, data: bytes, extra: dict) -> ObjectMeta:
        raise NotImplementedError

    def _get(self, key: str) -> tuple[bytes, ObjectMeta]:
        raise NotImplementedError

    def _get_meta(self, key: str) -> ObjectMeta | None:
        raise NotImplementedError

    def _list(self, prefix: str) -> list[ObjectMeta]:
        raise NotImplementedError

    def _delete(self, key: str) -> bool:
        raise NotImplementedError

    def snapshot(self) -> dict:
        return {"backend": type(self).__name__, "url": self.url}


# ---------------------------------------------------------------------------
# local filesystem backend
# ---------------------------------------------------------------------------


class LocalFSStore(ObjectStore):
    """Objects as files under ``root`` with a ``<key>.pmeta`` JSON
    sidecar.  Writes are atomic (tmp + rename), and the sidecar is
    written LAST so its presence is the commit marker: a crash
    mid-upload leaves a data file without metadata, which reads as
    absent rather than as a torn object."""

    def __init__(self, root: str, stats=None):
        super().__init__(stats=stats)
        self.root = os.path.abspath(os.path.expanduser(root))
        self.url = f"file://{self.root}"
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *key.split("/"))

    def _put(self, key: str, data: bytes, extra: dict) -> ObjectMeta:
        path = self._path(key)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        meta = ObjectMeta(
            key=key,
            size=len(data),
            sha256=sha256_hex(data),
            mtime=time.time(),
            extra=dict(extra),
        )
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        mtmp = path + META_SUFFIX + ".tmp"
        with open(mtmp, "w") as f:
            json.dump(meta.to_dict(), f)
        os.replace(mtmp, path + META_SUFFIX)
        return meta

    def _read_meta(self, key: str) -> ObjectMeta | None:
        try:
            with open(self._path(key) + META_SUFFIX) as f:
                return ObjectMeta.from_dict(json.load(f))
        except (OSError, ValueError):
            return None

    def _get(self, key: str) -> tuple[bytes, ObjectMeta]:
        meta = self._read_meta(key)
        if meta is None:
            raise StoreError(f"store object not found: {key!r}")
        try:
            with open(self._path(key), "rb") as f:
                return f.read(), meta
        except OSError as e:
            raise StoreError(f"store object unreadable: {key!r}: {e}") from e

    def _get_meta(self, key: str) -> ObjectMeta | None:
        return self._read_meta(key)

    def _list(self, prefix: str) -> list[ObjectMeta]:
        out: list[ObjectMeta] = []
        for dirpath, _dirs, files in os.walk(self.root):
            for name in files:
                if not name.endswith(META_SUFFIX):
                    continue
                full = os.path.join(dirpath, name)
                key = os.path.relpath(
                    full[: -len(META_SUFFIX)], self.root
                ).replace(os.sep, "/")
                if not key.startswith(prefix):
                    continue
                meta = self._read_meta(key)
                if meta is not None:
                    out.append(meta)
        out.sort(key=lambda m: m.key)
        return out

    def _delete(self, key: str) -> bool:
        existed = False
        for path in (self._path(key), self._path(key) + META_SUFFIX):
            try:
                os.unlink(path)
                existed = True
            except OSError:
                pass
        return existed


# ---------------------------------------------------------------------------
# S3-style HTTP backend
# ---------------------------------------------------------------------------


class HTTPStore(ObjectStore):
    """S3-style HTTP object store behind the same interface.

    Protocol (also what :func:`serve_store` serves):

    * ``PUT {base}/{key}`` — body is the object; ``X-Content-Sha256``
      carries the uploader's checksum (the server verifies it) and
      ``X-Store-Extra`` optional JSON metadata.
    * ``GET {base}/{key}`` — 200 body + the same headers back.
    * ``HEAD``-equivalent: ``GET {base}/{key}?meta=true`` — JSON meta.
    * ``DELETE {base}/{key}``.
    * ``GET {base}/?prefix=p`` — JSON ``{"objects": [meta, ...]}``.

    All ops are idempotent (puts replace whole objects), so every call
    rides the retry policy; the per-host breaker makes a down store
    fail fast instead of stalling hydration behind socket timeouts.
    """

    def __init__(
        self,
        base_url: str,
        stats=None,
        retry: "rz.RetryPolicy | None" = None,
        breakers: "rz.BreakerRegistry | None" = None,
        timeout: float = 30.0,
    ):
        super().__init__(stats=stats)
        parsed = urllib.parse.urlparse(base_url)
        if parsed.scheme != "http" or not parsed.netloc:
            raise StoreError(f"invalid http store url: {base_url!r}")
        self.url = base_url.rstrip("/")
        self.host = parsed.netloc
        self.base_path = parsed.path.rstrip("/")
        self.timeout = timeout
        self.retry = retry or rz.RetryPolicy(stats=stats)
        self.breakers = breakers or rz.BreakerRegistry(stats=stats)

    def _request(
        self,
        method: str,
        path: str,
        body: bytes = b"",
        headers: dict[str, str] | None = None,
    ) -> tuple[int, bytes, dict[str, str]]:
        def attempt():
            self.breakers.check(self.host)
            conn = None
            try:
                try:
                    conn = http.client.HTTPConnection(
                        self.host, timeout=self.timeout
                    )
                    conn.request(method, path, body=body, headers=headers or {})
                    resp = conn.getresponse()
                    data = resp.read()
                except rz.TRANSPORT_ERRORS:
                    self.breakers.record(self.host, False)
                    raise
                self.breakers.record(self.host, resp.status < 500)
                return (
                    resp.status,
                    data,
                    {k.lower(): v for k, v in resp.getheaders()},
                )
            finally:
                if conn is not None:
                    conn.close()

        return self.retry.call(attempt)

    def _key_path(self, key: str) -> str:
        return f"{self.base_path}/{urllib.parse.quote(key)}"

    def _put(self, key: str, data: bytes, extra: dict) -> ObjectMeta:
        meta = ObjectMeta(
            key=key, size=len(data), sha256=sha256_hex(data),
            mtime=time.time(), extra=dict(extra),
        )
        headers = {SHA_HEADER: meta.sha256}
        if extra:
            headers[EXTRA_HEADER] = json.dumps(extra, separators=(",", ":"))
        status, body, _ = self._request(
            "PUT", self._key_path(key), body=data, headers=headers
        )
        if status >= 300:
            raise StoreError(
                f"store put {key!r} failed: http {status}: "
                f"{body[:200].decode(errors='replace')}"
            )
        return meta

    @staticmethod
    def _meta_from_headers(key: str, data_len: int, headers: dict) -> ObjectMeta:
        extra: dict = {}
        raw = headers.get(EXTRA_HEADER.lower(), "")
        if raw:
            try:
                extra = json.loads(raw)
            except ValueError:
                extra = {}
        return ObjectMeta(
            key=key,
            size=data_len,
            sha256=headers.get(SHA_HEADER.lower(), ""),
            extra=extra,
        )

    def _get(self, key: str) -> tuple[bytes, ObjectMeta]:
        status, data, headers = self._request("GET", self._key_path(key))
        if status == 404:
            raise StoreError(f"store object not found: {key!r}")
        if status >= 300:
            raise StoreError(f"store get {key!r} failed: http {status}")
        return data, self._meta_from_headers(key, len(data), headers)

    def _get_meta(self, key: str) -> ObjectMeta | None:
        status, data, _ = self._request(
            "GET", self._key_path(key) + "?meta=true"
        )
        if status == 404:
            return None
        if status >= 300:
            raise StoreError(f"store head {key!r} failed: http {status}")
        try:
            return ObjectMeta.from_dict(json.loads(data))
        except ValueError as e:
            raise StoreError(f"store head {key!r}: bad meta: {e}") from e

    def _list(self, prefix: str) -> list[ObjectMeta]:
        q = urllib.parse.urlencode({"prefix": prefix})
        status, data, _ = self._request("GET", f"{self.base_path}/?{q}")
        if status >= 300:
            raise StoreError(f"store list failed: http {status}")
        try:
            doc = json.loads(data)
            return [ObjectMeta.from_dict(d) for d in doc.get("objects", [])]
        except (ValueError, AttributeError) as e:
            raise StoreError(f"store list: bad response: {e}") from e

    def _delete(self, key: str) -> bool:
        status, _, _ = self._request("DELETE", self._key_path(key))
        if status == 404:
            return False
        if status >= 300:
            raise StoreError(f"store delete {key!r} failed: http {status}")
        return True

    def snapshot(self) -> dict:
        out = super().snapshot()
        out["breaker"] = self.breakers.state(self.host)
        return out


# ---------------------------------------------------------------------------
# the serving side of the HTTP protocol (tests / smoke / simple deploys)
# ---------------------------------------------------------------------------


def serve_store(store: ObjectStore, host: str = "127.0.0.1", port: int = 0):
    """Serve ``store`` over the :class:`HTTPStore` protocol.  Returns a
    ``ThreadingHTTPServer`` (caller starts ``serve_forever`` on a
    thread and owns ``shutdown``).  This is how the tests and the
    tier-smoke exercise the S3-style backend for real — and a minimal
    single-node deployment of a shared store."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet
            pass

        def _key(self) -> str:
            path = urllib.parse.urlparse(self.path).path
            return urllib.parse.unquote(path.lstrip("/"))

        def _send(self, status: int, body: bytes = b"",
                  headers: dict | None = None) -> None:
            self.send_response(status)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, status: int, obj: Any) -> None:
            self._send(
                status,
                json.dumps(obj).encode(),
                {"Content-Type": "application/json"},
            )

        def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
            parsed = urllib.parse.urlparse(self.path)
            query = dict(urllib.parse.parse_qsl(parsed.query))
            key = self._key()
            try:
                if not key:
                    objs = store.list(query.get("prefix", ""))
                    self._send_json(200, {"objects": [m.to_dict() for m in objs]})
                    return
                if query.get("meta") == "true":
                    meta = store.get_meta(key)
                    if meta is None:
                        self._send_json(404, {"error": "not found"})
                    else:
                        self._send_json(200, meta.to_dict())
                    return
                meta = store.get_meta(key)
                if meta is None:
                    self._send_json(404, {"error": "not found"})
                    return
                data = store.get(key)
                headers = {SHA_HEADER: meta.sha256}
                if meta.extra:
                    headers[EXTRA_HEADER] = json.dumps(
                        meta.extra, separators=(",", ":")
                    )
                self._send(200, data, headers)
            except StoreChecksumError as e:
                self._send_json(502, {"error": str(e)})
            except StoreError as e:
                self._send_json(400, {"error": str(e)})

        def do_PUT(self) -> None:  # noqa: N802
            key = self._key()
            try:
                n = int(self.headers.get("Content-Length", "0"))
                data = self.rfile.read(n)
                claimed = self.headers.get(SHA_HEADER, "")
                if claimed and sha256_hex(data) != claimed:
                    # reject the torn upload before it becomes an object
                    self._send_json(
                        422, {"error": "content does not match X-Content-Sha256"}
                    )
                    return
                extra: dict = {}
                raw = self.headers.get(EXTRA_HEADER, "")
                if raw:
                    try:
                        extra = json.loads(raw)
                    except ValueError:
                        extra = {}
                meta = store.put(key, data, extra=extra)
                self._send_json(200, meta.to_dict())
            except StoreError as e:
                self._send_json(400, {"error": str(e)})

        def do_DELETE(self) -> None:  # noqa: N802
            try:
                existed = store.delete(self._key())
                self._send_json(200 if existed else 404, {"deleted": existed})
            except StoreError as e:
                self._send_json(400, {"error": str(e)})

    return ThreadingHTTPServer((host, port), _Handler)


class _ServedStore:
    """A LocalFSStore served over HTTP in-process, as one handle —
    convenience for tests/smoke: ``with _ServedStore(root) as url:``."""

    def __init__(self, root: str):
        self.local = LocalFSStore(root)
        self.server = serve_store(self.local)
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True, name="tier-store"
        )

    @property
    def url(self) -> str:
        host, port = self.server.server_address[:2]
        return f"http://{host}:{port}"

    def __enter__(self) -> str:
        self._thread.start()
        return self.url

    def __exit__(self, *exc) -> None:
        self.server.shutdown()
        self.server.server_close()


def open_store(
    url: str,
    stats=None,
    retry: "rz.RetryPolicy | None" = None,
    breakers: "rz.BreakerRegistry | None" = None,
) -> ObjectStore | None:
    """``[tier] store`` value -> backend.  ``""`` -> None (tier off);
    ``http://…`` -> :class:`HTTPStore`; ``file://path`` or a bare path
    -> :class:`LocalFSStore`."""
    if not url:
        return None
    if url.startswith("http://") or url.startswith("https://"):
        if url.startswith("https://"):
            raise StoreError("https store urls are not supported yet")
        return HTTPStore(url, stats=stats, retry=retry, breakers=breakers)
    if url.startswith("file://"):
        url = url[len("file://"):]
    return LocalFSStore(url, stats=stats)
