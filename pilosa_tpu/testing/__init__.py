"""Test-support subpackage — fault injection lives here so the chaos
layer is importable by the server for soak runs without dragging test
frameworks into the production tree."""
