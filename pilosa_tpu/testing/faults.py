"""Deterministic fault injection — the chaos layer behind the resilience
tests and ``make chaos-smoke`` soak runs.

A :class:`FaultPlan` is a list of match-and-fire rules injected at three
boundaries (via :func:`check` calls compiled into the hot paths):

* ``rpc.send`` — in :class:`~pilosa_tpu.net.client.InternalClient`,
  after the breaker/deadline gates and before the socket dial;
* ``rpc.recv`` — in ``Handler.dispatch``, as a request arrives at a
  node (an injected error surfaces to the caller as HTTP 500);
* ``device.launch`` — in the executor, before a fused device program
  dispatches (direct and coalesced paths);
* ``gossip.send`` — in ``GossipNodeSet._send``, before each UDP
  datagram leaves (``host`` = the SENDING member's identity, ``path``
  = the message type, e.g. ``ping``/``ack``) — seeded ``prob`` +
  ``mode=drop`` is the churn-soak's deterministic lossy network.

The plan comes from the ``PILOSA_FAULTS`` environment variable (read
lazily on first check) or from :func:`install` (tests, soak drivers).
Spec grammar — semicolon-separated rules, each ``stage:key=value,...``::

    PILOSA_FAULTS='rpc.send:host=127.0.0.1:5001,path=/index/*/query,nth=1,mode=error;
                   rpc.recv:path=/index/*/query,mode=delay,delay-ms=100,times=1'

Match keys (all optional; a rule with none matches every call at its
stage):

* ``path``  — fnmatch glob against the request path (no query string)
* ``host``  — exact ``host:port`` (the TARGET host for rpc.send, the
  SERVING node for rpc.recv)
* ``nth``   — fire only on the Nth statically-matching call (1-based)
* ``times`` — stop firing after this many hits
* ``prob``  — fire with this probability, drawn from a per-rule RNG
  seeded by ``seed`` (default 0) — a seeded run is fully deterministic

Actions: ``mode=delay`` sleeps ``delay-ms`` and continues; ``mode=error``
raises :class:`FaultError` (a ``ConnectionError``, so the retry policy
sees a transport failure); ``mode=drop`` sleeps ``delay-ms`` then raises
``socket.timeout`` — a request that vanished into a dead network.

When no plan is installed, :func:`check` is one module-global read.
"""

from __future__ import annotations

import fnmatch
import os
import random
import socket
import threading
import time

STAGES = ("rpc.send", "rpc.recv", "device.launch", "gossip.send")
MODES = ("delay", "error", "drop")


class FaultError(ConnectionError):
    """An injected transport error."""


class FaultSpecError(ValueError):
    pass


class FaultRule:
    def __init__(
        self,
        stage: str,
        path: str | None = None,
        host: str | None = None,
        nth: int | None = None,
        times: int | None = None,
        prob: float | None = None,
        seed: int | None = None,
        mode: str = "error",
        delay_ms: float = 0.0,
    ):
        if mode not in MODES:
            raise FaultSpecError(f"unknown fault mode: {mode!r}")
        self.stage = stage
        self.path = path
        self.host = host
        self.nth = int(nth) if nth is not None else None
        self.times = int(times) if times is not None else None
        self.prob = float(prob) if prob is not None else None
        self.mode = mode
        self.delay_ms = float(delay_ms)
        self._rng = random.Random(seed if seed is not None else 0)
        self._mu = threading.Lock()
        # calls: invocations passing the STATIC filters (stage/host/
        # path) — the counter ``nth`` indexes; hits: times fired.
        self.calls = 0
        self.hits = 0

    def _static_match(self, stage: str, host: str | None, path: str | None) -> bool:
        if stage != self.stage:
            return False
        if self.host is not None and host != self.host:
            return False
        if self.path is not None and not fnmatch.fnmatchcase(
            path or "", self.path
        ):
            return False
        return True

    def consider(self, stage: str, host: str | None, path: str | None) -> bool:
        """Count the call against the rule and decide whether to fire."""
        if not self._static_match(stage, host, path):
            return False
        with self._mu:
            self.calls += 1
            if self.nth is not None and self.calls != self.nth:
                return False
            if self.times is not None and self.hits >= self.times:
                return False
            if self.prob is not None and self._rng.random() >= self.prob:
                return False
            self.hits += 1
            return True

    def fire(self) -> None:
        if self.delay_ms > 0:
            time.sleep(self.delay_ms / 1000.0)
        if self.mode == "delay":
            return
        if self.mode == "drop":
            raise socket.timeout(f"injected drop ({self.stage})")
        raise FaultError(f"injected error ({self.stage})")

    def snapshot(self) -> dict:
        with self._mu:
            out = {
                "stage": self.stage,
                "mode": self.mode,
                "calls": self.calls,
                "hits": self.hits,
            }
        for k in ("path", "host", "nth", "times", "prob"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        if self.delay_ms:
            out["delayMs"] = self.delay_ms
        return out


class FaultPlan:
    def __init__(self, rules):
        self.rules = list(rules)

    def check(self, stage: str, host: str | None = None, path: str | None = None) -> None:
        for rule in self.rules:
            if rule.consider(stage, host, path):
                rule.fire()

    def snapshot(self) -> list[dict]:
        return [r.snapshot() for r in self.rules]


_INT_KEYS = {"nth", "times", "seed"}
_FLOAT_KEYS = {"prob", "delay_ms"}
_STR_KEYS = {"path", "host", "mode"}


def parse(spec: str) -> FaultPlan:
    """Parse a ``PILOSA_FAULTS`` spec string into a plan.  Raises
    :class:`FaultSpecError` on malformed input — a chaos run with a
    typo'd spec must fail loudly, not silently inject nothing."""
    rules = []
    for part in (p.strip() for p in spec.split(";")):
        if not part:
            continue
        stage, sep, opts = part.partition(":")
        stage = stage.strip()
        if not sep or not stage:
            raise FaultSpecError(f"fault rule needs 'stage:opts': {part!r}")
        kwargs: dict = {}
        for opt in (o.strip() for o in opts.split(",")):
            if not opt:
                continue
            key, sep, value = opt.partition("=")
            if not sep:
                raise FaultSpecError(f"fault option needs key=value: {opt!r}")
            key = key.strip().replace("-", "_")
            value = value.strip()
            try:
                if key in _INT_KEYS:
                    kwargs[key] = int(value)
                elif key in _FLOAT_KEYS:
                    kwargs[key] = float(value)
                elif key in _STR_KEYS:
                    kwargs[key] = value
                else:
                    raise FaultSpecError(f"unknown fault option: {key!r}")
            except ValueError as e:
                raise FaultSpecError(f"bad fault option {opt!r}: {e}") from e
        rules.append(FaultRule(stage, **kwargs))
    return FaultPlan(rules)


# ---------------------------------------------------------------------------
# process-global plan
# ---------------------------------------------------------------------------

_UNSET = object()  # env not consulted yet
_plan = _UNSET
_mu = threading.Lock()


def install(plan: "FaultPlan | str") -> FaultPlan:
    """Install a plan (or spec string) process-wide; returns it so tests
    can assert on per-rule hit counts."""
    global _plan
    if isinstance(plan, str):
        plan = parse(plan)
    _plan = plan
    return plan


def clear() -> None:
    """Deactivate fault injection (and stop consulting the env)."""
    global _plan
    _plan = None


def reset() -> None:
    """Forget any installed plan AND re-arm the lazy env read — the
    fresh-process state."""
    global _plan
    _plan = _UNSET


def active() -> FaultPlan | None:
    global _plan
    if _plan is _UNSET:
        with _mu:
            if _plan is _UNSET:
                spec = os.environ.get("PILOSA_FAULTS", "")
                _plan = parse(spec) if spec else None
    return _plan


def check(stage: str, host: str | None = None, path: str | None = None) -> None:
    """The injection point: no-op (one global read) unless a plan with
    matching rules is installed."""
    plan = _plan
    if plan is _UNSET:
        plan = active()
    if plan is not None:
        plan.check(stage, host=host, path=path)
