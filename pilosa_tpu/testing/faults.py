"""Deterministic fault injection — the chaos layer behind the resilience
tests and ``make chaos-smoke`` soak runs.

A :class:`FaultPlan` is a list of match-and-fire rules injected at three
boundaries (via :func:`check` calls compiled into the hot paths):

* ``rpc.send`` — in :class:`~pilosa_tpu.net.client.InternalClient`,
  after the breaker/deadline gates and before the socket dial;
* ``rpc.recv`` — in ``Handler.dispatch``, as a request arrives at a
  node (an injected error surfaces to the caller as HTTP 500);
* ``device.launch`` — in the executor, before a fused device program
  dispatches.  ``host`` is the node's identity (so chaos can target one
  NODE of an in-process cluster), ``path`` names the launch site —
  ``direct`` (executor direct launch), ``coalesce`` (a coalesced
  launch's waiter), ``collective`` (inside a mesh psum dispatch+fetch,
  where the launch watchdog can observe a hang), ``topn`` (the fused
  TopN scorer) — and the check fires once per PARTICIPATING DEVICE with
  ``device`` = its ordinal, so a ``device=`` rule can target ONE device
  of a mesh;
* ``gossip.send`` — in ``GossipNodeSet._send``, before each UDP
  datagram leaves (``host`` = the SENDING member's identity, ``path``
  = the message type, e.g. ``ping``/``ack``) — seeded ``prob`` +
  ``mode=drop`` is the churn-soak's deterministic lossy network.

The plan comes from the ``PILOSA_FAULTS`` environment variable (read
lazily on first check) or from :func:`install` (tests, soak drivers).
Spec grammar — semicolon-separated rules, each ``stage:key=value,...``::

    PILOSA_FAULTS='rpc.send:host=127.0.0.1:5001,path=/index/*/query,nth=1,mode=error;
                   rpc.recv:path=/index/*/query,mode=delay,delay-ms=100,times=1;
                   device.launch:kind=oom,device=3,times=4'

Match keys (all optional; a rule with none matches every call at its
stage):

* ``path``  — fnmatch glob against the request path (no query string);
  for ``device.launch``, the launch site (``direct`` / ``coalesce`` /
  ``collective`` / ``topn``)
* ``host``  — exact ``host:port`` (the TARGET host for rpc.send, the
  SERVING node for rpc.recv and device.launch)
* ``device``— device ordinal (``device.launch`` only): fire only when
  this device participates in the launch — targets one flaky device of
  a multi-device mesh
* ``nth``   — fire only on the Nth statically-matching call (1-based)
* ``times`` — stop firing after this many hits
* ``prob``  — fire with this probability, drawn from a per-rule RNG
  seeded by ``seed`` (default 0) — a seeded run is fully deterministic
* ``after-ms`` / ``until-ms`` — activation window measured from the
  moment the plan was installed (:func:`install` / first env read): the
  rule matches only while ``after_ms <= elapsed < until_ms``.  A whole
  composed-failure timeline (gameday) preinstalls one plan whose rules
  activate and deactivate on schedule — no mid-run re-installs.
  Windowed calls don't advance ``nth`` outside the window.

Actions: ``mode=delay`` sleeps ``delay-ms`` and continues; ``mode=error``
raises :class:`FaultError` (a ``ConnectionError``, so the retry policy
sees a transport failure); ``mode=drop`` sleeps ``delay-ms`` then raises
``socket.timeout`` — a request that vanished into a dead network.

``kind=`` (``device.launch`` only) picks the device-failure shape the
health layer classifies (device/health.py) and overrides ``mode``:

* ``kind=error`` — raises :class:`FaultError`, the shape of an XLA
  runtime error (transient; the executor retries once);
* ``kind=oom``   — raises :class:`FaultOOM` with RESOURCE_EXHAUSTED
  text, the shape of a device allocator failure;
* ``kind=hang``  — sleeps ``delay-ms`` (default 60000) and then
  RETURNS: a launch that wedged.  Inside a ``collective`` site this is
  what trips the launch watchdog.

When no plan is installed, :func:`check` is one module-global read.
"""

from __future__ import annotations

import fnmatch
import os
import random
import socket
import threading
import time

STAGES = ("rpc.send", "rpc.recv", "device.launch", "gossip.send")
MODES = ("delay", "error", "drop")
# device.launch failure shapes (see module docstring); classified by
# pilosa_tpu/device/health.py at the launch sites.
KINDS = ("oom", "error", "hang")
# How long an injected hang sleeps when the rule gives no delay-ms:
# long enough that any sane launch watchdog trips first.
DEFAULT_HANG_MS = 60_000.0


class FaultError(ConnectionError):
    """An injected transport error."""


class FaultOOM(RuntimeError):
    """An injected device out-of-memory: message carries the
    RESOURCE_EXHAUSTED marker real XLA allocator failures do, so the
    health classifier treats both identically."""


class FaultSpecError(ValueError):
    pass


class FaultRule:
    def __init__(
        self,
        stage: str,
        path: str | None = None,
        host: str | None = None,
        device: int | None = None,
        nth: int | None = None,
        times: int | None = None,
        prob: float | None = None,
        seed: int | None = None,
        mode: str = "error",
        kind: str | None = None,
        delay_ms: float = 0.0,
        after_ms: float | None = None,
        until_ms: float | None = None,
    ):
        if mode not in MODES:
            raise FaultSpecError(f"unknown fault mode: {mode!r}")
        if kind is not None and kind not in KINDS:
            raise FaultSpecError(f"unknown fault kind: {kind!r}")
        if kind is not None and stage != "device.launch":
            raise FaultSpecError("kind= applies only to device.launch rules")
        if device is not None and stage != "device.launch":
            raise FaultSpecError("device= applies only to device.launch rules")
        self.stage = stage
        self.path = path
        self.host = host
        self.device = int(device) if device is not None else None
        self.nth = int(nth) if nth is not None else None
        self.times = int(times) if times is not None else None
        self.prob = float(prob) if prob is not None else None
        self.mode = mode
        self.kind = kind
        self.delay_ms = float(delay_ms)
        self.after_ms = float(after_ms) if after_ms is not None else None
        self.until_ms = float(until_ms) if until_ms is not None else None
        if (
            self.after_ms is not None
            and self.until_ms is not None
            and self.until_ms <= self.after_ms
        ):
            raise FaultSpecError("until-ms must be > after-ms")
        # Timeline epoch: set when the rule joins an installed plan, so
        # after-ms/until-ms windows count from plan installation, not
        # rule construction.
        self._t0 = time.monotonic()
        self._rng = random.Random(seed if seed is not None else 0)
        self._mu = threading.Lock()
        # calls: invocations passing the STATIC filters (stage/host/
        # path/device) — the counter ``nth`` indexes; hits: times fired.
        self.calls = 0
        self.hits = 0

    def _static_match(
        self,
        stage: str,
        host: str | None,
        path: str | None,
        device: int | None,
    ) -> bool:
        if stage != self.stage:
            return False
        if self.host is not None and host != self.host:
            return False
        if self.device is not None and device != self.device:
            return False
        if self.path is not None and not fnmatch.fnmatchcase(
            path or "", self.path
        ):
            return False
        if self.after_ms is not None or self.until_ms is not None:
            elapsed_ms = (time.monotonic() - self._t0) * 1000.0
            if self.after_ms is not None and elapsed_ms < self.after_ms:
                return False
            if self.until_ms is not None and elapsed_ms >= self.until_ms:
                return False
        return True

    def consider(
        self,
        stage: str,
        host: str | None,
        path: str | None,
        device: int | None = None,
    ) -> bool:
        """Count the call against the rule and decide whether to fire."""
        if not self._static_match(stage, host, path, device):
            return False
        with self._mu:
            self.calls += 1
            if self.nth is not None and self.calls != self.nth:
                return False
            if self.times is not None and self.hits >= self.times:
                return False
            if self.prob is not None and self._rng.random() >= self.prob:
                return False
            self.hits += 1
            return True

    def fire(self) -> None:
        if self.kind is not None:
            if self.kind == "hang":
                # A launch that wedged: sleep (default long enough for
                # any watchdog to trip) and then RETURN — the hang, not
                # an error, is the injected fault.
                time.sleep((self.delay_ms or DEFAULT_HANG_MS) / 1000.0)
                return
            if self.kind == "oom":
                raise FaultOOM(
                    f"injected oom ({self.stage}): RESOURCE_EXHAUSTED: "
                    "out of memory while trying to allocate"
                )
            raise FaultError(f"injected error ({self.stage})")
        if self.delay_ms > 0:
            time.sleep(self.delay_ms / 1000.0)
        if self.mode == "delay":
            return
        if self.mode == "drop":
            raise socket.timeout(f"injected drop ({self.stage})")
        raise FaultError(f"injected error ({self.stage})")

    def snapshot(self) -> dict:
        with self._mu:
            out = {
                "stage": self.stage,
                "mode": self.mode,
                "calls": self.calls,
                "hits": self.hits,
            }
        for k in ("path", "host", "device", "nth", "times", "prob", "kind"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        if self.delay_ms:
            out["delayMs"] = self.delay_ms
        if self.after_ms is not None:
            out["afterMs"] = self.after_ms
        if self.until_ms is not None:
            out["untilMs"] = self.until_ms
        return out


class FaultPlan:
    def __init__(self, rules):
        self.rules = list(rules)

    def rearm(self) -> None:
        """Restart every rule's timeline epoch — after-ms/until-ms
        windows count from NOW.  Called by :func:`install` so a plan
        built ahead of time starts its timeline at installation."""
        now = time.monotonic()
        for rule in self.rules:
            rule._t0 = now

    def check(
        self,
        stage: str,
        host: str | None = None,
        path: str | None = None,
        device: int | None = None,
    ) -> None:
        for rule in self.rules:
            if rule.consider(stage, host, path, device):
                rule.fire()

    def snapshot(self) -> list[dict]:
        return [r.snapshot() for r in self.rules]


_INT_KEYS = {"nth", "times", "seed", "device"}
_FLOAT_KEYS = {"prob", "delay_ms", "after_ms", "until_ms"}
_STR_KEYS = {"path", "host", "mode", "kind"}


def parse(spec: str) -> FaultPlan:
    """Parse a ``PILOSA_FAULTS`` spec string into a plan.  Raises
    :class:`FaultSpecError` on malformed input — a chaos run with a
    typo'd spec must fail loudly, not silently inject nothing."""
    rules = []
    for part in (p.strip() for p in spec.split(";")):
        if not part:
            continue
        stage, sep, opts = part.partition(":")
        stage = stage.strip()
        if not sep or not stage:
            raise FaultSpecError(f"fault rule needs 'stage:opts': {part!r}")
        kwargs: dict = {}
        for opt in (o.strip() for o in opts.split(",")):
            if not opt:
                continue
            key, sep, value = opt.partition("=")
            if not sep:
                raise FaultSpecError(f"fault option needs key=value: {opt!r}")
            key = key.strip().replace("-", "_")
            value = value.strip()
            try:
                if key in _INT_KEYS:
                    kwargs[key] = int(value)
                elif key in _FLOAT_KEYS:
                    kwargs[key] = float(value)
                elif key in _STR_KEYS:
                    kwargs[key] = value
                else:
                    raise FaultSpecError(f"unknown fault option: {key!r}")
            except ValueError as e:
                raise FaultSpecError(f"bad fault option {opt!r}: {e}") from e
        rules.append(FaultRule(stage, **kwargs))
    return FaultPlan(rules)


# ---------------------------------------------------------------------------
# process-global plan
# ---------------------------------------------------------------------------

_UNSET = object()  # env not consulted yet
_plan = _UNSET
_mu = threading.Lock()


def install(plan: "FaultPlan | str") -> FaultPlan:
    """Install a plan (or spec string) process-wide; returns it so tests
    can assert on per-rule hit counts."""
    global _plan
    if isinstance(plan, str):
        plan = parse(plan)
    plan.rearm()
    _plan = plan
    return plan


def clear() -> None:
    """Deactivate fault injection (and stop consulting the env)."""
    global _plan
    _plan = None


def reset() -> None:
    """Forget any installed plan AND re-arm the lazy env read — the
    fresh-process state."""
    global _plan
    _plan = _UNSET


def active() -> FaultPlan | None:
    global _plan
    if _plan is _UNSET:
        with _mu:
            if _plan is _UNSET:
                spec = os.environ.get("PILOSA_FAULTS", "")
                _plan = parse(spec) if spec else None
    return _plan


def check(
    stage: str,
    host: str | None = None,
    path: str | None = None,
    device: int | None = None,
) -> None:
    """The injection point: no-op (one global read) unless a plan with
    matching rules is installed."""
    plan = _plan
    if plan is _UNSET:
        plan = active()
    if plan is not None:
        plan.check(stage, host=host, path=path, device=device)
