"""RowBitmap — a query-result row spanning many slices.

The reference's ``pilosa.Bitmap`` walks two sorted lists of per-slice
roaring segments with a merge iterator (reference: bitmap.go:28-134,
282-437).  Here a row result is a dict of ``slice -> uint32[32768]``
dense segments; set algebra is a dict merge with vectorized word ops, and
counts are memoized per segment like the reference's cached ``n``.

Segments may be numpy (host) or jax (device) arrays — ops use the ``^|&``
operators which dispatch correctly for both; ``.bits()`` and JSON/proto
conversion force a host copy.
"""

from __future__ import annotations

from collections.abc import Iterable
from typing import Any

import numpy as np

from pilosa_tpu.ops import bitplane as bp


class RowBitmap:
    """Segmented row bitmap with per-segment cached counts and row
    attributes (reference: bitmap.go:24-43)."""

    __slots__ = ("segments", "_counts", "attrs")

    def __init__(self):
        self.segments: dict[int, np.ndarray] = {}
        self._counts: dict[int, int] = {}
        self.attrs: dict[str, Any] = {}

    # --- construction ---

    @classmethod
    def from_segment(cls, slice_i: int, words, count: int | None = None) -> "RowBitmap":
        b = cls()
        b.set_segment(slice_i, words, count)
        return b

    @classmethod
    def from_bits(cls, bits: Iterable[int]) -> "RowBitmap":
        """Build from absolute column IDs (reference: bitmap.go:258-268
        decoding the protobuf flat bit list)."""
        b = cls()
        by_slice: dict[int, list[int]] = {}
        for col in bits:
            by_slice.setdefault(col // bp.SLICE_WIDTH, []).append(
                col % bp.SLICE_WIDTH
            )
        for s, offs in by_slice.items():
            b.segments[s] = bp.np_columns_to_row(np.asarray(offs, dtype=np.uint64))
        return b

    def set_segment(self, slice_i: int, words, count: int | None = None) -> None:
        self.segments[slice_i] = words
        if count is not None:
            self._counts[slice_i] = count
        else:
            self._counts.pop(slice_i, None)

    # --- set algebra (reference: bitmap.go:45-134) ---

    def _binary(self, other: "RowBitmap", op, keep: str) -> "RowBitmap":
        out = RowBitmap()
        if keep == "intersection":
            keys = self.segments.keys() & other.segments.keys()
            for s in keys:
                out.segments[s] = op(self.segments[s], other.segments[s])
        else:  # union of key sets; missing side = zeros
            for s in self.segments.keys() | other.segments.keys():
                a = self.segments.get(s)
                c = other.segments.get(s)
                if a is None:
                    a = np.zeros_like(c)
                if c is None:
                    c = np.zeros_like(a)
                out.segments[s] = op(a, c)
        return out

    def intersect(self, other: "RowBitmap") -> "RowBitmap":
        return self._binary(other, lambda a, c: a & c, "intersection")

    def union(self, other: "RowBitmap") -> "RowBitmap":
        return self._binary(other, lambda a, c: a | c, "union")

    def difference(self, other: "RowBitmap") -> "RowBitmap":
        return self._binary(other, lambda a, c: a & ~c, "difference")

    def xor(self, other: "RowBitmap") -> "RowBitmap":
        return self._binary(other, lambda a, c: a ^ c, "union")

    def merge(self, other: "RowBitmap") -> None:
        """In-place union used by the map/reduce combiner (reference:
        Bitmap.Merge, bitmap.go:137-156)."""
        for s, words in other.segments.items():
            if s in self.segments:
                self.segments[s] = self.segments[s] | words
                self._counts.pop(s, None)
            else:
                self.segments[s] = words
                if s in other._counts:
                    self._counts[s] = other._counts[s]

    # --- counts (reference: bitmap.go:159-217) ---

    def segment_count(self, slice_i: int) -> int:
        n = self._counts.get(slice_i)
        if n is None:
            n = int(bp.count(self.segments[slice_i]))
            self._counts[slice_i] = n
        return n

    def count(self) -> int:
        return sum(self.segment_count(s) for s in self.segments)

    def intersection_count(self, other: "RowBitmap") -> int:
        """Count-only AND without materializing (reference:
        bitmap.go:74-83 -> roaring.IntersectionCount)."""
        total = 0
        for s in self.segments.keys() & other.segments.keys():
            total += int(bp.count_and(self.segments[s], other.segments[s]))
        return total

    def invalidate_count(self) -> None:
        self._counts.clear()

    # --- materialization ---

    def _host_segment(self, slice_i: int) -> np.ndarray:
        return np.asarray(self.segments[slice_i], dtype=np.uint32)

    def bits(self) -> list[int]:
        """Sorted absolute column IDs (reference: Bitmap.Bits,
        bitmap.go:236-242)."""
        out: list[int] = []
        for s in sorted(self.segments):
            offs = bp.np_row_to_columns(self._host_segment(s))
            base = s * bp.SLICE_WIDTH
            out.extend(int(o) + base for o in offs)
        return out

    def set_bit(self, col: int) -> bool:
        """Host-side single-bit set, used when assembling results
        (reference: bitmap.go:166-173)."""
        s, off = divmod(col, bp.SLICE_WIDTH)
        if s not in self.segments:
            self.segments[s] = bp.empty_row()
        seg = np.asarray(self.segments[s], dtype=np.uint32).copy()
        word, shift = divmod(off, bp.WORD_BITS)
        mask = np.uint32(1 << shift)
        changed = not (seg[word] & mask)
        seg[word] |= mask
        self.segments[s] = seg
        if changed and s in self._counts:
            self._counts[s] += 1
        return changed

    def to_json_dict(self) -> dict:
        """{"attrs": ..., "bits": ...} (reference: bitmap.go:220-233)."""
        return {"attrs": self.attrs or {}, "bits": self.bits()}

    def __eq__(self, other) -> bool:
        if not isinstance(other, RowBitmap):
            return NotImplemented
        return self.bits() == other.bits()

    def __repr__(self) -> str:
        return f"RowBitmap(n={self.count()}, slices={sorted(self.segments)})"
