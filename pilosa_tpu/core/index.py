"""Index — a database: a named container of frames plus column attrs.

Reference behavior (reference: index.go): column label (default
"columnID"), a default time quantum inherited by new frames, JSON
``.meta`` persistence (reference uses protobuf; same file name/fields),
a column AttrStore at ``<index>/.data``, and remote max-slice tracking
learned from the cluster (reference: index.go:53-55,249-297) so query
slice ranges cover data held only by peers.
"""

from __future__ import annotations

import json
import os
import sys
import shutil
import threading

from pilosa_tpu.core.attr import AttrStore
from pilosa_tpu.core.frame import Frame
from pilosa_tpu.core.names import ValidationError, validate_label, validate_name
from pilosa_tpu.obs.stats import NopStatsClient
from pilosa_tpu.core import timequantum as tq

# reference: index.go:33-35
DEFAULT_COLUMN_LABEL = "columnID"


class IndexError_(RuntimeError):
    pass


class Index:
    def __init__(self, path: str, name: str):
        validate_name(name)
        self.path = path
        self.name = name
        self._mu = threading.RLock()
        self._frames: dict[str, Frame] = {}
        self.column_label = DEFAULT_COLUMN_LABEL
        self.time_quantum = ""
        self.column_attr_store = AttrStore(os.path.join(path, ".data"))
        # Highest slice numbers seen from the cluster (reference:
        # index.go:53-55).
        self.remote_max_slice = 0
        self.remote_max_inverse_slice = 0
        self.on_create_slice = None  # wired by Holder/Server
        self.stats = NopStatsClient()  # re-tagged by Holder._new_index
        self.logger = lambda msg: print(msg, file=sys.stderr)  # re-wired alongside stats

    # --- lifecycle (reference: index.go:134-228) ---

    @property
    def meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def open(self) -> None:
        with self._mu:
            os.makedirs(self.path, exist_ok=True)
            self._load_meta()
            self.column_attr_store.open()
            for entry in sorted(os.listdir(self.path)):
                full = os.path.join(self.path, entry)
                if not os.path.isdir(full):
                    continue
                try:
                    frame = self._new_frame(entry)
                except ValidationError:
                    continue  # skip stray dirs (reference: index.go:148-152)
                frame.open()
                self._frames[entry] = frame

    def close(self) -> None:
        with self._mu:
            self.column_attr_store.close()
            for frame in self._frames.values():
                frame.close()
            self._frames.clear()

    def _load_meta(self) -> None:
        try:
            with open(self.meta_path) as fh:
                meta = json.load(fh)
        except FileNotFoundError:
            return
        self.column_label = meta.get("columnLabel", DEFAULT_COLUMN_LABEL)
        self.time_quantum = meta.get("timeQuantum", "")

    def save_meta(self) -> None:
        with self._mu:
            os.makedirs(self.path, exist_ok=True)
            tmp = self.meta_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(
                    {
                        "columnLabel": self.column_label,
                        "timeQuantum": self.time_quantum,
                    },
                    fh,
                )
            os.replace(tmp, self.meta_path)

    def set_column_label(self, label: str) -> None:
        with self._mu:
            validate_label(label)
            self.column_label = label
            self.save_meta()

    def set_time_quantum(self, q: str) -> None:
        """reference: index.go:303-319"""
        with self._mu:
            self.time_quantum = tq.parse_time_quantum(q)
            self.save_meta()

    # --- frames (reference: index.go:336-435) ---

    def _new_frame(self, name: str) -> Frame:
        frame = Frame(os.path.join(self.path, name), self.name, name)
        frame.on_create_slice = self.on_create_slice
        frame.stats = self.stats.with_tags(f"frame:{name}")
        frame.logger = self.logger
        return frame

    def frame(self, name: str) -> Frame | None:
        with self._mu:
            return self._frames.get(name)

    def frames(self) -> dict[str, Frame]:
        with self._mu:
            return dict(self._frames)

    def create_frame(self, name: str, **options) -> Frame:
        with self._mu:
            if name in self._frames:
                raise IndexError_(f"frame already exists: {name!r}")
            return self._create_frame(name, options)

    def create_frame_if_not_exists(self, name: str, **options) -> Frame:
        with self._mu:
            frame = self._frames.get(name)
            if frame is not None:
                return frame
            return self._create_frame(name, options)

    def _create_frame(self, name: str, options: dict) -> Frame:
        # Row label must not collide with the index's column label
        # (reference: index.go:386-388).
        row_label = options.get("row_label") or "rowID"
        if row_label == self.column_label:
            raise ValidationError("row label and column label cannot be equal")
        frame = self._new_frame(name)
        frame.open()
        opts = {k: v for k, v in options.items() if v is not None}
        # New frames inherit the index's default time quantum (reference:
        # index.go:419-424).
        if not opts.get("time_quantum") and self.time_quantum:
            opts["time_quantum"] = self.time_quantum
        if opts:
            frame.set_options(**opts)
        else:
            frame.save_meta()
        self._frames[name] = frame
        return frame

    def delete_frame(self, name: str) -> None:
        """reference: index.go:437-456"""
        with self._mu:
            frame = self._frames.pop(name, None)
            if frame is not None:
                frame.close()
                shutil.rmtree(frame.path, ignore_errors=True)

    # --- slices (reference: index.go:249-297) ---

    def max_slice(self) -> int:
        with self._mu:
            local = max(
                (f.max_slice() for f in self._frames.values()), default=0
            )
            m = max(local, self.remote_max_slice)
            self.stats.gauge("maxSlice", float(m))  # reference: index.go:264
            return m

    def max_inverse_slice(self) -> int:
        with self._mu:
            local = max(
                (f.max_inverse_slice() for f in self._frames.values()), default=0
            )
            return max(local, self.remote_max_inverse_slice)

    def set_remote_max_slice(self, n: int) -> None:
        with self._mu:
            self.remote_max_slice = max(self.remote_max_slice, n)

    def set_remote_max_inverse_slice(self, n: int) -> None:
        with self._mu:
            self.remote_max_inverse_slice = max(self.remote_max_inverse_slice, n)

    def schema_dict(self) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "columnLabel": self.column_label,
                "timeQuantum": self.time_quantum,
                "frames": [f.schema_dict() for _, f in sorted(self._frames.items())],
            }
