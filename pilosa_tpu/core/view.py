"""View — one physical layout of a frame: standard, inverse, or a
time-quantum-generated sub-view.

Owns the fragments for its layout, on disk at
``<frame>/views/<name>/fragments/<slice>`` (reference: view.go:119-188),
routes bit writes by ``columnID // SLICE_WIDTH`` (reference:
view.go:262-279), and notifies the cluster when a write grows the max
slice (reference: view.go:218-250 broadcasting CreateSliceMessage — here
an ``on_create_slice`` callback wired up by the server).
"""

from __future__ import annotations

import os
import sys
import threading
from collections.abc import Callable

from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.obs.stats import NopStatsClient
from pilosa_tpu.ops.bitplane import SLICE_WIDTH

VIEW_STANDARD = "standard"
VIEW_INVERSE = "inverse"


def is_valid_view(name: str) -> bool:
    """reference: view.go:31-41"""
    return name in (VIEW_STANDARD, VIEW_INVERSE)


def is_inverse_view(name: str) -> bool:
    """Inverse views (incl. time sub-views) share the prefix (reference:
    view.go:43-46)."""
    return name.startswith(VIEW_INVERSE)


class View:
    def __init__(
        self,
        path: str,
        index: str,
        frame: str,
        name: str,
        cache_type: str = cache_mod.TYPE_RANKED,
        cache_size: int = cache_mod.DEFAULT_CACHE_SIZE,
        row_attr_store=None,
        on_create_slice: Callable[[str, str, int], None] | None = None,
    ):
        self.path = path
        self.index = index
        self.frame = frame
        self.name = name
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.row_attr_store = row_attr_store
        self.on_create_slice = on_create_slice
        self.stats = NopStatsClient()  # re-tagged by Frame._new_view
        self.logger = lambda msg: print(msg, file=sys.stderr)  # re-wired alongside stats
        self._mu = threading.RLock()
        self._fragments: dict[int, Fragment] = {}

    # --- lifecycle (reference: view.go:97-154) ---

    @property
    def fragments_path(self) -> str:
        return os.path.join(self.path, "fragments")

    def open(self) -> None:
        with self._mu:
            os.makedirs(self.fragments_path, exist_ok=True)
            for entry in sorted(os.listdir(self.fragments_path)):
                if not entry.isdigit():
                    continue  # skip .cache / .snapshotting / strays
                frag = self._new_fragment(int(entry))
                frag.open()
                self._fragments[int(entry)] = frag

    def close(self) -> None:
        with self._mu:
            for frag in self._fragments.values():
                frag.close()
            self._fragments.clear()

    def _new_fragment(self, slice_i: int) -> Fragment:
        frag = Fragment(
            os.path.join(self.fragments_path, str(slice_i)),
            self.index,
            self.frame,
            self.name,
            slice_i,
            cache_type=self.cache_type,
            cache_size=self.cache_size,
        )
        frag.row_attr_store = self.row_attr_store
        frag.stats = self.stats.with_tags(f"slice:{slice_i}")
        frag.cache.stats = frag.stats  # hit/miss/evict counters
        frag.logger = self.logger
        return frag

    # --- accessors ---

    def fragment(self, slice_i: int) -> Fragment | None:
        with self._mu:
            return self._fragments.get(slice_i)

    def fragments(self) -> list[Fragment]:
        with self._mu:
            return list(self._fragments.values())

    def fragment_slices(self) -> set[int]:
        """Snapshot of the slice numbers that have fragments — lets the
        executor's per-slice host walks skip slices this view never
        materialized (a frame rarely spans the whole index slice range;
        missing fragments contribute nothing to any query)."""
        with self._mu:
            return set(self._fragments)

    def max_slice(self) -> int:
        with self._mu:
            return max(self._fragments.keys(), default=0)

    def create_fragment_if_not_exists(self, slice_i: int) -> Fragment:
        """reference: view.go:218-250"""
        notify = False
        with self._mu:
            frag = self._fragments.get(slice_i)
            if frag is not None:
                return frag
            first = len(self._fragments) == 0
            grew = slice_i > self.max_slice()
            frag = self._new_fragment(slice_i)
            frag.open()
            self._fragments[slice_i] = frag
            notify = (grew or first) and self.on_create_slice is not None
        # OUTSIDE the view lock: the callback crosses into the net
        # layer (the server's gossip CreateSliceMessage broadcast —
        # socket I/O and the gossip mutex must not run under a core
        # data lock).  Found by PILOSA_LOCK_CHECK against the static
        # graph in PR 8; same rule as Fragment.close's listeners.
        if notify:
            # (index, view name, slice) — the view name tells the
            # server whether the new slice is inverse-oriented
            # (reference: view.go:236-241 CreateSliceMessage).
            self.on_create_slice(self.index, self.name, slice_i)
        return frag

    def remove_fragment(self, slice_i: int) -> bool:
        """Drop one fragment from service and DELETE its backing files
        — the rebalance source-release path: the fragment's device
        mirror/sparse rows deregister from the HBM pool (close), and
        its disk footprint returns.  Returns False when the slice has
        no fragment here."""
        with self._mu:
            frag = self._fragments.pop(slice_i, None)
        if frag is None:
            return False
        # close() outside the view lock (it notifies close listeners).
        frag.close()
        for path in (frag.path, frag.cache_path):
            try:
                os.unlink(path)
            except OSError:
                pass
        return True

    # --- writes (reference: view.go:262-279) ---

    def set_bit(self, row_id: int, column_id: int) -> bool:
        frag = self.create_fragment_if_not_exists(column_id // SLICE_WIDTH)
        return frag.set_bit(row_id, column_id)

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        frag = self.fragment(column_id // SLICE_WIDTH)
        if frag is None:
            return False
        return frag.clear_bit(row_id, column_id)
