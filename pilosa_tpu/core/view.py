"""View — one physical layout of a frame: standard, inverse, or a
time-quantum-generated sub-view.

Owns the fragments for its layout, on disk at
``<frame>/views/<name>/fragments/<slice>`` (reference: view.go:119-188),
routes bit writes by ``columnID // SLICE_WIDTH`` (reference:
view.go:262-279), and notifies the cluster when a write grows the max
slice (reference: view.go:218-250 broadcasting CreateSliceMessage — here
an ``on_create_slice`` callback wired up by the server).

Tiered storage (pilosa_tpu/tier) adds a third fragment state beyond
hot/absent: **cold** — the fragment's metadata is resident here (the
slice counts toward ``max_slice`` and ``fragment_slices``) but its
bytes live as a tar in the object store.  First touch through
:meth:`fragment` or :meth:`create_fragment_if_not_exists` hydrates via
the attached ``hydrator`` (the TierManager); a failed hydration raises
rather than silently serving an empty fragment.
"""

from __future__ import annotations

import os
import sys
import threading
from collections.abc import Callable

from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core.fragment import Fragment, FragmentRetiredError
from pilosa_tpu.obs.stats import NopStatsClient
from pilosa_tpu.ops.bitplane import SLICE_WIDTH

VIEW_STANDARD = "standard"
VIEW_INVERSE = "inverse"


def is_valid_view(name: str) -> bool:
    """reference: view.go:31-41"""
    return name in (VIEW_STANDARD, VIEW_INVERSE)


def is_inverse_view(name: str) -> bool:
    """Inverse views (incl. time sub-views) share the prefix (reference:
    view.go:43-46)."""
    return name.startswith(VIEW_INVERSE)


class View:
    def __init__(
        self,
        path: str,
        index: str,
        frame: str,
        name: str,
        cache_type: str = cache_mod.TYPE_RANKED,
        cache_size: int = cache_mod.DEFAULT_CACHE_SIZE,
        row_attr_store=None,
        on_create_slice: Callable[[str, str, int], None] | None = None,
    ):
        self.path = path
        self.index = index
        self.frame = frame
        self.name = name
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.row_attr_store = row_attr_store
        self.on_create_slice = on_create_slice
        self.stats = NopStatsClient()  # re-tagged by Frame._new_view
        self.logger = lambda msg: print(msg, file=sys.stderr)  # re-wired alongside stats
        self._mu = threading.RLock()
        self._fragments: dict[int, Fragment] = {}
        # COLD fragments: slice -> opaque store metadata (set by the
        # tier manager).  Metadata resident, bytes in the object store;
        # first touch hydrates through ``hydrator``.  Empty (and
        # hydrator None) on nodes without a configured tier — the hot
        # paths pay one falsy check.
        self._cold: dict[int, object] = {}
        self.hydrator = None  # TierManager, attached with cold entries

    # --- lifecycle (reference: view.go:97-154) ---

    @property
    def fragments_path(self) -> str:
        return os.path.join(self.path, "fragments")

    def open(self) -> None:
        with self._mu:
            os.makedirs(self.fragments_path, exist_ok=True)
            for entry in sorted(os.listdir(self.fragments_path)):
                if not entry.isdigit():
                    continue  # skip .cache / .snapshotting / strays
                frag = self._new_fragment(int(entry))
                frag.open()
                self._fragments[int(entry)] = frag

    def close(self) -> None:
        with self._mu:
            for frag in self._fragments.values():
                frag.close()
            self._fragments.clear()

    def _new_fragment(self, slice_i: int) -> Fragment:
        frag = Fragment(
            os.path.join(self.fragments_path, str(slice_i)),
            self.index,
            self.frame,
            self.name,
            slice_i,
            cache_type=self.cache_type,
            cache_size=self.cache_size,
        )
        frag.row_attr_store = self.row_attr_store
        frag.stats = self.stats.with_tags(f"slice:{slice_i}")
        frag.cache.stats = frag.stats  # hit/miss/evict counters
        frag.logger = self.logger
        return frag

    # --- accessors ---

    def fragment(self, slice_i: int) -> Fragment | None:
        with self._mu:
            frag = self._fragments.get(slice_i)
            if frag is not None:
                if self.hydrator is not None:
                    self.hydrator.touch(self, slice_i)
                return frag
            if slice_i not in self._cold or self.hydrator is None:
                return None
        # Cold: hydrate OUTSIDE the view lock (store I/O must not hold
        # a core data lock); the hydrator serializes per fragment.
        return self.hydrator.hydrate(self, slice_i)

    def fragments(self) -> list[Fragment]:
        """The HOT (locally materialized) fragments only — cold
        fragments have no local state to flush/close/account."""
        with self._mu:
            return list(self._fragments.values())

    def fragment_slices(self) -> set[int]:
        """Snapshot of the slice numbers that have fragments — hot OR
        cold: a cold fragment's bits must still be found by the
        executor's per-slice walks (the walk's ``fragment()`` call
        hydrates it).  Missing slices contribute nothing to any
        query."""
        with self._mu:
            return set(self._fragments) | set(self._cold)

    def max_slice(self) -> int:
        with self._mu:
            return max(
                max(self._fragments.keys(), default=0),
                max(self._cold.keys(), default=0),
            )

    def create_fragment_if_not_exists(self, slice_i: int) -> Fragment:
        """reference: view.go:218-250"""
        if self.hydrator is not None:
            with self._mu:
                cold = (
                    slice_i in self._cold and slice_i not in self._fragments
                )
            if cold:
                # A WRITE to a cold fragment revives it: hydrate first
                # so the write lands on the full restored plane, never
                # on a silently-empty shadow of it.  Hydration failures
                # raise (loud) — see tier/manager.py.
                frag = self.hydrator.hydrate(self, slice_i)
                if frag is not None:
                    return frag
        notify = False
        with self._mu:
            frag = self._fragments.get(slice_i)
            if frag is not None:
                return frag
            first = len(self._fragments) == 0 and not self._cold
            grew = slice_i > self.max_slice()
            frag = self._new_fragment(slice_i)
            frag.open()
            self._fragments[slice_i] = frag
            notify = (grew or first) and self.on_create_slice is not None
        # OUTSIDE the view lock: the callback crosses into the net
        # layer (the server's gossip CreateSliceMessage broadcast —
        # socket I/O and the gossip mutex must not run under a core
        # data lock).  Found by PILOSA_LOCK_CHECK against the static
        # graph in PR 8; same rule as Fragment.close's listeners.
        if notify:
            # (index, view name, slice) — the view name tells the
            # server whether the new slice is inverse-oriented
            # (reference: view.go:236-241 CreateSliceMessage).
            self.on_create_slice(self.index, self.name, slice_i)
        return frag

    def remove_fragment(self, slice_i: int) -> bool:
        """Drop one fragment from service and DELETE its backing files
        — the rebalance source-release path: the fragment's device
        mirror/sparse rows deregister from the HBM pool (close), and
        its disk footprint returns.  A COLD fragment releases by
        dropping its registration (there are no local bytes).  Returns
        False when the slice has no fragment here."""
        with self._mu:
            frag = self._fragments.pop(slice_i, None)
            was_cold = self._cold.pop(slice_i, None) is not None
        if frag is None:
            return was_cold
        # close() outside the view lock (it notifies close listeners).
        frag.close()
        for path in (frag.path, frag.cache_path):
            try:
                os.unlink(path)
            except OSError:
                pass
        return True

    # --- cold-fragment state (pilosa_tpu/tier) ---

    def register_cold(self, slice_i: int, meta: object) -> bool:
        """Record a cold fragment (bytes in the object store).  No-op
        (False) when a hot fragment already holds the slice."""
        with self._mu:
            if slice_i in self._fragments:
                return False
            self._cold[slice_i] = meta
            return True

    def cold_slices(self) -> set[int]:
        with self._mu:
            return set(self._cold)

    def cold_meta(self, slice_i: int) -> object | None:
        with self._mu:
            return self._cold.get(slice_i)

    def drop_cold(self, slice_i: int) -> None:
        with self._mu:
            self._cold.pop(slice_i, None)

    def _fragment_raw(self, slice_i: int) -> Fragment | None:
        """Plain hot-map lookup — no hydration, no touch.  The
        hydrator's own re-check path."""
        with self._mu:
            return self._fragments.get(slice_i)

    def adopt_hydrated(self, slice_i: int, frag: Fragment) -> None:
        """Install a freshly hydrated fragment and clear its cold
        registration, atomically under the view lock."""
        with self._mu:
            self._fragments[slice_i] = frag
            self._cold.pop(slice_i, None)

    def demote_fragment(
        self,
        slice_i: int,
        meta: object,
        expect: Fragment | None = None,
        expect_version: int | None = None,
    ) -> Fragment | None:
        """Flip a hot fragment to cold: RETIRE it (writes now raise and
        retry through the view, which revives by hydration), pop it,
        and register the store metadata — atomically under the view
        lock.  With ``expect``/``expect_version`` the flip is
        optimistic: it aborts (returns None, fragment stays hot) when
        the fragment was replaced or written since the caller captured
        the version — i.e. since the uploaded tar snapshot — so a
        demotion can never strand a write.  The caller closes the
        returned fragment and deletes its local files outside the
        lock."""
        with self._mu:
            frag = self._fragments.get(slice_i)
            if frag is None:
                return None
            if expect is not None:
                if frag is not expect or not frag.mark_retired_if_version(
                    expect_version or 0
                ):
                    return None
            else:
                frag.mark_retired()
            del self._fragments[slice_i]
            self._cold[slice_i] = meta
            return frag

    # --- writes (reference: view.go:262-279) ---

    def set_bit(self, row_id: int, column_id: int) -> bool:
        # Two attempts: a fragment retired by a concurrent demotion
        # (tier LRU / retention sweep) revives through hydration on the
        # retry; a second failure propagates loudly — a write is never
        # silently dropped into a retired plane.
        last: FragmentRetiredError | None = None
        for _ in range(2):
            frag = self.create_fragment_if_not_exists(
                column_id // SLICE_WIDTH
            )
            try:
                return frag.set_bit(row_id, column_id)
            except FragmentRetiredError as e:
                last = e
        raise last  # type: ignore[misc]

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        last: FragmentRetiredError | None = None
        for _ in range(2):
            frag = self.fragment(column_id // SLICE_WIDTH)
            if frag is None:
                return False
            try:
                return frag.clear_bit(row_id, column_id)
            except FragmentRetiredError as e:
                last = e
        raise last  # type: ignore[misc]
