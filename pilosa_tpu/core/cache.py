"""Row caches: the TopN ranked cache, an LRU cache, and Pair merging.

Reference behavior being reproduced (reference: cache.go):

* ``RankCache`` — keeps the top ``max_entries`` (row, count) pairs with a
  threshold floor so cold rows are rejected cheaply; re-sorts lazily at
  most every 10 s; trims at 1.1x capacity (reference: cache.go:29-32,
  136-286).
* ``LRUCache`` — plain bounded LRU (reference: cache.go:58-133).
* ``Pairs`` helpers — sorted (id, count) merging used in the TopN reduce
  (reference: cache.go:301-423).

The ranked cache is host-side control metadata: it chooses *candidate*
rows; the actual scoring runs as one batched TPU kernel
(ops.bitplane.top_counts) instead of the reference's per-row sequential
loop with threshold pruning.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass
from collections.abc import Iterable
from typing import Protocol

from pilosa_tpu.obs.stats import NopStatsClient

# reference: cache.go:29-32
DEFAULT_CACHE_SIZE = 50000
THRESHOLD_FACTOR = 1.1
RECALCULATE_INTERVAL_S = 10.0

TYPE_RANKED = "ranked"
TYPE_LRU = "lru"


@dataclass(frozen=True)
class Pair:
    """(row id, count) result pair (reference: cache.go:301-304)."""

    id: int
    count: int


def add_pairs(a: list[Pair], b: list[Pair]) -> list[Pair]:
    """Merge two pair lists summing counts by id (reference: Pairs.Add,
    cache.go:312-334) — the TopN reduce function."""
    counts: dict[int, int] = {}
    for p in a:
        counts[p.id] = counts.get(p.id, 0) + p.count
    for p in b:
        counts[p.id] = counts.get(p.id, 0) + p.count
    return [Pair(i, c) for i, c in counts.items()]


def sort_pairs(pairs: Iterable[Pair]) -> list[Pair]:
    """Count descending, then id ascending — the canonical TopN order."""
    return sorted(pairs, key=lambda p: (-p.count, p.id))


class Cache(Protocol):
    """Row-count cache interface (reference: cache.go:35-55)."""

    def add(self, row_id: int, n: int) -> None: ...
    def bulk_add(self, row_id: int, n: int) -> None: ...
    def get(self, row_id: int) -> int: ...
    def len(self) -> int: ...
    def ids(self) -> list[int]: ...
    def invalidate(self) -> None: ...
    def top(self) -> list[Pair]: ...
    def top_arrays(self): ...
    def recalculate(self) -> None: ...


class LRUCache:
    """Bounded LRU of (row -> count) (reference: cache.go:58-133)."""

    def __init__(self, max_entries: int = DEFAULT_CACHE_SIZE):
        self.max_entries = max_entries or DEFAULT_CACHE_SIZE
        self._od: OrderedDict[int, int] = OrderedDict()
        # Re-tagged by the owning fragment (index:/frame:/view:/slice:).
        self.stats = NopStatsClient()

    def add(self, row_id: int, n: int) -> None:
        self._od[row_id] = n
        self._od.move_to_end(row_id)
        while len(self._od) > self.max_entries:
            self._od.popitem(last=False)
            self.stats.count("cacheEvict")

    bulk_add = add

    def get(self, row_id: int) -> int:
        if row_id in self._od:
            self._od.move_to_end(row_id)
            self.stats.count("cacheHit")
            return self._od[row_id]
        self.stats.count("cacheMiss")
        return 0

    def len(self) -> int:
        return len(self._od)

    def ids(self) -> list[int]:
        return sorted(self._od.keys())

    def invalidate(self) -> None:
        pass

    def recalculate(self) -> None:
        pass

    def top(self) -> list[Pair]:
        return sort_pairs(Pair(i, c) for i, c in self._od.items())

    def top_arrays(self):
        """(ids, counts) int64 ndarrays in canonical (-count, id) order —
        the array-native twin of top() (LRU caches are small; built on
        demand)."""
        import numpy as np

        pairs = self.top()
        n = len(pairs)
        return (
            np.fromiter((p.id for p in pairs), np.int64, n),
            np.fromiter((p.count for p in pairs), np.int64, n),
        )


class RankCache:
    """Threshold-pruned ranked cache (reference: cache.go:136-286).

    Keeps every row seen until ``max_entries`` is exceeded, then prunes to
    the top ``max_entries`` and records ``threshold_value`` = the smallest
    kept count: later adds below the threshold are rejected without
    touching the rankings.  Rankings are recomputed lazily, at most every
    RECALCULATE_INTERVAL_S unless invalidated.
    """

    def __init__(self, max_entries: int = DEFAULT_CACHE_SIZE):
        self.max_entries = max_entries or DEFAULT_CACHE_SIZE
        self.entries: dict[int, int] = {}
        self._rankings: list[Pair] = []
        self._arrays = None  # (ids, counts) mirror of _rankings
        self._updated_at = 0.0
        self._stale = True
        self.threshold_value = 0
        # Re-tagged by the owning fragment (index:/frame:/view:/slice:).
        self.stats = NopStatsClient()

    def add(self, row_id: int, n: int) -> None:
        # Reject values below the established floor unless already present
        # (reference: cache.go:171-185).
        if (
            self.threshold_value
            and n < self.threshold_value
            and row_id not in self.entries
        ):
            return
        if n == 0:
            self.entries.pop(row_id, None)
        else:
            self.entries[row_id] = n
        self._stale = True
        if len(self.entries) > self.max_entries * THRESHOLD_FACTOR:
            self._prune()

    bulk_add = add

    def get(self, row_id: int) -> int:
        n = self.entries.get(row_id)
        if n is None:
            self.stats.count("cacheMiss")
            return 0
        self.stats.count("cacheHit")
        return n

    def len(self) -> int:
        return len(self.entries)

    def ids(self) -> list[int]:
        return sorted(self.entries.keys())

    def invalidate(self) -> None:
        """Mark rankings stale.  The actual re-sort stays throttled to
        RECALCULATE_INTERVAL_S (reference: cache.go:236-241) — call
        recalculate() to force it."""
        self._stale = True

    def recalculate(self) -> None:
        self._recompute(force=True)

    def top(self) -> list[Pair]:
        self._recompute()
        return list(self._rankings)

    def top_arrays(self):
        """(ids, counts) int64 ndarrays mirroring top()'s ranking order,
        cached until the next re-sort — the folded TopN path consumes
        candidates array-native, so the per-query cost is two array
        reads instead of an O(cache) Pair walk."""
        import numpy as np

        self._recompute()
        if self._arrays is None:
            n = len(self._rankings)
            self._arrays = (
                np.fromiter((p.id for p in self._rankings), np.int64, n),
                np.fromiter((p.count for p in self._rankings), np.int64, n),
            )
        return self._arrays

    def _recompute(self, force: bool = False) -> None:
        now = time.monotonic()
        if not self._stale:
            return
        if not force and self._rankings and (
            now - self._updated_at < RECALCULATE_INTERVAL_S
        ):
            return
        self._rankings = sort_pairs(
            Pair(i, c) for i, c in self.entries.items()
        )[: self.max_entries]
        self._arrays = None
        self._updated_at = now
        self._stale = False

    def _prune(self) -> None:
        dropped = len(self.entries)
        keep = sort_pairs(Pair(i, c) for i, c in self.entries.items())[
            : self.max_entries
        ]
        self.entries = {p.id: p.count for p in keep}
        dropped -= len(self.entries)
        if dropped > 0:
            self.stats.count("cacheEvict", dropped)
        if len(keep) == self.max_entries and keep:
            self.threshold_value = keep[-1].count
        self._stale = True


def new_cache(cache_type: str, size: int):
    if cache_type == TYPE_LRU:
        return LRUCache(size)
    if cache_type == TYPE_RANKED:
        return RankCache(size)
    raise ValueError(f"unknown cache type: {cache_type!r}")
