"""Time-quantum views: per-unit view naming and minimal range covers.

Frames with a time quantum write each bit into one generated view per
quantum unit (``<view>_2006``, ``<view>_200601``, ...), and ``Range``
queries union the minimal set of coarse+fine views covering
``[start, end)`` — walking up from small units to aligned boundaries,
then down (reference: time.go:28-167).
"""

from __future__ import annotations

from datetime import datetime, timedelta

VALID_QUANTUMS = frozenset(
    ["Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH", "H", ""]
)

_UNIT_FORMATS = {
    "Y": "%Y",
    "M": "%Y%m",
    "D": "%Y%m%d",
    "H": "%Y%m%d%H",
}


class InvalidTimeQuantumError(ValueError):
    pass


def parse_time_quantum(v: str) -> str:
    q = v.upper()
    if q not in VALID_QUANTUMS:
        raise InvalidTimeQuantumError(f"invalid time quantum: {v!r}")
    return q


def view_by_time_unit(name: str, t: datetime, unit: str) -> str:
    """reference: time.go:66-79"""
    fmt = _UNIT_FORMATS.get(unit)
    if fmt is None:
        return ""
    return f"{name}_{t.strftime(fmt)}"


def views_by_time(name: str, t: datetime, quantum: str) -> list[str]:
    """One view per quantum unit, for writes (reference: time.go:82-92)."""
    return [
        view_by_time_unit(name, t, unit)
        for unit in quantum
        if unit in _UNIT_FORMATS
    ]


def _go_add_date(t: datetime, years: int, months: int, days: int) -> datetime:
    """Date arithmetic with Go's time.AddDate normalization (overflowing
    days roll forward: Jan 31 + 1 month = Mar 2/3)."""
    y = t.year + years
    m = t.month + months
    y += (m - 1) // 12
    m = (m - 1) % 12 + 1
    base = datetime(y, m, 1, t.hour, t.minute, t.second, t.microsecond)
    return base + timedelta(days=t.day - 1 + days)


def _add_unit(t: datetime, unit: str) -> datetime:
    if unit == "Y":
        return _go_add_date(t, 1, 0, 0)
    if unit == "M":
        return _go_add_date(t, 0, 1, 0)
    if unit == "D":
        return t + timedelta(days=1)
    return t + timedelta(hours=1)


def _next_unit_gte(t: datetime, end: datetime, unit: str) -> bool:
    """True when ``end`` reaches the unit period after ``t`` (reference:
    time.go:168-194 nextYearGTE/nextMonthGTE/nextDayGTE): t+1unit lands in
    the same unit as end, or end is strictly after t+1unit."""
    nxt = _add_unit(t, unit)
    if unit == "Y":
        same = nxt.year == end.year
    elif unit == "M":
        same = (nxt.year, nxt.month) == (end.year, end.month)
    else:  # D
        same = (nxt.year, nxt.month, nxt.day) == (end.year, end.month, end.day)
    return same or end > nxt


_SUFFIX_UNITS = {4: "Y", 6: "M", 8: "D", 10: "H"}


def parse_time_view(name: str) -> tuple[str, datetime, str] | None:
    """Inverse of :func:`view_by_time_unit`: ``<base>_<stamp>`` ->
    ``(base, period_start, unit)``, or None when ``name`` is not a
    generated time view.  The tier retention sweep uses this to decide
    which sub-views have aged past their quantum."""
    base, sep, stamp = name.rpartition("_")
    if not sep or not base or not stamp.isdigit():
        return None
    unit = _SUFFIX_UNITS.get(len(stamp))
    if unit is None:
        return None
    try:
        t = datetime.strptime(stamp, _UNIT_FORMATS[unit])
    except ValueError:
        return None
    return base, t, unit


def view_period_end(t: datetime, unit: str) -> datetime:
    """First instant AFTER the view's quantum period — the moment its
    retention clock starts."""
    return _add_unit(t, unit)


def views_by_time_range(name: str, start: datetime, end: datetime, quantum: str) -> list[str]:
    """Minimal view cover of [start, end) (reference: time.go:95-167)."""
    has = {u: (u in quantum) for u in "YMDH"}
    t = start
    results: list[str] = []

    # Walk up small -> large until aligned on a larger-unit boundary.
    if has["H"] or has["D"] or has["M"]:
        while t < end:
            if has["H"]:
                if not _next_unit_gte(t, end, "D"):
                    break
                if t.hour != 0:
                    results.append(view_by_time_unit(name, t, "H"))
                    t = _add_unit(t, "H")
                    continue
            if has["D"]:
                if not _next_unit_gte(t, end, "M"):
                    break
                if t.day != 1:
                    results.append(view_by_time_unit(name, t, "D"))
                    t = _add_unit(t, "D")
                    continue
            if has["M"]:
                if not _next_unit_gte(t, end, "Y"):
                    break
                if t.month != 1:
                    results.append(view_by_time_unit(name, t, "M"))
                    t = _add_unit(t, "M")
                    continue
            break

    # Walk down large -> small to cover the rest.
    while t < end:
        if has["Y"] and _next_unit_gte(t, end, "Y"):
            results.append(view_by_time_unit(name, t, "Y"))
            t = _add_unit(t, "Y")
        elif has["M"] and _next_unit_gte(t, end, "M"):
            results.append(view_by_time_unit(name, t, "M"))
            t = _add_unit(t, "M")
        elif has["D"] and _next_unit_gte(t, end, "D"):
            results.append(view_by_time_unit(name, t, "D"))
            t = _add_unit(t, "D")
        elif has["H"]:
            results.append(view_by_time_unit(name, t, "H"))
            t = _add_unit(t, "H")
        else:
            break

    return results
