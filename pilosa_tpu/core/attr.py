"""AttrStore — typed row/column attributes with anti-entropy checksums.

The reference stores attrs in BoltDB (key = big-endian u64 id, value =
protobuf AttrMap) with an in-memory cache and SHA1 block checksums per
100 ids for sync diffing (reference: attr.go:43-254, 411-508).  This
implementation uses stdlib sqlite3 (embedded, transactional, no new
deps) with JSON-encoded values; the block/diff protocol semantics are
the same.

Value types: str | int | bool | float (reference: attr.go:34-40);
``None`` deletes a key (reference: attr.go:285-289).
"""

from __future__ import annotations

import hashlib
import json
import os
import sqlite3
import threading
from typing import Any

# reference: attr.go:31-32
ATTR_BLOCK_SIZE = 100


def _to_db_id(id_: int) -> int:
    """Map a uint64 id into SQLite's signed 64-bit INTEGER (two's
    complement); the reference's boltdb keys are raw big-endian u64 so
    ids up to 2^64-1 are legal at the API."""
    id_ &= (1 << 64) - 1
    return id_ - (1 << 64) if id_ >= (1 << 63) else id_


def _from_db_id(id_: int) -> int:
    return id_ + (1 << 64) if id_ < 0 else id_


def validate_attrs(attrs: dict[str, Any]) -> None:
    for k, v in attrs.items():
        if v is None:
            continue
        if not isinstance(v, (str, int, bool, float)):
            raise TypeError(f"invalid attr type for {k!r}: {type(v).__name__}")


class AttrStore:
    """sqlite-backed attribute store with in-memory cache."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.RLock()
        self._cache: dict[int, dict[str, Any]] = {}
        self._db: sqlite3.Connection | None = None
        # Per-block checksums, maintained INCREMENTALLY at write time:
        # a block's digest is the XOR of sha1(id || json) over its
        # non-empty rows (order-independent, so a write updates it in
        # O(1) by xoring out the row's old term and xoring in the new
        # one) plus a non-empty-row count to detect emptied blocks.
        # blocks() then costs O(#blocks) dict reads instead of
        # SELECT+JSON-parsing the whole table per sync tick per peer.
        self._block_sums: dict[int, bytes] = {}
        self._block_counts: dict[int, int] = {}
        self._scanned = False  # digests cover the whole table

    # --- lifecycle ---

    def open(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._db = sqlite3.connect(self.path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS attrs (id INTEGER PRIMARY KEY, data TEXT)"
        )
        self._db.commit()
        self._block_sums = {}
        self._block_counts = {}
        # A fresh (empty) store's digests are trivially complete, and
        # every subsequent write maintains them — the common path never
        # scans.  A store reopened over existing rows digests lazily on
        # the first blocks() call (one streaming pass, once per open).
        row = self._db.execute("SELECT 1 FROM attrs LIMIT 1").fetchone()
        self._scanned = row is None

    def close(self) -> None:
        if self._db is not None:
            self._db.close()
            self._db = None
        self._cache.clear()
        self._block_sums = {}
        self._block_counts = {}
        self._scanned = False

    def _conn(self) -> sqlite3.Connection:
        if self._db is None:
            raise RuntimeError("attr store is not open")
        return self._db

    # --- reads ---

    def attrs(self, id_: int) -> dict[str, Any]:
        with self._lock:
            if id_ in self._cache:
                return dict(self._cache[id_])
            row = self._conn().execute(
                "SELECT data FROM attrs WHERE id = ?", (_to_db_id(id_),)
            ).fetchone()
            m = json.loads(row[0]) if row else {}
            self._cache[id_] = m
            return dict(m)

    # --- writes ---

    def set_attrs(self, id_: int, attrs: dict[str, Any]) -> None:
        """Merge attrs into the stored map; None values delete keys
        (reference: attr.go:120-155, 268-303)."""
        validate_attrs(attrs)
        with self._lock:
            old = self.attrs(id_)
            cur = dict(old)
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            self._conn().execute(
                "INSERT OR REPLACE INTO attrs (id, data) VALUES (?, ?)",
                (_to_db_id(id_), json.dumps(cur, sort_keys=True)),
            )
            self._conn().commit()
            self._cache[id_] = cur
            self._digest_update_locked(id_, old, cur)

    # SQLite's bound-parameter ceiling is 999 before 3.32; stay under it.
    _SELECT_BATCH = 500

    def set_bulk_attrs(self, attr_sets: dict[int, dict[str, Any]]) -> None:
        """Sorted batch write in ONE transaction (reference:
        SetBulkAttrs, attr.go:158-191 runs a single bolt Update): the
        current values of all touched ids load via batched ``IN``
        selects instead of a per-id Python-loop SELECT, the merged rows
        land through one executemany, and a failure anywhere rolls the
        whole batch back."""
        if not attr_sets:
            return
        with self._lock:
            ids = sorted(attr_sets)
            for id_ in ids:
                validate_attrs(attr_sets[id_])
            conn = self._conn()
            missing = [i for i in ids if i not in self._cache]
            for lo in range(0, len(missing), self._SELECT_BATCH):
                chunk = missing[lo : lo + self._SELECT_BATCH]
                marks = ",".join("?" * len(chunk))
                rows = conn.execute(
                    f"SELECT id, data FROM attrs WHERE id IN ({marks})",
                    [_to_db_id(i) for i in chunk],
                ).fetchall()
                for db_id, data in rows:
                    self._cache[_from_db_id(db_id)] = json.loads(data)
            params: list[tuple[int, str]] = []
            merged: dict[int, dict[str, Any]] = {}
            olds: dict[int, dict[str, Any]] = {}
            for id_ in ids:
                old = self._cache.get(id_, {})
                cur = dict(old)
                for k, v in attr_sets[id_].items():
                    if v is None:
                        cur.pop(k, None)
                    else:
                        cur[k] = v
                params.append((_to_db_id(id_), json.dumps(cur, sort_keys=True)))
                merged[id_] = cur
                olds[id_] = old
            try:
                conn.executemany(
                    "INSERT OR REPLACE INTO attrs (id, data) VALUES (?, ?)",
                    params,
                )
                conn.commit()
            except sqlite3.Error:
                conn.rollback()
                raise
            # Cache updates only after the transaction commits — a
            # rolled-back batch must not leave phantom attrs in memory.
            self._cache.update(merged)
            for id_ in ids:
                self._digest_update_locked(id_, olds[id_], merged[id_])

    # --- anti-entropy (reference: attr.go:193-254, 411-441) ---

    @staticmethod
    def _row_term(id_: int, data: str) -> int:
        """One non-empty row's digest term: sha1 over the unsigned id
        and the row's canonical json text (writes always store
        sort_keys=True, so text identity == value identity)."""
        h = hashlib.sha1()
        h.update(id_.to_bytes(8, "big"))
        h.update(data.encode())
        return int.from_bytes(h.digest(), "big")

    def _digest_update_locked(
        self, id_: int, old: dict[str, Any], new: dict[str, Any]
    ) -> None:
        """O(1) block-digest maintenance for one row write: xor out the
        old term, xor in the new one.  Skipped while the store hasn't
        digested its pre-existing rows yet (the lazy first scan reads
        this write's committed value from the table anyway)."""
        if not self._scanned or old == new:
            return
        b = id_ // ATTR_BLOCK_SIZE
        acc = int.from_bytes(self._block_sums.get(b, b"\0" * 20), "big")
        n = self._block_counts.get(b, 0)
        if old:
            acc ^= self._row_term(id_, json.dumps(old, sort_keys=True))
            n -= 1
        if new:
            acc ^= self._row_term(id_, json.dumps(new, sort_keys=True))
            n += 1
        if n <= 0:
            self._block_sums.pop(b, None)
            self._block_counts.pop(b, None)
        else:
            self._block_sums[b] = acc.to_bytes(20, "big")
            self._block_counts[b] = n

    def blocks(self) -> list[tuple[int, bytes]]:
        """[(block_id, digest)] over all ids, blocked per 100 ids.

        A block's digest is the XOR of its rows' sha1 terms —
        order-independent, so writes keep it current in O(1)
        (_digest_update_locked) and this call is a dict copy, not the
        full SELECT+JSON-parse of every row the sync loop used to pay
        per tick per peer.  Only a store reopened over existing rows
        pays one streaming digest pass, on its first blocks() call."""
        with self._lock:
            if not self._scanned:
                self._scan_all_blocks_locked()
            return sorted(self._block_sums.items())

    def _scan_all_blocks_locked(self) -> None:
        """One streaming pass over the whole table — only on the first
        blocks() after an open() that found existing rows."""
        sums: dict[int, int] = {}
        counts: dict[int, int] = {}
        cur = self._conn().execute("SELECT id, data FROM attrs")
        for db_id, data in cur:
            if data == "{}" or json.loads(data) == {}:
                continue
            id_ = _from_db_id(db_id)
            b = id_ // ATTR_BLOCK_SIZE
            sums[b] = sums.get(b, 0) ^ self._row_term(id_, data)
            counts[b] = counts.get(b, 0) + 1
        self._block_sums = {b: v.to_bytes(20, "big") for b, v in sums.items()}
        self._block_counts = counts
        self._scanned = True

    def _block_rows_locked(self, block_id: int):
        """One block's rows as ``(unsigned id, raw json text)`` in
        unsigned-id order, streamed by cursor.  "ORDER BY (id < 0), id"
        is unsigned order under the two's-complement id mapping."""
        lo = block_id * ATTR_BLOCK_SIZE
        hi = lo + ATTR_BLOCK_SIZE
        dlo, dhi = _to_db_id(lo), _to_db_id(hi - 1)
        if dlo <= dhi:
            cur = self._conn().execute(
                "SELECT id, data FROM attrs WHERE id >= ? AND id <= ?"
                " ORDER BY (id < 0), id",
                (dlo, dhi),
            )
        else:  # block straddles the uint63 sign boundary
            cur = self._conn().execute(
                "SELECT id, data FROM attrs WHERE id >= ? OR id <= ?"
                " ORDER BY (id < 0), id",
                (dlo, dhi),
            )
        for db_id, data in cur:
            yield _from_db_id(db_id), data

    def block_data(self, block_id: int) -> dict[int, dict[str, Any]]:
        """All attrs in one block (reference: BlockData, attr.go:226-254),
        streamed straight off the range cursor."""
        with self._lock:
            out: dict[int, dict[str, Any]] = {}
            for id_, data in self._block_rows_locked(block_id):
                m = json.loads(data)
                if m:
                    out[id_] = m
            return out


def diff_blocks(
    local: list[tuple[int, bytes]], remote: list[tuple[int, bytes]]
) -> list[int]:
    """Block ids that differ between two checksum lists (reference:
    AttrBlocks.Diff, attr.go:411-441): present on only one side, or
    present on both with different checksums."""
    lmap = dict(local)
    rmap = dict(remote)
    out = []
    for b in sorted(lmap.keys() | rmap.keys()):
        if lmap.get(b) != rmap.get(b):
            out.append(b)
    return out
